"""Ragged inference state: sequence descriptors + paged KV cache + batch
metadata.

TPU-native re-design of the reference's ragged subsystem
(``inference/v2/ragged/``): ``DSSequenceDescriptor``
(sequence_descriptor.py, 280 LoC), ``BlockedKVCache`` (kv_cache.py, 208),
``DSStateManager`` (ragged_manager.py), ``RaggedBatchWrapper``
(ragged_wrapper.py, 292 — pinned host-staged batch metadata).

Differences forced/afforded by XLA:
* the KV cache is one jnp array [L, num_blocks, block_size, 2, Hkv, D]
  updated functionally with scatter (donated across steps — in-place in
  practice);
* batch metadata is a fixed-shape numpy struct (XLA needs static shapes —
  the reference's pinned "fast host buffer" maps to plain numpy staged
  via device_put, its variable batch to padding up to the token budget).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .allocator import BlockedAllocator

# Sentinel token value in a pending queue meaning "the value is the
# previous pipelined step's on-device sample for this sequence's slot" —
# the host schedules position/blocks for it without ever reading the
# token back (engine.py substitutes it inside the jitted step from the
# prior step's [max_seqs] sample array).  Real token ids are >= 0.
FEEDBACK_TOKEN = -1

# root parent digest of every per-sequence block hash chain
_CHAIN_ROOT = b"kv-prefix-chain-v1"

# revive rounds a single queued request may trigger before match_prefix
# stops probing the tier for it: reviving allocates destination blocks,
# and in a tiny pool that allocation can evict (and re-demote) the very
# ancestors the chain needs — the cap turns that churn into a bounded
# cost and falls through to a plain resident match / re-prefill
_MAX_REVIVE_ATTEMPTS = 2


class RestageEntry(NamedTuple):
    """One queued tier->HBM block restage: the engine resolves ``op``
    (tier.ReviveOp) at its pre-dispatch drain, uploads the verified
    payload into block ``dst`` and registers ``digest`` — or frees
    ``dst`` when verification fails (the caller re-prefills)."""
    uid: int
    digest: bytes
    parent: bytes
    tokens: Tuple[int, ...]
    dst: int
    op: object


def chain_hash(parent: bytes, tokens) -> bytes:
    """Rolling content hash of one FULL KV block: digest of
    ``(parent_hash, block_tokens)``.  128-bit blake2b — the index maps
    digest -> physical block and a collision would silently alias wrong
    KV, so a real cryptographic digest (not Python's ``hash``) is the
    cheap insurance; hashing a 64-token block is ~1 µs."""
    toks = np.asarray(tokens, np.int64).tobytes()
    return hashlib.blake2b(parent + toks, digest_size=16).digest()


def iter_prefix_chain_digests(tokens, block_size: int,
                              max_blocks: Optional[int] = None):
    """Lazily yield the chain digest of each FULL block-aligned prefix
    of ``tokens`` — a GENERATOR so consumers that stop at the first
    index miss (``match_prefix`` on a cache-miss admission) hash one
    block, not the whole prompt."""
    n = len(tokens) // block_size
    if max_blocks is not None:
        n = min(n, max_blocks)
    parent = _CHAIN_ROOT
    for k in range(n):
        parent = chain_hash(parent, tokens[k * block_size:
                                           (k + 1) * block_size])
        yield parent


def prefix_chain_digests(tokens, block_size: int,
                         max_blocks: Optional[int] = None) -> List[bytes]:
    """Chain digests of every FULL block-aligned prefix of ``tokens`` —
    the engine-independent form of the prefix-cache key.  Entry ``k`` is
    the digest a :class:`StateManager` index holds iff the first
    ``(k+1) * block_size`` tokens of this stream are resident, so a
    fleet router can score cache affinity for a prompt against any
    replica's digest set without touching that replica's engine
    (docs/SERVING.md "Fleet: routing, failover, migration").
    ``match_prefix`` consumes the same digests (lazily, via
    :func:`iter_prefix_chain_digests`), so router-side scoring and
    engine-side matching can never disagree on the key."""
    return list(iter_prefix_chain_digests(tokens, block_size,
                                          max_blocks))


@dataclasses.dataclass
class KVCacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    block_size: int = 64
    num_blocks: int = 128
    dtype: object = jnp.bfloat16
    # "none" | "int8" | "fp8": store the paged cache quantized with one
    # scale per written (token, k|v, head) vector — halves the KV HBM
    # stream that dominates long-context decode (reference analog:
    # ZeRO-Inference KV quantization, deepspeed/inference/quantization/)
    quant: str = "none"

    @property
    def max_context(self) -> int:
        return self.num_blocks * self.block_size

    def __post_init__(self):
        if self.quant not in ("none", "int8", "fp8"):
            raise ValueError(
                f"kv_quant={self.quant!r}: the paged cache supports "
                "'int8' or 'fp8' (per-vector scales); weight_quant is "
                "the option that also takes 'int4'")

    def kv_zeros(self):
        """A pristine cache: a single array, or (data, scales) when
        quantized (a plain tuple — a pytree, so jit/donate/device_put
        treat it like the array everywhere the engine is agnostic)."""
        shape = (self.num_layers, self.num_blocks + 1, self.block_size, 2,
                 self.num_kv_heads, self.head_dim)
        if self.quant == "none":
            return jnp.zeros(shape, self.dtype)
        qdt = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}[self.quant]
        return (jnp.zeros(shape, qdt), jnp.zeros(shape[:-1], jnp.float32))


@dataclasses.dataclass
class SequenceDescriptor:
    """(reference: DSSequenceDescriptor sequence_descriptor.py)."""
    uid: int
    seen_tokens: int = 0                       # tokens already in KV
    blocks: List[int] = dataclasses.field(default_factory=list)
    tokens: List[int] = dataclasses.field(default_factory=list)  # generated
    # --- prefix-cache state --------------------------------------------
    cached_tokens: int = 0        # tokens served from the prefix cache
    # token ids in KV order while every value is host-known; a deferred
    # on-device token (FEEDBACK_TOKEN) or a device-side decode burst
    # breaks the chain — blocks past the break are never content-hashed
    chain: List[int] = dataclasses.field(default_factory=list)
    chain_broken: bool = False
    # per-full-block rolling hashes (parallel to ``blocks``' prefix);
    # pre-seeded by a prefix match, extended as chain blocks fill
    hashes: List[bytes] = dataclasses.field(default_factory=list)
    # speculative-decode state: number of DRAFTED tokens in the most
    # recent scheduled step whose acceptance has not resolved yet.
    # While nonzero, the last ``draft_len`` chain tokens / KV rows are
    # provisional: prefix-cache registration is deferred (a shared
    # block must never contain tokens that may roll back) and
    # :meth:`StateManager.resolve_draft` either commits them or rewinds
    # the write cursor.
    draft_len: int = 0

    def blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.seen_tokens + new_tokens
        needed = -(-total // block_size)       # ceil
        return max(0, needed - len(self.blocks))

    @property
    def resumable(self) -> bool:
        """The host knows every KV row's token id in order: the chain
        is intact and no unresolved draft window holds provisional
        rows.  This is THE eligibility predicate shared by
        preemption-by-eviction, failure-recovery re-queueing, and
        ``engine.snapshot()`` — a resumable sequence can be released
        and re-prefilled token-identically; a non-resumable one holds
        device-side tokens the host never saw (a deferred feedback
        marker or a decode burst) and can only be closed."""
        return (not self.chain_broken and self.draft_len == 0
                and len(self.chain) == self.seen_tokens)


class RaggedBatch(NamedTuple):
    """Fixed-shape device view of one engine step (the RaggedBatchWrapper
    analog).  All arrays are padded to (token_budget, max_seqs)."""
    token_ids: jnp.ndarray       # [T] i32
    positions: jnp.ndarray       # [T] i32, position within its sequence
    seq_slot: jnp.ndarray        # [T] i32, row into block_tables
    token_valid: jnp.ndarray     # [T] bool, False for budget padding
    block_tables: jnp.ndarray    # [max_seqs, max_blocks] i32; -1 pad
                                 # (wraps to the trash row on gather)
    context_lens: jnp.ndarray    # [max_seqs] i32, ctx len AFTER this step
    logits_idx: jnp.ndarray      # [max_seqs] i32, flat idx of each seq's
                                 # last token this step (-1 if none)
    n_tokens: int                # real token count (static python int)
    n_seqs: int
    feedback_src: Optional[jnp.ndarray] = None
                                 # [T] i32: slot whose previous-step
                                 # on-device sample supplies this token's
                                 # id (-1 = token_ids holds the value)
    seq_uids: Optional[jnp.ndarray] = None
                                 # [max_seqs] u32: uid occupying each
                                 # slot (masked to 32 bits; 0 when
                                 # empty).  Feeds the schedule-invariant
                                 # per-(uid, position) sampling keys —
                                 # see sampler.sample_rows
    verify_idx: Optional[jnp.ndarray] = None
                                 # [max_seqs, n_verify] i32: flat token
                                 # indices of each slot's speculative
                                 # verify window (-1 pad).  Column j of
                                 # a drafting row is the fed token
                                 # (j=0) / j-th draft; column 0 of a
                                 # non-drafting row is its logits_idx.
                                 # Present only on verify-step batches
                                 # (None keeps the legacy single-sample
                                 # program byte-identical)


class BatchStager:
    """Two alternating host-side staging buffer sets for RaggedBatch
    metadata (the reference's pinned "fast host buffer",
    ragged_wrapper.py).  The depth-2 serving pipeline builds step N+1's
    metadata while step N executes on device; alternating buffers
    guarantee the host never rewrites a set whose ``device_put`` transfer
    for the previous step may still be draining.  Two sets suffice for
    exactly one step in flight (``pipeline_depth=2``); deeper pipelines
    get ``depth`` sets."""

    def __init__(self, token_budget: int, max_seqs: int, max_blocks: int,
                 depth: int = 2, n_verify: int = 1):
        self.shape_key = (token_budget, max_seqs, max_blocks)
        # widest speculative verify window this engine may stage
        # (spec_max_draft + 1); batches slice the columns they use
        self.n_verify = max(1, n_verify)
        self._bufs = [self._alloc(token_budget, max_seqs, max_blocks,
                                  self.n_verify)
                      for _ in range(max(2, depth))]
        self._i = 0

    @staticmethod
    def _alloc(T: int, S: int, nb: int, nv: int) -> Dict[str, np.ndarray]:
        return {
            "token_ids": np.zeros(T, np.int32),
            "positions": np.zeros(T, np.int32),
            "seq_slot": np.zeros(T, np.int32),
            "block_tables": np.full((S, nb), -1, np.int32),
            "context_lens": np.zeros(S, np.int32),
            "logits_idx": np.full(S, -1, np.int32),
            "feedback_src": np.full(T, -1, np.int32),
            "seq_uids": np.zeros(S, np.uint32),
            "verify_idx": np.full((S, nv), -1, np.int32),
        }

    def next_buffers(self) -> Dict[str, np.ndarray]:
        """The next staging set, reset to its fill values."""
        b = self._bufs[self._i]
        self._i = (self._i + 1) % len(self._bufs)
        b["token_ids"].fill(0)
        b["positions"].fill(0)
        b["seq_slot"].fill(0)
        b["block_tables"].fill(-1)
        b["context_lens"].fill(0)
        b["logits_idx"].fill(-1)
        b["feedback_src"].fill(-1)
        b["seq_uids"].fill(0)
        b["verify_idx"].fill(-1)
        return b


class StateManager:
    """Owns allocator + sequence table + the paged KV cache + the
    prefix-cache hash index (reference: DSStateManager ragged_manager.py).

    With ``prefix_cache=True``, every FULL block whose token chain is
    host-known is registered in a ``digest -> physical block`` index as
    it fills; :meth:`match_prefix` aliases an incoming prompt's longest
    cached block-aligned prefix into the new sequence's block table
    (refcounted, read-only) so prefill starts at the first uncached
    token.  Unreferenced cached blocks rest on the allocator's LRU
    cached-free pool until evicted for a fresh allocation."""

    def __init__(self, cfg: KVCacheConfig, max_seqs: int = 16,
                 max_blocks_per_seq: Optional[int] = None,
                 prefix_cache: bool = False):
        self.cfg = cfg
        self.max_seqs = max_seqs
        self.max_blocks_per_seq = max_blocks_per_seq or cfg.num_blocks
        self.prefix_cache = prefix_cache
        self.allocator = BlockedAllocator(cfg.num_blocks,
                                          on_evict=self._on_evict)
        # tpulint: ledger=allocator — every live descriptor owns blocks
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self._slots: Dict[int, int] = {}       # uid -> batch row
        self._free_slots = list(range(max_seqs))
        # prefix-cache index: chain digest -> physical block (1:1), plus
        # the reverse map the eviction callback uses
        self._hash_index: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        # copy-on-write copies queued by match_prefix: (uid, src, dst).
        # The ENGINE drains these with a device block copy before the
        # next step dispatch (the scheduler itself never touches the
        # device); release() drops a flushed sequence's entries
        self.cow_pending: List[Tuple[int, int, int]] = []
        # fired with the uid AFTER a sequence's blocks/slot are
        # released — the engine closes the request's lifecycle record
        # here so no exit path (flush, preemption, deadline, direct
        # release) can leak an open record
        self.on_release: Optional[callable] = None
        # (digest, block) index entries registered since the last
        # build_batch began: a registration promises the block HOLDS
        # the hashed content, but the device write that honors it rides
        # the same step — if that step FAILS, the engine must
        # unregister exactly these entries (docs/SERVING.md "Failure
        # domains & recovery") or a later prefix match would alias
        # never-written KV
        self.round_registered: List[Tuple[bytes, int]] = []
        # tiered KV (tier.py, attached by the engine when kv_tier
        # resolves on; None = discard-on-evict, the pre-tier behavior).
        # Demotions and restages are QUEUES the engine drains around its
        # pre-dispatch device work — the scheduler itself never touches
        # the device or the disk
        self.tier = None
        # (parent_digest, chain_digest, block_tokens, block) — content
        # evicted from the index this round, payload still on device
        # until the next dispatched step overwrites the block
        self.tier_pending_demote: List[
            Tuple[bytes, bytes, Tuple[int, ...], int]] = []
        self.tier_pending_restage: List[RestageEntry] = []
        # uid -> outstanding restage ops; a uid in here is deferred by
        # the scheduler (admitted next round, once its chain re-indexes)
        self._restaging_uids: Dict[int, int] = {}
        self._revive_attempts: Dict[int, int] = {}
        # block -> (parent_digest, block_tokens): what _on_evict needs
        # to demote a block's content under its chain key; tracks
        # _block_hash keys exactly
        self._block_meta: Dict[int, Tuple[bytes, Tuple[int, ...]]] = {}
        # paged KV: [L, blocks+1, block_size, 2, Hkv, D] — the extra row is
        # the trash block that padding tokens' KV writes are routed to
        # (plus per-vector scales when cfg.quant != "none")
        self.kv = cfg.kv_zeros()

    # ---- sequence lifecycle ---------------------------------------------
    def get_or_create(self, uid: int) -> SequenceDescriptor:
        if uid not in self.seqs:
            if not self._free_slots:
                raise RuntimeError("No free sequence slots")
            self.seqs[uid] = SequenceDescriptor(uid=uid)
            self._slots[uid] = self._free_slots.pop(0)
        return self.seqs[uid]

    def slot(self, uid: int) -> int:
        return self._slots[uid]

    def release(self, uid: int) -> None:
        """(reference: flush engine_v2.py:242).  Blocks drop one
        reference each: a block whose content is index-registered and
        whose refcount hits zero retires to the cached-free LRU pool
        (matchable until evicted); the rest go back to the free list."""
        self._revive_attempts.pop(uid, None)
        seq = self.seqs.pop(uid, None)
        if seq is None:
            return
        if self.cow_pending:
            # a queued-but-undrained COW copy must die with its owner:
            # its dst block is freed right here and may be reallocated
            # before the engine would have executed the copy
            self.cow_pending = [c for c in self.cow_pending if c[0] != uid]
        if seq.blocks:
            # retire TAIL blocks into the cached-free LRU first: a chain
            # block is only matchable when every ancestor is still
            # indexed, so eviction (oldest-released first) must consume
            # chains leaf-first — a surviving cached prefix stays useful
            self.allocator.free(list(reversed(seq.blocks)))
        self._free_slots.append(self._slots.pop(uid))
        if self.on_release is not None:
            self.on_release(uid)

    # ---- prefix cache ----------------------------------------------------
    def _on_evict(self, block: int) -> None:
        """Allocator reclaimed a cached-free block: drop its index entry
        (nothing may match content about to be overwritten).  With a
        tier attached the content is queued for demotion instead of
        dying — the engine reads the block off the device BEFORE the
        step that overwrites it dispatches (same pre-dispatch ordering
        COW drains rely on)."""
        h = self._block_hash.pop(block, None)
        meta = self._block_meta.pop(block, None)
        if h is not None:
            self._hash_index.pop(h, None)
            if self.tier is not None and meta is not None:
                self.tier_pending_demote.append(
                    (meta[0], h, meta[1], block))

    def match_prefix(self, uid: int, tokens: List[int],
                     max_pool_take: Optional[int] = None) -> int:
        """Alias the longest cached block-aligned prefix of ``tokens``
        into a NEW sequence ``uid`` and return the number of prompt
        tokens served from the cache (0 = no match; the caller drops the
        matched tokens from its pending queue, so prefill starts at the
        first uncached token).

        ``max_pool_take`` caps how many blocks the match may REMOVE from
        the allocatable pool (reviving a cached-free block and the COW
        copy below each count; aliasing a live block is free) — the
        scheduler passes its unreserved headroom so a mid-round match
        can never consume blocks already promised to an earlier admit.

        At least one token is always left for the prefill step (the
        forward must run to produce the first logits).  When the cached
        chain covers the whole prompt, the last matched block therefore
        becomes a shared *partial* block from this sequence's view — it
        is copy-on-write'd: a fresh block is allocated, a device copy
        (queued on ``cow_pending``) duplicates the content, and the
        sequence's table points at the private copy while the original
        stays in the index for future matchers."""
        bs = self.cfg.block_size
        if (not self.prefix_cache or uid in self.seqs
                or not self._free_slots or len(tokens) <= bs):
            return 0
        if max_pool_take is None:
            max_pool_take = self.allocator.free_blocks
        hashes: List[bytes] = []
        blocks: List[int] = []
        takes = 0
        revive_run: List[bytes] = []
        # lazy digests: a cache-miss admission hashes ONE block and
        # stops, instead of pre-hashing the whole prompt
        digest_iter = iter_prefix_chain_digests(tokens, bs,
                                                self.max_blocks_per_seq)
        for h in digest_iter:
            b = self._hash_index.get(h)
            if b is None:
                if (self.tier is not None
                        and uid not in self._restaging_uids
                        and self._revive_attempts.get(uid, 0)
                        < _MAX_REVIVE_ATTEMPTS
                        and h in self.tier):
                    # the resident run ends in the tier: gather the
                    # contiguous spilled continuation, bounded by the
                    # pool headroom its destination blocks will consume.
                    # max_pool_take is the scheduler's UNRESERVED
                    # headroom — at <= 0 a restage dst would steal a
                    # block already promised to this round's admitted
                    # batch, so the revive waits for a later round
                    budget = min(max_pool_take,
                                 self.allocator.free_blocks)
                    if budget <= 0:
                        break
                    revive_run.append(h)
                    for h2 in digest_iter:
                        if len(revive_run) >= budget \
                                or h2 not in self.tier:
                            break
                        revive_run.append(h2)
                break
            t = 1 if self.allocator.refcount(b) == 0 else 0
            if takes + t > max_pool_take:
                break
            takes += t
            hashes.append(h)
            blocks.append(b)
        if revive_run and self._begin_restage(uid, revive_run):
            # the whole match ABORTS (no refs were taken): the caller
            # re-queues the request and the engine's restage drain
            # re-indexes the chain, so next round's match covers both
            # the resident run and the revived continuation
            return 0
        if not blocks:
            return 0
        for b in blocks:
            self.allocator.ref(b)
        matched = len(blocks) * bs
        if matched >= len(tokens):
            # full cover: re-schedule the last token so the step has
            # output; it re-writes position matched-1 inside the last
            # matched block -> copy-on-write (the rewrite is
            # content-equivalent but must not touch a shared block)
            matched = len(tokens) - 1
            if takes < max_pool_take and self.allocator.free_blocks >= 1:
                src = blocks[-1]
                [dst] = self.allocator.allocate(1)
                self.cow_pending.append((uid, src, dst))
                self.allocator.free([src])     # swap our alias for the copy
                blocks[-1] = dst
            else:
                # no room for the private copy: drop back to a
                # block-aligned match instead
                self.allocator.free([blocks.pop()])
                hashes.pop()
                matched = len(blocks) * bs
                if not blocks:
                    return 0
        seq = self.get_or_create(uid)
        seq.blocks = list(blocks)
        seq.seen_tokens = matched
        seq.cached_tokens = matched
        seq.chain = list(tokens[:matched])
        seq.hashes = hashes
        self._revive_attempts.pop(uid, None)
        return matched

    def _register_chain_blocks(self, seq: SequenceDescriptor) -> None:
        """Content-hash and index any chain blocks that just became full
        (called from build_batch after the chain is extended — so a block
        is matchable from the very step that fills it; device ordering
        makes the write land before any aliasing step's read)."""
        bs = self.cfg.block_size
        while len(seq.hashes) < len(seq.chain) // bs:
            k = len(seq.hashes)
            parent = seq.hashes[-1] if seq.hashes else _CHAIN_ROOT
            h = chain_hash(parent, seq.chain[k * bs:(k + 1) * bs])
            seq.hashes.append(h)
            if h not in self._hash_index:
                b = seq.blocks[k]
                self._hash_index[h] = b
                self._block_hash[b] = h
                self._block_meta[b] = (
                    parent, tuple(seq.chain[k * bs:(k + 1) * bs]))
                self.allocator.mark_cached(b)
                self.round_registered.append((h, b))

    def unregister_blocks(self, entries: List[Tuple[bytes, int]]) -> None:
        """Withdraw specific ``(digest, block)`` index registrations —
        the failure-recovery path for registrations whose backing KV
        write died with a failed step.  Unregistering is always SAFE
        (worst case a future match misses); only entries still mapping
        the same block are touched, so stale lists from older rounds
        are harmless."""
        for h, b in entries:
            if self._hash_index.get(h) != b:
                continue
            del self._hash_index[h]
            self._block_hash.pop(b, None)
            self._block_meta.pop(b, None)
            self.allocator.unmark_cached(b)

    def reset_prefix_cache(self) -> None:
        """Drop every index entry; cached-free blocks become plain free.
        (Used when cache CONTENT is invalidated, e.g. the engine's
        attn-impl probe rewrites the pool with synthetic tokens.)"""
        for b in list(self._block_hash):
            self.allocator.unmark_cached(b)
        self._block_hash.clear()
        self._hash_index.clear()
        self._block_meta.clear()
        self.cow_pending.clear()
        # invalidated content must not be demoted or restaged either:
        # dump the demote queue and free every pending restage's
        # destination (its tier entry was consumed — acceptable loss on
        # a content reset, which only happens before real traffic)
        self.tier_pending_demote.clear()
        for ent in self.tier_pending_restage:
            self.allocator.free([ent.dst])
        self.tier_pending_restage.clear()
        self._restaging_uids.clear()

    def prefix_digests(self) -> frozenset:
        """Hex digests resident in the prefix-cache index right now —
        the router's live cache-affinity key.  The same set
        ``engine.snapshot()["prefix_index"]`` freezes at snapshot time;
        score a prompt against it with :func:`prefix_chain_digests`."""
        return frozenset(h.hex() for h in self._hash_index)

    def pool_stats(self) -> Dict[str, int]:
        """Allocator-truth pool occupancy — the numbers the engine's
        ``serving_kv_*`` pull-gauges export (docs/OBSERVABILITY.md
        "Device & compiler telemetry").  Computed from the SAME state
        ``BlockedAllocator.assert_invariants`` checks, so the scheduler
        fuzz can cross-check gauge == truth after every op; pure host
        ints, safe to read at any phase boundary."""
        al = self.allocator
        return {
            "free": len(al._free),
            "cached_free": al.cached_free_blocks,
            "referenced": al.referenced_blocks,
            "total": al.total_blocks,
            "peak_referenced": al.peak_referenced_blocks,
            "prefix_index_entries": len(self._hash_index),
            "live_seqs": len(self.seqs),
            "free_slots": len(self._free_slots),
        }

    def take_cow_copies(self) -> List[Tuple[int, int]]:
        """Hand the queued (src, dst) copy-on-write block copies to the
        engine (which executes them on device BEFORE the next step) and
        clear the queue."""
        out = [(s, d) for _, s, d in self.cow_pending]
        self.cow_pending.clear()
        return out

    # ---- tier plumbing (tier.py; docs/KV_TIERING.md) ---------------------
    def _begin_restage(self, uid: int, run: List[bytes]) -> bool:
        """Start restaging a contiguous run of tiered chain digests for
        a deferred request: consume each tier entry (NVMe reads are
        queued inside ``begin_revive`` so they overlap the scheduler
        round) and allocate its destination block, held at refcount 1
        until the engine's drain commits or aborts the upload."""
        if not run or self.allocator.free_blocks < len(run):
            return False
        if len(self._revive_attempts) > 1024:
            # bounded: uids cancelled while still queued never release()
            self._revive_attempts.pop(next(iter(self._revive_attempts)))
        self._revive_attempts[uid] = \
            self._revive_attempts.get(uid, 0) + 1
        started = 0
        for h in run:
            op = self.tier.begin_revive(h)
            if op is None:
                break
            [dst] = self.allocator.allocate(1)
            self.tier_pending_restage.append(
                RestageEntry(uid, h, op.parent, op.tokens, dst, op))
            started += 1
        if not started:
            return False
        self._restaging_uids[uid] = \
            self._restaging_uids.get(uid, 0) + started
        return True

    def restaging(self, uid: int) -> bool:
        """Whether ``uid`` has restage ops in flight — the scheduler
        defers (keeps queued, schedules nothing for) such a request."""
        return uid in self._restaging_uids

    def commit_restage(self, ent: RestageEntry) -> None:
        """The engine verified and uploaded ``ent``'s payload into
        ``ent.dst``: register the digest and retire the block to the
        cached-free pool (matchable, evictable — restaged content IS
        cache content).  Joins ``round_registered`` so a failed step
        unwinds the registration like any other."""
        b, h = ent.dst, ent.digest
        if h not in self._hash_index:
            self._hash_index[h] = b
            self._block_hash[b] = h
            self._block_meta[b] = (ent.parent, tuple(ent.tokens))
            self.allocator.mark_cached(b)
            self.round_registered.append((h, b))
        # a racing prefill may have re-registered the digest while the
        # restage was in flight — our copy is then redundant and the
        # free below retires it straight to the plain free list
        self.allocator.free([b])
        self._restage_done(ent.uid)

    def abort_restage(self, ent: RestageEntry) -> None:
        """Verification failed (or the payload died with its spill
        file): free the destination unregistered — the request falls
        back to a plain re-prefill, which rebuilds the chain."""
        self.allocator.free([ent.dst])
        self._restage_done(ent.uid)

    def _restage_done(self, uid: int) -> None:
        n = self._restaging_uids.get(uid, 0) - 1
        if n <= 0:
            self._restaging_uids.pop(uid, None)
        else:
            self._restaging_uids[uid] = n

    def take_tier_demotes(self) -> List[Tuple[bytes, bytes,
                                              Tuple[int, ...], int]]:
        """Hand the queued (parent, digest, tokens, block) demotions to
        the engine, which reads each block off the device BEFORE the
        step that overwrites it dispatches."""
        out = self.tier_pending_demote
        self.tier_pending_demote = []
        return out

    def stage_chain_demotes(self, uid: int) -> int:
        """Queue a device→tier COPY for every still-indexed full block
        of ``uid``'s chain and return how many were queued — the
        prefill→decode handoff's KV export (docs/SERVING.md
        "Disaggregated pools & elasticity").  Unlike the eviction path
        (``_on_evict``) the blocks stay indexed and cached-free on this
        replica: the tier entry is a copy ``export_tier_chain`` can
        ship, not a move.  Blocks already tiered (or never registered —
        the partial tail, cache-off runs) are skipped; the destination
        re-prefills whatever the exported run doesn't cover."""
        seq = self.seqs.get(uid)
        if seq is None or self.tier is None:
            return 0
        n = 0
        for b in seq.blocks:
            h = self._block_hash.get(b)
            meta = self._block_meta.get(b)
            if h is None or meta is None or h in self.tier:
                continue
            self.tier_pending_demote.append((meta[0], h, meta[1], b))
            n += 1
        return n

    def take_tier_restage(self) -> List[RestageEntry]:
        out = self.tier_pending_restage
        self.tier_pending_restage = []
        return out

    # ---- scheduling query ------------------------------------------------
    @property
    def max_context_tokens(self) -> int:
        return self.max_blocks_per_seq * self.cfg.block_size

    def context_remaining(self, uid: int) -> int:
        seq = self.seqs.get(uid)
        seen = seq.seen_tokens if seq else 0
        return self.max_context_tokens - seen

    def can_schedule(self, uid: int, new_tokens: int) -> bool:
        """(reference: can_schedule engine_v2.py:184)."""
        seq = self.seqs.get(uid) or SequenceDescriptor(uid=uid)
        need = seq.blocks_needed(new_tokens, self.cfg.block_size)
        slot_ok = uid in self._slots or bool(self._free_slots)
        return (need <= self.allocator.free_blocks and slot_ok
                and new_tokens <= self.context_remaining(uid))

    def reserve_ahead(self, uid: int, n_tokens: int) -> bool:
        """Pre-allocate KV blocks covering ``n_tokens`` beyond the
        current context (device-side decode bursts write K tokens
        between host block allocations).  Returns False when the pool
        or context limit cannot cover it."""
        seq = self.seqs[uid]
        if n_tokens > self.context_remaining(uid):
            return False
        need = seq.blocks_needed(n_tokens, self.cfg.block_size)
        if need > self.allocator.free_blocks:
            return False
        if need:
            seq.blocks.extend(self.allocator.allocate(need))
        return True

    def resolve_draft(self, uid: int, accepted: int) -> int:
        """Resolve a speculative verify step for ``uid``: commit the
        ``accepted`` leading draft tokens and REWIND the write cursor
        over the rejected tail (the engine's accept-longest-matching-
        prefix check decides ``accepted``; docs/SERVING.md "Speculative
        decoding").

        The rejected rows' KV stays physically in place but becomes
        dead weight the very next scheduled token overwrites: rollback
        is just ``seen_tokens``/chain truncation, no device work.  The
        trailing blocks allocated for the rejected rows are kept — they
        are private by construction (registration was deferred while
        the draft was unresolved, so no other sequence can alias them)
        and the growing sequence refills them.  Prefix-cache
        registration of chain blocks completed by the window happens
        HERE, post-rollback, so the index only ever maps hashes to
        committed content.

        Returns the number of rejected tokens rolled back (0 when the
        sequence died mid-flight or carried no unresolved draft —
        idempotent by construction)."""
        seq = self.seqs.get(uid)
        if seq is None or not seq.draft_len:
            return 0
        k = seq.draft_len
        seq.draft_len = 0
        if not 0 <= accepted <= k:
            raise ValueError(f"accepted={accepted} outside 0..{k}")
        rejected = k - accepted
        if rejected:
            seq.seen_tokens -= rejected
            if not seq.chain_broken:
                del seq.chain[-rejected:]
        if self.prefix_cache and not seq.chain_broken:
            self._register_chain_blocks(seq)
        return rejected

    def advance(self, uid: int, n_tokens: int) -> None:
        """Account tokens written device-side (burst iterations past the
        first host-fed token).  Burst-written KV bypasses build_batch, so
        the content hash chain ends here — prompt blocks registered
        earlier stay matchable."""
        seq = self.seqs[uid]
        seq.seen_tokens += n_tokens
        seq.chain_broken = True

    # ---- batch building --------------------------------------------------
    def build_batch(self, requests: List[tuple], token_budget: int,
                    stager: Optional[BatchStager] = None,
                    draft_lens: Optional[Dict[int, int]] = None,
                    n_verify: int = 1) -> RaggedBatch:
        """requests: [(uid, list_of_new_token_ids)]; allocates KV blocks and
        produces the padded device metadata.  A token id of
        :data:`FEEDBACK_TOKEN` (single-token decode continuations only)
        marks a deferred on-device token: the host stages id 0 and
        records the sequence's slot in ``feedback_src`` so the jitted
        step substitutes the previous step's sample.  With ``stager``,
        metadata is written into its alternating pre-allocated buffers
        instead of fresh arrays.

        ``draft_lens``: per-uid count of trailing SPECULATIVE tokens in
        that request's token list (a decode verify window ``[fed token,
        draft_1..draft_k]``).  The window's KV rows are written like any
        chunked prefill, but the sequence is marked draft-pending:
        prefix-cache registration defers and the engine's collect calls
        :meth:`resolve_draft` to commit or rewind.  ``n_verify > 1``
        emits ``verify_idx`` ([max_seqs, n_verify]) so the compiled step
        samples every window position (-1 pads; non-drafting rows use
        column 0 = their last token)."""
        max_blocks = self.cfg.num_blocks
        T = token_budget
        # fresh registration ledger for this round (see round_registered)
        self.round_registered = []
        if stager is not None \
                and stager.shape_key == (T, self.max_seqs, max_blocks) \
                and stager.n_verify >= n_verify:
            bufs = stager.next_buffers()
            token_ids = bufs["token_ids"]
            positions = bufs["positions"]
            seq_slot = bufs["seq_slot"]
            block_tables = bufs["block_tables"]
            context_lens = bufs["context_lens"]
            logits_idx = bufs["logits_idx"]
            feedback_src = bufs["feedback_src"]
            seq_uids = bufs["seq_uids"]
            verify_idx = bufs["verify_idx"]
        else:
            token_ids = np.zeros(T, np.int32)
            positions = np.zeros(T, np.int32)
            seq_slot = np.full(T, 0, np.int32)
            # -1 pad: negative gather wraps to the KV array's last row,
            # which is the zeroed trash block — padded columns can never
            # alias a live block (they are also masked by position)
            block_tables = np.full((self.max_seqs, max_blocks), -1, np.int32)
            context_lens = np.zeros(self.max_seqs, np.int32)
            logits_idx = np.full(self.max_seqs, -1, np.int32)
            feedback_src = np.full(T, -1, np.int32)
            seq_uids = np.zeros(self.max_seqs, np.uint32)
            verify_idx = np.full((self.max_seqs, max(1, n_verify)), -1,
                                 np.int32)

        # keep existing sequences' tables valid even if not in this batch
        for uid, seq in self.seqs.items():
            s = self._slots[uid]
            block_tables[s, :len(seq.blocks)] = seq.blocks
            context_lens[s] = seq.seen_tokens
            seq_uids[s] = np.uint32(uid & 0xFFFFFFFF)

        cursor = 0
        n_seqs = 0
        for uid, new_tokens in requests:
            n = len(new_tokens)
            if n == 0:
                continue
            k_draft = draft_lens.get(uid, 0) if draft_lens else 0
            if k_draft and (k_draft >= n or n_verify <= k_draft):
                raise ValueError(
                    f"uid {uid}: {k_draft} drafts need a {k_draft + 1}-"
                    f"token window and n_verify > {k_draft}")
            if cursor + n > T:
                raise ValueError(f"token budget {T} exceeded")
            seq = self.get_or_create(uid)
            if seq.draft_len:
                raise ValueError(
                    f"uid {uid}: unresolved draft window "
                    f"({seq.draft_len} tokens) — resolve_draft must run "
                    "before more tokens are scheduled")
            if n > self.context_remaining(uid):
                raise ValueError(
                    f"uid {uid}: {n} new tokens exceed remaining context "
                    f"({self.context_remaining(uid)} of "
                    f"{self.max_context_tokens})")
            need = seq.blocks_needed(n, self.cfg.block_size)
            if need:
                seq.blocks.extend(self.allocator.allocate(need))
            s = self._slots[uid]
            block_tables[s, :len(seq.blocks)] = seq.blocks
            if n == 1 and new_tokens[0] == FEEDBACK_TOKEN:
                # deferred decode token: value comes from the previous
                # step's on-device sample at this sequence's slot
                token_ids[cursor] = 0
                feedback_src[cursor] = s
                # the host never learns this KV row's token id in order,
                # so content hashing stops here for this sequence
                seq.chain_broken = True
            else:
                token_ids[cursor:cursor + n] = new_tokens
                if not seq.chain_broken:
                    # the chain is kept even with the prefix cache off:
                    # it is the host-known "KV contents in order" record
                    # that preemption-by-eviction re-queues (the index
                    # registration below stays cache-gated)
                    seq.chain.extend(int(t) for t in new_tokens)
            positions[cursor:cursor + n] = np.arange(
                seq.seen_tokens, seq.seen_tokens + n)
            seq_slot[cursor:cursor + n] = s
            seq.seen_tokens += n
            context_lens[s] = seq.seen_tokens
            seq_uids[s] = np.uint32(uid & 0xFFFFFFFF)
            logits_idx[s] = cursor + n - 1
            if n_verify > 1:
                # column 0 is always the row's last token (the legacy
                # sample); a drafting row's window spans its trailing
                # k_draft + 1 tokens
                verify_idx[s, 0] = cursor + n - 1
                if k_draft:
                    verify_idx[s, :k_draft + 1] = np.arange(
                        cursor + n - 1 - k_draft, cursor + n)
                    seq.draft_len = k_draft
            cursor += n
            n_seqs += 1
            if self.prefix_cache and not seq.chain_broken \
                    and not seq.draft_len:
                # draft-pending sequences defer registration to
                # resolve_draft: a shared block must never hold tokens
                # that may roll back
                self._register_chain_blocks(seq)

        return RaggedBatch(
            token_ids=jnp.asarray(token_ids),
            positions=jnp.asarray(positions),
            seq_slot=jnp.asarray(seq_slot),
            token_valid=jnp.asarray(np.arange(T) < cursor),
            block_tables=jnp.asarray(block_tables),
            context_lens=jnp.asarray(context_lens),
            logits_idx=jnp.asarray(logits_idx),
            n_tokens=cursor, n_seqs=n_seqs,
            feedback_src=jnp.asarray(feedback_src),
            seq_uids=jnp.asarray(seq_uids),
            verify_idx=(jnp.asarray(verify_idx[:, :n_verify])
                        if n_verify > 1 else None))
