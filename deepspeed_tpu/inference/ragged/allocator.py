"""Blocked KV-cache allocator (host-side free list).

TPU-native port of the reference's ``BlockedAllocator``
(``deepspeed/inference/v2/ragged/blocked_allocator.py`` — 105 LoC linked
free-list over an int tensor).  Pure host Python here: allocation happens
between steps, never inside jit, so a plain list beats a device tensor.
"""

from __future__ import annotations

from typing import List, Set


class BlockedAllocator:
    """Fixed pool of KV blocks handed out to sequences."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._free_set: Set[int] = set(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> List[int]:
        if num_blocks > len(self._free):
            raise ValueError(
                f"Cannot allocate {num_blocks} blocks: {len(self._free)} free")
        out = self._free[:num_blocks]
        del self._free[:num_blocks]
        self._free_set.difference_update(out)
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"Invalid block id {b}")
            if b in self._free_set:
                raise ValueError(f"Double free of block {b}")
        self._free.extend(blocks)
        self._free_set.update(blocks)
