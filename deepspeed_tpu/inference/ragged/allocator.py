"""Blocked KV-cache allocator (host-side free list + refcounts).

TPU-native port of the reference's ``BlockedAllocator``
(``deepspeed/inference/v2/ragged/blocked_allocator.py`` — 105 LoC linked
free-list over an int tensor), grown for automatic prefix caching: blocks
are REFCOUNTED (several sequences may alias one physical block read-only)
and a block whose content is registered in the prefix-cache hash index
retires to a *cached-free* LRU pool instead of the plain free list when
its last reference drops.  Allocation prefers plain-free blocks and only
then evicts from the cached pool, oldest first — reuse before overwrite.

Pure host Python: allocation happens between steps, never inside jit.

Accounting invariant (checked by ``assert_invariants`` and the scheduler
fuzz tests)::

    referenced + cached_free + free == total

where *referenced* counts blocks with refcount >= 1, *cached_free* the
evictable prefix-cache pool, and *free* the plain free list.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Set


class BlockedAllocator:
    """Fixed pool of KV blocks handed out to sequences.

    ``on_evict(block)`` fires when a cached-free block is reclaimed for a
    fresh allocation (the owner of the hash index drops its entry there).
    """

    def __init__(self, num_blocks: int,
                 on_evict: Optional[Callable[[int], None]] = None):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks))
        self._free_set: Set[int] = set(self._free)
        self._refs: Dict[int, int] = {}            # block -> refcount >= 1
        # block -> None, insertion-ordered: oldest released first (the
        # LRU eviction order); value unused, OrderedDict is the O(1)
        # ordered set
        self._cached_free: "OrderedDict[int, None]" = OrderedDict()
        self._hashed: Set[int] = set()   # blocks registered in the index
        self.on_evict = on_evict
        # high-water mark of the referenced pool (pure int compare on
        # the paths that grow it — the pool-pressure gauge device
        # telemetry exports; reset_peaks() rearms it for a bench leg)
        self._peak_referenced = 0

    # ---- introspection ---------------------------------------------------
    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: plain free + evictable cached-free."""
        return len(self._free) + len(self._cached_free)

    @property
    def cached_free_blocks(self) -> int:
        return len(self._cached_free)

    @property
    def referenced_blocks(self) -> int:
        return len(self._refs)

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    @property
    def peak_referenced_blocks(self) -> int:
        """High-water mark of concurrently referenced blocks."""
        return self._peak_referenced

    def reset_peaks(self) -> None:
        self._peak_referenced = len(self._refs)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def is_cached(self, block: int) -> bool:
        """Whether the block's content is registered in the hash index
        (set via :meth:`mark_cached`; survives release into the
        cached-free pool, cleared on eviction)."""
        return block in self._hashed

    def assert_invariants(self) -> None:
        """referenced + cached_free + free == total, pools disjoint."""
        ref = set(self._refs)
        cf = set(self._cached_free)
        fr = self._free_set
        assert not (ref & cf) and not (ref & fr) and not (cf & fr), \
            "allocator pools overlap"
        assert len(ref) + len(cf) + len(fr) == self._num_blocks, (
            f"referenced({len(ref)}) + cached_free({len(cf)}) + "
            f"free({len(fr)}) != total({self._num_blocks})")
        assert len(self._free) == len(fr), "free list duplicates"
        assert all(c >= 1 for c in self._refs.values())
        # cached-free blocks are by definition index-registered
        assert cf <= self._hashed, "cached-free block without a hash"

    # ---- allocation ------------------------------------------------------
    def allocate(self, num_blocks: int) -> List[int]:
        """Hand out ``num_blocks`` blocks at refcount 1, drawing from the
        plain free list first and then evicting cached-free blocks oldest
        first (``on_evict`` notifies the hash-index owner per block)."""
        if num_blocks > self.free_blocks:
            raise ValueError(
                f"Cannot allocate {num_blocks} blocks: "
                f"{self.free_blocks} free")
        out = self._free[:num_blocks]
        del self._free[:num_blocks]
        self._free_set.difference_update(out)
        while len(out) < num_blocks:
            b, _ = self._cached_free.popitem(last=False)   # LRU: oldest
            self._hashed.discard(b)
            if self.on_evict is not None:
                self.on_evict(b)
            out.append(b)
        for b in out:
            self._refs[b] = 1
        if len(self._refs) > self._peak_referenced:
            self._peak_referenced = len(self._refs)
        return out

    def ref(self, block: int) -> None:
        """Add a reference: alias a live shared block (refcount += 1) or
        revive a cached-free block into the referenced pool."""
        if block in self._refs:
            self._refs[block] += 1
        elif block in self._cached_free:
            del self._cached_free[block]
            self._refs[block] = 1
            if len(self._refs) > self._peak_referenced:
                self._peak_referenced = len(self._refs)
        else:
            raise ValueError(
                f"Cannot ref block {block}: not referenced or cached-free")

    def mark_cached(self, block: int) -> None:
        """Declare the (referenced) block's content index-registered: when
        its last reference drops it retires to the cached-free pool."""
        if block not in self._refs:
            raise ValueError(f"Cannot cache block {block}: not referenced")
        self._hashed.add(block)

    def unmark_cached(self, block: int) -> None:
        """Withdraw index registration.  A block already resting in the
        cached-free pool moves to the plain free list (nothing can match
        it any more)."""
        self._hashed.discard(block)
        if block in self._cached_free:
            del self._cached_free[block]
            self._free.append(block)
            self._free_set.add(block)

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per listed block.  A block whose refcount
        hits zero retires to the cached-free pool when its content is
        index-registered, else to the plain free list.  Validation is
        atomic: a rejected call (unknown block, or more frees than
        references — including duplicates WITHIN this call) mutates
        nothing."""
        counts: Dict[int, int] = {}
        for b in blocks:
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"Invalid block id {b}")
            counts[b] = counts.get(b, 0) + 1
        for b, c in counts.items():
            if self._refs.get(b, 0) < c:
                raise ValueError(f"Double free of block {b}")
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b]:
                continue
            del self._refs[b]
            if b in self._hashed:
                self._cached_free[b] = None    # newest at the LRU tail
            else:
                self._free.append(b)
                self._free_set.add(b)
