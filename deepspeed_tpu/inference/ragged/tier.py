"""Tiered KV block cache: bounded host-RAM ring with NVMe overflow.

The ZeRO-Offload playbook (arxiv 2101.06840; reference
``runtime/swap_tensor/`` + ``csrc/aio``) applied to inference KV: when
:class:`~.allocator.BlockedAllocator` evicts a cached-free block, the
engine demotes its content here instead of discarding it, keyed by the
block's prefix-chain digest (:func:`~.state.chain_hash` — the digest
binds the parent chain, so ``(parent_digest, block_digest)`` is one
bytes key).  A later ``match_prefix`` that misses HBM but hits this tier
revives the block asynchronously: NVMe reads are queued through
``ops/aio.py`` at *probe* time and resolved at the engine's pre-dispatch
drain, overlapping the restage with the depth-2 dispatch-ahead window
(the same pattern COW drains use) so a spilled-chain hit pays block
uploads, not a re-prefill.

Verification contract (docs/KV_TIERING.md): every payload carries a
blake2b-16 checksum over its leaf bytes, computed at demotion and
re-checked at every boundary crossing — NVMe read-back, cross-replica
export, remote import (which additionally recomputes the chain digest
from ``(parent, tokens)``).  A failed check silently *drops the entry*
(the caller falls back to re-prefill); corrupted spill bytes can never
reach the device cache.

Pure host-side numpy + file I/O — no jax imports; the engine owns all
device transfers.  RAM-only when no spill dir is configured or the aio
toolchain is unavailable (overflow is then discarded, exactly the old
behavior one level down the hierarchy).
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...utils.logging import logger
from .state import chain_hash


def payload_checksum(leaves: Sequence[np.ndarray]) -> bytes:
    """blake2b-16 over leaf dtypes/shapes/bytes — the integrity stamp a
    demoted block carries across every tier boundary."""
    h = hashlib.blake2b(digest_size=16)
    for a in leaves:
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.digest()


class _Entry:
    """One demoted block.  ``leaves`` holds the payload while RAM-
    resident; after spilling, ``path`` names the file and ``meta`` the
    per-leaf (dtype, shape) needed to deserialize it."""

    __slots__ = ("parent", "tokens", "checksum", "nbytes", "origin",
                 "leaves", "path", "meta", "iobuf")

    def __init__(self, parent: bytes, tokens: Tuple[int, ...],
                 checksum: bytes, nbytes: int, origin: str,
                 leaves: Optional[List[np.ndarray]]):
        self.parent = parent
        self.tokens = tokens
        self.checksum = checksum
        self.nbytes = nbytes
        self.origin = origin              # "local" | "remote"
        self.leaves = leaves              # RAM tier only
        self.path: Optional[str] = None   # NVMe tier only
        self.meta: Optional[List[Tuple[np.dtype, tuple]]] = None
        self.iobuf: Optional[np.ndarray] = None  # in-flight write buffer


class ReviveOp:
    """A revive in flight: carries the payload (RAM hit) or the read
    buffer an ``async_pread`` was queued into (NVMe hit, issued at probe
    time so the read overlaps scheduling).  ``resolve()`` on the owning
    tier hands back verified leaves or ``None``."""

    __slots__ = ("digest", "parent", "tokens", "checksum", "source",
                 "leaves", "buf", "meta", "path", "failed")

    def __init__(self, digest: bytes, ent: _Entry, source: str):
        self.digest = digest
        self.parent = ent.parent
        self.tokens = ent.tokens
        self.checksum = ent.checksum
        self.source = source              # "ram" | "nvme" | "remote"
        self.leaves = ent.leaves
        self.buf: Optional[np.ndarray] = None
        self.meta = ent.meta
        self.path = ent.path
        self.failed = False


def _deserialize(buf: np.ndarray,
                 meta: List[Tuple[np.dtype, tuple]]) -> List[np.ndarray]:
    out, off = [], 0
    for dtype, shape in meta:
        n = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        out.append(buf[off:off + n].view(dtype).reshape(shape))
        off += n
    return out


class KVBlockTier:
    """Host-RAM ring + NVMe spill directory, both byte-bounded, LRU
    within each tier.  Demotion flows HBM -> RAM -> NVMe -> dropped;
    revival consumes the entry (the block re-registers in the HBM index
    on restage, which supersedes the tier copy)."""

    def __init__(self, ram_bytes: int, nvme_dir: Optional[str] = None,
                 nvme_bytes: int = 0, aio_factory=None):
        self.ram_bytes = int(ram_bytes)
        self.nvme_dir = nvme_dir
        self.nvme_bytes = int(nvme_bytes) if nvme_dir else 0
        if self.nvme_bytes:
            try:
                os.makedirs(nvme_dir, exist_ok=True)
            except OSError as e:
                logger.warning("kv tier: spill dir %r unusable (%s); "
                               "running RAM-only", nvme_dir, e)
                self.nvme_bytes = 0
        self._ram: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._nvme: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._ram_used = 0
        self._nvme_used = 0
        self._aio = None
        self._aio_failed = False
        self._aio_factory = aio_factory
        self._io_pending = False
        # strong refs to every buffer a queued aio op targets — a numpy
        # buffer freed under an in-flight native read/write is heap
        # corruption, so nothing here is released before a wait()
        self._inflight: List[np.ndarray] = []
        self.spill_failures = 0        # writes/reads the backend failed

    # ---- aio plumbing ----------------------------------------------------
    def _handle(self):
        """Lazy aio handle (first spill pays the native-lib load); None
        when the toolchain is unavailable — the tier degrades to
        RAM-only and overflow is dropped."""
        if self._aio is not None or self._aio_failed:
            return self._aio
        try:
            if self._aio_factory is not None:
                self._aio = self._aio_factory()
            else:
                from ...ops.aio import AsyncIOHandle
                from ...ops.builder import AsyncIOBuilder
                if not AsyncIOBuilder().is_compatible():
                    raise RuntimeError("aio toolchain unavailable")
                self._aio = AsyncIOHandle(thread_count=2)
        except Exception as e:
            logger.warning("kv tier: NVMe spill disabled (%s); "
                           "running RAM-only", e)
            self._aio_failed = True
            self.nvme_bytes = 0
        return self._aio

    def _drain_io(self) -> None:
        """Complete every queued aio op and release the buffer holds."""
        if not self._io_pending:
            return
        failed = self._aio.wait()
        if failed:
            self.spill_failures += failed
        self._inflight.clear()
        for ent in self._nvme.values():
            ent.iobuf = None
        self._io_pending = False

    def __del__(self):
        # runs before attribute teardown: drain while the in-flight
        # buffers are still strongly referenced
        h = self.__dict__.get("_aio")
        if h is not None and self.__dict__.get("_io_pending"):
            h.wait()

    # ---- write side ------------------------------------------------------
    def put(self, parent: bytes, digest: bytes, tokens: Sequence[int],
            leaves: Sequence[np.ndarray],
            origin: str = "local") -> Dict[str, int]:
        """Demote one block's payload into the RAM ring (spilling the
        ring's overflow down to NVMe).  Returns an event dict the engine
        turns into counters: ``stored`` (0/1 — dup keys are no-ops),
        ``nbytes``, ``spilled`` blocks and ``spilled_bytes`` pushed to
        NVMe by the ring overflow, ``dropped`` blocks discarded off the
        bottom."""
        ev = {"stored": 0, "nbytes": 0, "spilled": 0, "spilled_bytes": 0,
              "dropped": 0}
        if digest in self._ram or digest in self._nvme:
            return ev
        arrs = [np.ascontiguousarray(np.asarray(a)) for a in leaves]
        nbytes = sum(a.nbytes for a in arrs)
        if nbytes > max(self.ram_bytes, self.nvme_bytes):
            ev["dropped"] = 1
            return ev
        ent = _Entry(parent, tuple(int(t) for t in tokens),
                     payload_checksum(arrs), nbytes, origin, arrs)
        self._ram[digest] = ent
        self._ram_used += nbytes
        ev["stored"], ev["nbytes"] = 1, nbytes
        while self._ram_used > self.ram_bytes and self._ram:
            old_digest, old = self._ram.popitem(last=False)
            self._ram_used -= old.nbytes
            if self._spill(old_digest, old):
                ev["spilled"] += 1
                ev["spilled_bytes"] += old.nbytes
            else:
                ev["dropped"] += 1
        return ev

    def _spill(self, digest: bytes, ent: _Entry) -> bool:
        """Push a RAM-evicted entry to its NVMe file (async write; the
        serialized buffer stays referenced until the next drain)."""
        if ent.nbytes > self.nvme_bytes or self._handle() is None:
            return False
        buf = np.empty(ent.nbytes, np.uint8)
        off = 0
        meta = []
        for a in ent.leaves:
            n = a.nbytes
            buf[off:off + n] = a.reshape(-1).view(np.uint8)
            off += n
            meta.append((a.dtype, a.shape))
        path = os.path.join(self.nvme_dir, digest.hex() + ".kv")
        self._aio.async_pwrite(buf, path, truncate=True)
        self._io_pending = True
        self._inflight.append(buf)
        ent.leaves = None
        ent.path = path
        ent.meta = meta
        ent.iobuf = buf
        self._nvme[digest] = ent
        self._nvme_used += ent.nbytes
        while self._nvme_used > self.nvme_bytes and self._nvme:
            dead_digest, dead = self._nvme.popitem(last=False)
            if dead_digest == digest:     # the entry we just spilled
                self._nvme[dead_digest] = dead
                break
            self._evict_nvme(dead)
        return True

    def _evict_nvme(self, ent: _Entry) -> None:
        self._nvme_used -= ent.nbytes
        if ent.iobuf is not None:
            self._drain_io()              # never unlink under a write
        try:
            os.remove(ent.path)
        except OSError:
            pass  # already gone — the index entry is what matters

    # ---- read side -------------------------------------------------------
    def contains(self, digest: bytes) -> bool:
        return digest in self._ram or digest in self._nvme

    def __contains__(self, digest: bytes) -> bool:
        return self.contains(digest)

    def __len__(self) -> int:
        return len(self._ram) + len(self._nvme)

    def digests(self) -> frozenset:
        """Every chain digest currently revivable from this tier."""
        return frozenset(self._ram) | frozenset(self._nvme)

    def begin_revive(self, digest: bytes) -> Optional[ReviveOp]:
        """Start restaging ``digest``, CONSUMING the tier entry (on
        success the block re-registers in the HBM index; on failure the
        content was bad anyway).  NVMe hits queue their ``async_pread``
        right here — probe time — so the disk read overlaps the
        scheduler round and the dispatch-ahead window before
        ``resolve()`` needs the bytes."""
        ent = self._ram.pop(digest, None)
        if ent is not None:
            self._ram_used -= ent.nbytes
            src = "remote" if ent.origin == "remote" else "ram"
            return ReviveOp(digest, ent, src)
        ent = self._nvme.pop(digest, None)
        if ent is None:
            return None
        self._nvme_used -= ent.nbytes
        src = "remote" if ent.origin == "remote" else "nvme"
        op = ReviveOp(digest, ent, src)
        if ent.iobuf is not None:
            self._drain_io()              # write must land before read
        op.buf = np.empty(ent.nbytes, np.uint8)
        from ...ops.aio import AioError
        try:
            self._aio.async_pread(op.buf, ent.path)
            self._io_pending = True
            self._inflight.append(op.buf)
        except AioError as e:
            logger.warning("kv tier: spill file lost from under us "
                           "(%s); reviving as a miss", e)
            self.spill_failures += 1
            op.failed = True
            self._remove_file(op.path)
        return op

    def _remove_file(self, path: Optional[str]) -> None:
        if path is None:
            return
        try:
            os.remove(path)
        except OSError:
            pass  # already gone — the index entry is what matters

    def resolve(self, op: ReviveOp) -> Optional[List[np.ndarray]]:
        """Finish a revive: drain outstanding I/O, deserialize, verify
        the checksum.  ``None`` means the payload failed verification
        (or the file died) — the caller re-prefills."""
        if op.failed:
            return None
        if op.buf is not None:
            self._drain_io()              # the queued pread lands here
            leaves = _deserialize(op.buf, op.meta)
            self._remove_file(op.path)    # consumed — file can go now
        else:
            leaves = op.leaves
        if leaves is None or payload_checksum(leaves) != op.checksum:
            self.spill_failures += 1
            logger.warning("kv tier: checksum mismatch on revive of "
                           "%s from %s — dropping, caller re-prefills",
                           op.digest.hex()[:12], op.source)
            return None
        return leaves

    # ---- cross-replica export/import ------------------------------------
    def export(self, digest: bytes) -> Optional[dict]:
        """Non-destructively copy one entry out for a peer replica
        (fleet chain fetch).  NVMe entries are read back synchronously
        and verified first — a corrupted spill file exports as a miss,
        never as bytes."""
        ent = self._ram.get(digest)
        if ent is not None:
            self._ram.move_to_end(digest)
            leaves = ent.leaves
        else:
            ent = self._nvme.get(digest)
            if ent is None:
                return None
            if ent.iobuf is not None:
                self._drain_io()
            buf = np.empty(ent.nbytes, np.uint8)
            from ...ops.aio import AioError
            try:
                failed = self._aio.sync_pread(buf, ent.path)
            except AioError:
                failed = 1
            if failed:
                self.spill_failures += 1
                self._drop_nvme(digest, ent)
                return None
            leaves = _deserialize(buf, ent.meta)
        if payload_checksum(leaves) != ent.checksum:
            self.spill_failures += 1
            logger.warning("kv tier: checksum mismatch exporting %s — "
                           "dropping the entry", digest.hex()[:12])
            self._drop(digest)
            return None
        return {"digest": digest, "parent": ent.parent,
                "tokens": list(ent.tokens),
                "leaves": [np.array(a) for a in leaves],
                "checksum": ent.checksum}

    def _drop(self, digest: bytes) -> None:
        ent = self._ram.pop(digest, None)
        if ent is not None:
            self._ram_used -= ent.nbytes
            return
        ent = self._nvme.pop(digest, None)
        if ent is not None:
            self._drop_nvme_entry(ent)

    def _drop_nvme(self, digest: bytes, ent: _Entry) -> None:
        self._nvme.pop(digest, None)
        self._drop_nvme_entry(ent)

    def _drop_nvme_entry(self, ent: _Entry) -> None:
        self._nvme_used -= ent.nbytes
        if ent.iobuf is not None:
            # the spill write is still in flight: land it first, then
            # unlink — dropping the index entry alone would leak the
            # file on disk forever (the entry left self._nvme, so no
            # later evict/drop pass can ever see it again)
            self._drain_io()
        try:
            os.remove(ent.path)
        except OSError:
            pass  # already gone — the index entry is what matters

    @staticmethod
    def verify_record(rec: dict) -> bool:
        """The arrival-side contract for a fetched block record: the
        chain digest must recompute from ``(parent, tokens)`` and the
        payload checksum must match the leaves.  Pure — callable before
        any state is touched."""
        try:
            if chain_hash(rec["parent"], rec["tokens"]) != rec["digest"]:
                return False
            return payload_checksum(rec["leaves"]) == rec["checksum"]
        except (KeyError, TypeError, ValueError):
            return False

    def insert_record(self, rec: dict) -> Dict[str, int]:
        """Import a verified peer record (``verify_record`` first —
        this trusts its caller)."""
        return self.put(rec["parent"], rec["digest"], rec["tokens"],
                        rec["leaves"], origin="remote")

    # ---- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, int]:
        return {"ram_entries": len(self._ram),
                "ram_bytes": self._ram_used,
                "nvme_entries": len(self._nvme),
                "nvme_bytes": self._nvme_used,
                "spill_failures": self.spill_failures}
