from .allocator import BlockedAllocator
from .state import KVCacheConfig, RaggedBatch, SequenceDescriptor, StateManager

__all__ = ["BlockedAllocator", "KVCacheConfig", "RaggedBatch",
           "SequenceDescriptor", "StateManager"]
