"""Ragged-batch model forward with paged KV cache.

TPU-native analog of the reference's FastGen model layer
(``inference/v2/model_implementations/inference_transformer_base.py:48``
building per-layer DSModules, and the ragged kernel suite
``linear_blocked_kv_rotary`` (QKV+rotary written straight into paged KV),
``blocked_flash`` (paged attention over block tables), ``ragged_embed``,
``logits_gather`` (last-token-only unembed) — SURVEY §2.2/§3.4).

One jit-compiled function processes a fixed token budget T of mixed
prefill/decode tokens (Dynamic SplitFuse's fixed-shape forward is exactly
XLA-friendly):
  embed [T] → per layer: qkv + rope(positions) → scatter K/V into the
  paged cache → per-token attention over the owning sequence's block
  table → mlp/moe → final norm → unembed only at each sequence's last
  scheduled token.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..models import layers as L
from ..models.transformer import TransformerConfig, _norm
from .ragged.state import RaggedBatch


def _write_kv(kv_layer, k, v, batch: RaggedBatch, block_size: int):
    """Scatter per-token K/V into the paged cache.

    kv_layer: [blocks, bs, 2, Hkv, D]; k/v: [T, Hkv, D]
    (reference kernel: linear_blocked_kv_rotary / linear_kv_copy).
    """
    blk = batch.block_tables[batch.seq_slot,
                             batch.positions // block_size]      # [T]
    # budget-padding tokens write to the trash block (last row) so they
    # can never clobber a live sequence's KV
    trash = kv_layer.shape[0] - 1
    blk = jnp.where(batch.token_valid, blk, trash)
    off = batch.positions % block_size                           # [T]
    kv_layer = kv_layer.at[blk, off, 0].set(k)
    kv_layer = kv_layer.at[blk, off, 1].set(v)
    return kv_layer


def _paged_attention_pallas(kv_layer, q, batch: RaggedBatch,
                            block_size: int, max_blocks_per_seq: int,
                            scale: float):
    """Pallas streaming kernel behind the same signature
    (ops/paged_attention.py — reference: blocked_flash)."""
    from ..ops.paged_attention import paged_attention
    return paged_attention(kv_layer, q, batch.seq_slot, batch.positions,
                           batch.block_tables, block_size,
                           max_blocks_per_seq, scale)


def _paged_attention(kv_layer, q, batch: RaggedBatch, block_size: int,
                     max_blocks_per_seq: int, scale: float):
    """Per-token attention over the owning sequence's context
    (reference kernel: blocked_flash / flash_attn_by_atoms).

    q: [T, H, D] → out [T, H, D].  XLA formulation: gather each token's
    block table (bounded by max_blocks_per_seq), mask by position.  The
    Pallas streaming variant (``_paged_attention_pallas``) drops in
    behind the same signature; ``InferenceEngine`` probes both.
    """
    T, H, D = q.shape
    Hkv = kv_layer.shape[3]
    rep = H // Hkv
    C = max_blocks_per_seq * block_size

    tables = batch.block_tables[batch.seq_slot, :max_blocks_per_seq]  # [T, nb]
    ctx = kv_layer[tables]            # [T, nb, bs, 2, Hkv, D]
    ctx = ctx.reshape(T, C, 2, Hkv, D)
    k_ctx, v_ctx = ctx[:, :, 0], ctx[:, :, 1]                     # [T, C, Hkv, D]

    qg = q.reshape(T, Hkv, rep, D)
    s = jnp.einsum("thrd,tchd->thrc", qg, k_ctx).astype(jnp.float32) * scale
    cols = jnp.arange(C)[None, :]                                  # [1, C]
    valid = cols <= batch.positions[:, None]                       # [T, C]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    o = jnp.einsum("thrc,tchd->thrd", p, v_ctx)
    return o.reshape(T, H, D)


def ragged_forward(cfg: TransformerConfig, params, kv, batch: RaggedBatch,
                   block_size: int, max_blocks_per_seq: int,
                   rng: Optional[jax.Array] = None,
                   attn_impl: str = "xla",
                   quant=None,
                   kv_host: bool = False
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (last_token_logits [max_seqs, vocab], new_kv).

    ``kv``: [L, blocks, bs, 2, Hkv, D].  Rows of the logits output whose
    ``batch.logits_idx`` is -1 are garbage (callers mask by it).
    ``attn_impl``: "xla" (gather) | "pallas" (streaming kernel).
    ``quant``: ZeRO-Inference weight-quant tree (inference/quantization
    ``quantize_model_params``) — one layer is dequantized at a time
    inside the scan body, so dense weights never all coexist in HBM.
    ``kv_host``: the cache lives in host memory; each scan step streams
    one layer through HBM and writes it back (ZeRO-Inference KV offload)
    so device memory holds a single layer's KV at a time.
    """
    if quant is not None:
        from .quantization import merge_layer
        from ..ops.quant import dequantize_any
    if quant is not None and "embed" in quant:
        embed_tab = {"table": dequantize_any(quant["embed"]["table"])}
        dt = embed_tab["table"].dtype
    else:
        embed_tab = params["embed"]
        dt = embed_tab["table"].dtype
    norm = _norm(cfg)
    act = L.ACTIVATIONS[cfg.activation]
    scale = 1.0 / (cfg.head_dim ** 0.5)

    x = L.embed(embed_tab, batch.token_ids).astype(dt)             # [T, dm]
    if cfg.position == "learned":
        x = x + params["pos_embed"]["table"][batch.positions].astype(dt)
        cos = sin = None
    else:
        cos, sin = L.rope_freqs(cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta)

    def block(x, xs):
        lp, kv_layer, li = xs
        if kv_host:
            kv_layer = jax.device_put(kv_layer, jax.memory.Space.Device)
        if quant is not None:
            lp = merge_layer(lp, quant["blocks"], li, dt)
        ap = lp["attn"]
        h = norm(lp["ln1"], x)
        q = jnp.einsum("td,dhk->thk", h, ap["wq"].astype(dt))
        k = jnp.einsum("td,dhk->thk", h, ap["wk"].astype(dt))
        v = jnp.einsum("td,dhk->thk", h, ap["wv"].astype(dt))
        if cfg.attn_bias:
            q = q + ap["bq"].astype(dt)
            k = k + ap["bk"].astype(dt)
            v = v + ap["bv"].astype(dt)
        if cfg.position == "rope":
            # apply_rope expects [B, S, H, D]; use B=1 with per-token pos
            pos = batch.positions[None]
            q = L.apply_rope(q[None], cos, sin, positions=pos)[0]
            k = L.apply_rope(k[None], cos, sin, positions=pos)[0]
        kv_layer = _write_kv(kv_layer, k, v, batch, block_size)
        attn = (_paged_attention_pallas if attn_impl == "pallas"
                else _paged_attention)
        o = attn(kv_layer, q, batch, block_size, max_blocks_per_seq, scale)
        o = jnp.einsum("thk,hkd->td", o, ap["wo"].astype(dt))
        if cfg.attn_bias:
            o = o + ap["bo"].astype(dt)
        if not cfg.parallel_block:
            x = x + o
            h = norm(lp["ln2"], x)
        # parallel residual (falcon/phi): MLP reads the same ln1 output
        if cfg.num_experts > 1:
            from ..parallel import moe as M

            d, _ = M.moe_ffn(lp["gate"], lp["experts"], h[None],
                             top_k=cfg.moe_top_k,
                             capacity_factor=cfg.eval_capacity_factor,
                             min_capacity=cfg.min_capacity,
                             activation=act, gated=cfg.gated_mlp)
            d = d[0]
        else:
            mp = lp["mlp"]
            u = h @ mp["wi"].astype(dt)
            if cfg.mlp_bias:
                u = u + mp["bi"].astype(dt)
            if cfg.gated_mlp:
                u = act(h @ mp["wg"].astype(dt)) * u
            else:
                u = act(u)
            d = u @ mp["wo"].astype(dt)
            if cfg.mlp_bias:
                d = d + mp["bo"].astype(dt)
        if kv_host:
            kv_layer = jax.device_put(kv_layer, jax.memory.Space.Host)
        if cfg.parallel_block:
            return x + o + d, kv_layer
        return x + d, kv_layer

    x, new_kv = jax.lax.scan(
        block, x, (params["blocks"], kv,
                   jnp.arange(cfg.num_layers, dtype=jnp.int32)))

    # logits only at each sequence's last scheduled token
    # (reference kernel: gather_for_logits / logits_gather)
    idx = jnp.maximum(batch.logits_idx, 0)
    last = x[idx]                                                  # [S, dm]
    last = norm(params["ln_f"], last)
    if cfg.tie_embeddings:
        logits = last @ embed_tab["table"].astype(dt).T
    else:
        logits = last @ params["lm_head"]["kernel"].astype(dt)
        if cfg.head_bias:
            logits = logits + params["lm_head"]["bias"].astype(dt)
    return logits.astype(jnp.float32), new_kv
