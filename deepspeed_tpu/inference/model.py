"""Ragged-batch model forward with paged KV cache.

TPU-native analog of the reference's FastGen model layer
(``inference/v2/model_implementations/inference_transformer_base.py:48``
building per-layer DSModules, and the ragged kernel suite
``linear_blocked_kv_rotary`` (QKV+rotary written straight into paged KV),
``blocked_flash`` (paged attention over block tables), ``ragged_embed``,
``logits_gather`` (last-token-only unembed) — SURVEY §2.2/§3.4).

One jit-compiled function processes a fixed token budget T of mixed
prefill/decode tokens (Dynamic SplitFuse's fixed-shape forward is exactly
XLA-friendly):
  embed [T] → per layer: qkv + rope(positions) → scatter K/V into the
  paged cache → per-token attention over the owning sequence's block
  table → mlp/moe → final norm → unembed only at each sequence's last
  scheduled token.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compat import shard_map
from ..comm.overlap import (ServingComm, shard_matmul_allgather,
                            shard_matmul_allreduce)
from ..models import layers as L
from ..models.transformer import TransformerConfig, _norm
from .ragged.state import RaggedBatch
from .sampler import row_keys, window_keys


_KV_QMAX = {jnp.dtype(jnp.int8): 127.0,
            jnp.dtype(jnp.float8_e4m3fn): 448.0}


def _kv_parts(kv_layer):
    """(data, scales-or-None) view of a paged cache operand — quantized
    caches travel as a (data, scales) tuple pytree."""
    if isinstance(kv_layer, tuple):
        return kv_layer[0], kv_layer[1]
    return kv_layer, None


def _quantize_kv(x, qdt):
    """x: [..., D] → (codes [..., D] in ``qdt``, scales [...] f32) with
    one symmetric scale per trailing vector."""
    xf = x.astype(jnp.float32)
    qmax = _KV_QMAX[jnp.dtype(qdt)]
    scale = jnp.max(jnp.abs(xf), axis=-1) / qmax
    scale = jnp.maximum(scale, 1e-8)
    q = xf / scale[..., None]
    if jnp.dtype(qdt) == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(q), -127, 127)
    return q.astype(qdt), scale


def _dequant_ctx(data, scales, dt):
    """data: [..., D] codes, scales: [...] → [..., D] in ``dt``."""
    return (data.astype(jnp.float32)
            * scales[..., None]).astype(dt)


def _write_kv(kv_layer, k, v, batch: RaggedBatch, block_size: int):
    """Scatter per-token K/V into the paged cache (quantizing on write
    when the cache is a (data, scales) pair).

    kv_layer: [blocks, bs, 2, Hkv, D]; k/v: [T, Hkv, D]
    (reference kernel: linear_blocked_kv_rotary / linear_kv_copy).
    """
    data, scales = _kv_parts(kv_layer)
    blk = batch.block_tables[batch.seq_slot,
                             batch.positions // block_size]      # [T]
    # budget-padding tokens write to the trash block (last row) so they
    # can never clobber a live sequence's KV
    trash = data.shape[0] - 1
    blk = jnp.where(batch.token_valid, blk, trash)
    off = batch.positions % block_size                           # [T]
    if scales is None:
        data = data.at[blk, off, 0].set(k)
        data = data.at[blk, off, 1].set(v)
        return data
    kq, ks = _quantize_kv(k, data.dtype)
    vq, vs = _quantize_kv(v, data.dtype)
    data = data.at[blk, off, 0].set(kq)
    data = data.at[blk, off, 1].set(vq)
    scales = scales.at[blk, off, 0].set(ks)
    scales = scales.at[blk, off, 1].set(vs)
    return (data, scales)


def _paged_attention_pallas(kv_layer, q, batch: RaggedBatch,
                            block_size: int, max_blocks_per_seq: int,
                            scale: float, shard_mesh=None, slopes=None):
    """Pallas streaming kernel behind the same signature
    (ops/paged_attention.py — reference: blocked_flash).

    With ``shard_mesh`` (TP serving), the kernel runs under ``shard_map``:
    attention is embarrassingly parallel over heads, so each chip streams
    only its own head group's KV blocks (kv head-split on the ``tensor``
    mesh axis) — the TPU analog of the reference's TP-aware blocked_flash
    dispatch (inference/v2/model_implementations/sharding/attn.py)."""
    from ..ops.paged_attention import paged_attention

    if shard_mesh is None:
        return paged_attention(kv_layer, q, batch.seq_slot, batch.positions,
                               batch.block_tables, block_size,
                               max_blocks_per_seq, scale, slopes=slopes)
    from jax.sharding import PartitionSpec as P

    from ..comm.mesh import TENSOR_AXIS

    data_spec = P(None, None, None, TENSOR_AXIS, None)  # [blocks,bs,2,Hkv,D]
    kv_spec = (data_spec if not isinstance(kv_layer, tuple)
               else (data_spec, P(None, None, None, TENSOR_AXIS)))
    q_spec = P(None, TENSOR_AXIS, None)               # [T, H, D]
    in_specs = [kv_spec, q_spec, P(), P(), P()]
    operands = [kv_layer, q, batch.seq_slot, batch.positions,
                batch.block_tables]
    if slopes is not None:
        in_specs.append(P(TENSOR_AXIS, None))   # slopes [Hkv, rep] split
        operands.append(jnp.asarray(slopes, jnp.float32).reshape(
            _kv_parts(kv_layer)[0].shape[3], -1))   # with the kv heads
    f = shard_map(
        lambda kvl, qq, ss, pos, bt, *sl: paged_attention(
            kvl, qq, ss, pos, bt, block_size, max_blocks_per_seq, scale,
            slopes=sl[0] if sl else None),
        mesh=shard_mesh,
        in_specs=tuple(in_specs),
        out_specs=q_spec, check_vma=False)
    return f(*operands)


# one-shot gather cap: [T, C, 2, Hkv, D] materializes T*C*2*Hkv*D
# elements; past this many BYTES the chunked online-softmax path runs
# instead (bench shapes at GPT-2s blew HBM: 3.2 GB gather -> 18.5 G
# peak on a 15.75 G v5e — BENCH_r02's probe JaxRuntimeError)
_ONE_SHOT_GATHER_BYTES = 512 * 1024 * 1024


def _paged_attention(kv_layer, q, batch: RaggedBatch, block_size: int,
                     max_blocks_per_seq: int, scale: float, slopes=None):
    """Per-token attention over the owning sequence's context
    (reference kernel: blocked_flash / flash_attn_by_atoms).

    q: [T, H, D] → out [T, H, D].  XLA formulation: gather each token's
    block table (bounded by max_blocks_per_seq), mask by position.  When
    the full-context gather would exceed ``_ONE_SHOT_GATHER_BYTES`` the
    computation streams one KV block at a time with an online-softmax
    accumulator instead (memory ∝ T·block_size, not T·context).  The
    Pallas streaming variant (``_paged_attention_pallas``) drops in
    behind the same signature; ``InferenceEngine`` probes both.
    """
    T, H, D = q.shape
    data, scales = _kv_parts(kv_layer)
    Hkv = data.shape[3]
    C = max_blocks_per_seq * block_size
    gather_bytes = T * C * 2 * Hkv * D * data.dtype.itemsize
    if gather_bytes > _ONE_SHOT_GATHER_BYTES:
        return _paged_attention_chunked(kv_layer, q, batch, block_size,
                                        max_blocks_per_seq, scale,
                                        slopes=slopes)
    rep = H // Hkv

    tables = batch.block_tables[batch.seq_slot, :max_blocks_per_seq]  # [T, nb]
    ctx = data[tables]                # [T, nb, bs, 2, Hkv, D]
    ctx = ctx.reshape(T, C, 2, Hkv, D)
    k_ctx, v_ctx = ctx[:, :, 0], ctx[:, :, 1]                     # [T, C, Hkv, D]
    if scales is not None:
        sctx = scales[tables].reshape(T, C, 2, Hkv)
        k_ctx = _dequant_ctx(k_ctx, sctx[:, :, 0], q.dtype)
        v_ctx = _dequant_ctx(v_ctx, sctx[:, :, 1], q.dtype)

    qg = q.reshape(T, Hkv, rep, D)
    s = jnp.einsum("thrd,tchd->thrc", qg, k_ctx).astype(jnp.float32) * scale
    cols = jnp.arange(C)[None, :]                                  # [1, C]
    if slopes is not None:      # ALiBi: slope_h * absolute key position
        s = s + (slopes.reshape(Hkv, rep)[None, :, :, None]
                 * cols[:, None, None, :].astype(jnp.float32))
    valid = cols <= batch.positions[:, None]                       # [T, C]
    s = jnp.where(valid[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    o = jnp.einsum("thrc,tchd->thrd", p, v_ctx)
    return o.reshape(T, H, D)


def _paged_attention_chunked(kv_layer, q, batch: RaggedBatch,
                             block_size: int, max_blocks_per_seq: int,
                             scale: float, slopes=None):
    """Streaming XLA paged attention: scan over the block-table columns,
    gathering ONE context block per step ([T, bs, 2, Hkv, D]) and folding
    it into an online-softmax accumulator — same numerics as the
    one-shot softmax, peak memory ∝ T·block_size."""
    T, H, D = q.shape
    data, scales = _kv_parts(kv_layer)
    Hkv = data.shape[3]
    rep = H // Hkv
    bs = block_size

    tables = batch.block_tables[batch.seq_slot, :max_blocks_per_seq]  # [T, nb]
    qg = q.reshape(T, Hkv, rep, D)
    offs = jnp.arange(bs)

    def fold(carry, j):
        m, l, acc = carry
        blk = tables[:, j]                          # [T] (-1 pad -> trash)
        ctx = data[blk]                             # [T, bs, 2, Hkv, D]
        k, v = ctx[:, :, 0], ctx[:, :, 1]           # [T, bs, Hkv, D]
        if scales is not None:
            sc = scales[blk]                        # [T, bs, 2, Hkv]
            k = _dequant_ctx(k, sc[:, :, 0], q.dtype)
            v = _dequant_ctx(v, sc[:, :, 1], q.dtype)
        s = jnp.einsum("thrd,tbhd->thrb", qg, k).astype(jnp.float32) * scale
        cols = j * bs + offs[None, :]               # [1, bs]
        if slopes is not None:
            s = s + (slopes.reshape(Hkv, rep)[None, :, :, None]
                     * cols[:, None, None, :].astype(jnp.float32))
        valid = cols <= batch.positions[:, None]    # [T, bs]
        s = jnp.where(valid[:, None, None, :], s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        w = jnp.exp(m - m_new)
        l = l * w + p.sum(axis=-1)
        pv = jnp.einsum("thrb,tbhd->thrd", p.astype(q.dtype), v)
        acc = acc * w[..., None] + pv.astype(jnp.float32)
        return (m_new, l, acc), None

    init = (jnp.full((T, Hkv, rep), -jnp.inf, jnp.float32),
            jnp.zeros((T, Hkv, rep), jnp.float32),
            jnp.zeros((T, Hkv, rep, D), jnp.float32))
    (m, l, acc), _ = jax.lax.scan(
        fold, init, jnp.arange(max_blocks_per_seq, dtype=jnp.int32))
    o = acc / jnp.maximum(l, 1e-30)[..., None]
    return o.reshape(T, H, D).astype(q.dtype)




def _stream_layer(stream, li, dt, mixed_gemm: bool = False):
    """Fetch layer ``li``'s weights from the NVMe store (host callback)
    and dequantize any streamed quantized payloads on device — or, with
    ``mixed_gemm``, keep row-wise int8 payloads quantized for the
    VMEM-dequant kernel (the weight stays int8-sized from NVMe through
    HBM into the MXU feed)."""
    rec = stream.fetch_layer(li)
    lp = {k: (dict(v) if isinstance(v, dict) else v)
          for k, v in rec["dense"].items()}
    if "quant" in rec:
        from ..ops.quant import (QuantizedTensor, dequantize_any,
                                 is_mixed_gemm_layout)
        from .quantization import DENSE_ONLY_GROUPS
        for gname, grp in rec["quant"].items():
            g = dict(lp.get(gname, {}))
            for name, arrs in grp.items():
                bits, shp, odt, layout = stream.qmeta[gname][name]
                qt = QuantizedTensor(arrs["data"], arrs["scale"],
                                     arrs.get("zero"), bits, shp, odt,
                                     layout=layout)
                if mixed_gemm and gname not in DENSE_ONLY_GROUPS \
                        and is_mixed_gemm_layout(qt):
                    g[name] = qt
                else:
                    g[name] = dequantize_any(qt, dt)
            lp[gname] = g
    return lp


def _mm(x, w, dt, contract_dims: int = 1):
    """``x @ w`` where ``w`` is dense — or a row-wise QuantizedTensor,
    routed through the mixed-input VMEM-dequant kernel
    (ops/mixed_gemm.py; reference: cuda_linear fp6_linear.cu).

    Always returns ``dt``: a wider activation (e.g. the attention output
    under an f32 KV cache with bf16 weights) must not promote the
    residual stream past the serving dtype — the scan carry is ``dt``,
    and the mixed-GEMM branch emits ``dt`` unconditionally."""
    from ..ops.quant import QuantizedTensor
    if isinstance(w, QuantizedTensor):
        from ..ops.mixed_gemm import mixed_matmul
        return mixed_matmul(x, w, contract_dims=contract_dims,
                            out_dtype=dt)
    wshape = w.shape
    K = int(np.prod(wshape[:contract_dims]))
    y = (x.reshape(-1, K) @ w.reshape(K, -1).astype(dt)).astype(dt)
    return y.reshape(*x.shape[:-1], *wshape[contract_dims:])


def _qkv_proj(cfg, ap, h, dt, cos, sin, positions):
    """Shared qkv projection + biases + rotary for the serving forwards
    (ragged step and decode burst)."""
    q = _mm(h, ap["wq"], dt)
    k = _mm(h, ap["wk"], dt)
    v = _mm(h, ap["wv"], dt)
    if cfg.attn_bias:
        q = q + ap["bq"].astype(dt)
        k = k + ap["bk"].astype(dt)
        v = v + ap["bv"].astype(dt)
    if cfg.position == "rope":
        # apply_rope expects [B, S, H, D]; B=1 with per-token positions
        q = L.apply_rope(q[None], cos, sin, positions=positions[None])[0]
        k = L.apply_rope(k[None], cos, sin, positions=positions[None])[0]
    return q, k, v


def _dense_weight(w) -> bool:
    """Whether ``w`` is a plain array (mixed-GEMM QuantizedTensor
    weights keep their VMEM-dequant kernel path and never route through
    the decomposed collectives)."""
    from ..ops.quant import QuantizedTensor
    return not isinstance(w, QuantizedTensor)


def _ffn(cfg, lp, h, dt, act, comm: Optional[ServingComm] = None):
    """Shared MLP / MoE branch of a serving layer.

    With ``comm`` (TP serving, comm_overlap on), the down-projection —
    the layer's one row-parallel GEMM, whose partial-sum all-reduce
    GSPMD would otherwise run serially after it — goes through the
    T3-style tile-decomposed matmul+allreduce instead
    (comm/overlap.py; bitwise-identical on the default exact rung)."""
    if cfg.num_experts > 1:
        from ..models.transformer import _shared_expert
        from ..parallel import moe as M

        d, _ = M.moe_ffn(lp["gate"], lp["experts"], h[None],
                         top_k=cfg.moe_top_k,
                         capacity_factor=cfg.eval_capacity_factor,
                         min_capacity=cfg.min_capacity,
                         activation=act, gated=cfg.gated_mlp,
                         norm_topk=cfg.moe_norm_topk)
        d = d[0]
        if "shared" in lp:       # qwen2-moe sigmoid-gated shared expert
            d = d + _shared_expert(lp["shared"], h, act, cfg.gated_mlp)
        return d
    mp = lp["mlp"]
    u = _mm(h, mp["wi"], dt)
    if cfg.mlp_bias:
        u = u + mp["bi"].astype(dt)
    if cfg.gated_mlp:
        u = act(_mm(h, mp["wg"], dt)) * u
    else:
        u = act(u)
    wo = mp["wo"]
    if comm is not None and comm.downproj and _dense_weight(wo):
        d = shard_matmul_allreduce(u, wo, comm, dt)
    else:
        d = _mm(u, wo, dt)
    if cfg.mlp_bias:
        d = d + mp["bo"].astype(dt)
    return d


def ragged_forward(cfg: TransformerConfig, params, kv, batch: RaggedBatch,
                   block_size: int, max_blocks_per_seq: int,
                   rng: Optional[jax.Array] = None,
                   attn_impl: str = "xla",
                   quant=None,
                   kv_host: bool = False,
                   shard_mesh=None,
                   stream=None,
                   mixed_gemm: bool = False,
                   comm: Optional[ServingComm] = None,
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """→ (last_token_logits [max_seqs, vocab], new_kv).

    ``kv``: [L, blocks, bs, 2, Hkv, D].  Rows of the logits output whose
    ``batch.logits_idx`` is -1 are garbage (callers mask by it).

    Every path is position-absolute: a batch whose tokens START at a
    nonzero context offset (chunked SplitFuse prefill — and, same
    mechanism, a prefill resuming after a prefix-cache hit aliased the
    leading blocks) needs no special handling: rope/learned positions
    index ``batch.positions``, KV writes land at
    ``block_tables[pos // bs], pos % bs``, and attention masks by
    absolute key position ≤ query position over whatever the block
    table references.
    ``attn_impl``: "xla" (gather) | "pallas" (streaming kernel).
    ``quant``: ZeRO-Inference weight-quant tree (inference/quantization
    ``quantize_model_params``) — one layer is dequantized at a time
    inside the scan body, so dense weights never all coexist in HBM.
    ``kv_host``: the cache lives in host memory; each scan step streams
    one layer through HBM and writes it back (ZeRO-Inference KV offload)
    so device memory holds a single layer's KV at a time.
    ``stream``: an :class:`~.weight_stream.NVMeWeightStore` — the layer
    scan fetches each layer's (possibly quantized) weights from NVMe via
    ``io_callback`` so HBM holds one layer's weights at a time
    (reference: partitioned_param_swapper.py:290 / ZeRO-Inference NVMe).
    ``comm``: a resolved :class:`~..comm.overlap.ServingComm` plan — the
    MLP down-projection's all-reduce and/or the unembed's logits gather
    run tile-decomposed (T3) and optionally quantized (EQuARX) instead
    of as GSPMD's serial collectives (docs/SERVING.md "Overlapped &
    quantized collectives").
    """
    if quant is not None:
        from .quantization import merge_layer
        from ..ops.quant import dequantize_any
    if quant is not None and "embed" in quant:
        embed_tab = {"table": dequantize_any(quant["embed"]["table"])}
        dt = embed_tab["table"].dtype
    else:
        embed_tab = params["embed"]
        dt = embed_tab["table"].dtype
    norm = _norm(cfg)
    act = L.ACTIVATIONS[cfg.activation]
    scale = (cfg.attn_scale if cfg.attn_scale is not None
             else 1.0 / (cfg.head_dim ** 0.5))

    x = L.embed(embed_tab, batch.token_ids).astype(dt)             # [T, dm]
    if cfg.embed_norm:                  # bloom word_embeddings_layernorm
        x = norm(params["ln_embed"], x)
    slopes = None
    cos = sin = None
    if cfg.position == "learned":
        x = x + params["pos_embed"]["table"][batch.positions].astype(dt)
    elif cfg.position == "alibi":
        slopes = L.alibi_slopes(cfg.num_heads)
    else:
        cos, sin = L.rope_freqs(cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta)

    def block(x, xs):
        if stream is None:
            lp, kv_layer, li = xs
        else:
            kv_layer, li = xs
            lp = _stream_layer(stream, li, dt, mixed_gemm=mixed_gemm)
        if kv_host:
            kv_layer = jax.device_put(kv_layer, jax.memory.Space.Device)
        if quant is not None:
            lp = merge_layer(lp, quant["blocks"], li, dt,
                             mixed=mixed_gemm)
        ap = lp["attn"]
        h = norm(lp["ln1"], x)
        q, k, v = _qkv_proj(cfg, ap, h, dt, cos, sin, batch.positions)
        kv_layer = _write_kv(kv_layer, k, v, batch, block_size)
        if attn_impl == "pallas":
            o = _paged_attention_pallas(kv_layer, q, batch, block_size,
                                        max_blocks_per_seq, scale,
                                        shard_mesh=shard_mesh,
                                        slopes=slopes)
        else:
            o = _paged_attention(kv_layer, q, batch, block_size,
                                 max_blocks_per_seq, scale, slopes=slopes)
        o = _mm(o.reshape(o.shape[0], -1), ap["wo"], dt,
                contract_dims=2)
        if cfg.attn_out_bias:
            o = o + ap["bo"].astype(dt)
        if not cfg.parallel_block:
            x = x + o
            h = norm(lp["ln2"], x)
        elif cfg.parallel_separate_norms:
            h = norm(lp["ln2"], x)   # gpt-neox: MLP norms the original x
        # parallel residual (falcon/phi): MLP reads the same ln1 output
        d = _ffn(cfg, lp, h, dt, act, comm=comm)
        if kv_host:
            kv_layer = jax.device_put(kv_layer, jax.memory.Space.Host)
        if cfg.parallel_block:
            return x + o + d, kv_layer
        return x + d, kv_layer

    layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    if stream is None:
        x, new_kv = jax.lax.scan(block, x,
                                 (params["blocks"], kv, layer_ids))
    else:
        x, new_kv = jax.lax.scan(block, x, (kv, layer_ids))

    # logits only at each sequence's last scheduled token
    # (reference kernel: gather_for_logits / logits_gather) — or, on a
    # speculative verify batch, at every position of each sequence's
    # draft window ([S, W] gather; -1 pads read token 0 and produce
    # garbage rows the caller masks, exactly like logits_idx == -1)
    if batch.verify_idx is not None:
        idx = jnp.maximum(batch.verify_idx, 0)                 # [S, W]
    else:
        idx = jnp.maximum(batch.logits_idx, 0)
    last = x[idx]                                            # [S(,W), dm]
    last = norm(params["ln_f"], last)
    # the unembed is the step's other heavy TP collective: a
    # vocab-split GEMM whose logits all-gather rides the tile-
    # decomposed ppermute chain under a comm plan (pure data movement
    # — bitwise-identical to the serial gather)
    if cfg.tie_embeddings:
        wmat = embed_tab["table"].astype(dt).T
        if comm is not None and comm.unembed:
            logits = shard_matmul_allgather(last, wmat, comm, dt)
        else:
            logits = last @ wmat
    else:
        k = params["lm_head"]["kernel"]
        if comm is not None and comm.unembed and _dense_weight(k):
            logits = shard_matmul_allgather(last, k.astype(dt), comm, dt)
        else:
            logits = last @ k.astype(dt)
        if cfg.head_bias:
            logits = logits + params["lm_head"]["bias"].astype(dt)
    return logits.astype(jnp.float32), new_kv


def pipelined_ragged_step(cfg: TransformerConfig, params, quant, kv,
                          batch: RaggedBatch, prev_toks, rng, sample_fn,
                          block_size: int, max_blocks_per_seq: int,
                          **fw_kwargs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One serving pipeline stage, entirely on device: substitute
    deferred feedback tokens from the previous step's on-device samples,
    run the ragged forward, sample every slot's next token.

    ``prev_toks``: [max_seqs] i32, the previous step's sample output
    (still on device — the engine reads a step's tokens back only after
    dispatching the next one).  ``batch.feedback_src[t] == s`` means
    token ``t``'s id is ``prev_toks[s]`` rather than
    ``batch.token_ids[t]``; -1 keeps the host-staged id.  ``rng`` is the
    caller's BASE key; each row samples with a key folded by its
    (uid, position) — see ``sampler.row_keys`` — so sampled values are
    invariant to scheduling (pipeline depth, chunking, prefix-cache
    hits).  ``sample_fn(logits, keys)`` consumes the per-row keys
    (greedy ignores them and XLA drops the fold).  Returns (sampled
    tokens [max_seqs] i32, new_kv); rows of the token output whose
    ``batch.logits_idx`` is -1 are garbage (callers mask by the
    schedule, exactly like the logits of :func:`ragged_forward`).

    On a speculative verify batch (``batch.verify_idx`` [S, W] present)
    the step samples EVERY window position and returns [S, W] tokens:
    column ``j`` is the model's choice for the token AFTER window
    position ``j``, keyed by ``fold_in(fold_in(rng, uid), pos_j + 1)``
    — the identical fold the single-sample path applies, so column 0 of
    a non-drafting row is bit-for-bit the legacy sample and a drafting
    row's columns reproduce the exact non-speculative stream
    (acceptance is a host-side prefix compare at collect).
    ``prev_toks`` may then be the previous verify step's [S, W] output;
    feedback reads its column 0 (markers are only ever speculated for
    non-drafting rows, whose sample lives there)."""
    fb = batch.feedback_src
    if fb is not None:
        prev = prev_toks if prev_toks.ndim == 1 else prev_toks[:, 0]
        tok = jnp.where(fb >= 0, prev[jnp.maximum(fb, 0)],
                        batch.token_ids)
        batch = batch._replace(token_ids=tok)
    logits, new_kv = ragged_forward(cfg, params, kv, batch, block_size,
                                    max_blocks_per_seq, quant=quant,
                                    **fw_kwargs)
    if batch.verify_idx is not None:
        S, W = batch.verify_idx.shape
        vidx = jnp.maximum(batch.verify_idx, 0)
        # window column j holds the token AT sequence position
        # positions[vidx]; its sample therefore lands at position + 1 —
        # the same "context length after the token" index row_keys folds
        wpos = batch.positions[vidx] + 1                       # [S, W]
        keys = window_keys(rng, batch.seq_uids, wpos)
        flat = sample_fn(logits.reshape(S * W, -1),
                         keys.reshape((S * W,) + keys.shape[2:]))
        return flat.reshape(S, W), new_kv
    keys = row_keys(rng, batch.seq_uids, batch.context_lens)
    return sample_fn(logits, keys), new_kv


# --------------------------------------------------------------------------
# Device-side decode bursts (multi-token decode in one dispatch)
# --------------------------------------------------------------------------

def snapshot_prefix(kv, block_tables, P: int, block_size: int):
    """Gather each slot's first ``P`` context tokens into a dense
    read-only buffer [L, S, P, 2, Hkv, D] (the burst's attention operand;
    gathered ONCE per burst, never carried through the scan — carrying
    the paged cache itself copies it every iteration).  A quantized
    cache snapshots as a (codes, scales [L, S, P, 2, Hkv]) pair — the
    burst dequantizes per layer in its attention, so the snapshot stays
    1 byte/element."""
    data, scales = _kv_parts(kv)
    nb = P // block_size
    tables = block_tables[:, :nb]                     # [S, nb]
    trash = data.shape[1] - 1
    tables = jnp.where(tables < 0, trash, tables)
    ctx = data[:, tables]          # [L, S, nb, bs, 2, Hkv, D]
    L, S = ctx.shape[0], ctx.shape[1]
    ctx = ctx.reshape(L, S, P, 2, ctx.shape[-2], ctx.shape[-1])
    if scales is None:
        return ctx
    sctx = scales[:, tables].reshape(L, S, P, 2, ctx.shape[-2])
    return (ctx, sctx)


def decode_burst_forward(cfg: TransformerConfig, params, prefix,
                         base_ctx, token0, steps: int, sample_fn,
                         rng, uids=None, quant=None,
                         mixed_gemm: bool = False):
    """Run ``steps`` decode iterations entirely on device.

    prefix: [L, S, P, 2, Hkv, D] dense read-only context (closure-sized
    operand); base_ctx: [S] i32 tokens already in context per slot;
    token0: [S] i32 the last fed token per slot; uids: [S] u32 the uid
    occupying each slot (sampling keys fold the base ``rng`` by
    (uid, position) exactly like the stepwise path, so seeded bursts
    match seeded steps token-for-token).  Returns
    (tokens [steps, S], tail [L, S, steps, 2, Hkv, D]) — the caller
    scatters the tail back into the paged cache.

    Attention per token = ONLINE-SOFTMAX MERGE of (a) dense attention
    over the prefix (masked by base_ctx) and (b) attention over the
    in-burst tail (masked by iteration) — no concatenation, the prefix
    is never copied."""
    pdata, pscales = _kv_parts(prefix)
    nL = pdata.shape[0]
    S, P = pdata.shape[1], pdata.shape[2]
    Hkv, D = pdata.shape[4], pdata.shape[5]
    H = cfg.num_heads
    rep = H // Hkv
    norm = _norm(cfg)
    act = L.ACTIVATIONS[cfg.activation]
    scale = (cfg.attn_scale if cfg.attn_scale is not None
             else 1.0 / (cfg.head_dim ** 0.5))
    if quant is not None:
        from .quantization import merge_layer
        from ..ops.quant import dequantize_any
    if quant is not None and "embed" in quant:
        embed_tab = {"table": dequantize_any(quant["embed"]["table"])}
    else:
        embed_tab = params["embed"]
    dt = embed_tab["table"].dtype
    cos = sin = slopes = None
    if cfg.position == "rope":
        cos, sin = L.rope_freqs(cfg.rotary_dim, cfg.max_seq_len,
                                cfg.rope_theta)
    elif cfg.position == "alibi":
        slopes = L.alibi_slopes(H).reshape(Hkv, rep)

    def one_layer(x, lp, li, tail_l, pos, j):
        """x: [S, dm]; tail_l: [S, K, 2, Hkv, D] this layer's in-burst
        KV.  Returns (y, tail_l with slot j written)."""
        if quant is not None:
            lp = merge_layer(lp, quant["blocks"], li, dt,
                             mixed=mixed_gemm)
        ap = lp["attn"]
        h = norm(lp["ln1"], x)
        q, k, v = _qkv_proj(cfg, ap, h, dt, cos, sin, pos)
        tail_l = tail_l.at[:, j, 0].set(k)
        tail_l = tail_l.at[:, j, 1].set(v)

        qg = q.reshape(S, Hkv, rep, D)
        # (a) prefix attention, masked by each slot's true context length
        kp = pdata[li, :, :, 0]                       # [S, P, Hkv, D]
        vp = pdata[li, :, :, 1]
        if pscales is not None:
            kp = _dequant_ctx(kp, pscales[li, :, :, 0], dt)
            vp = _dequant_ctx(vp, pscales[li, :, :, 1], dt)
        sa = jnp.einsum("shrd,sphd->shrp", qg, kp.astype(dt)
                        ).astype(jnp.float32) * scale
        cols = jnp.arange(P)[None, :]
        if slopes is not None:      # ALiBi over absolute prefix positions
            sa = sa + (slopes[None, :, :, None]
                       * cols[:, None, None, :].astype(jnp.float32))
        valid = cols < base_ctx[:, None]              # [S, P]
        sa = jnp.where(valid[:, None, None, :], sa, -1e30)
        ma = sa.max(axis=-1)
        pa = jnp.exp(sa - ma[..., None])
        la = pa.sum(axis=-1)
        oa = jnp.einsum("shrp,sphd->shrd", pa.astype(dt), vp.astype(dt))
        # (b) in-burst tail attention, masked by iteration (<= j)
        kt = tail_l[:, :, 0]                          # [S, K, Hkv, D]
        vt = tail_l[:, :, 1]
        sb = jnp.einsum("shrd,skhd->shrk", qg, kt).astype(jnp.float32) \
            * scale
        if slopes is not None:  # tail key k sits at position base_ctx+k
            kpos = (base_ctx[:, None]
                    + jnp.arange(tail_l.shape[1])[None, :]).astype(
                        jnp.float32)                  # [S, K]
            sb = sb + slopes[None, :, :, None] * kpos[:, None, None, :]
        it_valid = jnp.arange(tail_l.shape[1]) <= j
        sb = jnp.where(it_valid[None, None, None, :], sb, -1e30)
        mb = sb.max(axis=-1)
        pb = jnp.exp(sb - mb[..., None])
        lb = pb.sum(axis=-1)
        ob = jnp.einsum("shrk,skhd->shrd", pb.astype(dt), vt)
        # online-softmax merge of the two parts
        m = jnp.maximum(ma, mb)
        wa = jnp.exp(ma - m)
        wb = jnp.exp(mb - m)
        denom = la * wa + lb * wb
        o = (oa.astype(jnp.float32) * wa[..., None]
             + ob.astype(jnp.float32) * wb[..., None]) / \
            jnp.maximum(denom, 1e-30)[..., None]
        o = o.reshape(S, H, D).astype(dt)

        o = _mm(o.reshape(o.shape[0], -1), ap["wo"], dt,
                contract_dims=2)
        if cfg.attn_out_bias:
            o = o + ap["bo"].astype(dt)
        if not cfg.parallel_block:
            x = x + o
            h = norm(lp["ln2"], x)
        elif cfg.parallel_separate_norms:
            h = norm(lp["ln2"], x)   # gpt-neox: MLP norms the original x
        d = _ffn(cfg, lp, h, dt, act)
        y = (x + o + d) if cfg.parallel_block else (x + d)
        return y, tail_l

    tail0 = jnp.zeros((nL, S, steps, 2, Hkv, D), dt)
    if uids is None:
        uids = jnp.zeros(S, jnp.uint32)

    def iteration(carry, xs):
        tok, tail = carry
        j = xs
        pos = base_ctx + j                           # this token's position
        x = L.embed(embed_tab, tok).astype(dt)
        if cfg.embed_norm:              # bloom word_embeddings_layernorm
            x = norm(params["ln_embed"], x)
        if cfg.position == "learned":
            x = x + params["pos_embed"]["table"][pos].astype(dt)

        def body(x, xs2):
            lp, li, tl = xs2
            y, tl = one_layer(x, lp, li, tl, pos, j)
            return y, tl

        x, tail = jax.lax.scan(
            body, x, (params["blocks"],
                      jnp.arange(cfg.num_layers, dtype=jnp.int32), tail))
        x = norm(params["ln_f"], x)
        if cfg.tie_embeddings:
            logits = x @ embed_tab["table"].astype(dt).T
        else:
            logits = x @ params["lm_head"]["kernel"].astype(dt)
            if cfg.head_bias:
                logits = logits + params["lm_head"]["bias"].astype(dt)
        # sampled token j lands at position pos+1 = its post-step context
        # length — the same (uid, position) fold the stepwise path uses
        keys = row_keys(rng, uids, pos + 1)
        nxt = sample_fn(logits.astype(jnp.float32), keys)
        return (nxt, tail), nxt

    (_, tail), toks = jax.lax.scan(
        iteration, (token0, tail0),
        jnp.arange(steps, dtype=jnp.int32))
    return toks, tail


def scatter_tail(kv, tail, block_tables, base_ctx, block_size: int):
    """Write the burst's tail KV into the paged cache (one donated
    dispatch after the scan): token (slot s, iter j) lands at block
    tables[s, (base+j)//bs], offset (base+j)%bs.  Quantized caches
    quantize the dense in-burst tail here, on commit."""
    data, scales = _kv_parts(kv)
    nL, S, K = tail.shape[0], tail.shape[1], tail.shape[2]
    pos = base_ctx[:, None] + jnp.arange(K)[None, :]          # [S, K]
    blk = jnp.take_along_axis(block_tables, pos // block_size,
                              axis=1)                          # [S, K]
    trash = data.shape[1] - 1
    blk = jnp.where(blk < 0, trash, blk)
    off = pos % block_size
    li = jnp.arange(nL)[:, None, None]
    # kv[l, blk[s,k], off[s,k]] <- tail[l, s, k]  ([2, Hkv, D] payload)
    if scales is None:
        return data.at[li, blk[None], off[None]].set(tail)
    tq, ts = _quantize_kv(tail, data.dtype)   # ts: [L, S, K, 2, Hkv]
    data = data.at[li, blk[None], off[None]].set(tq)
    scales = scales.at[li, blk[None], off[None]].set(ts)
    return (data, scales)
