"""ZeRO-Inference: serve big models on small chips via weight
quantization + host-memory KV.

TPU-native analog of the reference's ZeRO-Inference stack
(``inference/quantization/quantization.py`` _init_group_wise_weight_
quantization, ``layers.py`` QuantizedLinear wrappers, and the KV-offload
config of the ZeRO-Inference blog/README: int4/int8 grouped weights +
CPU-offloaded KV cache for over-HBM models).

Design (XLA-first, no module wrapping):

* matmul weights of the stacked ``blocks`` tree are group-quantized
  PER LAYER (``jax.vmap`` over the leading layers dim) into int8/int4
  ``QuantizedTensor``s that live OUTSIDE the scan: the layer body
  dequantizes exactly one layer's weights at a time, so peak dense
  memory is one layer + activations — HBM holds only the int data
  (2-4x smaller, the 20x-bigger-model claim of README.md:35 composes
  from this + host KV);
* dequantize ops sit next to their consuming matmul, so XLA fuses the
  int->bf16 convert into the MXU operand load where possible;
* biases/norms stay dense (tiny); embeddings optionally quantized
  (``quantize_embeddings`` — they double as the unembed projection, so
  default off for quality).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.quant import (MINIFLOAT_BY_BITS, QuantizedTensor,
                         default_groups, dequantize_any,
                         minifloat_quantize, quantize)

# weights eligible for quantization inside a block (2D+ matmul operands)
_BLOCK_WEIGHTS = ("wq", "wk", "wv", "wo", "wi", "wg")

# block groups whose weights are consumed DENSE by the serving forward —
# "experts" feeds moe_ffn's ragged/scatter dispatch, "shared" feeds the
# qwen2-moe sigmoid-gated shared expert (models/transformer._shared_expert,
# plain ``@`` matmuls) — so they must never reach a mixed-input GEMM as
# QuantizedTensors, and they don't count toward mixed-GEMM eligibility
DENSE_ONLY_GROUPS = ("experts", "shared")


def _quantize_stacked(w: jax.Array, bits: int,
                      contract_dims: int = 1) -> QuantizedTensor:
    """Quantize a [L, ...] stacked weight layer-by-layer (eager, at
    engine build), so a single layer can be dequantized without touching
    the others.  bits 8 = row-wise weight-shaped; 4 = PACKED row-wise
    nibbles (real 0.5 byte/weight storage+bandwidth — reference:
    cuda_linear/linear_kernels_cuda.cu); 6/12 = emulated minifloat
    (reference: csrc/fp_quantizer FP6/FP12)."""
    if bits == 8:
        # row-wise weight-shaped layout: per (layer, row) scales, data in
        # the weight's own shape — dequant fuses into the consuming
        # matmul with no reshape/layout copy (ops/quant.quantize_rowwise)
        from ..ops.quant import _quantize_leading
        return _quantize_leading(w, lead_dims=2)
    if bits == 4:
        from ..ops.quant import quantize_rowwise4
        K = 1
        for d in w.shape[1:1 + contract_dims]:
            K *= d
        if K % 2 == 0:
            return quantize_rowwise4(w, contract_dims=contract_dims,
                                     lead_dims=1)
        # odd contraction cannot pack strided halves — grouped fallback
    if bits == 6 and w.shape[-1] % 4 == 0:
        # REAL 0.75-byte/weight packed fp6 (reference: fp_quantize.cu);
        # indivisible trailing dims fall back to the emulated layout
        from ..ops.quant import quantize_rowwise6
        return quantize_rowwise6(w, lead_dims=1)
    if bits == 12 and w.shape[-1] % 2 == 0:
        # packed fp12: 1.5 byte/weight instead of the int16 container
        from ..ops.quant import quantize_rowwise12
        return quantize_rowwise12(w, lead_dims=1)
    groups = default_groups(w[0].size)
    if bits in MINIFLOAT_BY_BITS:
        fmt = MINIFLOAT_BY_BITS[bits]
        qts = [minifloat_quantize(w[i], fmt=fmt, num_groups=groups)
               for i in range(w.shape[0])]
    else:
        qts = [quantize(w[i], bits=bits, num_groups=groups)
               for i in range(w.shape[0])]
    return QuantizedTensor(
        data=jnp.stack([q.data for q in qts]),
        scale=jnp.stack([q.scale for q in qts]),
        zero=None if qts[0].zero is None
        else jnp.stack([q.zero for q in qts]),
        bits=bits, shape=(w.shape[0],) + qts[0].shape, dtype=qts[0].dtype)


def layer_qt(qt: QuantizedTensor, i) -> QuantizedTensor:
    """Layer ``i``'s slice of a stacked QuantizedTensor, still quantized
    (the mixed-input GEMM consumes this directly — ops/mixed_gemm.py)."""
    return QuantizedTensor(qt.data[i], qt.scale[i],
                           None if qt.zero is None else qt.zero[i],
                           qt.bits, qt.shape[1:], qt.dtype,
                           layout=qt.layout)


def layer_weight(qt: QuantizedTensor, i, dt) -> jax.Array:
    """Dequantize layer ``i`` of a stacked QuantizedTensor."""
    return dequantize_any(layer_qt(qt, i), dt)


def quantize_model_params(params: Dict[str, Any], bits: int = 8,
                          quantize_embeddings: bool = False
                          ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Split ``params`` into (dense_tree, quant_tree).

    ``dense_tree`` mirrors ``params`` minus the quantized leaves;
    ``quant_tree`` holds stacked per-layer QuantizedTensors under the
    same paths (only ``blocks`` weights, plus optionally the embedding
    table).  The pair feeds ``ragged_forward(..., quant=quant_tree)``."""
    dense = jax.tree.map(lambda x: x, params)    # shallow-ish copy
    quant: Dict[str, Any] = {"blocks": {}}

    blocks = dense["blocks"]
    for group_name, group in list(blocks.items()):
        if not isinstance(group, dict):
            continue
        qgroup = {}
        for name, w in list(group.items()):
            if name in _BLOCK_WEIGHTS and w.ndim >= 3:   # [L, ...] weight
                # the attention output projection contracts its leading
                # (H, Dh) dims — the packed-int4 layout must flatten the
                # same split the serving matmul uses (_mm contract_dims)
                cd = 2 if (group_name == "attn" and name == "wo"
                           and w.ndim >= 4) else 1
                qgroup[name] = _quantize_stacked(w, bits, contract_dims=cd)
                del group[name]
        if qgroup:
            quant["blocks"][group_name] = qgroup

    if quantize_embeddings:
        tab = dense["embed"]["table"]
        if bits in MINIFLOAT_BY_BITS:
            quant["embed"] = {"table": minifloat_quantize(
                tab, fmt=MINIFLOAT_BY_BITS[bits])}
        elif bits == 8:
            # row-wise like the block weights: per-vocab-row scales,
            # weight-shaped payload, fused dequant (the table is the
            # largest single tensor — it must not keep the slow chain)
            from ..ops.quant import quantize_rowwise
            quant["embed"] = {"table": quantize_rowwise(tab)}
        else:
            quant["embed"] = {"table": quantize(tab, bits=bits)}
        del dense["embed"]["table"]
    return dense, quant


def merge_layer(lp: Dict[str, Any], quant_blocks: Dict[str, Any], i,
                dt, mixed: bool = False) -> Dict[str, Any]:
    """Reassemble one layer's full param dict: the scanned dense slice
    plus this layer's quantized weights — dequantized here, or (with
    ``mixed=True``) left as row-wise QuantizedTensors for the
    mixed-input GEMM (dequant happens in VMEM inside the kernel)."""
    from ..ops.quant import is_mixed_gemm_layout
    out = dict(lp)
    for group_name, qgroup in quant_blocks.items():
        g = dict(out.get(group_name, {}))
        for name, qt in qgroup.items():
            # expert/shared-expert weights are consumed DENSE (moe_ffn's
            # ragged dispatch, _shared_expert's plain matmuls) — never
            # hand them a QuantizedTensor
            if mixed and group_name not in DENSE_ONLY_GROUPS \
                    and is_mixed_gemm_layout(qt):
                g[name] = layer_qt(qt, i)
            else:
                g[name] = layer_weight(qt, i, dt)
        out[group_name] = g
    return out
