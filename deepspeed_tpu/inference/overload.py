"""Overload policy for the serving engine: SLA-aware admission,
backpressure, and preemption-by-eviction.

The SplitFuse scheduler (`engine._schedule`) packs a fixed token budget
per step; this module holds the *policy* layer that decides WHICH
requests get that budget when the offered load exceeds capacity
(docs/SERVING.md "Surviving overload"):

* **Admission tiers** — every request carries a ``priority`` (lower
  number = more important, like a nice level; default 0) and an optional
  ``deadline_ms`` relative to arrival.  The scheduler orders candidates
  by *effective* priority: waiting ``aging_ms`` promotes a request one
  tier, so low-priority traffic is delayed under load but never starved
  (anti-starvation aging).
* **Backpressure** — the admission queue is bounded
  (``max_queued_requests`` / ``max_queued_tokens``).  ``engine.put()``
  returns an :class:`AdmissionVerdict` instead of silently growing the
  backlog; over the bound the ``shed_policy`` decides: ``"reject"``
  sheds the newcomer, ``"evict-lowest"`` sheds the worst-priority
  *queued* request when the newcomer outranks it, ``"degrade"`` accepts
  everyone but demotes the newcomer to the background tier
  (``degrade_priority``) — the ZeRO-Offload trade (arxiv 2101.06840):
  a slower-but-alive path beats hard failure.
* **Preemption-by-eviction** — when the block pool or slot table
  starves a strictly higher-priority candidate, the scheduler evicts a
  running victim: its KV blocks release back through the refcounted
  allocator (full content-hashed blocks retire to the cached-free LRU
  pool, so with the prefix cache on, "evict and re-prefill from cache"
  costs one aliasing pass, not a recompute) and its full host-known
  token stream is re-queued as a prompt.  Seeded sampling keys are
  (uid, position)-folded, so a preempted-then-resumed request emits
  token-identical output (tests/test_scheduler_fuzz.py parity test).
* **Chunked prefill** — ``prefill_chunk`` caps the prompt tokens one
  request may take per step, so a long prompt is interleaved across
  steps instead of monopolizing the budget (decode tokens are always
  packed first; leftover budget still flows to prefill — the split is
  work-conserving).

Every decision here is pure host-side arithmetic over small dicts —
policy evaluation adds no device work and no syncs.  The scheduler's
decisions under load are measured through the PR-5 lifecycle records
(new terminal states ``shed`` / ``deadline_exceeded`` /
``context_exhausted`` and per-record preemption counts), which is what
``tools/loadgen.py`` turns into TTFT/TPOT-vs-load SLO curves.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, NamedTuple, Optional, Tuple

SHED_POLICIES = ("reject", "evict-lowest", "degrade")


@dataclasses.dataclass
class OverloadConfig:
    """Knobs for the admission / backpressure / preemption policy.

    The defaults reproduce the legacy cooperative-client behavior
    exactly: unbounded queue, no chunk cap, and preemption that can
    never trigger while every request rides the same priority tier —
    so ``InferenceConfig()`` engines are bit-for-bit unchanged."""
    # admission-queue bounds (None = unbounded).  "Queued" counts
    # requests waiting for their FIRST admission — a request that
    # already holds KV is live, not queued, and is never shed here.
    max_queued_requests: Optional[int] = None
    max_queued_tokens: Optional[int] = None
    # what to do with a NEW request that would exceed a bound
    shed_policy: str = "reject"          # reject | evict-lowest | degrade
    # max prompt tokens one request may take per step (None = no cap).
    # Decode tokens are packed first either way; leftover budget after
    # every prefill had its chunk is handed back out (work-conserving).
    prefill_chunk: Optional[int] = None
    # preemption-by-eviction of strictly lower-priority running
    # sequences when a candidate starves on blocks/slots
    preemption: bool = True
    max_preemptions_per_step: int = 2
    # anti-starvation aging: waiting this many ms promotes a queued
    # request by one priority tier (None disables aging)
    aging_ms: Optional[float] = 1000.0
    # the tier "degrade" demotes to — below any sane client priority,
    # so degraded requests only consume otherwise-idle capacity
    degrade_priority: int = 1_000_000
    # finished-record retention: how many terminally-closed requests
    # the lifecycle tracker remembers (ring-bounded).  query() answers
    # a terminal status as far back as this ring reaches; a uid that
    # aged out answers "forgotten" (distinct from the never-seen
    # "unknown"), so long-lived load-harness clients can tell a
    # retention miss from a request the engine never had
    status_retention: int = 4096

    def __post_init__(self):
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy={self.shed_policy!r}: expected one of "
                f"{SHED_POLICIES}")
        if self.max_preemptions_per_step < 0:
            raise ValueError("max_preemptions_per_step must be >= 0")
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1 (or None)")
        if self.status_retention < 1:
            raise ValueError("status_retention must be >= 1")


@dataclasses.dataclass
class RequestMeta:
    """Per-request admission metadata the engine keeps from ``put()``
    until the request reaches a terminal state."""
    priority: int = 0
    deadline_ms: Optional[float] = None
    t_arrival: float = 0.0               # perf_counter seconds
    degraded: bool = False               # admitted via shed_policy=degrade

    def expired(self, now: float) -> bool:
        return (self.deadline_ms is not None
                and (now - self.t_arrival) * 1e3 > self.deadline_ms)


class AdmissionVerdict(NamedTuple):
    """What ``engine.put()`` did with the request.  ``admitted`` means
    the tokens entered the engine (queued or continuing) — it does NOT
    promise scheduling; ``status`` is one of ``queued`` (new request
    accepted), ``continued`` (tokens appended to a known request),
    ``degraded`` (accepted at the background tier), or ``shed``.
    ``evicted_uids``: queued requests shed to make room under the
    ``evict-lowest`` policy (several, when the token bound needs more
    than one eviction to hold).  ``replica``: which fleet replica
    admitted the request when the verdict came through a
    :class:`~deepspeed_tpu.serving.FleetRouter` (None from a bare
    engine; a router-level shed with ``replica=None`` is the
    fleet-saturated 429-equivalent — every routable replica's own
    bound rejected it)."""
    admitted: bool
    status: str
    reason: str = ""
    evicted_uids: Tuple[int, ...] = ()
    replica: Optional[str] = None

    def __bool__(self) -> bool:          # `if eng.put(...):` reads right
        return self.admitted


def effective_priority(priority: int, t_arrival: float, now: float,
                       aging_ms: Optional[float]) -> float:
    """Aged priority: lower is better; waiting ``aging_ms`` subtracts a
    whole tier, so any finite-priority request eventually outranks a
    static lower tier (anti-starvation)."""
    if not aging_ms:
        return float(priority)
    return priority - max(0.0, (now - t_arrival) * 1e3) / aging_ms


def admission_decision(
        cfg: OverloadConfig, priority: int, n_tokens: int,
        queued: List[Tuple[int, float, int]], now: float,
) -> Tuple[str, Tuple[int, ...]]:
    """Decide what ``put()`` does with a NEW request given the current
    backlog.  ``queued``: ``(uid, effective_priority, pending_tokens)``
    for every request still waiting for its first admission.  Returns
    ``(action, victim_uids)`` with action one of ``admit`` / ``shed`` /
    ``evict`` (shed every ``victim_uids``, admit the newcomer) /
    ``degrade``."""
    def fits(n_req: int, n_tok: int) -> bool:
        if cfg.max_queued_requests is not None \
                and n_req >= cfg.max_queued_requests:
            return False
        if cfg.max_queued_tokens is not None \
                and n_tok + n_tokens > cfg.max_queued_tokens:
            return False
        return True

    if fits(len(queued), sum(q[2] for q in queued)):
        return "admit", ()
    if cfg.shed_policy == "degrade":
        return "degrade", ()
    if cfg.shed_policy == "evict-lowest" and queued:
        # evict worst-first until BOTH bounds actually hold for the
        # newcomer (the token bound can need several evictions) — only
        # entries STRICTLY worse than the newcomer's RAW priority
        # qualify: ties shed the newcomer, never churn the backlog
        victims: List[int] = []
        n_req = len(queued)
        n_tok = sum(q[2] for q in queued)
        for uid, eff, ntok in sorted(queued, key=lambda q: (q[1], q[0]),
                                     reverse=True):
            if eff <= priority:
                break
            victims.append(uid)
            n_req -= 1
            n_tok -= ntok
            if fits(n_req, n_tok):
                return "evict", tuple(victims)
    return "shed", ()


def select_victim(candidates: Iterable[Tuple[int, float, int]],
                  better_than: float) -> Optional[int]:
    """Pick the preemption victim among running sequences:
    ``candidates`` are ``(uid, priority, n_blocks)`` for every
    *eligible* live sequence (the engine filters out sequences with
    in-flight steps or host-unknown tokens).  Only a victim with
    priority STRICTLY worse (numerically greater) than ``better_than``
    qualifies; among those, the worst tier wins and ties break toward
    the sequence holding the most KV blocks (one eviction frees the
    most headroom)."""
    worst_key = None
    worst_uid = None
    for uid, pri, n_blocks in candidates:
        if pri <= better_than:
            continue
        key = (pri, n_blocks)
        if worst_key is None or key > worst_key:
            worst_key, worst_uid = key, uid
    return worst_uid
