from .config import (Config, ConfigError, ConfigModel, FP16Config, BF16Config,
                     OptimizerConfig, SchedulerConfig, ZeroConfig, OffloadConfig,
                     MeshConfig, PipelineConfig, TensorParallelConfig,
                     SequenceParallelConfig, MoEConfig,
                     ActivationCheckpointingConfig, CommsLoggerConfig,
                     FlopsProfilerConfig, AioConfig, CheckpointConfig,
                     ElasticityConfig, load_config)

__all__ = [
    "Config", "ConfigError", "ConfigModel", "FP16Config", "BF16Config",
    "OptimizerConfig", "SchedulerConfig", "ZeroConfig", "OffloadConfig",
    "MeshConfig", "PipelineConfig", "TensorParallelConfig",
    "SequenceParallelConfig", "MoEConfig", "ActivationCheckpointingConfig",
    "CommsLoggerConfig", "FlopsProfilerConfig", "AioConfig",
    "CheckpointConfig", "ElasticityConfig", "load_config",
]
