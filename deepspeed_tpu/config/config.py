"""Typed, JSON-driven configuration.

TPU-native analog of the reference's config system
(``deepspeed/runtime/config.py`` — ``DeepSpeedConfig`` assembling ~40 feature
sub-configs, batch-size triangulation config.py:802-884, duplicate-key
detection config.py:699, pydantic-style models ``runtime/config_utils.py``).

Design: plain ``dataclasses`` with a small ``from_dict`` layer that
  * validates unknown keys (error, like pydantic's extra="forbid"),
  * supports deprecated/aliased keys,
  * recursively builds nested sub-configs.

Everything flows through :class:`Config`, as in the reference where everything
flows through the JSON config.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Tuple, Type, TypeVar

from . import constants as C
from ..utils.logging import logger

T = TypeVar("T", bound="ConfigModel")


class ConfigError(ValueError):
    pass


def _reject_duplicate_keys(pairs):
    """json.load object_pairs_hook that errors on duplicate keys
    (reference: runtime/config.py:699)."""
    out = {}
    for k, v in pairs:
        if k in out:
            raise ConfigError(f"Duplicate config key: {k!r}")
        out[k] = v
    return out


@dataclass
class ConfigModel:
    """Base for all sub-configs: dict round-trip + alias handling."""

    @classmethod
    def aliases(cls) -> Dict[str, str]:
        # subclasses may map alias -> canonical field name
        return {}

    @classmethod
    def from_dict(cls: Type[T], d: Optional[Dict[str, Any]]) -> T:
        if d is None:
            d = {}
        if not isinstance(d, dict):
            raise ConfigError(f"{cls.__name__} expects a dict, got {type(d).__name__}")
        alias = cls.aliases()
        known = {f.name: f for f in fields(cls) if not f.name.startswith("_")}
        kwargs: Dict[str, Any] = {}
        for key, value in d.items():
            name = alias.get(key, key)
            if name not in known:
                raise ConfigError(f"Unknown key {key!r} in {cls.__name__} config. "
                                  f"Known keys: {sorted(known)}")
            if name in kwargs:
                raise ConfigError(f"Key {key!r} (alias of {name!r}) set twice in {cls.__name__}")
            f = known[name]
            sub = _subconfig_type(f)
            if sub is not None and isinstance(value, dict):
                value = sub.from_dict(value)
            kwargs[name] = value
        try:
            return cls(**kwargs)
        except TypeError as e:
            raise ConfigError(f"Bad {cls.__name__} config: {e}") from e

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for f in fields(self):
            if f.name.startswith("_"):
                continue
            v = getattr(self, f.name)
            out[f.name] = v.to_dict() if isinstance(v, ConfigModel) else v
        return out


def _subconfig_type(f: dataclasses.Field):
    t = f.type
    # with `from __future__ import annotations` every annotation is a string,
    # possibly wrapped in Optional[...]
    if isinstance(t, str):
        name = t.strip()
        if name.startswith("Optional[") and name.endswith("]"):
            name = name[len("Optional["):-1]
        t = globals().get(name, None)
        if t is None:
            return None
    try:
        if isinstance(t, type) and issubclass(t, ConfigModel):
            return t
    except TypeError:
        pass
    return None


# --------------------------------------------------------------------------
# Precision
# --------------------------------------------------------------------------

@dataclass
class FP16Config(ConfigModel):
    """fp16 + dynamic loss scaling (reference: runtime/fp16/loss_scaler.py)."""
    enabled: bool = False
    loss_scale: float = 0.0          # 0.0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    consecutive_hysteresis: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0


@dataclass
class BF16Config(ConfigModel):
    """bf16 params with fp32 master copy (reference: runtime/bf16_optimizer.py:34)."""
    enabled: bool = False
    # keep fp32 master weights + accumulate grads in fp32 (recommended on TPU)
    master_weights: bool = True
    immediate_grad_update: bool = False


# --------------------------------------------------------------------------
# Optimizer / scheduler
# --------------------------------------------------------------------------

@dataclass
class OptimizerConfig(ConfigModel):
    """{"type": "adamw", "params": {...}} (reference: engine._configure_basic_optimizer)."""
    type: str = "adamw"
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SchedulerConfig(ConfigModel):
    type: str = "WarmupLR"
    params: Dict[str, Any] = field(default_factory=dict)


# --------------------------------------------------------------------------
# ZeRO
# --------------------------------------------------------------------------

@dataclass
class OffloadConfig(ConfigModel):
    """Offload target for params or optimizer states
    (reference: runtime/zero/offload_config.py)."""
    device: str = "none"               # none | cpu | nvme
    nvme_path: Optional[str] = None
    pin_memory: bool = True
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    ratio: float = 1.0                  # fraction of states offloaded


@dataclass
class ZeroConfig(ConfigModel):
    """ZeRO stages mapped to sharding specs over the fsdp mesh axis.

    stage 0: pure DP (replicated params/grads/opt state, psum grads)
    stage 1: optimizer states sharded over fsdp axis
    stage 2: + gradients reduce-scattered over fsdp axis
    stage 3: + parameters sharded over fsdp axis (gathered per-use by XLA SPMD)
    (reference: runtime/zero/stage_1_and_2.py:96, stage3.py:109)
    """
    stage: int = 0
    # params smaller than this stay replicated (reference: stage3
    # persistence_threshold / stage3_param_persistence_threshold)
    param_persistence_threshold: int = 10_000
    # hpZ: shard params over intra-slice secondary axis only (ZeRO++;
    # reference zero_hpz_partition_size runtime/zero/config.py:40)
    zero_hpz_partition_size: int = 1
    # qwZ: int8-quantized weight all-gather (ZeRO++)
    zero_quantized_weights: bool = False
    # qgZ: quantized gradient reduce (ZeRO++)
    zero_quantized_gradients: bool = False
    offload_param: OffloadConfig = field(default_factory=OffloadConfig)
    offload_optimizer: OffloadConfig = field(default_factory=OffloadConfig)
    # MiCS-style: shard over a subgroup of this size instead of the full axis
    mics_shard_size: int = -1
    overlap_comm: bool = True
    contiguous_gradients: bool = True
    reduce_bucket_size: int = 500_000_000
    # round-robin-style balanced partitioning of the flat param space
    round_robin_gradients: bool = False

    def __post_init__(self):
        if self.stage not in (0, 1, 2, 3):
            raise ConfigError(f"zero_optimization.stage must be 0-3, got {self.stage}")


# --------------------------------------------------------------------------
# Parallel topology
# --------------------------------------------------------------------------

@dataclass
class MeshConfig(ConfigModel):
    """Named-axis device mesh (replaces the reference's process groups,
    deepspeed/utils/groups.py).  Sizes of -1/0 mean 'infer'."""
    data: int = -1        # pure data-parallel replicas
    fsdp: int = 1         # ZeRO sharding axis
    tensor: int = 1       # tensor parallel
    seq: int = 1          # Ulysses / ring context parallel
    expert: int = 1       # MoE expert parallel
    pipe: int = 1         # pipeline stages
    # devices per slice for ICI-vs-DCN-aware axis layout (multi-pod)
    devices_per_slice: int = -1


@dataclass
class PipelineConfig(ConfigModel):
    """(reference: runtime/pipe/module.py, schedule.py)."""
    stages: int = 1
    partition_method: str = "parameters"   # parameters | uniform | type:<regex>
    num_microbatches: int = 0              # 0 => one per pipeline stage
    activation_checkpoint_interval: int = 0
    # Schedules match the reference's TrainSchedule surface (schedule.py:
    # 189): gpipe (autodiff backward) and true 1F1B (eager-grad, O(S)
    # activation memory).  Megatron-style interleaved virtual stages are
    # deliberately NOT offered: under the lockstep SPMD scan every tick
    # already executes a full stage-slice of work, so interleaving buys
    # no bubble reduction here — requesting it is a config error, not a
    # silent fallback.
    schedule: str = "1f1b"                 # 1f1b | gpipe

    def __post_init__(self):
        if self.schedule not in ("1f1b", "gpipe"):
            raise ConfigError(
                f"pipeline.schedule must be '1f1b' or 'gpipe', got "
                f"{self.schedule!r} (interleaved virtual stages are not "
                "supported: the SPMD lockstep schedule has no bubble for "
                "them to shrink)")


@dataclass
class TensorParallelConfig(ConfigModel):
    size: int = 1
    # autotp-style: shard linear layers automatically by rules
    auto: bool = True


# --------------------------------------------------------------------------
# Data efficiency (reference: runtime/data_pipeline/config.py +
# legacy curriculum_learning engine hooks runtime/engine.py:288)
# --------------------------------------------------------------------------

@dataclass
class CurriculumLearningConfig(ConfigModel):
    """Seqlen curriculum (reference: curriculum_scheduler.py; engine
    truncates each batch to the scheduled difficulty).  NOTE: on TPU
    every distinct difficulty value compiles one program — pick
    ``difficulty_step`` in ``schedule_config`` coarse (e.g. 64+)."""
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"    # fixed_linear|fixed_root|fixed_discrete
    schedule_config: Dict[str, Any] = field(default_factory=dict)
    # Any curriculum_type other than "seqlen" names a DataAnalyzer metric:
    # this points at the analyzer save dir holding
    # <curriculum_type>/sample_to_metric.npy (reference: the
    # index_to_sample/index_to_metric paths in data_sampling config)
    data_analyzer_path: str = ""


@dataclass
class RandomLTDConfig(ConfigModel):
    """Random layerwise token dropping (reference:
    data_routing/basic_layer.py + scheduler).  ``seq_per_step`` also
    bounds compiled program count — each kept-token value is one
    program."""
    enabled: bool = False
    min_value: int = 128                   # starting kept tokens
    max_value: int = 0                     # 0 => the batch's full seqlen
    require_steps: int = 1000              # steps to anneal to max_value
    seq_per_step: int = 64


@dataclass
class DataRoutingConfig(ConfigModel):
    enabled: bool = False
    random_ltd: RandomLTDConfig = field(default_factory=RandomLTDConfig)


@dataclass
class DataSamplingConfig(ConfigModel):
    enabled: bool = False
    curriculum_learning: CurriculumLearningConfig = field(
        default_factory=CurriculumLearningConfig)


@dataclass
class DataEfficiencyConfig(ConfigModel):
    """(reference: data_efficiency config block, data_pipeline/config.py)."""
    enabled: bool = False
    data_sampling: DataSamplingConfig = field(
        default_factory=DataSamplingConfig)
    data_routing: DataRoutingConfig = field(default_factory=DataRoutingConfig)


@dataclass
class PLDConfig(ConfigModel):
    """Progressive layer drop (reference: progressive_layer_drop.py;
    theta(t) = (1-theta)·exp(-gamma·t)+theta)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


@dataclass
class EigenvalueConfig(ConfigModel):
    """(reference: runtime/eigenvalue.py — paces MoQ bit reduction)."""
    enabled: bool = False
    max_iter: int = 20
    tol: float = 1e-2
    stability: float = 1e-6


@dataclass
class QuantizeTrainingConfig(ConfigModel):
    """MoQ quantize-aware training (reference: runtime/quantize.py
    Quantizer — progressive fake-quant of 2-D+ weights in the forward,
    bits halving each ``quantize_period`` until ``target_bits``;
    optionally paced by the Hessian eigenvalue)."""
    enabled: bool = False
    start_bits: int = 16
    target_bits: int = 8
    quantize_period: int = 1000
    quantize_groups: int = 1
    eigenvalue: EigenvalueConfig = field(default_factory=EigenvalueConfig)


@dataclass
class SequenceParallelConfig(ConfigModel):
    """(reference: deepspeed/sequence/layer.py — Ulysses)."""
    size: int = 1
    mode: str = "ulysses"                  # ulysses | ring
    overlap_comm: bool = False


@dataclass
class MoEConfig(ConfigModel):
    """(reference: deepspeed/moe/layer.py, sharded_moe.py)."""
    enabled: bool = False
    num_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None     # None | 'Jitter' | 'RSample'
    drop_tokens: bool = True
    use_rts: bool = True
    expert_parallel_size: int = 1
    aux_loss_coef: float = 0.01


# --------------------------------------------------------------------------
# Aux subsystems
# --------------------------------------------------------------------------

@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """(reference: runtime/activation_checkpointing/checkpointing.py)."""
    enabled: bool = False
    # jax.checkpoint policy name: 'nothing' | 'dots' | 'dots_no_batch' | 'everything'
    policy: str = "nothing"
    # checkpoint every Nth layer when scanning over layers
    interval: int = 1


@dataclass
class CommsLoggerConfig(ConfigModel):
    """(reference: comm timed_op comm/comm.py:101 + utils/comms_logging.py)."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    prof_ops: List[str] = field(default_factory=list)
    debug: bool = False


@dataclass
class CommConfig(ConfigModel):
    """Overlapped / quantized gradient-sync collectives
    (comm/overlap.py; T3 arxiv 2401.16677, EQuARX arxiv 2506.17615;
    docs/SERVING.md "Overlapped & quantized collectives").

    ``overlap``: the per-microbatch gradient reduction runs through the
    tile-decomposed reduce-scatter/all-reduce inside a manual shard_map
    region instead of GSPMD's one monolithic collective per leaf —
    slice *i*'s comm carries no dependency on slice *i+1* (or on the
    next microbatch's backward), so XLA may co-schedule them.  The
    default exact rung is bitwise-identical to the plain reduction.

    ``quantized_allreduce``: "int8" | "int4" — promote the qgZ wire
    format from a zero_quantized_gradients-only leg to a first-class
    mesh-wide option: every DP-axis gradient collective carries bits/8
    of the exact payload.  Error-bounded, not exact.

    Both ride ``_manual_reduce_axes``, so meshes that cannot host the
    manual region (pipeline/sequence parallel, legacy-jax stage-3/TP)
    keep the PR-1 contract: loud degradation to the plain exact
    reduction (or a ConfigError unless ``allow_feature_degradation``).
    ``zero_quantized_gradients`` (qgZ proper) and the 1-bit optimizers
    take precedence when configured."""
    overlap: bool = False
    tiles: int = 4
    quantized_allreduce: Optional[str] = None      # "int8" | "int4"

    def __post_init__(self):
        if self.quantized_allreduce not in (None, "int8", "int4"):
            raise ConfigError(
                "comm.quantized_allreduce must be null, 'int8' or "
                f"'int4', got {self.quantized_allreduce!r}")
        if self.tiles < 1:
            raise ConfigError(f"comm.tiles must be >= 1, got {self.tiles}")


@dataclass
class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class TelemetryConfig(ConfigModel):
    """Host-side telemetry (telemetry/ — docs/OBSERVABILITY.md): the
    metrics registry is always on (plain host counter bumps); ``trace``
    additionally records per-phase spans of every training step into a
    ring buffer for Chrome-trace export
    (``engine.tracer.export_chrome_trace(path)``, open in Perfetto)."""
    trace: bool = False
    trace_capacity: int = 1 << 16       # spans retained (ring wraps)
    # device & compiler telemetry (telemetry/device.py): per-program
    # cost_analysis (one explicit AOT compile per program — why this is
    # opt-in), derived training_mfu / training_hbm_bw_util pull-gauges,
    # and memory_stats polling at the steps_per_print boundary.  The
    # compile/retrace counters are always on regardless.
    device: bool = False
    # streaming anomaly detection (telemetry/anomaly.py,
    # docs/OBSERVABILITY.md "Anomaly detection & deep capture"):
    # EWMA+MAD detectors over the train step's host phases (step
    # interval, host ms) and the retrace storm signal, counted as
    # training_anomalies_total{signal=...}; a fire arms a deep-capture
    # window when ``profile`` names a directory.  Off adds nothing to
    # the step path.
    anomaly: bool = False
    # deep-capture directory (telemetry/profiler.py): ``profile`` with
    # ``profile_steps > 0`` arms a bounded jax.profiler window over
    # the first N train steps at construction; ``engine.capture()``
    # arms explicit windows any time.  tools/tracemerge.py merges each
    # capture into one Perfetto timeline with the host phase spans.
    profile: Optional[str] = None
    profile_steps: int = 4


@dataclass
class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@dataclass
class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@dataclass
class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


@dataclass
class CheckpointConfig(ConfigModel):
    """(reference: checkpoint_engine config — nebula's tier-1 async
    persistence maps to a background fragment writer here)."""
    async_save: bool = False


@dataclass
class CometConfig(ConfigModel):
    """(reference: monitor/config.py CometConfig)."""
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: bool = True
    mode: str = "create"                 # create | get | get_or_create


@dataclass
class AioConfig(ConfigModel):
    """Native async-IO layer knobs (reference: csrc/aio, op config read at
    swap_tensor/partitioned_param_swapper.py:83).  All knobs are consumed
    by the native pool (ops/aio.py AsyncIOHandle)."""
    block_size: int = 1048576
    queue_depth: int = 128
    # our pool threads are plain pread/pwrite workers (cheap), not libaio
    # contexts — default matches AsyncIOHandle's longstanding 4, so
    # config-driven pools don't serialize chunk fan-out
    thread_count: int = 4
    single_submit: bool = False
    overlap_events: bool = True
    # page-cache bypass for 4096-aligned spans (falls back silently on
    # filesystems without O_DIRECT, e.g. tmpfs)
    use_odirect: bool = False
    # "auto" | "uring" | "threads": io_uring submission (real kernel
    # queue depth + registered O_DIRECT buffers — the libaio analog) vs
    # the pread/pwrite worker pool; auto probes io_uring_setup once
    backend: str = "auto"


@dataclass
class CheckpointConfig(ConfigModel):
    use_node_local_storage: bool = False
    parallel_write: bool = True
    tag_validation: str = "Warn"         # Ignore | Warn | Fail
    load_universal: bool = False
    async_save: bool = False


@dataclass
class DataTypesConfig(ConfigModel):
    grad_accum_dtype: Optional[str] = None     # None | 'fp32' | 'bf16' | 'fp16'


@dataclass
class ElasticityConfig(ConfigModel):
    """(reference: deepspeed/elasticity/elasticity.py)."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_devices: int = 1
    max_devices: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    version: float = 0.2


# --------------------------------------------------------------------------
# Top-level
# --------------------------------------------------------------------------

@dataclass
class Config(ConfigModel):
    """Top-level config (reference: ``DeepSpeedConfig`` runtime/config.py)."""

    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_device: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    steps_per_print: int = C.STEPS_PER_PRINT_DEFAULT
    wall_clock_breakdown: bool = False
    gradient_clipping: float = C.GRADIENT_CLIPPING_DEFAULT
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    # sparse embedding-grad reduction over DP (reference:
    # sparse_gradients_enabled; runtime/sparse_grads.py) — untied
    # embeddings only (tied heads produce dense vocab gradients)
    sparse_gradients: bool = False
    # manual-reduction features (qgZ / sparse_gradients / 1-bit) cannot
    # yet compose with pipeline or sequence parallelism, and sparse+qgZ
    # conflict.  By default such combinations raise a ConfigError; set
    # True to degrade to the plain (uncompressed/dense) reduction with a
    # warning instead
    allow_feature_degradation: bool = False
    seed: int = C.SEED_DEFAULT
    # loss reported to monitor/scheduler is averaged over data axis
    dump_state: bool = False

    # data efficiency family: legacy top-level curriculum (reference
    # engine.py:288) + the nested data_efficiency block, PLD and MoQ
    curriculum_learning: CurriculumLearningConfig = field(
        default_factory=CurriculumLearningConfig)
    data_efficiency: DataEfficiencyConfig = field(
        default_factory=DataEfficiencyConfig)
    progressive_layer_drop: PLDConfig = field(default_factory=PLDConfig)
    quantize_training: QuantizeTrainingConfig = field(
        default_factory=QuantizeTrainingConfig)

    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = field(default_factory=FP16Config)
    bf16: BF16Config = field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = field(default_factory=SequenceParallelConfig)
    moe: MoEConfig = field(default_factory=MoEConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(
        default_factory=ActivationCheckpointingConfig)
    comm: CommConfig = field(default_factory=CommConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    csv_monitor: CSVConfig = field(default_factory=CSVConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    comet: CometConfig = field(default_factory=CometConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    aio: AioConfig = field(default_factory=AioConfig)
    data_types: DataTypesConfig = field(default_factory=DataTypesConfig)
    elasticity: ElasticityConfig = field(default_factory=ElasticityConfig)

    @classmethod
    def aliases(cls) -> Dict[str, str]:
        return {
            # DeepSpeed-compatible aliases
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU: "train_micro_batch_size_per_device",
        }

    # ---- batch-size triangulation (reference: runtime/config.py:802-884) ----
    def resolve_batch_sizes(self, dp_world_size: int) -> Tuple[int, int, int]:
        """Given the data-parallel world size, fill in the missing member of
        (train_batch_size, micro_batch, gradient_accumulation_steps) such that
        ``train = micro * gas * dp_world_size``.  Returns the resolved triple
        and writes it back onto self.
        """
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_device
        gas = self.gradient_accumulation_steps

        if train is not None and micro is not None and gas is not None:
            if train != micro * gas * dp_world_size:
                raise ConfigError(
                    f"Inconsistent batch sizes: train_batch_size={train} != "
                    f"micro({micro}) * gas({gas}) * dp({dp_world_size})")
        elif train is not None and micro is not None:
            if train % (micro * dp_world_size) != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by micro*dp "
                    f"({micro}*{dp_world_size})")
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None:
            if train % (gas * dp_world_size) != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by gas*dp "
                    f"({gas}*{dp_world_size})")
            micro = train // (gas * dp_world_size)
        elif micro is not None:
            gas = gas or 1
            train = micro * gas * dp_world_size
        elif train is not None:
            gas = 1
            if train % dp_world_size != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by dp {dp_world_size}")
            micro = train // dp_world_size
        else:
            raise ConfigError(
                "At least one of train_batch_size / "
                "train_micro_batch_size_per_device must be set")

        self.train_batch_size = train
        self.train_micro_batch_size_per_device = micro
        self.gradient_accumulation_steps = gas
        return train, micro, gas

    # ---- precision -------------------------------------------------------
    @property
    def precision(self) -> str:
        if self.fp16.enabled and self.bf16.enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        if self.fp16.enabled:
            return C.PRECISION_FP16
        if self.bf16.enabled:
            return C.PRECISION_BF16
        return C.PRECISION_FP32

    def __post_init__(self):
        if self.gradient_clipping < 0:
            raise ConfigError("gradient_clipping must be >= 0")
        self.reconcile_mesh()

    def reconcile_mesh(self) -> None:
        """Propagate per-feature parallel sizes (sequence_parallel.size,
        pipeline.stages, tensor_parallel.size, moe.expert_parallel_size)
        into the mesh axes, erroring on contradictions — so configuring a
        feature without hand-editing the mesh Just Works."""
        pairs = [("seq", self.sequence_parallel.size),
                 ("pipe", self.pipeline.stages),
                 ("tensor", self.tensor_parallel.size),
                 ("expert", self.moe.expert_parallel_size)]
        for axis, size in pairs:
            if size and size > 1:
                mesh_size = getattr(self.mesh, axis)
                if mesh_size in (None, 0, -1, 1):
                    setattr(self.mesh, axis, size)
                elif mesh_size != size:
                    raise ConfigError(
                        f"mesh.{axis}={mesh_size} contradicts the "
                        f"feature-level parallel size {size}")


def load_config(config: Any) -> Config:
    """Build a :class:`Config` from a dict, JSON path, or Config instance."""
    if isinstance(config, Config):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f, object_pairs_hook=_reject_duplicate_keys)
    if not isinstance(config, dict):
        raise ConfigError(f"config must be dict, path, or Config, got {type(config)}")
    cfg = Config.from_dict(config)
    logger.debug("Loaded config: %s", cfg.to_dict())
    return cfg
