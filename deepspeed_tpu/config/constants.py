"""Config key constants and defaults.

Analog of the reference's ``deepspeed/runtime/constants.py`` — key strings are
kept DeepSpeed-compatible where a concept carries over so user configs port
with minimal edits (``train_batch_size``, ``gradient_accumulation_steps``,
``zero_optimization.stage`` …).  TPU-only knobs are new keys.
"""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_DEVICE = "train_micro_batch_size_per_device"
# accepted alias for configs ported from the reference
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"

OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"

FP16 = "fp16"
BF16 = "bf16"

ZERO_OPTIMIZATION = "zero_optimization"

PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"

SEED = "seed"
SEED_DEFAULT = 42

# mesh / parallelism topology
MESH = "mesh"
PIPELINE = "pipeline"
TENSOR_PARALLEL = "tensor_parallel"
SEQUENCE_PARALLEL = "sequence_parallel"
MOE = "moe"

ACTIVATION_CHECKPOINTING = "activation_checkpointing"
COMMS_LOGGER = "comms_logger"
FLOPS_PROFILER = "flops_profiler"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_CSV = "csv_monitor"
MONITOR_WANDB = "wandb"
DATA_TYPES = "data_types"
COMPRESSION = "compression"
ELASTICITY = "elasticity"
AIO = "aio"
CHECKPOINT = "checkpoint"

# precision modes
PRECISION_BF16 = "bf16"
PRECISION_FP16 = "fp16"
PRECISION_FP32 = "fp32"
