"""HuggingFace checkpoint import: state dict → scan-layout param tree.

TPU-native equivalent of the reference's checkpoint-loading machinery
(``module_inject/load_checkpoint.py`` + ``inference/v2/checkpoint/
huggingface_engine.py`` + the per-model parameter-mapping containers
``inference/v2/model_implementations/common_parameters/`` — qkv fusion,
transpose conventions, MP resharding).  The converter maps family-specific
HF names onto the single transformer core's tree (models/transformer.py
``init_params``): per-layer tensors stack on a leading ``layers`` dim
(scan layout), attention projections reshape to heads-major
``[dm, H, D]`` / ``[H, D, dm]``.

Zero-egress friendly: takes an in-memory ``state_dict`` (torch tensors or
numpy) — load it from local files with ``torch.load`` / safetensors
however you like.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ..models.transformer import TransformerConfig
from ..utils.logging import logger


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def _stack(sd: Dict[str, Any], fmt: str, n: int, transform=None) -> np.ndarray:
    outs = []
    for i in range(n):
        x = _np(sd[fmt.format(i)])
        outs.append(transform(x) if transform else x)
    return np.stack(outs)


def _qkv_heads(w: np.ndarray, H: int, D: int, transpose: bool) -> np.ndarray:
    """HF linear weight → [dm, H, D].  ``transpose``: HF stores
    [out, in] (torch Linear) vs GPT-2's Conv1D [in, out]."""
    if transpose:
        w = w.T                       # → [in(dm), out]
    dm = w.shape[0]
    return w.reshape(dm, H, D)


def _o_heads(w: np.ndarray, H: int, D: int, transpose: bool) -> np.ndarray:
    """HF out-proj weight → [H, D, dm]."""
    if transpose:
        w = w.T                       # → [in(H*D), dm]
    dm = w.shape[1]
    return w.reshape(H, D, dm)


# --------------------------------------------------------------------------
# GPT-2 (Conv1D layout: weights already [in, out]; fused c_attn)
# --------------------------------------------------------------------------

def _convert_gpt2(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    H, D, dm, nl = cfg.num_heads, cfg.head_dim, cfg.d_model, cfg.num_layers
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    def attn(i):
        w = _np(sd[f"{pre}h.{i}.attn.c_attn.weight"])       # [dm, 3dm]
        b = _np(sd[f"{pre}h.{i}.attn.c_attn.bias"])
        wq, wk, wv = np.split(w, 3, axis=1)
        bq, bk, bv = np.split(b, 3)
        return dict(
            wq=wq.reshape(dm, H, D), wk=wk.reshape(dm, H, D),
            wv=wv.reshape(dm, H, D),
            bq=bq.reshape(H, D), bk=bk.reshape(H, D), bv=bv.reshape(H, D),
            wo=_np(sd[f"{pre}h.{i}.attn.c_proj.weight"]).reshape(H, D, dm),
            bo=_np(sd[f"{pre}h.{i}.attn.c_proj.bias"]))

    def mlp(i):
        return dict(
            wi=_np(sd[f"{pre}h.{i}.mlp.c_fc.weight"]),
            bi=_np(sd[f"{pre}h.{i}.mlp.c_fc.bias"]),
            wo=_np(sd[f"{pre}h.{i}.mlp.c_proj.weight"]),
            bo=_np(sd[f"{pre}h.{i}.mlp.c_proj.bias"]))

    def ln(i, which):
        return dict(scale=_np(sd[f"{pre}h.{i}.{which}.weight"]),
                    bias=_np(sd[f"{pre}h.{i}.{which}.bias"]))

    def stacked(fn):
        outs = [fn(i) for i in range(nl)]
        return {k: np.stack([o[k] for o in outs]) for k in outs[0]}

    params = {
        "embed": {"table": _np(sd[f"{pre}wte.weight"])},
        "pos_embed": {"table": _np(sd[f"{pre}wpe.weight"])},
        "blocks": {
            "attn": stacked(attn),
            "mlp": stacked(mlp),
            "ln1": stacked(lambda i: ln(i, "ln_1")),
            "ln2": stacked(lambda i: ln(i, "ln_2")),
        },
        "ln_f": {"scale": _np(sd[f"{pre}ln_f.weight"]),
                 "bias": _np(sd[f"{pre}ln_f.bias"])},
    }
    return params


# --------------------------------------------------------------------------
# Llama / Mistral (torch Linear layout [out, in]; separate q/k/v; RMSNorm)
# --------------------------------------------------------------------------

def _convert_llama(cfg: TransformerConfig, sd: Dict[str, Any],
                   with_mlp: bool = True) -> Dict:
    H, D, Hkv = cfg.num_heads, cfg.head_dim, cfg.num_kv_heads
    dm, nl = cfg.d_model, cfg.num_layers
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    L = pre + "layers.{}."

    params = {
        "embed": {"table": _np(sd[f"{pre}embed_tokens.weight"])},
        "blocks": {
            "attn": {
                "wq": _stack(sd, L + "self_attn.q_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wk": _stack(sd, L + "self_attn.k_proj.weight", nl,
                             lambda w: _qkv_heads(w, Hkv, D, True)),
                "wv": _stack(sd, L + "self_attn.v_proj.weight", nl,
                             lambda w: _qkv_heads(w, Hkv, D, True)),
                "wo": _stack(sd, L + "self_attn.o_proj.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
            },
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl)},
            "ln2": {"scale": _stack(
                sd, L + "post_attention_layernorm.weight", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}norm.weight"])},
    }
    # qwen2: q/k/v projection biases (no o bias) — llama layout otherwise
    if L.format(0) + "self_attn.q_proj.bias" in sd:
        attn = params["blocks"]["attn"]
        attn["bq"] = _stack(sd, L + "self_attn.q_proj.bias", nl,
                            lambda b: b.reshape(H, D))
        attn["bk"] = _stack(sd, L + "self_attn.k_proj.bias", nl,
                            lambda b: b.reshape(Hkv, D))
        attn["bv"] = _stack(sd, L + "self_attn.v_proj.bias", nl,
                            lambda b: b.reshape(Hkv, D))
    if with_mlp:
        params["blocks"]["mlp"] = {
            "wg": _stack(sd, L + "mlp.gate_proj.weight", nl,
                         lambda w: w.T),
            "wi": _stack(sd, L + "mlp.up_proj.weight", nl,
                         lambda w: w.T),
            "wo": _stack(sd, L + "mlp.down_proj.weight", nl,
                         lambda w: w.T),
        }
    head_key = "lm_head.weight"
    if head_key in sd and not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": _np(sd[head_key]).T}
    return params


# --------------------------------------------------------------------------
# OPT (learned positions w/ offset, LayerNorm, fused decoder naming)
# --------------------------------------------------------------------------

def _convert_opt(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    H, D, dm, nl = cfg.num_heads, cfg.head_dim, cfg.d_model, cfg.num_layers
    pre = next((p for p in ("model.decoder.", "decoder.", "")
                if f"{p}embed_tokens.weight" in sd), "")
    L = pre + "layers.{}."

    def lin(fmt, out_heads=False, kv=False):
        hh = H
        if out_heads:
            return _stack(sd, fmt, nl, lambda w: _o_heads(w, H, D, True))
        return _stack(sd, fmt, nl, lambda w: _qkv_heads(w, hh, D, True))

    # OPT's learned positional table has a +2 offset (HF quirk)
    pos = _np(sd[f"{pre}embed_positions.weight"])[2:]
    params = {
        "embed": {"table": _np(sd[f"{pre}embed_tokens.weight"])},
        "pos_embed": {"table": pos},
        "blocks": {
            "attn": {
                "wq": lin(L + "self_attn.q_proj.weight"),
                "wk": lin(L + "self_attn.k_proj.weight"),
                "wv": lin(L + "self_attn.v_proj.weight"),
                "wo": lin(L + "self_attn.out_proj.weight", out_heads=True),
                "bq": _stack(sd, L + "self_attn.q_proj.bias", nl,
                             lambda b: b.reshape(H, D)),
                "bk": _stack(sd, L + "self_attn.k_proj.bias", nl,
                             lambda b: b.reshape(H, D)),
                "bv": _stack(sd, L + "self_attn.v_proj.bias", nl,
                             lambda b: b.reshape(H, D)),
                "bo": _stack(sd, L + "self_attn.out_proj.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, L + "fc1.weight", nl, lambda w: w.T),
                "bi": _stack(sd, L + "fc1.bias", nl),
                "wo": _stack(sd, L + "fc2.weight", nl, lambda w: w.T),
                "bo": _stack(sd, L + "fc2.bias", nl),
            },
            "ln1": {"scale": _stack(sd, L + "self_attn_layer_norm.weight", nl),
                    "bias": _stack(sd, L + "self_attn_layer_norm.bias", nl)},
            "ln2": {"scale": _stack(sd, L + "final_layer_norm.weight", nl),
                    "bias": _stack(sd, L + "final_layer_norm.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}final_layer_norm.weight"]),
                 "bias": _np(sd[f"{pre}final_layer_norm.bias"])},
    }
    return params


# --------------------------------------------------------------------------
# Falcon (fused MQA query_key_value, parallel residual, single block LN)
# --------------------------------------------------------------------------

def _convert_falcon(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    H, D, Hkv = cfg.num_heads, cfg.head_dim, cfg.num_kv_heads
    dm, nl = cfg.d_model, cfg.num_layers
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) \
        else ""
    L = pre + "h.{}."

    def qkv(i):
        # fused [(H + 2*Hkv) * D, dm]: q heads then k then v
        w = _np(sd[L.format(i) + "self_attention.query_key_value.weight"]).T
        wq = w[:, :H * D].reshape(dm, H, D)
        wk = w[:, H * D:(H + Hkv) * D].reshape(dm, Hkv, D)
        wv = w[:, (H + Hkv) * D:].reshape(dm, Hkv, D)
        return dict(wq=wq, wk=wk, wv=wv)

    def stacked(fn):
        outs = [fn(i) for i in range(nl)]
        return {k: np.stack([o[k] for o in outs]) for k in outs[0]}

    attn = stacked(qkv)
    attn["wo"] = _stack(sd, L + "self_attention.dense.weight", nl,
                        lambda w: _o_heads(w, H, D, True))
    params = {
        "embed": {"table": _np(sd[f"{pre}word_embeddings.weight"])},
        "blocks": {
            "attn": attn,
            "mlp": {
                "wi": _stack(sd, L + "mlp.dense_h_to_4h.weight", nl,
                             lambda w: w.T),
                "wo": _stack(sd, L + "mlp.dense_4h_to_h.weight", nl,
                             lambda w: w.T),
            },
            # parallel residual: one shared input layernorm
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl),
                    "bias": _stack(sd, L + "input_layernorm.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}ln_f.weight"]),
                 "bias": _np(sd[f"{pre}ln_f.bias"])},
    }
    return params


# --------------------------------------------------------------------------
# Phi (partial rotary, parallel residual, biased linears + biased lm_head)
# --------------------------------------------------------------------------

def _convert_phi(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    H, D, dm, nl = cfg.num_heads, cfg.head_dim, cfg.d_model, cfg.num_layers
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    L = pre + "layers.{}."

    params = {
        "embed": {"table": _np(sd[f"{pre}embed_tokens.weight"])},
        "blocks": {
            "attn": {
                "wq": _stack(sd, L + "self_attn.q_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wk": _stack(sd, L + "self_attn.k_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wv": _stack(sd, L + "self_attn.v_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wo": _stack(sd, L + "self_attn.dense.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
                "bq": _stack(sd, L + "self_attn.q_proj.bias", nl,
                             lambda b: b.reshape(H, D)),
                "bk": _stack(sd, L + "self_attn.k_proj.bias", nl,
                             lambda b: b.reshape(H, D)),
                "bv": _stack(sd, L + "self_attn.v_proj.bias", nl,
                             lambda b: b.reshape(H, D)),
                "bo": _stack(sd, L + "self_attn.dense.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, L + "mlp.fc1.weight", nl, lambda w: w.T),
                "bi": _stack(sd, L + "mlp.fc1.bias", nl),
                "wo": _stack(sd, L + "mlp.fc2.weight", nl, lambda w: w.T),
                "bo": _stack(sd, L + "mlp.fc2.bias", nl),
            },
            # parallel residual: one shared input layernorm
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl),
                    "bias": _stack(sd, L + "input_layernorm.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}final_layernorm.weight"]),
                 "bias": _np(sd[f"{pre}final_layernorm.bias"])},
        "lm_head": {"kernel": _np(sd["lm_head.weight"]).T,
                    "bias": _np(sd["lm_head.bias"])},
    }
    return params


# --------------------------------------------------------------------------
# Mixtral (llama attention + block-sparse MoE experts)
# --------------------------------------------------------------------------

def _convert_mixtral(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    params = _convert_llama(cfg, sd, with_mlp=False)
    nl, E = cfg.num_layers, cfg.num_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    L = pre + "layers.{}."

    def experts(i, name):
        # HF: w1 [ffn, dm] (gate), w3 [ffn, dm] (up), w2 [dm, ffn] (down)
        return np.stack([
            _np(sd[L.format(i) +
                   f"block_sparse_moe.experts.{e}.{name}.weight"]).T
            for e in range(E)])

    params["blocks"]["gate"] = {"kernel": _stack(
        sd, L + "block_sparse_moe.gate.weight", nl, lambda w: w.T)}
    params["blocks"]["experts"] = {
        "wg": np.stack([experts(i, "w1") for i in range(nl)]),
        "wi": np.stack([experts(i, "w3") for i in range(nl)]),
        "wo": np.stack([experts(i, "w2") for i in range(nl)]),
    }
    return params


def _convert_gptj(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """GPT-J (reference container: containers/gptj.py): partial rotary +
    parallel residual with ONE shared LayerNorm.  HF GPT-J rotates
    INTERLEAVED (even/odd) head-dim pairs; this core rotates half-split
    pairs — the converter permutes the rotary columns of wq/wk
    (interleaved→half), which is score-invariant because q and k share
    the permutation."""
    H, D, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
    pre = next((p for p in ("transformer.", "")
                if f"{p}wte.weight" in sd))
    L = pre + "h.{}."
    R = cfg.rotary_dim
    perm = np.concatenate([np.arange(0, R, 2), np.arange(1, R, 2),
                           np.arange(R, D)])

    def qk(w):
        return _qkv_heads(w, H, D, True)[:, :, perm]    # [dm, H, D]

    params = {
        "embed": {"table": _np(sd[f"{pre}wte.weight"])},
        "blocks": {
            "attn": {
                "wq": _stack(sd, L + "attn.q_proj.weight", nl, qk),
                "wk": _stack(sd, L + "attn.k_proj.weight", nl, qk),
                "wv": _stack(sd, L + "attn.v_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wo": _stack(sd, L + "attn.out_proj.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
            },
            "mlp": {
                "wi": _stack(sd, L + "mlp.fc_in.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, L + "mlp.fc_in.bias", nl),
                "wo": _stack(sd, L + "mlp.fc_out.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, L + "mlp.fc_out.bias", nl),
            },
            "ln1": {"scale": _stack(sd, L + "ln_1.weight", nl),
                    "bias": _stack(sd, L + "ln_1.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}ln_f.weight"]),
                 "bias": _np(sd[f"{pre}ln_f.bias"])},
        "lm_head": {"kernel": _np(sd["lm_head.weight"]).T,
                    "bias": _np(sd["lm_head.bias"])},
    }
    return params


def _convert_gpt_neox(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """GPT-NeoX / Pythia (reference container: containers/gptneox.py):
    parallel residual with SEPARATE input/post-attention norms, partial
    half-split rotary, head-interleaved fused query_key_value."""
    H, D, dm, nl = cfg.num_heads, cfg.head_dim, cfg.d_model, cfg.num_layers
    pre = next((p for p in ("gpt_neox.", "")
                if f"{p}embed_in.weight" in sd), "gpt_neox.")
    L = pre + "layers.{}."

    def qkv(i):
        # fused [(H*3*D), dm], per-head q,k,v contiguous — convert each
        # layer's tensor ONCE and split (falcon pattern)
        w = _np(sd[L.format(i) + "attention.query_key_value.weight"])
        w = w.reshape(H, 3, D, dm)                    # [H, 3, D, dm]
        b = _np(sd[L.format(i) + "attention.query_key_value.bias"])
        b = b.reshape(H, 3, D)
        out = {}
        for which, (wn, bn) in enumerate((("wq", "bq"), ("wk", "bk"),
                                          ("wv", "bv"))):
            out[wn] = np.transpose(w[:, which], (2, 0, 1))  # [dm, H, D]
            out[bn] = b[:, which]                           # [H, D]
        return out

    def qkv_stacked():
        outs = [qkv(i) for i in range(nl)]
        return {k: np.stack([o[k] for o in outs]) for k in outs[0]}

    params = {
        "embed": {"table": _np(sd[f"{pre}embed_in.weight"])},
        "blocks": {
            "attn": {
                **qkv_stacked(),
                "wo": _stack(sd, L + "attention.dense.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, L + "attention.dense.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, L + "mlp.dense_h_to_4h.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, L + "mlp.dense_h_to_4h.bias", nl),
                "wo": _stack(sd, L + "mlp.dense_4h_to_h.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, L + "mlp.dense_4h_to_h.bias", nl),
            },
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl),
                    "bias": _stack(sd, L + "input_layernorm.bias", nl)},
            "ln2": {"scale": _stack(
                        sd, L + "post_attention_layernorm.weight", nl),
                    "bias": _stack(
                        sd, L + "post_attention_layernorm.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}final_layer_norm.weight"]),
                 "bias": _np(sd[f"{pre}final_layer_norm.bias"])},
        "lm_head": {"kernel": _np(sd["embed_out.weight"]).T},
    }
    return params


def _convert_bloom(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """BLOOM (reference container: module_inject/containers/bloom.py —
    ALiBi position, word-embedding layernorm, head-interleaved fused
    query_key_value, tied embeddings)."""
    H, D, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
    pre = next((p for p in ("transformer.", "")
                if f"{p}word_embeddings.weight" in sd), "transformer.")
    L = pre + "h.{}."

    def qkv(i):
        w = _np(sd[L.format(i) + "self_attention.query_key_value.weight"])
        w = w.reshape(H, 3, D, cfg.d_model)           # [H, 3, D, dm]
        b = _np(sd[L.format(i) + "self_attention.query_key_value.bias"])
        b = b.reshape(H, 3, D)
        out = {}
        for which, (wn, bn) in enumerate((("wq", "bq"), ("wk", "bk"),
                                          ("wv", "bv"))):
            out[wn] = np.transpose(w[:, which], (2, 0, 1))  # [dm, H, D]
            out[bn] = b[:, which]
        return out

    def qkv_stacked():
        outs = [qkv(i) for i in range(nl)]
        return {k: np.stack([o[k] for o in outs]) for k in outs[0]}

    return {
        "embed": {"table": _np(sd[f"{pre}word_embeddings.weight"])},
        "ln_embed": {
            "scale": _np(sd[f"{pre}word_embeddings_layernorm.weight"]),
            "bias": _np(sd[f"{pre}word_embeddings_layernorm.bias"])},
        "blocks": {
            "attn": {
                **qkv_stacked(),
                "wo": _stack(sd, L + "self_attention.dense.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, L + "self_attention.dense.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, L + "mlp.dense_h_to_4h.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, L + "mlp.dense_h_to_4h.bias", nl),
                "wo": _stack(sd, L + "mlp.dense_4h_to_h.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, L + "mlp.dense_4h_to_h.bias", nl),
            },
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl),
                    "bias": _stack(sd, L + "input_layernorm.bias", nl)},
            "ln2": {"scale": _stack(
                        sd, L + "post_attention_layernorm.weight", nl),
                    "bias": _stack(
                        sd, L + "post_attention_layernorm.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}ln_f.weight"]),
                 "bias": _np(sd[f"{pre}ln_f.bias"])},
    }




def _convert_phi3(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """Phi-3 (reference: inference/v2/model_implementations/phi3/
    policy.py): llama-ish RMSNorm + gated silu, but the checkpoint fuses
    qkv_proj [(H+2Hkv)·D, dm] and gate_up_proj [2·ffn, dm]."""
    H, D, Hkv = cfg.num_heads, cfg.head_dim, cfg.num_kv_heads
    dm, nl, ffn = cfg.d_model, cfg.num_layers, cfg.d_ff
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    L = pre + "layers.{}."

    def qkv(i):
        w = _np(sd[L.format(i) + "self_attn.qkv_proj.weight"])
        q, k, v = np.split(w, [H * D, H * D + Hkv * D])
        return {"wq": _qkv_heads(q, H, D, True),
                "wk": _qkv_heads(k, Hkv, D, True),
                "wv": _qkv_heads(v, Hkv, D, True)}

    def gate_up(i):
        # one conversion per layer: the fused tensor is the model's
        # largest (phi3-mini: ~200 MB fp32) — split once
        w = _np(sd[L.format(i) + "mlp.gate_up_proj.weight"])
        g, u = np.split(w, 2)
        return g.T, u.T

    qkvs = [qkv(i) for i in range(nl)]
    gus = [gate_up(i) for i in range(nl)]
    params = {
        "embed": {"table": _np(sd[f"{pre}embed_tokens.weight"])},
        "blocks": {
            "attn": {
                **{k: np.stack([o[k] for o in qkvs])
                   for k in ("wq", "wk", "wv")},
                "wo": _stack(sd, L + "self_attn.o_proj.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
            },
            "mlp": {
                "wg": np.stack([g for g, _ in gus]),
                "wi": np.stack([u for _, u in gus]),
                "wo": _stack(sd, L + "mlp.down_proj.weight", nl,
                             lambda w: w.T),
            },
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl)},
            "ln2": {"scale": _stack(
                sd, L + "post_attention_layernorm.weight", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}norm.weight"])},
    }
    if "lm_head.weight" in sd and not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
    return params


def _convert_internlm(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """InternLM (reference container: module_inject/containers/
    internlm.py): llama tensor layout with q/k/v AND o-projection
    biases."""
    H, D, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
    params = _convert_llama(cfg, sd)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    L = pre + "layers.{}."
    if L.format(0) + "self_attn.o_proj.bias" in sd:
        params["blocks"]["attn"]["bo"] = _stack(
            sd, L + "self_attn.o_proj.bias", nl)
    return params


def _convert_gptneo(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """GPT-Neo (reference container: module_inject/containers/
    gptneo.py): learned positions, separate UNBIASED q/k/v with a biased
    out projection, and NO attention scaling (cfg.attn_scale=1).  Like
    the reference's injection kernels, the alternating local-attention
    layers serve as dense causal attention."""
    H, D, dm, nl = cfg.num_heads, cfg.head_dim, cfg.d_model, cfg.num_layers
    pre = "transformer." if any(k.startswith("transformer.")
                                for k in sd) else ""
    L = pre + "h.{}."
    return {
        "embed": {"table": _np(sd[f"{pre}wte.weight"])},
        "pos_embed": {"table": _np(sd[f"{pre}wpe.weight"])},
        "blocks": {
            "attn": {
                "wq": _stack(sd, L + "attn.attention.q_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wk": _stack(sd, L + "attn.attention.k_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wv": _stack(sd, L + "attn.attention.v_proj.weight", nl,
                             lambda w: _qkv_heads(w, H, D, True)),
                "wo": _stack(sd, L + "attn.attention.out_proj.weight",
                             nl, lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, L + "attn.attention.out_proj.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, L + "mlp.c_fc.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, L + "mlp.c_fc.bias", nl),
                "wo": _stack(sd, L + "mlp.c_proj.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, L + "mlp.c_proj.bias", nl),
            },
            "ln1": {"scale": _stack(sd, L + "ln_1.weight", nl),
                    "bias": _stack(sd, L + "ln_1.bias", nl)},
            "ln2": {"scale": _stack(sd, L + "ln_2.weight", nl),
                    "bias": _stack(sd, L + "ln_2.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[f"{pre}ln_f.weight"]),
                 "bias": _np(sd[f"{pre}ln_f.bias"])},
    }


def _convert_qwen2_moe(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """Qwen2-MoE (reference: inference/v2/model_implementations/
    qwen_v2_moe/model.py): qwen2 attention (qkv biases, no o bias) +
    sparse experts with RAW top-k softmax probs (norm_topk_prob=False)
    + a sigmoid-gated dense shared expert."""
    params = _convert_llama(cfg, sd, with_mlp=False)
    nl, E = cfg.num_layers, cfg.num_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    L = pre + "layers.{}."

    def experts(i, name):
        return np.stack([
            _np(sd[L.format(i) + f"mlp.experts.{e}.{name}.weight"]).T
            for e in range(E)])

    params["blocks"]["gate"] = {"kernel": _stack(
        sd, L + "mlp.gate.weight", nl, lambda w: w.T)}
    params["blocks"]["experts"] = {
        "wg": np.stack([experts(i, "gate_proj") for i in range(nl)]),
        "wi": np.stack([experts(i, "up_proj") for i in range(nl)]),
        "wo": np.stack([experts(i, "down_proj") for i in range(nl)]),
    }
    params["blocks"]["shared"] = {
        "wg": _stack(sd, L + "mlp.shared_expert.gate_proj.weight", nl,
                     lambda w: w.T),
        "wi": _stack(sd, L + "mlp.shared_expert.up_proj.weight", nl,
                     lambda w: w.T),
        "wo": _stack(sd, L + "mlp.shared_expert.down_proj.weight", nl,
                     lambda w: w.T),
        "gate": _stack(sd, L + "mlp.shared_expert_gate.weight", nl,
                       lambda w: w.T),
    }
    return params


def _convert_megatron(cfg: TransformerConfig, sd: Dict[str, Any]) -> Dict:
    """Megatron-LM GPT checkpoints (reference container:
    module_inject/containers/megatron_gpt.py + megatron_gpt_moe.py):
    megatron naming (``language_model.…``/``transformer.layers.N``) with
    the fused query_key_value stored PER-HEAD INTERLEAVED
    [H·3·D, dm] — (q_h, k_h, v_h) chunks per head, the layout the
    reference container's qkv_copy() deinterleaves."""
    H, D, dm, nl = cfg.num_heads, cfg.head_dim, cfg.d_model, cfg.num_layers
    emb = next((p for p in
                ("language_model.embedding.", "embedding.", "")
                if f"{p}word_embeddings.weight" in sd), None)
    if emb is None:
        raise KeyError("not a megatron-lm GPT state dict "
                       "(no *word_embeddings.weight)")
    lpre = next((p for p in
                 ("language_model.transformer.", "transformer.",
                  "language_model.encoder.", "encoder.")
                 if f"{p}layers.0.input_layernorm.weight" in sd),
                "transformer.")
    L = lpre + "layers.{}."

    def qkv(i):
        w = _np(sd[L.format(i) + "attention.query_key_value.weight"])
        b = _np(sd[L.format(i) + "attention.query_key_value.bias"])
        w = w.reshape(H, 3, D, dm)              # per-head (q,k,v) chunks
        b = b.reshape(H, 3, D)
        out = {}
        for j, (wn, bn) in enumerate((("wq", "bq"), ("wk", "bk"),
                                      ("wv", "bv"))):
            out[wn] = np.transpose(w[:, j], (2, 0, 1))      # [dm, H, D]
            out[bn] = b[:, j]
        return out

    qkvs = [qkv(i) for i in range(nl)]
    fl = next((k for k in (lpre + "final_layernorm.weight",
                           "final_layernorm.weight") if k in sd))
    return {
        "embed": {"table": _np(sd[f"{emb}word_embeddings.weight"])},
        "pos_embed": {"table": _np(
            sd[f"{emb}position_embeddings.weight"])},
        "blocks": {
            "attn": {
                **{k: np.stack([o[k] for o in qkvs])
                   for k in ("wq", "wk", "wv", "bq", "bk", "bv")},
                "wo": _stack(sd, L + "attention.dense.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, L + "attention.dense.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, L + "mlp.dense_h_to_4h.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, L + "mlp.dense_h_to_4h.bias", nl),
                "wo": _stack(sd, L + "mlp.dense_4h_to_h.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, L + "mlp.dense_4h_to_h.bias", nl),
            },
            "ln1": {"scale": _stack(sd, L + "input_layernorm.weight", nl),
                    "bias": _stack(sd, L + "input_layernorm.bias", nl)},
            "ln2": {"scale": _stack(
                        sd, L + "post_attention_layernorm.weight", nl),
                    "bias": _stack(
                        sd, L + "post_attention_layernorm.bias", nl)},
        },
        "ln_f": {"scale": _np(sd[fl]),
                 "bias": _np(sd[fl.replace(".weight", ".bias")])},
    }


CONVERTERS: Dict[str, Callable] = {
    "gpt2": _convert_gpt2,
    "llama": _convert_llama,
    "mistral": _convert_llama,     # same tensor layout
    "qwen2": _convert_llama,
    "mixtral": _convert_mixtral,
    "falcon": _convert_falcon,
    "phi": _convert_phi,
    "opt": _convert_opt,
    "gptj": _convert_gptj,
    "gpt_neox": _convert_gpt_neox,
    "bloom": _convert_bloom,
    "phi3": _convert_phi3,
    "internlm": _convert_internlm,
    "gpt_neo": _convert_gptneo,
    "qwen2_moe": _convert_qwen2_moe,
    "megatron": _convert_megatron,
}


def family_of(name_or_type: str) -> str:
    s = name_or_type.lower()
    if "gpt-j" in s or "gptj" in s:      # canonical repo ids hyphenate
        return "gptj"
    if "neox" in s or "pythia" in s:
        return "gpt_neox"
    if "gpt-neo" in s or "gpt_neo" in s:
        return "gpt_neo"
    if "qwen2_moe" in s or "qwen2-moe" in s:
        return "qwen2_moe"
    if "phi3" in s or "phi-3" in s:
        return "phi3"
    for fam in ("megatron", "internlm", "mixtral", "llama", "mistral",
                "qwen2", "gpt2", "falcon", "phi", "opt", "bloom"):
        if fam in s:
            return fam
    raise ValueError(f"no HF converter for {name_or_type!r}; "
                     f"known families: {sorted(CONVERTERS)}")


def load_hf_state_dict(cfg: TransformerConfig, state_dict: Dict[str, Any],
                       family: str, dtype=None,
                       reference_params: Optional[Dict] = None) -> Dict:
    """Convert an HF ``state_dict`` to this framework's param tree.

    ``reference_params`` (e.g. ``model.params``) enables a structural
    check: every leaf converted must match the target shape."""
    params = CONVERTERS[family_of(family)](cfg, state_dict)
    if dtype is not None:
        import jax
        params = jax.tree.map(lambda x: np.asarray(x, dtype), params)
    if reference_params is not None:
        import jax
        ref_flat = dict(jax.tree_util.tree_flatten_with_path(
            reference_params)[0])
        got_flat = dict(jax.tree_util.tree_flatten_with_path(params)[0])
        missing = set(map(str, ref_flat)) - set(map(str, got_flat))
        extra = set(map(str, got_flat)) - set(map(str, ref_flat))
        if missing or extra:
            raise ValueError(
                f"HF conversion tree mismatch: missing={sorted(missing)} "
                f"extra={sorted(extra)}")
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            want = ref_flat[path].shape
            if tuple(leaf.shape) != tuple(want):
                raise ValueError(
                    f"shape mismatch at {jax.tree_util.keystr(path)}: "
                    f"got {leaf.shape}, model expects {want}")
    logger.info("converted %d HF tensors (%s family)",
                len(state_dict), family_of(family))
    return params


def load_hf_bert(cfg, state_dict: Dict[str, Any], dtype=None) -> Dict:
    """BERT-class encoder state dict → ``models/encoder.py`` tree
    (reference container: module_inject/containers/bert.py:13).
    DistilBERT's different naming goes through :func:`load_hf_distilbert`
    (reference: distil_bert.py).  ``cfg``: an
    :class:`~deepspeed_tpu.models.encoder.EncoderConfig`."""
    H, D, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
    pre = next((p for p in ("bert.", "")
                if f"{p}embeddings.word_embeddings.weight" in state_dict),
               "bert.")
    sd = state_dict
    Lf = pre + "encoder.layer.{}."

    def attn_w(sub):
        return _stack(sd, Lf + f"attention.self.{sub}.weight", nl,
                      lambda w: _qkv_heads(w, H, D, True))

    def attn_b(sub):
        return _stack(sd, Lf + f"attention.self.{sub}.bias", nl,
                      lambda b: b.reshape(H, D))

    params = {
        "embed": {"table": _np(
            sd[f"{pre}embeddings.word_embeddings.weight"])},
        "pos_embed": {"table": _np(
            sd[f"{pre}embeddings.position_embeddings.weight"])},
        "type_embed": {"table": _np(
            sd[f"{pre}embeddings.token_type_embeddings.weight"])},
        "ln_embed": {
            "scale": _np(sd[f"{pre}embeddings.LayerNorm.weight"]),
            "bias": _np(sd[f"{pre}embeddings.LayerNorm.bias"])},
        "blocks": {
            "attn": {
                "wq": attn_w("query"), "bq": attn_b("query"),
                "wk": attn_w("key"), "bk": attn_b("key"),
                "wv": attn_w("value"), "bv": attn_b("value"),
                "wo": _stack(sd, Lf + "attention.output.dense.weight",
                             nl, lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, Lf + "attention.output.dense.bias", nl),
            },
            "ln_attn": {
                "scale": _stack(
                    sd, Lf + "attention.output.LayerNorm.weight", nl),
                "bias": _stack(
                    sd, Lf + "attention.output.LayerNorm.bias", nl)},
            "mlp": {
                "wi": _stack(sd, Lf + "intermediate.dense.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, Lf + "intermediate.dense.bias", nl),
                "wo": _stack(sd, Lf + "output.dense.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, Lf + "output.dense.bias", nl),
            },
            "ln_mlp": {
                "scale": _stack(sd, Lf + "output.LayerNorm.weight", nl),
                "bias": _stack(sd, Lf + "output.LayerNorm.bias", nl)},
        },
    }
    if cfg.pooler:
        pk = next((k for k in (f"{pre}pooler.dense.weight",
                               "pooler.dense.weight") if k in sd), None)
        if pk is None:
            raise KeyError(
                "cfg.pooler=True but the checkpoint has no pooler "
                "weights (e.g. BertForMaskedLM / add_pooling_layer="
                "False); build with EncoderConfig(pooler=False)")
        pb = pk.replace(".weight", ".bias")
        params["pooler"] = {"kernel": _np(sd[pk]).T, "bias": _np(sd[pb])}
    if dtype is not None:
        import jax
        params = jax.tree.map(lambda x: np.asarray(x, dtype), params)
    logger.info("converted %d HF tensors (bert encoder)", len(sd))
    return params


def load_hf_distilbert(cfg, state_dict: Dict[str, Any],
                       dtype=None) -> Dict:
    """DistilBERT state dict → encoder tree (reference container:
    module_inject/containers/distil_bert.py).  DistilBERT has no segment
    embeddings and no pooler — build with
    ``EncoderConfig(type_vocab_size=0, pooler=False)``."""
    H, D, nl = cfg.num_heads, cfg.head_dim, cfg.num_layers
    sd = state_dict
    pre = next((p for p in ("distilbert.", "")
                if f"{p}embeddings.word_embeddings.weight" in sd),
               "distilbert.")
    Lf = pre + "transformer.layer.{}."

    def attn_w(sub):
        return _stack(sd, Lf + f"attention.{sub}.weight", nl,
                      lambda w: _qkv_heads(w, H, D, True))

    def attn_b(sub):
        return _stack(sd, Lf + f"attention.{sub}.bias", nl,
                      lambda b: b.reshape(H, D))

    params = {
        "embed": {"table": _np(
            sd[f"{pre}embeddings.word_embeddings.weight"])},
        "pos_embed": {"table": _np(
            sd[f"{pre}embeddings.position_embeddings.weight"])},
        "ln_embed": {
            "scale": _np(sd[f"{pre}embeddings.LayerNorm.weight"]),
            "bias": _np(sd[f"{pre}embeddings.LayerNorm.bias"])},
        "blocks": {
            "attn": {
                "wq": attn_w("q_lin"), "bq": attn_b("q_lin"),
                "wk": attn_w("k_lin"), "bk": attn_b("k_lin"),
                "wv": attn_w("v_lin"), "bv": attn_b("v_lin"),
                "wo": _stack(sd, Lf + "attention.out_lin.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, Lf + "attention.out_lin.bias", nl),
            },
            "ln_attn": {
                "scale": _stack(sd, Lf + "sa_layer_norm.weight", nl),
                "bias": _stack(sd, Lf + "sa_layer_norm.bias", nl)},
            "mlp": {
                "wi": _stack(sd, Lf + "ffn.lin1.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, Lf + "ffn.lin1.bias", nl),
                "wo": _stack(sd, Lf + "ffn.lin2.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, Lf + "ffn.lin2.bias", nl),
            },
            "ln_mlp": {
                "scale": _stack(sd, Lf + "output_layer_norm.weight", nl),
                "bias": _stack(sd, Lf + "output_layer_norm.bias", nl)},
        },
    }
    if dtype is not None:
        import jax
        params = jax.tree.map(lambda x: np.asarray(x, dtype), params)
    logger.info("converted %d HF tensors (distilbert encoder)", len(sd))
    return params


def load_hf_clip(cfg, state_dict: Dict[str, Any], dtype=None) -> Dict:
    """HF CLIPModel state dict → ``models/clip.py`` tree (reference
    container: module_inject/containers/clip.py:13 — both towers are
    CLIPEncoderLayers).  ``cfg``: a
    :class:`~deepspeed_tpu.models.clip.CLIPConfig`."""
    sd = state_dict

    def tower(pre, tw):
        H, D, nl = tw.num_heads, tw.width // tw.num_heads, tw.num_layers
        Lf = pre + "encoder.layers.{}."
        return {
            "ln1": {"scale": _stack(sd, Lf + "layer_norm1.weight", nl),
                    "bias": _stack(sd, Lf + "layer_norm1.bias", nl)},
            "ln2": {"scale": _stack(sd, Lf + "layer_norm2.weight", nl),
                    "bias": _stack(sd, Lf + "layer_norm2.bias", nl)},
            "attn": {
                **{wn: _stack(sd, Lf + f"self_attn.{hn}_proj.weight",
                              nl, lambda w: _qkv_heads(w, H, D, True))
                   for wn, hn in (("wq", "q"), ("wk", "k"), ("wv", "v"))},
                **{bn: _stack(sd, Lf + f"self_attn.{hn}_proj.bias", nl,
                              lambda b: b.reshape(H, D))
                   for bn, hn in (("bq", "q"), ("bk", "k"), ("bv", "v"))},
                "wo": _stack(sd, Lf + "self_attn.out_proj.weight", nl,
                             lambda w: _o_heads(w, H, D, True)),
                "bo": _stack(sd, Lf + "self_attn.out_proj.bias", nl),
            },
            "mlp": {
                "wi": _stack(sd, Lf + "mlp.fc1.weight", nl,
                             lambda w: w.T),
                "bi": _stack(sd, Lf + "mlp.fc1.bias", nl),
                "wo": _stack(sd, Lf + "mlp.fc2.weight", nl,
                             lambda w: w.T),
                "bo": _stack(sd, Lf + "mlp.fc2.bias", nl),
            },
        }

    v = "vision_model."
    t = "text_model."
    # HF's key really is spelled "pre_layrnorm"
    pre_ln = v + ("pre_layrnorm" if v + "pre_layrnorm.weight" in sd
                  else "pre_layernorm")
    params = {
        "visual": {
            "patch_embed": {"kernel": np.transpose(
                _np(sd[v + "embeddings.patch_embedding.weight"]),
                (2, 3, 1, 0))},                      # OIHW -> HWIO
            "class_embed": _np(sd[v + "embeddings.class_embedding"]),
            "pos_embed": _np(
                sd[v + "embeddings.position_embedding.weight"]),
            "ln_pre": {"scale": _np(sd[pre_ln + ".weight"]),
                       "bias": _np(sd[pre_ln + ".bias"])},
            "blocks": tower(v, cfg.vision),
            "ln_post": {"scale": _np(sd[v + "post_layernorm.weight"]),
                        "bias": _np(sd[v + "post_layernorm.bias"])},
            "proj": _np(sd["visual_projection.weight"]).T,
        },
        "text": {
            "embed": {"table": _np(
                sd[t + "embeddings.token_embedding.weight"])},
            "pos_embed": _np(
                sd[t + "embeddings.position_embedding.weight"]),
            "blocks": tower(t, cfg.text),
            "ln_final": {"scale": _np(sd[t + "final_layer_norm.weight"]),
                         "bias": _np(sd[t + "final_layer_norm.bias"])},
            "proj": _np(sd["text_projection.weight"]).T,
        },
        "logit_scale": _np(sd["logit_scale"]),
    }
    if dtype is not None:
        import jax
        params = jax.tree.map(lambda x: np.asarray(x, dtype), params)
    logger.info("converted %d HF tensors (clip dual-tower)", len(sd))
    return params
