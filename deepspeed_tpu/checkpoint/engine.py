"""Checkpoint save/load with a fragment store.

TPU-native re-design of the reference checkpoint stack
(``runtime/engine.py:3109`` save / :2763 load, per-DP-rank ZeRO shard files
:3528, ``CheckpointEngine`` ABC ``runtime/checkpoint_engine/``, the offline
universal-checkpoint converter ``checkpoint/ds_to_universal.py:112`` and
shape-shifting loader ``checkpoint/universal_checkpoint.py:22``, and the
``zero_to_fp32.py`` consolidation script).

Instead of rank-indexed monolithic files that must be converted offline to
resume at a different parallelism degree, every leaf is stored as
**fragments with global index metadata**:

    <dir>/<tag>/manifest.json       # tree structure, shapes, dtypes, step…
    <dir>/<tag>/p<proc>_<n>.npy     # one fragment = one owned shard slice
    <dir>/latest                    # tag pointer (reference: `latest` file)

* save: each process writes the shards it owns (``replica_id == 0`` dedupe),
  recording each fragment's global slice. Multi-host safe, no gather.
* load: ``jax.make_array_from_callback`` assembles each target shard from
  overlapping fragments — ANY source↔target mesh/ZeRO-stage combination
  works, so elastic resume and universal checkpointing are the default
  behavior, not an offline tool.
* consolidate: reading all fragments yields full fp32 weights — the
  ``zero_to_fp32.py`` analog — without a training run.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding

from ..utils.logging import log_dist, logger

MANIFEST = "manifest.json"
LATEST = "latest"


# --------------------------------------------------------------------------
# path <-> string keys
# --------------------------------------------------------------------------

def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _index_to_slices(index, shape) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


# --------------------------------------------------------------------------
# save
# --------------------------------------------------------------------------

class HostShards:
    """Host-side snapshot of one (possibly sharded) array — the payload
    of an async save.  Captures exactly the fragments the synchronous
    writer would emit (addressable replica-0 shards), so the on-disk
    layout is identical whichever path wrote it."""

    def __init__(self, arr):
        self.shape = tuple(np.shape(arr))
        if isinstance(arr, jax.Array):
            self.dtype = arr.dtype
            self.shards = [(shard.index, np.asarray(shard.data))
                           for shard in arr.addressable_shards
                           if shard.replica_id == 0]
        else:
            a = np.asarray(arr)
            self.dtype = a.dtype
            # replicated/host leaf: process 0 writes it whole
            self.shards = ([(tuple(slice(0, d) for d in self.shape), a)]
                           if jax.process_index() == 0 else [])


def save_tree(tree: Any, ckpt_dir: str, extra_meta: Optional[Dict] = None) -> None:
    """Write a pytree of (possibly sharded, possibly multi-host) jax
    arrays — or of :class:`HostShards` snapshots (async path)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    proc = jax.process_index()
    # re-saving into an existing tag: clear stale fragments/manifests first
    # (a previous save from more processes would otherwise leak old
    # fragments into the merged manifest — silent corruption on load)
    if proc == 0:
        for fn in os.listdir(ckpt_dir):
            if fn.endswith(".npy") or fn.startswith("manifest"):
                os.remove(os.path.join(ckpt_dir, fn))
    _barrier()
    entries: Dict[str, Dict] = {}
    frag_n = 0
    for key, leaf in _leaf_paths(tree):
        if isinstance(leaf, HostShards):
            frags = []
            for index, data in leaf.shards:
                fname = f"p{proc}_{frag_n}.npy"
                frag_n += 1
                np.save(os.path.join(ckpt_dir, fname), data)
                frags.append({"file": fname,
                              "index": _index_to_slices(index,
                                                        leaf.shape)})
            if frags:
                entries[key] = {"shape": list(leaf.shape),
                                "dtype": str(leaf.dtype),
                                "fragments": frags}
            continue
        arr = jax.numpy.asarray(leaf) if np.isscalar(leaf) else leaf
        shape = tuple(np.shape(arr))
        dtype = str(np.asarray(arr).dtype if not hasattr(arr, "dtype")
                    else arr.dtype)
        frags = []
        if isinstance(arr, jax.Array):
            for shard in arr.addressable_shards:
                if shard.replica_id != 0:
                    continue
                fname = f"p{proc}_{frag_n}.npy"
                frag_n += 1
                np.save(os.path.join(ckpt_dir, fname),
                        np.asarray(shard.data))
                frags.append({"file": fname,
                              "index": _index_to_slices(shard.index, shape)})
        else:
            # replicated / host array: process 0 writes it whole
            if proc == 0:
                fname = f"p0_{frag_n}.npy"
                frag_n += 1
                np.save(os.path.join(ckpt_dir, fname), np.asarray(arr))
                frags.append({"file": fname,
                              "index": [[0, d] for d in shape]})
        if frags:
            entries[key] = {"shape": list(shape), "dtype": dtype,
                            "fragments": frags}

    # merge manifests across processes: each process writes its own partial
    # manifest; process 0 merges (single-host: trivial).
    part = os.path.join(ckpt_dir, f"manifest_p{proc}.json")
    with open(part, "w") as f:
        json.dump(entries, f)
    _barrier()
    if proc == 0:
        merged: Dict[str, Dict] = {}
        for fn in sorted(os.listdir(ckpt_dir)):
            if fn.startswith("manifest_p") and fn.endswith(".json"):
                with open(os.path.join(ckpt_dir, fn)) as f:
                    for k, v in json.load(f).items():
                        if k in merged:
                            merged[k]["fragments"].extend(v["fragments"])
                        else:
                            merged[k] = v
        treedef = jax.tree_util.tree_structure(tree)
        meta = {"leaves": merged,
                "treedef": str(treedef),
                "time": time.time(),
                **(extra_meta or {})}
        with open(os.path.join(ckpt_dir, MANIFEST), "w") as f:
            json.dump(meta, f, indent=1)
    _barrier()


def _barrier():
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu_ckpt")


# --------------------------------------------------------------------------
# load
# --------------------------------------------------------------------------

class _FragmentReader:
    """Assemble arbitrary global slices from saved fragments (memory-mapped)."""

    def __init__(self, ckpt_dir: str, entry: Dict):
        self.dir = ckpt_dir
        self.shape = tuple(entry["shape"])
        self.dtype = np.dtype(entry["dtype"])
        self.fragments = entry["fragments"]
        self._cache: Dict[str, np.ndarray] = {}

    def _frag(self, fname: str) -> np.ndarray:
        if fname not in self._cache:
            self._cache[fname] = np.load(os.path.join(self.dir, fname),
                                         mmap_mode="r")
        return self._cache[fname]

    def read(self, index: Tuple[slice, ...]) -> np.ndarray:
        """Read the global slice `index` by overlapping saved fragments."""
        tgt = _index_to_slices(index, self.shape)
        if not tgt:  # scalar
            return np.asarray(self._frag(self.fragments[0]["file"]))
        out_shape = tuple(b - a for a, b in tgt)
        out = np.empty(out_shape, self.dtype)
        filled = 0
        for frag in self.fragments:
            src = frag["index"]
            inter = [(max(a1, a2), min(b1, b2))
                     for (a1, b1), (a2, b2) in zip(tgt, src)]
            if any(a >= b for a, b in inter):
                continue
            dst_sel = tuple(slice(a - t[0], b - t[0])
                            for (a, b), t in zip(inter, tgt))
            src_sel = tuple(slice(a - s[0], b - s[0])
                            for (a, b), s in zip(inter, src))
            out[dst_sel] = self._frag(frag["file"])[src_sel]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled != int(np.prod(out_shape)):
            raise ValueError(
                f"Checkpoint fragments only cover {filled}/{np.prod(out_shape)} "
                f"elements of requested slice (corrupt or partial checkpoint)")
        return out


def load_tree(template: Any, shardings: Any, ckpt_dir: str,
              strict: bool = True) -> Tuple[Any, Dict]:
    """Load a pytree saved by :func:`save_tree` onto `shardings`.

    `template` supplies structure+shape+dtype (abstract or concrete).
    Returns (tree, manifest_meta).  Resharding/resize is implicit.
    """
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        meta = json.load(f)
    entries = meta["leaves"]

    keys_leaves = _leaf_paths(template)
    flat_shards = jax.tree.leaves(
        shardings, is_leaf=lambda x: isinstance(x, (NamedSharding,
                                                    jax.sharding.Sharding)))
    out_leaves = []
    for (key, leaf), sh in zip(keys_leaves, flat_shards):
        if key not in entries:
            if strict:
                raise KeyError(f"Checkpoint missing leaf {key}")
            out_leaves.append(leaf)
            continue
        entry = entries[key]
        shape = tuple(np.shape(leaf))
        if tuple(entry["shape"]) != shape:
            raise ValueError(
                f"Shape mismatch for {key}: ckpt {entry['shape']} vs {shape}")
        reader = _FragmentReader(ckpt_dir, entry)
        tgt_dtype = leaf.dtype if hasattr(leaf, "dtype") else reader.dtype

        def cb(index, reader=reader, tgt_dtype=tgt_dtype):
            return reader.read(index).astype(tgt_dtype)

        out_leaves.append(jax.make_array_from_callback(shape, sh, cb))
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, out_leaves), meta


def load_tree_host(template: Any, ckpt_dir: str,
                   strict: bool = True) -> Tuple[Any, Dict]:
    """Like :func:`load_tree` but assembles plain numpy arrays on the host
    (no device placement) — used by the ZeRO-Infinity path, whose fp32
    state must land on NVMe rather than in HBM."""
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        meta = json.load(f)
    entries = meta["leaves"]
    out_leaves = []
    for key, leaf in _leaf_paths(template):
        if key not in entries:
            if strict:
                raise KeyError(f"Checkpoint missing leaf {key}")
            out_leaves.append(leaf)
            continue
        entry = entries[key]
        shape = tuple(np.shape(leaf))
        if tuple(entry["shape"]) != shape:
            raise ValueError(
                f"Shape mismatch for {key}: ckpt {entry['shape']} vs {shape}")
        reader = _FragmentReader(ckpt_dir, entry)
        full = tuple(slice(0, d) for d in reader.shape)
        arr = reader.read(full)
        tgt = getattr(leaf, "dtype", None)
        out_leaves.append(arr.astype(tgt) if tgt is not None else arr)
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, out_leaves), meta


# --------------------------------------------------------------------------
# engine-level save/load (reference: engine.save_checkpoint :3109)
# --------------------------------------------------------------------------

def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None) -> str:
    tag = tag or f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, tag)
    state = engine.state
    extra = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "zero_stage": engine.zero.stage,
        "precision": engine.precision,
        "mesh": dict(engine.topology.axis_sizes),
        "client_state": client_state or {},
    }
    save_tree(state, ckpt_dir, extra_meta=extra)
    if jax.process_index() == 0:
        with open(os.path.join(save_dir, LATEST), "w") as f:
            f.write(tag)
    log_dist(f"saved checkpoint {ckpt_dir}")
    return ckpt_dir


class AsyncCheckpointSaver:
    """Background checkpoint writes (reference:
    ``runtime/checkpoint_engine/nebula_checkpoint_engine.py`` — tier-1
    async persistence).  The device state is snapshotted to host
    SYNCHRONOUSLY (donated buffers die at the next step, so the copy
    cannot be deferred), then serialization and the ``latest`` pointer
    update run on a worker thread while training continues.  At most one
    save is in flight; a new submit drains the previous one first."""

    def __init__(self):
        import atexit

        self._thread = None
        self._error = None
        # the final save of a run must land even if the script never
        # calls wait_checkpoint(): join at interpreter exit (the thread
        # is non-daemon anyway, but the join also surfaces errors)
        atexit.register(self._drain_silent)

    def _drain_silent(self):
        try:
            self.wait()
        except BaseException as e:          # best-effort at exit
            import sys
            # logging may already be torn down at interpreter exit
            print(f"async checkpoint failed at exit: {e!r}",  # tpulint: disable=print
                  file=sys.stderr)

    def submit(self, host_state, ckpt_dir: str, extra: Dict,
               save_dir: str, tag: str) -> None:
        import threading

        self.wait()

        def work():
            try:
                save_tree(host_state, ckpt_dir, extra_meta=extra)
                if jax.process_index() == 0:
                    # written only after every fragment landed — a crash
                    # mid-save can never point `latest` at a torn tag
                    with open(os.path.join(save_dir, LATEST), "w") as f:
                        f.write(tag)
                log_dist(f"async-saved checkpoint {ckpt_dir}")
            # deliberately deferred: re-raised to the caller on the next
            # wait()/submit(), so the failure is never lost
            except BaseException as e:  # tpulint: disable=silent-except
                # happens-before: wait() joins this thread before it
                # reads or clears _error, and submit() calls wait()
                # first, so at most one save thread is ever in flight —
                # the join is the synchronization edge a lock would add
                self._error = e  # tpulint: disable=shared-state-race

        self._thread = threading.Thread(target=work, daemon=False,
                                        name="async-ckpt")
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight save; re-raises its failure, if any."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def save_checkpoint_async(engine, saver: AsyncCheckpointSaver,
                          save_dir: str, tag: Optional[str] = None,
                          client_state: Optional[Dict] = None) -> str:
    """Non-blocking variant of :func:`save_checkpoint` (single-host:
    save_tree's multi-host barriers are device collectives that would
    race the training stream from a worker thread)."""
    if jax.process_count() > 1:
        raise RuntimeError("async checkpoint saves are single-host; "
                           "multi-host runs must save synchronously")
    tag = tag or f"global_step{engine.global_steps}"
    ckpt_dir = os.path.join(save_dir, tag)
    extra = {
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "zero_stage": engine.zero.stage,
        "precision": engine.precision,
        "mesh": dict(engine.topology.axis_sizes),
        "client_state": client_state or {},
    }
    # host snapshot of this process's addressable shards; fragments are
    # written from these, so the device buffers are free immediately
    # (the next step's donation would invalidate them)
    host_state = jax.tree.map(HostShards, engine.state)
    saver.submit(host_state, ckpt_dir, extra, save_dir, tag)
    return ckpt_dir


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None):
    if tag is None:
        latest = os.path.join(load_dir, LATEST)
        if not os.path.exists(latest):
            raise FileNotFoundError(f"No {LATEST} file in {load_dir}")
        with open(latest) as f:
            tag = f.read().strip()
    ckpt_dir = os.path.join(load_dir, tag)
    shardings = engine.state_shardings
    state, meta = load_tree(engine.state, shardings, ckpt_dir)
    engine.state = state
    engine.global_steps = int(meta.get("global_steps", 0))
    engine.global_samples = int(meta.get("global_samples", 0))
    log_dist(f"loaded checkpoint {ckpt_dir} (step {engine.global_steps})")
    return ckpt_dir, meta.get("client_state", {})


# --------------------------------------------------------------------------
# consolidation (reference: utils/zero_to_fp32.py)
# --------------------------------------------------------------------------

def consolidate(ckpt_dir: str, prefix: str = ".master") -> Dict[str, np.ndarray]:
    """Reassemble full (fp32) arrays from a fragment checkpoint — the
    ``zero_to_fp32.py`` analog, shape-agnostic by construction."""
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        meta = json.load(f)
    out = {}
    for key, entry in meta["leaves"].items():
        if prefix and prefix not in key:
            continue
        reader = _FragmentReader(ckpt_dir, entry)
        full = tuple(slice(0, d) for d in reader.shape)
        out[key] = reader.read(full)
    return out
