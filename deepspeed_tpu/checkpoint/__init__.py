from .engine import (save_tree, load_tree, save_checkpoint, load_checkpoint,
                     consolidate)

__all__ = ["save_tree", "load_tree", "save_checkpoint", "load_checkpoint",
           "consolidate"]
