from .compress import (CompressionScheduler, TechniqueSpec,
                       activation_quantization, head_pruning,
                       init_compression, redundancy_clean, row_pruning,
                       sparse_pruning, weight_quantization)

__all__ = ["CompressionScheduler", "TechniqueSpec", "init_compression",
           "redundancy_clean", "weight_quantization",
           "activation_quantization", "sparse_pruning", "row_pruning",
           "head_pruning"]
