"""Config-driven model compression: quantization, pruning, layer reduction.

TPU-native equivalent of the reference compression suite
(``compression/compress.py:100,148,192`` init_compression /
redundancy_clean; ``compression/basic_layer.py:121``
``LinearLayer_Compress`` with weight/activation quantization, sparse/row/
head pruning; ``compression/scheduler.py`` step-gated activation;
``compression/config.py`` the ``compression_training`` config block).

The reference wraps nn.Modules; here compression is a **pure function on
the param tree**: ``CompressionScheduler.apply(params, step)`` returns
compressed params, matching modules by parameter-path regex instead of
module name.  Quantization is straight-through (compress in forward,
dense master retained) — exactly the reference's QAT behavior where the
fp32 copy keeps training.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quant import dequantize, quantize
from ..utils.logging import logger


# ---- techniques (reference: basic_layer.py LinearLayer_Compress) ----------

def weight_quantization(w: jax.Array, bits: int = 8,
                        groups: int = 1) -> jax.Array:
    """Fake-quantize (quantize->dequantize) — QAT forward
    (reference: basic_layer.py weight quantization path)."""
    from ..ops.quant import default_groups
    groups = default_groups(w.size, max(1, w.size // max(1, groups)))
    return dequantize(quantize(w, bits=bits, num_groups=groups))


def activation_quantization(x: jax.Array, bits: int = 8) -> jax.Array:
    return dequantize(quantize(x, bits=bits, num_groups=1))


def sparse_pruning(w: jax.Array, ratio: float,
                   method: str = "l1") -> jax.Array:
    """Unstructured magnitude pruning (reference: basic_layer.py
    sparse_pruning, method l1/topk)."""
    if ratio <= 0:
        return w
    flat = jnp.abs(w.reshape(-1))
    k = int(flat.size * ratio)
    if k == 0:
        return w
    thresh = jnp.sort(flat)[k - 1]
    return jnp.where(jnp.abs(w) > thresh, w, 0).astype(w.dtype)


def row_pruning(w: jax.Array, ratio: float) -> jax.Array:
    """Structured row pruning by row L1 norm (reference: basic_layer.py
    row_pruning) — rows zeroed, shape kept (XLA-friendly static shapes)."""
    if ratio <= 0 or w.ndim < 2:
        return w
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))
    k = int(norms.size * ratio)
    if k == 0:
        return w
    thresh = jnp.sort(norms)[k - 1]
    mask = (norms > thresh).astype(w.dtype)
    return w * mask.reshape((-1,) + (1,) * (w.ndim - 1))


def head_pruning(w: jax.Array, num_heads: int, ratio: float) -> jax.Array:
    """Zero whole attention heads by head-block norm (reference:
    basic_layer.py head_pruning on the output projection)."""
    if ratio <= 0:
        return w
    d = w.shape[0]
    assert d % num_heads == 0, (d, num_heads)
    blocks = w.reshape(num_heads, d // num_heads, *w.shape[1:])
    norms = jnp.sum(jnp.abs(blocks), axis=tuple(range(1, blocks.ndim)))
    k = int(num_heads * ratio)
    if k == 0:
        return w
    thresh = jnp.sort(norms)[k - 1]
    mask = (norms > thresh).astype(w.dtype)
    return (blocks * mask.reshape((-1,) + (1,) * (blocks.ndim - 1))
            ).reshape(w.shape)


# ---- schedule (reference: compression/scheduler.py + config) --------------

@dataclass
class TechniqueSpec:
    """One technique applied to params matching ``pattern``."""
    pattern: str                       # regex on the param path
    method: str                        # quantize|sparse_prune|row_prune|head_prune
    schedule_offset: int = 0           # steps before it activates
    # method params
    bits: int = 8
    groups: int = 1
    ratio: float = 0.0
    num_heads: int = 1

    def apply(self, w: jax.Array) -> jax.Array:
        if self.method == "quantize":
            return weight_quantization(w, self.bits, self.groups)
        if self.method == "sparse_prune":
            return sparse_pruning(w, self.ratio)
        if self.method == "row_prune":
            return row_pruning(w, self.ratio)
        if self.method == "head_prune":
            return head_pruning(w, self.num_heads, self.ratio)
        raise ValueError(f"unknown compression method {self.method!r}")


def _specs_from_config(cc: Dict) -> List[TechniqueSpec]:
    """Translate the reference's ``compression_training`` config block
    (compression/config.py layout: technique -> shared_parameters +
    different_groups) into TechniqueSpecs."""
    key_map = {
        "weight_quantization": ("quantize", "wq1"),
        "sparse_pruning": ("sparse_prune", "sp1"),
        "row_pruning": ("row_prune", "rp1"),
        "head_pruning": ("head_prune", "hp1"),
    }
    specs: List[TechniqueSpec] = []
    for key, (method, _) in key_map.items():
        tech = cc.get(key)
        if not tech or not tech.get("shared_parameters", {}).get(
                "enabled", False):
            continue
        shared = tech.get("shared_parameters", {})
        offset = int(shared.get("schedule_offset", 0))
        for gname, group in (tech.get("different_groups") or {}).items():
            gp = group.get("params", {})
            modules = group.get("modules", ["*"])
            # reference configs carry dense_ratio = fraction KEPT;
            # TechniqueSpec.ratio is the fraction PRUNED
            if "dense_ratio" in gp:
                ratio = 1.0 - float(gp["dense_ratio"])
            else:
                ratio = float(gp.get("sparse_ratio", gp.get("ratio", 0.0)))
            if method != "quantize" and ratio <= 0:
                logger.warning(
                    "compression group %s/%s: no dense_ratio/ratio given "
                    "— pruning disabled for this group", key, gname)
            for mod in modules:
                pattern = ".*" if mod == "*" else mod.replace(
                    "*", ".*")
                specs.append(TechniqueSpec(
                    pattern=pattern, method=method,
                    schedule_offset=offset,
                    bits=int(gp.get("start_bits",
                                    gp.get("target_bits", 8))),
                    groups=int(gp.get("quantization_groups", 1)),
                    ratio=ratio,
                    num_heads=int(gp.get("num_heads", 1))))
    return specs


class CompressionScheduler:
    """Applies techniques whose schedule_offset has passed
    (reference: compression/scheduler.py CompressionScheduler)."""

    def __init__(self, specs: Sequence[TechniqueSpec]):
        self.specs = list(specs)

    @classmethod
    def from_config(cls, compression_config: Dict) -> "CompressionScheduler":
        return cls(_specs_from_config(compression_config or {}))

    def active(self, step: int) -> List[TechniqueSpec]:
        return [s for s in self.specs if step >= s.schedule_offset]

    def apply(self, params: Any, step: int) -> Any:
        active = self.active(step)
        if not active:
            return params

        def leaf(path, w):
            name = "/".join(str(getattr(p, "key", getattr(p, "idx", "")))
                            for p in path)
            for s in active:
                if np.ndim(w) >= 1 and re.search(s.pattern, name):
                    w = s.apply(w)
            return w

        return jax.tree_util.tree_map_with_path(leaf, params)


def init_compression(params: Any, ds_config: Dict) -> CompressionScheduler:
    """(reference: compress.py:100 init_compression — returns the wrapped
    model; here: the scheduler to call inside your loss fn or step)."""
    cc = ds_config.get("compression_training", {})
    sched = CompressionScheduler.from_config(cc)
    logger.info("compression: %d technique spec(s)", len(sched.specs))
    return sched


def redundancy_clean(params: Any, ds_config: Dict,
                     step: int = 10**9) -> Any:
    """Bake all compression into the weights for deployment
    (reference: compress.py:148 redundancy_clean)."""
    return CompressionScheduler.from_config(
        ds_config.get("compression_training", {})).apply(params, step)


# --------------------------------------------------------------------------
# Layer reduction + distillation init (reference: compress.py:119
# init_compression layer_reduction branch, :192 student_initialization;
# config.py LAYER_REDUCTION keep_number_layer/teacher_layer)
# --------------------------------------------------------------------------

def student_initialization(student_params: Any, teacher_params: Any,
                           ds_config: Dict) -> Any:
    """Initialize a depth-reduced student from chosen teacher layers.

    The stacked-blocks layout makes the reference's per-module copy loop
    (student_initialization compress.py:192-230) a single gather on the
    leading layers dim: ``blocks[teacher_layer]``.  Embeddings, final
    norm, and any other non-block leaves are copied whole.

    Config (reference: config.py layer_reduction)::

        {"compression_training": {"layer_reduction": {
            "enabled": true,
            "keep_number_layer": 6,
            "teacher_layer": [1, 3, 5, 7, 9, 11]   # default: even spread
        }}}
    """
    lr = (ds_config.get("compression_training", {})
          .get("layer_reduction", {}))
    if not lr.get("enabled", False):
        raise ValueError("layer_reduction.enabled must be true")
    t_blocks = teacher_params["blocks"]
    n_teacher = jax.tree.leaves(t_blocks)[0].shape[0]
    keep = int(lr.get("keep_number_layer",
                      jax.tree.leaves(student_params["blocks"])[0].shape[0]))
    layers = lr.get("teacher_layer")
    if layers is None:
        # even spread, biased to later layers (reference default keeps
        # a contiguous prefix; the spread matches common KD practice)
        layers = np.linspace(0, n_teacher - 1, keep).round().astype(int)
    layers = np.asarray(layers, np.int32)
    n_student = jax.tree.leaves(student_params["blocks"])[0].shape[0]
    if keep != n_student:
        raise ValueError(f"keep_number_layer={keep} but the student has "
                         f"{n_student} layers")
    if len(layers) != keep:
        raise ValueError(f"teacher_layer has {len(layers)} entries but "
                         f"keep_number_layer={keep}")
    if layers.min() < 0 or layers.max() >= n_teacher:
        raise ValueError(f"teacher_layer {layers.tolist()} out of range "
                         f"({n_teacher} teacher layers)")

    out = {k: v for k, v in student_params.items()}
    out["blocks"] = jax.tree.map(lambda w: w[layers], t_blocks)
    for k in student_params:
        if k == "blocks":
            continue
        if k in teacher_params:
            ts = jax.tree.map(np.shape, teacher_params[k])
            ss = jax.tree.map(np.shape, student_params[k])
            if ts == ss:
                out[k] = teacher_params[k]
    logger.info("student initialized from teacher layers %s",
                layers.tolist())
    return out


def kd_loss(student_logits: jax.Array, teacher_logits: jax.Array,
            temperature: float = 1.0) -> jax.Array:
    """Distillation soft cross-entropy — KL(teacher-softened || student)
    up to the teacher-entropy constant — the loss the layer-reduced
    student trains against (DeepSpeed compression tutorial pairing;
    reference ships the init, examples ship the loss)."""
    t = temperature
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    p = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    return -(p * s).sum(axis=-1).mean() * (t * t)
