from .mesh import (MeshTopology, AXIS_ORDER, PIPE_AXIS, DATA_AXIS, FSDP_AXIS,
                   EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS, BATCH_AXES)
from .collectives import (Collectives, init_distributed, get_world_size,
                          get_rank, log_summary, lax_psum, lax_pmean,
                          lax_all_gather, lax_reduce_scatter, lax_all_to_all,
                          lax_ppermute)
from .comms_logging import comms_logger, CommsLogger, calc_bw_log
from .overlap import (ServingComm, overlapped_matmul_allreduce,
                      overlapped_matmul_allgather, overlapped_all_reduce,
                      overlapped_reduce_scatter, ring_all_gather,
                      ring_all_reduce, ring_reduce_scatter, wire_bytes)

__all__ = [
    "ServingComm", "overlapped_matmul_allreduce",
    "overlapped_matmul_allgather", "overlapped_all_reduce",
    "overlapped_reduce_scatter", "ring_all_gather", "ring_all_reduce",
    "ring_reduce_scatter", "wire_bytes",
    "MeshTopology", "AXIS_ORDER", "PIPE_AXIS", "DATA_AXIS", "FSDP_AXIS",
    "EXPERT_AXIS", "SEQ_AXIS", "TENSOR_AXIS", "BATCH_AXES",
    "Collectives", "init_distributed", "get_world_size", "get_rank",
    "log_summary", "lax_psum", "lax_pmean", "lax_all_gather",
    "lax_reduce_scatter", "lax_all_to_all", "lax_ppermute",
    "comms_logger", "CommsLogger", "calc_bw_log",
]
