"""Collective-sweep microbenchmark CLI — the ``ds_bench`` analog.

Reference: ``bin/ds_bench`` driving the communication benchmark suite
(all_reduce/all_gather/reduce_scatter/all_to_all/broadcast over a
doubling message-size sweep, reporting latency + algbw/busbw per size —
the pod-bringup tool).  TPU-native: collectives run as jitted ``psum``/
``all_gather``/``psum_scatter``/``all_to_all`` over a named mesh axis,
so the sweep measures exactly the XLA collectives training uses, on ICI
when the axis spans a slice and on DCN when it spans hosts.

Usage (single host, all local devices)::

    python -m deepspeed_tpu.comm.bench --ops all_reduce,all_gather \
        --maxsize 28 --trials 20

Multi-host: launch one process per host with the runner
(``python -m deepspeed_tpu.launcher.runner --hostfile ...``); the mesh
then spans the pod and the sweep exercises the cross-host fabric.

Timing barrier: a scalar fetch after ``block_until_ready`` — on
tunneled/virtualized chips ``block_until_ready`` alone is advisory.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..compat import shard_map
from .comms_logging import calc_bw_log, convert_size

OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
       "broadcast")


def _build_op(op: str, mesh, axis: str):
    """One jitted collective over ``axis``; input sharded on dim 0 for
    the scatter/gather family, replicated for all_reduce/broadcast."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis))

    def wrap(body, in_spec):
        f = shard_map(body, mesh=mesh, in_specs=in_spec,
                          out_specs=in_spec, check_vma=False)
        return jax.jit(f), (repl if in_spec == P() else shard)

    if op == "all_reduce":
        def body(x):
            with jax.named_scope(f"bench_all_reduce_{axis}"):
                return jax.lax.psum(x, axis)
        return wrap(body, P())
    if op == "all_gather":
        # per-device shard -> full tensor, then keep the local slice so
        # input/output specs match (steady-state ZeRO gather shape)
        def body(x):
            with jax.named_scope(f"bench_all_gather_{axis}"):
                g = jax.lax.all_gather(x, axis, tiled=True)
            return jax.lax.dynamic_slice_in_dim(
                g, jax.lax.axis_index(axis) * x.shape[0], x.shape[0])
        return wrap(body, P(axis))
    if op == "reduce_scatter":
        def body(x):
            with jax.named_scope(f"bench_reduce_scatter_{axis}"):
                s = jax.lax.psum_scatter(x, axis, scatter_dimension=0,
                                         tiled=True)
            return jnp.concatenate([s] * n, axis=0)
        return wrap(body, P(axis))
    if op == "all_to_all":
        def body(x):
            with jax.named_scope(f"bench_all_to_all_{axis}"):
                return jax.lax.all_to_all(
                    x.reshape(n, -1), axis, split_axis=0, concat_axis=0,
                    tiled=False).reshape(x.shape)
        return wrap(body, P(axis))
    if op == "broadcast":
        def body(x):
            with jax.named_scope(f"bench_broadcast_{axis}"):
                root = jnp.where(jax.lax.axis_index(axis) == 0, x,
                                 jnp.zeros_like(x))
                return jax.lax.psum(root, axis)
        return wrap(body, P())
    raise ValueError(f"unknown op {op!r} (choose from {OPS})")


def sweep(ops: List[str], min_pow: int = 12, max_pow: int = 26,
          trials: int = 10, warmups: int = 3, dtype: str = "bfloat16",
          axis: str = "x", mesh=None,
          print_table: bool = True) -> List[Dict]:
    """Run the sweep; returns one record per (op, size) with latency
    and algbw/busbw in Gbps (NCCL-style accounting)."""
    dt = jnp.dtype(dtype)
    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = jax.sharding.Mesh(devs, (axis,))
    n = mesh.shape[axis]
    out: List[Dict] = []
    for op in ops:
        fn, in_sh = _build_op(op, mesh, axis)
        if print_table:  # tpulint: disable-file=print — bench CLI table output
            print(f"\n---- {op} over {n} devices "
                  f"({jax.devices()[0].platform}) ----")
            print(f"{'size':>10} {'latency':>12} {'algbw Gbps':>12} "
                  f"{'busbw Gbps':>12}")
        for p in range(min_pow, max_pow + 1):
            nbytes = 1 << p
            elems = max(n * n, nbytes // dt.itemsize)
            # reduce_scatter/all_to_all split the LOCAL shard n ways
            # again, so round to a multiple of n^2 (matters on
            # non-power-of-two meshes)
            elems = (elems // (n * n)) * (n * n)
            x = jax.device_put(
                jnp.ones((elems,), dt), in_sh)
            for _ in range(warmups):
                x = fn(x)
            jax.block_until_ready(x)
            float(jnp.sum(x[:1]))           # real barrier (tunnel-safe)
            t0 = time.perf_counter()
            for _ in range(trials):
                x = fn(x)
            jax.block_until_ready(x)
            float(jnp.sum(x[:1]))
            lat = (time.perf_counter() - t0) / trials
            size_bytes = elems * dt.itemsize
            algbw, busbw = calc_bw_log(op, size_bytes, lat, n)
            # 4 decimals: sub-0.01 Gbps links (emulated meshes, tunneled
            # chips) must not quantize to a 0.0 record
            rec = dict(op=op, bytes=size_bytes, latency_us=lat * 1e6,
                       algbw_gbps=round(algbw, 4),
                       busbw_gbps=round(busbw, 4), devices=n)
            out.append(rec)
            if print_table:
                print(f"{convert_size(size_bytes):>10} "
                      f"{lat * 1e6:>10.1f}us {algbw:>12.2f} "
                      f"{busbw:>12.2f}")
    return out


def overlap_bench(mesh=None, axis: str = "x", rows: int = 256,
                  k: int = 4096, nmodel: int = 1024, tiles: int = 4,
                  trials: int = 20, warmups: int = 3,
                  dtype: str = "float32",
                  profile_dir: Optional[str] = None) -> Dict:
    """Overlapped-vs-serial matmul+allreduce microbench — the T3 leg
    (arxiv 2401.16677) the multichip driver and bench.py record.

    One row-parallel GEMM ([rows, k] x [k, nmodel], contraction sharded
    over ``axis``) under four comm plans: serial psum (the GSPMD
    shape), tile-decomposed psum (``tiles`` tiles — exact, bitwise),
    tile-decomposed ppermute ring, and tile-decomposed + int8 quantized
    wire (EQuARX, arxiv 2506.17615).  Values are cross-checked before
    timing (exact plans bitwise vs serial; the quantized plan within
    its error bound), so a bench capture that would publish wrong
    numerics fails instead.

    Returns benchdiff-gateable metrics (``*_ms`` down-is-better,
    ``*_speedup`` up) plus the modeled wire-byte halving.  With
    ``profile_dir``, the timed overlapped run executes inside a
    ``jax.profiler`` trace so ``tools/tracemerge`` can render the tile
    scopes against the GEMM device activity."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .overlap import (overlapped_matmul_allreduce, wire_bytes)

    dt = jnp.dtype(dtype)
    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = jax.sharding.Mesh(devs, (axis,))
    n = mesh.shape[axis]
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(rows, k), dt)
    w = jnp.asarray(rng.randn(k, nmodel), dt)
    x = jax.device_put(x, NamedSharding(mesh, P(None, axis)))
    w = jax.device_put(w, NamedSharding(mesh, P(axis, None)))

    def build(fn):
        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(P(None, axis), P(axis, None)),
            out_specs=P(), check_vma=False))

    def serial_body(a, b):
        with jax.named_scope("serial_mm_ar"):
            return jax.lax.psum((a @ b).astype(dt), axis)

    plans = {
        "serial": build(serial_body),
        "overlapped": build(lambda a, b: overlapped_matmul_allreduce(
            a, b, axis, tiles=tiles)),
        "ring": build(lambda a, b: overlapped_matmul_allreduce(
            a, b, axis, tiles=tiles, strategy="ring")),
        "quant": build(lambda a, b: overlapped_matmul_allreduce(
            a, b, axis, tiles=tiles, quant_bits=8)),
    }
    # numerics gate before timing — EVERY rung: exact plans bitwise,
    # the ring close (same summands, rotated rounding order), quant
    # inside its error bound
    ref = np.asarray(plans["serial"](x, w))
    if not np.array_equal(np.asarray(plans["overlapped"](x, w)), ref):
        raise AssertionError("overlapped plan is not bitwise-equal to "
                             "the serial all-reduce")
    if not np.allclose(np.asarray(plans["ring"](x, w)), ref,
                       rtol=1e-4, atol=1e-4):
        raise AssertionError("ring plan diverged from the serial "
                             "all-reduce beyond rounding order")
    bound = n * np.abs(ref).max() / 127.0 + 1e-6
    if np.abs(np.asarray(plans["quant"](x, w)) - ref).max() > bound:
        raise AssertionError("quantized plan exceeded its error bound")

    out: Dict = {"devices": int(n), "rows": rows, "k": k, "n": nmodel,
                 "tiles": tiles, "dtype": str(dt)}
    for name, fn in plans.items():
        y = fn(x, w)
        for _ in range(warmups):
            y = fn(x, w)
        jax.block_until_ready(y)
        float(jnp.sum(y[:1]))           # real barrier (tunnel-safe)
        prof = (jax.profiler.trace(profile_dir)
                if profile_dir and name == "overlapped" else None)
        if prof is not None:
            prof.__enter__()
        t0 = time.perf_counter()
        for _ in range(trials):
            y = fn(x, w)
        jax.block_until_ready(y)
        float(jnp.sum(y[:1]))
        ms = (time.perf_counter() - t0) / trials * 1e3
        if prof is not None:
            prof.__exit__(None, None, None)
        out[f"comm_{name}_ms"] = round(ms, 4)
    for name, metric in (("overlapped", "comm_overlap_speedup"),
                         ("ring", "comm_ring_speedup"),
                         ("quant", "comm_quant_speedup")):
        out[metric] = round(
            out["comm_serial_ms"] / max(out[f"comm_{name}_ms"], 1e-9), 4)
    out["wire_bytes_exact"] = wire_bytes(
        "all_reduce", rows * nmodel, dt.itemsize, n)
    out["wire_bytes_quant"] = wire_bytes(
        "all_reduce", rows * nmodel, dt.itemsize, n, quant_bits=8)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="deepspeed_tpu.comm.bench",
        description="collective sweep microbenchmark (ds_bench analog)")
    ap.add_argument("--ops", default="all_reduce",
                    help=f"comma list from {','.join(OPS)} or 'all'")
    ap.add_argument("--minsize", type=int, default=12,
                    help="log2 of smallest message bytes")
    ap.add_argument("--maxsize", type=int, default=26,
                    help="log2 of largest message bytes")
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--warmups", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line per record instead of a table")
    ap.add_argument("--multihost", action="store_true",
                    help="call jax.distributed.initialize() first "
                         "(under the launcher/runner env)")
    ap.add_argument("--overlap", action="store_true",
                    help="run the overlapped-vs-serial matmul+allreduce "
                         "leg (T3) instead of the op sweep")
    ap.add_argument("--tiles", type=int, default=4)
    ap.add_argument("--profile-dir", default=None,
                    help="with --overlap: jax.profiler trace dir for "
                         "the overlapped timed run")
    args = ap.parse_args(argv)
    if args.multihost:
        jax.distributed.initialize()
    if args.overlap:
        rec = overlap_bench(tiles=args.tiles, trials=args.trials,
                            warmups=args.warmups, dtype=args.dtype,
                            profile_dir=args.profile_dir)
        print(json.dumps(rec))  # tpulint: disable=print — the leg's one JSON line
        return 0
    ops = list(OPS) if args.ops == "all" else args.ops.split(",")
    recs = sweep(ops, args.minsize, args.maxsize, args.trials,
                 args.warmups, args.dtype, print_table=not args.json)
    if args.json:
        for r in recs:
            print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
