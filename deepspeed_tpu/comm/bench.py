"""Collective-sweep microbenchmark CLI — the ``ds_bench`` analog.

Reference: ``bin/ds_bench`` driving the communication benchmark suite
(all_reduce/all_gather/reduce_scatter/all_to_all/broadcast over a
doubling message-size sweep, reporting latency + algbw/busbw per size —
the pod-bringup tool).  TPU-native: collectives run as jitted ``psum``/
``all_gather``/``psum_scatter``/``all_to_all`` over a named mesh axis,
so the sweep measures exactly the XLA collectives training uses, on ICI
when the axis spans a slice and on DCN when it spans hosts.

Usage (single host, all local devices)::

    python -m deepspeed_tpu.comm.bench --ops all_reduce,all_gather \
        --maxsize 28 --trials 20

Multi-host: launch one process per host with the runner
(``python -m deepspeed_tpu.launcher.runner --hostfile ...``); the mesh
then spans the pod and the sweep exercises the cross-host fabric.

Timing barrier: a scalar fetch after ``block_until_ready`` — on
tunneled/virtualized chips ``block_until_ready`` alone is advisory.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..compat import shard_map
from .comms_logging import calc_bw_log, convert_size

OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
       "broadcast")


def _build_op(op: str, mesh, axis: str):
    """One jitted collective over ``axis``; input sharded on dim 0 for
    the scatter/gather family, replicated for all_reduce/broadcast."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis))

    def wrap(body, in_spec):
        f = shard_map(body, mesh=mesh, in_specs=in_spec,
                          out_specs=in_spec, check_vma=False)
        return jax.jit(f), (repl if in_spec == P() else shard)

    if op == "all_reduce":
        return wrap(lambda x: jax.lax.psum(x, axis), P())
    if op == "all_gather":
        # per-device shard -> full tensor, then keep the local slice so
        # input/output specs match (steady-state ZeRO gather shape)
        def body(x):
            g = jax.lax.all_gather(x, axis, tiled=True)
            return jax.lax.dynamic_slice_in_dim(
                g, jax.lax.axis_index(axis) * x.shape[0], x.shape[0])
        return wrap(body, P(axis))
    if op == "reduce_scatter":
        def body(x):
            s = jax.lax.psum_scatter(x, axis, scatter_dimension=0,
                                     tiled=True)
            return jnp.concatenate([s] * n, axis=0)
        return wrap(body, P(axis))
    if op == "all_to_all":
        return wrap(lambda x: jax.lax.all_to_all(
            x.reshape(n, -1), axis, split_axis=0, concat_axis=0,
            tiled=False).reshape(x.shape), P(axis))
    if op == "broadcast":
        def body(x):
            root = jnp.where(jax.lax.axis_index(axis) == 0, x,
                             jnp.zeros_like(x))
            return jax.lax.psum(root, axis)
        return wrap(body, P())
    raise ValueError(f"unknown op {op!r} (choose from {OPS})")


def sweep(ops: List[str], min_pow: int = 12, max_pow: int = 26,
          trials: int = 10, warmups: int = 3, dtype: str = "bfloat16",
          axis: str = "x", mesh=None,
          print_table: bool = True) -> List[Dict]:
    """Run the sweep; returns one record per (op, size) with latency
    and algbw/busbw in Gbps (NCCL-style accounting)."""
    dt = jnp.dtype(dtype)
    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = jax.sharding.Mesh(devs, (axis,))
    n = mesh.shape[axis]
    out: List[Dict] = []
    for op in ops:
        fn, in_sh = _build_op(op, mesh, axis)
        if print_table:  # tpulint: disable-file=print — bench CLI table output
            print(f"\n---- {op} over {n} devices "
                  f"({jax.devices()[0].platform}) ----")
            print(f"{'size':>10} {'latency':>12} {'algbw Gbps':>12} "
                  f"{'busbw Gbps':>12}")
        for p in range(min_pow, max_pow + 1):
            nbytes = 1 << p
            elems = max(n * n, nbytes // dt.itemsize)
            # reduce_scatter/all_to_all split the LOCAL shard n ways
            # again, so round to a multiple of n^2 (matters on
            # non-power-of-two meshes)
            elems = (elems // (n * n)) * (n * n)
            x = jax.device_put(
                jnp.ones((elems,), dt), in_sh)
            for _ in range(warmups):
                x = fn(x)
            jax.block_until_ready(x)
            float(jnp.sum(x[:1]))           # real barrier (tunnel-safe)
            t0 = time.perf_counter()
            for _ in range(trials):
                x = fn(x)
            jax.block_until_ready(x)
            float(jnp.sum(x[:1]))
            lat = (time.perf_counter() - t0) / trials
            size_bytes = elems * dt.itemsize
            algbw, busbw = calc_bw_log(op, size_bytes, lat, n)
            # 4 decimals: sub-0.01 Gbps links (emulated meshes, tunneled
            # chips) must not quantize to a 0.0 record
            rec = dict(op=op, bytes=size_bytes, latency_us=lat * 1e6,
                       algbw_gbps=round(algbw, 4),
                       busbw_gbps=round(busbw, 4), devices=n)
            out.append(rec)
            if print_table:
                print(f"{convert_size(size_bytes):>10} "
                      f"{lat * 1e6:>10.1f}us {algbw:>12.2f} "
                      f"{busbw:>12.2f}")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="deepspeed_tpu.comm.bench",
        description="collective sweep microbenchmark (ds_bench analog)")
    ap.add_argument("--ops", default="all_reduce",
                    help=f"comma list from {','.join(OPS)} or 'all'")
    ap.add_argument("--minsize", type=int, default=12,
                    help="log2 of smallest message bytes")
    ap.add_argument("--maxsize", type=int, default=26,
                    help="log2 of largest message bytes")
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--warmups", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line per record instead of a table")
    ap.add_argument("--multihost", action="store_true",
                    help="call jax.distributed.initialize() first "
                         "(under the launcher/runner env)")
    args = ap.parse_args(argv)
    if args.multihost:
        jax.distributed.initialize()
    ops = list(OPS) if args.ops == "all" else args.ops.split(",")
    recs = sweep(ops, args.minsize, args.maxsize, args.trials,
                 args.warmups, args.dtype, print_table=not args.json)
    if args.json:
        for r in recs:
            print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
