"""T3-style decomposed collectives + EQuARX-style quantized allreduce.

The multi-chip hot paths (TP serving, ZeRO gradient sync) spend their
collectives *serially* with compute: GSPMD inserts one monolithic
all-reduce after each row-parallel GEMM and one reduce-scatter per
gradient leaf, and nothing else can run while it drains.  T3
(arxiv 2401.16677) hides that wire time by decomposing each collective
into tiles whose communication carries no data dependency on the next
tile's GEMM — XLA's scheduler is then free to run tile *i*'s reduction
behind tile *i+1*'s matmul.  EQuARX (arxiv 2506.17615) stacks a second
win on top: quantizing the all-reduce payload inside the program is a
near-free 2x (int8) / 4x (int4) on the wire.

Everything here is written to run **inside shard_map** (manual mesh
axes); the ``shard_*`` entry points at the bottom wrap the tiled bodies
in a full-manual ``shard_map`` for use from GSPMD-sharded jit programs
(the serving forward).  Every comm stage carries a ``jax.named_scope``
label so ``tools/tracemerge.py`` renders the tile chain as distinct
device slices next to the GEMMs they overlap (the measurement bar for
this whole module).

The exactness ladder (docs/SERVING.md "Overlapped & quantized
collectives"):

* ``strategy="psum"`` (default) — per-tile ``lax.psum`` /
  ``psum_scatter``.  Collective reduction is elementwise, and splitting
  rows into tiles does not change any element's cross-rank reduction
  order, so the result is **bitwise-identical** to the serial baseline
  (asserted by tests on 1-chip and 8-device meshes).
* ``strategy="ring"`` — explicit ppermute ring (reduce-scatter +
  all-gather hops).  Exact arithmetic over the same summands, but the
  per-destination accumulation order is a ring rotation, so results can
  differ from ``psum`` in the last ulp.  Maximum scheduling freedom —
  each 1/n-sized hop is its own schedulable op.
* ``quant_bits=8|4`` — quantized wire (grouped int8/int4 payloads,
  ``ops/quant.py``).  Error-bounded, not exact; the bound is asserted
  in tests and documented.  Gather-only collectives (the unembed's
  logits all-gather) never quantize — pure data movement stays bitwise.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..compat import axis_size, shard_map

STRATEGIES = ("psum", "ring")


def _resolve_tiles(rows: int, tiles: int) -> int:
    """Largest tile count <= ``tiles`` that divides ``rows``."""
    t = max(1, min(int(tiles), int(rows) or 1))
    while rows % t:
        t -= 1
    return t


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


# --------------------------------------------------------------------------
# ring primitives (ppermute chains)
# --------------------------------------------------------------------------

def ring_all_gather(x, axis_name: str, axis: int = 0,
                    scope: str = "ring_ag"):
    """All-gather along ``axis`` as an n-1 hop ppermute chain.

    Pure data movement — bitwise-identical to
    ``lax.all_gather(..., tiled=True)`` — but each hop is its own
    schedulable op, so XLA can interleave the chain with unrelated
    compute.  After ``s`` rotations rank ``r`` holds rank ``r-s``'s
    shard; the stack is rolled into absolute-rank order before the
    concat so every rank assembles the same layout."""
    n = axis_size(axis_name)
    if n == 1:
        return x
    parts = [x]
    cur = x
    perm = _ring_perm(n)
    for s in range(n - 1):
        with jax.named_scope(f"{scope}_hop{s}"):
            cur = jax.lax.ppermute(cur, axis_name, perm)
        parts.append(cur)
    st = jnp.stack(parts)                      # slot s <- rank (r - s)
    r = jax.lax.axis_index(axis_name)
    st = st[(r - jnp.arange(n)) % n]           # absolute-rank order
    return jnp.moveaxis(st, 0, axis).reshape(
        x.shape[:axis] + (n * x.shape[axis],) + x.shape[axis + 1:])


def ring_reduce_scatter(x, axis_name: str, scatter_dim: int = 0,
                        scope: str = "ring_rs"):
    """Classic ring reduce-scatter: the partial destined for each rank
    travels the ring accumulating every rank's chunk — n-1 hops of
    1/n-sized payload (the bandwidth-optimal wire pattern).  EXACT
    arithmetic over the same summands as ``psum_scatter``, but the
    accumulation order is a ring rotation, so the result need not be
    bit-identical to it (exactness ladder, docs/SERVING.md)."""
    n = axis_size(axis_name)
    if n == 1:
        return x
    if scatter_dim != 0:
        x = jnp.moveaxis(x, scatter_dim, 0)
    D = x.shape[0]
    assert D % n == 0, (x.shape, n)
    chunks = x.reshape(n, D // n, *x.shape[1:])
    r = jax.lax.axis_index(axis_name)
    perm = _ring_perm(n)
    # the partial for destination d starts at rank d+1 and accumulates
    # chunks_j[d] at every rank j it visits, landing home after n-1 hops
    acc = chunks[(r - 1) % n]
    for s in range(1, n):
        with jax.named_scope(f"{scope}_hop{s - 1}"):
            acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + chunks[(r - 1 - s) % n]
    if scatter_dim != 0:
        acc = jnp.moveaxis(acc, 0, scatter_dim)
    return acc


def ring_all_reduce(x, axis_name: str, scope: str = "ring_ar"):
    """Ring allreduce = ring reduce-scatter + ring all-gather over the
    flattened (zero-padded to a multiple of n) payload — 2(n-1)/n of
    the data on the wire, every hop independently schedulable."""
    n = axis_size(axis_name)
    if n == 1:
        return x
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    red = ring_reduce_scatter(flat, axis_name, scope=scope)
    out = ring_all_gather(red, axis_name, scope=scope)
    if pad:
        out = out[:x.size]
    return out.reshape(x.shape)


# --------------------------------------------------------------------------
# tiled (overlappable) collectives
# --------------------------------------------------------------------------

def _tile_all_reduce(p, axis_name: str, strategy: str,
                     quant_bits: Optional[int], scope: str):
    """One tile's partial-sum reduction on the chosen rung of the
    exactness ladder."""
    if quant_bits:
        from ..ops.quant import quantized_all_reduce
        with jax.named_scope(f"{scope}_qar{quant_bits}"):
            return quantized_all_reduce(p, axis_name, bits=quant_bits,
                                        pad=True)
    if strategy == "ring":
        return ring_all_reduce(p, axis_name, scope=scope)
    with jax.named_scope(f"{scope}_ar"):
        return jax.lax.psum(p, axis_name)


def overlapped_matmul_allreduce(x, w, axis_name: str, tiles: int = 4,
                                strategy: str = "psum",
                                quant_bits: Optional[int] = None,
                                out_dtype=None,
                                scope: str = "t3_mm_ar"):
    """Row-parallel matmul + allreduce, tile-decomposed T3-style.

    Call INSIDE shard_map.  ``x``: [rows, K_local]; ``w``: [K_local, N]
    — this rank's contraction shard.  The row dim splits into ``tiles``
    tiles; tile *i*'s partial-sum reduction carries no dependency on
    tile *i+1*'s GEMM, so XLA may co-schedule them (the named scopes
    make the interleaving visible in a merged tracemerge timeline).

    ``strategy="psum"`` is bitwise-identical to the serial
    ``psum(x @ w)`` for any tile count; see the module docstring's
    exactness ladder for "ring" and ``quant_bits``."""
    assert strategy in STRATEGIES, strategy
    dt = out_dtype or x.dtype
    rows = x.shape[0]
    t = _resolve_tiles(rows, tiles)
    step = rows // t
    outs = []
    for i in range(t):
        with jax.named_scope(f"{scope}_gemm_t{i}"):
            p = (x[i * step:(i + 1) * step] @ w.astype(dt)).astype(dt)
        outs.append(_tile_all_reduce(p, axis_name, strategy, quant_bits,
                                     f"{scope}_comm_t{i}"))
    return outs[0] if t == 1 else jnp.concatenate(outs, axis=0)


def overlapped_matmul_allgather(x, w, axis_name: str, tiles: int = 4,
                                out_dtype=None,
                                scope: str = "t3_mm_ag"):
    """Column-parallel matmul + all-gather (the unembed shape),
    tile-decomposed.

    Call INSIDE shard_map.  ``x``: [rows, K] (replicated contraction);
    ``w``: [K, N_local].  Tile *i*'s ppermute gather chain overlaps tile
    *i+1*'s GEMM.  The gather is pure data movement, so the result is
    bitwise-identical to the serial GSPMD matmul + all-gather for any
    tile count — which is why the logits gather never quantizes (a
    perturbed logit could flip a greedy argmax)."""
    dt = out_dtype or x.dtype
    rows = x.shape[0]
    t = _resolve_tiles(rows, tiles)
    step = rows // t
    outs = []
    for i in range(t):
        with jax.named_scope(f"{scope}_gemm_t{i}"):
            p = (x[i * step:(i + 1) * step] @ w.astype(dt)).astype(dt)
        outs.append(ring_all_gather(p, axis_name, axis=1,
                                    scope=f"{scope}_comm_t{i}"))
    return outs[0] if t == 1 else jnp.concatenate(outs, axis=0)


def overlapped_all_reduce(x, axis_name: str, tiles: int = 4,
                          strategy: str = "psum",
                          quant_bits: Optional[int] = None,
                          scope: str = "t3_ar"):
    """Tiled allreduce for replicated leaves (ZeRO grad sync of leaves
    no mesh axis owns).  Tiles along dim 0 when it divides; scalars and
    indivisible leaves run as one tile."""
    assert strategy in STRATEGIES, strategy
    if x.ndim == 0:
        # a scalar has no quantization group or ring chunk; the exact
        # psum stands in on every rung of the ladder
        with jax.named_scope(f"{scope}_ar"):
            return jax.lax.psum(x, axis_name)
    t = _resolve_tiles(x.shape[0], tiles)
    step = x.shape[0] // t
    outs = [_tile_all_reduce(x[i * step:(i + 1) * step], axis_name,
                             strategy, quant_bits, f"{scope}_t{i}")
            for i in range(t)]
    return outs[0] if t == 1 else jnp.concatenate(outs, axis=0)


def _rs_tile_dim(shape, scatter_dim: int, tiles: int) -> Optional[int]:
    """Largest dim other than ``scatter_dim`` that ``tiles`` divides —
    tiling along the scattered dim itself would permute the output
    layout relative to the serial ``psum_scatter``."""
    best = None
    for d, s in enumerate(shape):
        if d == scatter_dim or tiles <= 1 or s % tiles or s < tiles:
            continue
        if best is None or s > shape[best]:
            best = d
    return best


def overlapped_reduce_scatter(x, axis_name: str, scatter_dim: int = 0,
                              tiles: int = 4, strategy: str = "psum",
                              quant_bits: Optional[int] = None,
                              scope: str = "t3_rs"):
    """Tiled reduce-scatter for ZeRO stage-2/3 gradient sync.

    Call INSIDE shard_map.  The leaf is split into ``tiles`` slices
    along its largest non-scattered dim (a leaf with no such dim runs
    serial), each slice reduced by ``psum_scatter`` (bitwise vs the
    serial op), a ppermute ring, or the qgZ int8/int4 wire — so the
    reduce-scatter of gradient slice *i* can ride behind whatever
    compute (the next microbatch's backward GEMMs) XLA has in flight."""
    assert strategy in STRATEGIES, strategy
    n = axis_size(axis_name)

    def one(xt, sc):
        if quant_bits:
            from ..ops.quant import quantized_psum_scatter_dim
            with jax.named_scope(f"{sc}_qrs{quant_bits}"):
                return quantized_psum_scatter_dim(xt, axis_name,
                                                  dim=scatter_dim,
                                                  bits=quant_bits)
        if strategy == "ring" and xt.shape[scatter_dim] % n == 0:
            return ring_reduce_scatter(xt, axis_name,
                                       scatter_dim=scatter_dim, scope=sc)
        with jax.named_scope(f"{sc}_rs"):
            return jax.lax.psum_scatter(xt, axis_name,
                                        scatter_dimension=scatter_dim,
                                        tiled=True)

    td = _rs_tile_dim(x.shape, scatter_dim, tiles)
    if td is None:
        return one(x, f"{scope}_t0")
    t = _resolve_tiles(x.shape[td], tiles)
    step = x.shape[td] // t
    idx = [slice(None)] * x.ndim
    outs = []
    for i in range(t):
        idx[td] = slice(i * step, (i + 1) * step)
        outs.append(one(x[tuple(idx)], f"{scope}_t{i}"))
    return outs[0] if t == 1 else jnp.concatenate(outs, axis=td)


# --------------------------------------------------------------------------
# wire accounting
# --------------------------------------------------------------------------

def wire_bytes(op: str, elems: int, itemsize: float, n: int,
               quant_bits: Optional[int] = None) -> float:
    """Modeled per-rank bytes on the wire for one collective over ``n``
    ranks, NCCL-style (the ``comms_logging.calc_bw_log`` factors):
    all-reduce moves 2(n-1)/n of the payload, reduce-scatter /
    all-gather (n-1)/n, everything else the payload.  A quantized op's
    payload is ``bits/8`` bytes per element instead of ``itemsize`` —
    exactly the bits/8 ratio the telemetry reconciliation test asserts
    (scale sidecars are excluded from both sides of the ratio by
    design; they are <1% of payload at the default group size)."""
    if n <= 1:
        return 0.0
    payload = elems * ((quant_bits / 8.0) if quant_bits else itemsize)
    if op == "all_reduce":
        return payload * 2 * (n - 1) / n
    if op in ("reduce_scatter", "all_gather"):
        return payload * (n - 1) / n
    return payload


# --------------------------------------------------------------------------
# GSPMD-context entry points (the serving forward)
# --------------------------------------------------------------------------

class ServingComm(NamedTuple):
    """Resolved serving-side comm plan, built once by
    ``InferenceEngine._resolve_serving_comm`` and threaded through the
    compiled forward: which of the two heavy TP collectives run
    decomposed, over which mesh/axis, at what tile count, and whether
    the all-reduce payload rides the quantized wire."""
    mesh: object                 # jax.sharding.Mesh
    axis_name: str               # the tensor-parallel mesh axis
    tiles: int
    quant_bits: Optional[int]    # None = exact; 8 | 4 = EQuARX wire
    downproj: bool               # MLP down-projection all-reduce
    unembed: bool                # logits all-gather


def shard_matmul_allreduce(x, w, comm: ServingComm, dt):
    """Tile-decomposed row-parallel matmul+allreduce, callable from a
    GSPMD-sharded jit program: wraps the tiled body in a full-manual
    shard_map over ``comm.mesh``.  ``x``: [..., K] with K sharded over
    ``comm.axis_name``; ``w``: [K, N] sharded on dim 0.  Returns the
    replicated [..., N] product in ``dt``."""
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    f = shard_map(
        lambda a, b: overlapped_matmul_allreduce(
            a, b, comm.axis_name, tiles=comm.tiles,
            quant_bits=comm.quant_bits, out_dtype=dt),
        mesh=comm.mesh,
        in_specs=(P(None, comm.axis_name), P(comm.axis_name, None)),
        out_specs=P(), check_vma=False)
    return f(x2, w).reshape(*lead, w.shape[-1])


def shard_matmul_allgather(x, w, comm: ServingComm, dt):
    """Tile-decomposed column-parallel matmul+all-gather (the unembed),
    callable from a GSPMD-sharded jit program.  ``x``: [..., K]
    replicated; ``w``: [K, N] with N sharded over ``comm.axis_name``.
    Returns the replicated [..., N] logits in ``dt`` — bitwise-equal to
    the serial path (the gather moves data, it never rounds)."""
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    f = shard_map(
        lambda a, b: overlapped_matmul_allgather(
            a, b, comm.axis_name, tiles=comm.tiles, out_dtype=dt),
        mesh=comm.mesh,
        in_specs=(P(), P(None, comm.axis_name)),
        out_specs=P(), check_vma=False)
    return f(x2, w).reshape(*lead, w.shape[-1])
