"""Per-collective latency/size logging with algbw/busbw accounting.

Carries over the reference's comms profiling design
(``deepspeed/utils/comms_logging.py:34`` bandwidth math, ``comm/comm.py:422``
``log_summary`` with straggler detection) — the one part of the NCCL comm
stack the survey marked "worth keeping" verbatim in spirit (SURVEY.md §2.3).
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List

from ..utils.logging import log_dist, logger


def convert_size(size_bytes: float) -> str:
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB", "PB")
    i = int(math.floor(math.log(size_bytes, 1024)))
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int) -> tuple:
    """algbw/busbw for a collective over ``n`` participants.

    Bus-bandwidth correction factors follow the standard NCCL accounting the
    reference uses (utils/comms_logging.py:34): ring all-gather /
    reduce-scatter move (n-1)/n of the data per link; all-reduce moves
    2(n-1)/n; all-to-all and p2p move the full payload.
    """
    duration_s = max(duration_s, 1e-9)
    algbw = size_bytes / duration_s  # bytes/s
    if comm_op in ("all_gather", "reduce_scatter", "all_gather_into_tensor",
                   "reduce_scatter_tensor"):
        busbw = algbw * (n - 1) / max(n, 1)
    elif comm_op in ("all_reduce", "psum"):
        busbw = algbw * 2 * (n - 1) / max(n, 1)
    else:  # all_to_all, broadcast, send/recv, ppermute
        busbw = algbw
    # report in Gbps like the reference
    return algbw * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:
    """Accumulates per-op records; ``log_summary`` prints the table
    (reference: comm/comm.py:422)."""

    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops: List[str] = None, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # op_name -> msg_size -> [count, total_lat_s, total_algbw, total_busbw]
        self.comms_dict: Dict[str, Dict[int, List[float]]] = defaultdict(dict)
        # optional PR-5 metrics mirror (attach_registry)
        self._c_time = self._c_bytes = self._c_ops = None

    def attach_registry(self, registry) -> None:
        """Mirror every profiled op record into a
        :class:`~deepspeed_tpu.telemetry.metrics.MetricsRegistry` as
        ``training_comm_*`` counters (op as a label), so comm time
        reaches the Prometheus exposition and flight dumps instead of
        only the ad-hoc :meth:`log_all` table.  One registry at a time
        — the latest attach wins (this is a module singleton; the
        training engine attaches its registry at construction)."""
        self._c_time = registry.counter(
            "training_comm_time_ms_total",
            "cumulative wall ms in profiled eager collectives "
            "(comms_logger; label op)")
        self._c_bytes = registry.counter(
            "training_comm_msg_bytes_total",
            "cumulative message bytes through profiled eager "
            "collectives (comms_logger; label op)", int_valued=True)
        self._c_ops = registry.counter(
            "training_comm_ops_profiled_total",
            "profiled eager collective calls (comms_logger; label op)",
            int_valued=True)

    def configure(self, enabled=None, verbose=None, prof_all=None, prof_ops=None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops

    def should_profile(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, raw_name: str, latency_s: float,
               msg_size: int, n_participants: int) -> None:
        algbw, busbw = calc_bw_log(op_name, msg_size, latency_s, n_participants)
        if self._c_time is not None:
            self._c_time.inc(latency_s * 1e3, op=op_name)
            self._c_bytes.inc(msg_size, op=op_name)
            self._c_ops.inc(1, op=op_name)
        per_size = self.comms_dict[raw_name]
        if msg_size in per_size:
            rec = per_size[msg_size]
            rec[0] += 1
            rec[1] += latency_s
            rec[2] += algbw
            rec[3] += busbw
        else:
            per_size[msg_size] = [1, latency_s, algbw, busbw]
        if self.verbose:
            logger.info(
                f"comm op: {raw_name} | time(ms): {latency_s*1000:.2f} | "
                f"msg size: {convert_size(msg_size)} | algbw(Gbps): {algbw:.2f} | "
                f"busbw(Gbps): {busbw:.2f}")

    def log_all(self, print_log: bool = True, show_straggler: bool = False) -> Dict:
        """Summarize all recorded collectives; returns the table dict."""
        out = {}
        lines = [f"{'Comm. Op':20s} {'Message Size':>14s} {'Count':>8s} "
                 f"{'Total Lat(ms)':>14s} {'Avg Lat(ms)':>12s} "
                 f"{'tput_avg(Gbps)':>15s} {'busbw_avg(Gbps)':>16s}"]
        for op, sizes in sorted(self.comms_dict.items()):
            for size, (cnt, lat, algbw, busbw) in sorted(sizes.items()):
                avg_lat = lat / cnt
                out.setdefault(op, {})[size] = dict(
                    count=cnt, total_latency_ms=lat * 1000,
                    avg_latency_ms=avg_lat * 1000,
                    algbw_gbps=algbw / cnt, busbw_gbps=busbw / cnt)
                lines.append(
                    f"{op:20s} {convert_size(size):>14s} {cnt:>8d} "
                    f"{lat*1000:>14.2f} {avg_lat*1000:>12.2f} "
                    f"{algbw/cnt:>15.2f} {busbw/cnt:>16.2f}")
        if print_log:
            log_dist("\n".join(lines))
        return out

    def reset(self) -> None:
        self.comms_dict.clear()


# module-level singleton, configured via Config.comms_logger
comms_logger = CommsLogger()
