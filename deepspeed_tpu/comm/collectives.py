"""Device-agnostic collectives façade over XLA collectives.

TPU-native analog of ``deepspeed/comm/comm.py`` (module-level collectives
:222-520, ``timed_op`` profiling decorator :101, ``init_distributed`` :619)
and ``comm/torch.py``'s ``TorchBackend``.  There is no NCCL/process-group
layer: every collective is a ``jax.lax`` op inside a ``shard_map`` over a
named mesh axis; XLA routes it over ICI/DCN.

Two usage modes:

* **Inside a jitted step function** (the hot path): use the ``lax_*``
  re-exports directly (``lax_psum`` etc.) — these are zero-overhead aliases
  with named-scope annotations for profile readability.
* **Eager, engine/host level** (microbenchmarks, broadcast at init, barrier,
  metric reduction): the :class:`Collectives` object bound to a
  :class:`MeshTopology`, whose ops are profiled via ``comms_logger``
  exactly like the reference's ``timed_op``.
"""

from __future__ import annotations

import functools
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map

from .comms_logging import comms_logger
from .mesh import MeshTopology
from ..utils.logging import logger


# --------------------------------------------------------------------------
# In-jit aliases (hot path)
# --------------------------------------------------------------------------

def lax_psum(x, axis_name):
    with jax.named_scope(f"all_reduce_{axis_name}"):
        return lax.psum(x, axis_name)


def lax_pmean(x, axis_name):
    with jax.named_scope(f"all_reduce_mean_{axis_name}"):
        return lax.pmean(x, axis_name)


def lax_all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    with jax.named_scope(f"all_gather_{axis_name}"):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def lax_reduce_scatter(x, axis_name, scatter_dimension: int = 0):
    with jax.named_scope(f"reduce_scatter_{axis_name}"):
        return lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension, tiled=True)


def lax_all_to_all(x, axis_name, split_axis: int, concat_axis: int, tiled: bool = True):
    with jax.named_scope(f"all_to_all_{axis_name}"):
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def lax_ppermute(x, axis_name, perm):
    with jax.named_scope(f"ppermute_{axis_name}"):
        return lax.ppermute(x, axis_name, perm)


# --------------------------------------------------------------------------
# init_distributed
# --------------------------------------------------------------------------

_initialized = False


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host initialization (reference: comm/comm.py:619).

    On TPU pods this wraps ``jax.distributed.initialize``; single-process
    (one host, or CPU emulation) is a no-op.  Safe to call repeatedly.
    """
    global _initialized
    if _initialized:
        return
    import os

    explicit = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if explicit or os.environ.get("JAX_NUM_PROCESSES"):
        jax.distributed.initialize(
            coordinator_address=explicit,
            num_processes=num_processes or int(os.environ.get("JAX_NUM_PROCESSES", 0)) or None,
            process_id=process_id if process_id is not None
            else (int(os.environ["JAX_PROCESS_ID"]) if "JAX_PROCESS_ID" in os.environ else None),
        )
        logger.info("jax.distributed initialized: process %d/%d",
                    jax.process_index(), jax.process_count())
    _initialized = True


def get_world_size() -> int:
    return jax.device_count()


def get_rank() -> int:
    return jax.process_index()


# --------------------------------------------------------------------------
# Eager collectives over a mesh axis
# --------------------------------------------------------------------------

def _timed(op_name: str):
    """Profiling wrapper — the reference's ``timed_op`` (comm/comm.py:101)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self: "Collectives", x, *args, **kwargs):
            profile = comms_logger.should_profile(op_name)
            if profile:
                jax.block_until_ready(x)
                t0 = time.perf_counter()
            out = fn(self, x, *args, **kwargs)
            if profile:
                out = jax.block_until_ready(out)
                dt = time.perf_counter() - t0
                axis = kwargs.get("axis_name") or (args[0] if args else "data")
                n = self.topology.axis_sizes.get(axis, 1)
                size = x.size * x.dtype.itemsize
                comms_logger.append(op_name, kwargs.get("log_name", op_name),
                                    dt, size, n)
            return out

        return wrapper

    return deco


class Collectives:
    """Eager collectives bound to a mesh, for host-level orchestration and
    comm microbenchmarks.  Arrays are treated as sharded along dim 0 over
    ``axis_name`` (all_gather/reduce_scatter) or replicated (all_reduce).

    The jitted-executable cache is keyed by (op, axis, **shape/dtype**)
    and LRU-bounded (the serving ``_pstep_fns`` discipline): each key
    sees exactly one specialization, so evicting an entry really frees
    its executable — the unkeyed cache used to retain every shape ever
    reduced.  Fills and runtime retraces count through the PR-9
    compile-observatory counters (``training_comm_collective_*``) on
    ``metrics`` (an optional shared
    :class:`~deepspeed_tpu.telemetry.metrics.MetricsRegistry`; a
    private one is created when none is passed)."""

    _CACHE_CAP = 16

    def __init__(self, topology: MeshTopology, metrics=None):
        self.topology = topology
        self._cache = {}
        self._compiled_ever = set()
        if metrics is None:
            from ..telemetry.metrics import MetricsRegistry
            metrics = MetricsRegistry()
        self.metrics = metrics
        self._c_compiles = metrics.counter(
            "training_comm_collective_compiles_total",
            "eager-collective executables built (cache fills)",
            int_valued=True)
        self._c_retraces = metrics.counter(
            "training_comm_collective_retraces_total",
            "re-builds of an eager-collective key already compiled "
            "(LRU thrash across shapes/dtypes — each warns loudly)",
            int_valued=True)

    @property
    def mesh(self) -> Mesh:
        return self.topology.mesh

    def _sig(self, x) -> tuple:
        # key on dtype WITHOUT materializing x on device — jnp.asarray
        # here would pay a full H2D transfer per call just to read a
        # field, and the real transfer happens inside the jitted op
        dt = getattr(x, "dtype", None)
        return (tuple(np.shape(x)),
                str(dt if dt is not None else jnp.result_type(x)))

    def _jit(self, key, build):
        fn = self._cache.pop(key, None)
        if fn is None:
            if len(self._cache) >= self._CACHE_CAP:
                self._cache.pop(next(iter(self._cache)))
            fn = build()
            self._c_compiles.inc()
            if key in self._compiled_ever:
                self._c_retraces.inc()
                logger.warning(
                    "eager collective %r re-built at runtime (retrace "
                    "#%d) — the executable cache is thrashing across "
                    "shapes/dtypes", key, int(self._c_retraces.value()))
            else:
                self._compiled_ever.add(key)
        self._cache[key] = fn            # reinsert: LRU, not FIFO
        return fn

    # -- ops ---------------------------------------------------------------
    @_timed("all_reduce")
    def all_reduce(self, x, axis_name: str = "data", op: str = "sum", **_):
        mesh = self.mesh

        def build():
            def f(v):
                with jax.named_scope(f"all_reduce_{axis_name}"):
                    r = lax.psum(v, axis_name)
                return r / self.topology.size(axis_name) if op == "mean" else r

            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False))

        fn = self._jit(("ar", axis_name, op) + self._sig(x), build)
        return fn(x)

    @_timed("all_gather")
    def all_gather(self, x, axis_name: str = "data", **_):
        """x sharded on dim 0 over axis_name -> fully replicated concat."""
        mesh = self.mesh

        def build():
            def f(v):
                with jax.named_scope(f"all_gather_{axis_name}"):
                    return lax.all_gather(v, axis_name, axis=0, tiled=True)

            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=P(axis_name), out_specs=P(),
                check_vma=False))

        fn = self._jit(("ag", axis_name) + self._sig(x), build)
        return fn(x)

    @_timed("reduce_scatter")
    def reduce_scatter(self, x, axis_name: str = "data", **_):
        """x replicated -> dim-0 shards of the sum across axis_name."""
        mesh = self.mesh

        def build():
            def f(v):
                with jax.named_scope(f"reduce_scatter_{axis_name}"):
                    return lax.psum_scatter(v, axis_name,
                                            scatter_dimension=0, tiled=True)

            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=P(), out_specs=P(axis_name),
                check_vma=False))

        fn = self._jit(("rs", axis_name) + self._sig(x), build)
        return fn(x)

    @_timed("all_to_all")
    def all_to_all(self, x, axis_name: str = "data", split_dim: int = 0,
                   concat_dim: int = 0, **_):
        mesh = self.mesh

        def build():
            def f(v):
                with jax.named_scope(f"all_to_all_{axis_name}"):
                    return lax.all_to_all(v, axis_name, split_axis=split_dim,
                                          concat_axis=concat_dim, tiled=True)

            spec = [None] * x.ndim
            spec[concat_dim] = axis_name
            in_spec = P(*spec)
            out_spec_l = [None] * x.ndim
            out_spec_l[split_dim] = axis_name
            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=in_spec, out_specs=P(*out_spec_l),
                check_vma=False))

        fn = self._jit(("a2a", axis_name, split_dim, concat_dim)
                       + self._sig(x), build)
        return fn(x)

    @_timed("broadcast")
    def broadcast(self, x, axis_name: str = "data", src: int = 0, **_):
        """Replicate rank ``src``'s shard to all ranks along axis."""
        mesh = self.mesh

        def build():
            def f(v):
                with jax.named_scope(f"broadcast_{axis_name}"):
                    idx = lax.axis_index(axis_name)
                    v = jnp.where(idx == src, v, jnp.zeros_like(v))
                    return lax.psum(v, axis_name)

            return jax.jit(shard_map(
                f, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False))

        fn = self._jit(("bc", axis_name, src) + self._sig(x), build)
        return fn(x)

    def barrier(self) -> None:
        """Block until all devices reach this point (reference: comm barrier)."""
        x = jnp.zeros((), dtype=jnp.int32)
        out = self.all_reduce(x, axis_name=DATA_DEFAULT_AXIS(self.topology))
        jax.block_until_ready(out)


def DATA_DEFAULT_AXIS(topology: MeshTopology) -> str:
    for a in ("data", "fsdp", "tensor"):
        if topology.axis_sizes.get(a, 1) >= 1:
            return a
    return "data"


def log_summary(show_straggler: bool = False):
    """Print the accumulated comm table (reference: comm/comm.py:422)."""
    return comms_logger.log_all(print_log=True, show_straggler=show_straggler)
