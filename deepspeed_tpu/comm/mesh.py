"""Named-axis device mesh topology.

TPU-native replacement for the reference's process-group topology
(``deepspeed/utils/groups.py`` — ``_create_model_parallel`` groups.py:68,
expert/data groups :117/:257, sequence groups :472-515, hpZ secondary groups
:529).  On TPU there are no process groups: a single
:class:`jax.sharding.Mesh` with named axes expresses every parallelism
dimension, and XLA lowers collectives onto ICI (intra-slice) or DCN
(inter-slice) links.

Axis names (outermost/DCN-friendly first):

* ``pipe``   — pipeline stages (point-to-point ``ppermute`` traffic only)
* ``data``   — pure data-parallel replicas (gradient psum; DCN-tolerant)
* ``fsdp``   — ZeRO shard axis (all-gather / reduce-scatter; wants ICI)
* ``expert`` — MoE expert parallel (all-to-all; wants ICI)
* ``seq``    — sequence/context parallel (all-to-all / ppermute; wants ICI)
* ``tensor`` — tensor parallel (per-layer all-reduce; innermost, needs ICI)

The ordering is deliberate: ``jax.experimental.mesh_utils`` assigns the
fastest-varying (physically adjacent) devices to the *last* mesh axes, so the
highest-bandwidth-hungry axes sit innermost.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config.config import MeshConfig
from ..utils.logging import log_dist

# canonical axis order, outermost first
AXIS_ORDER: Tuple[str, ...] = ("pipe", "data", "fsdp", "expert", "seq", "tensor")

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
TENSOR_AXIS = "tensor"

# axes over which the batch dimension is split
BATCH_AXES: Tuple[str, ...] = (DATA_AXIS, FSDP_AXIS)


@dataclasses.dataclass
class MeshTopology:
    """Resolved mesh + conventional sharding specs.

    The analog of the reference's ``PipelineParallelGrid``/``ProcessTopology``
    (runtime/pipe/topology.py:12,251) plus ``deepspeed/utils/groups.py``,
    collapsed into one object.
    """

    mesh: Mesh
    axis_sizes: Dict[str, int]

    # ---- constructors ----------------------------------------------------
    @classmethod
    def build(cls, config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> "MeshTopology":
        config = config or MeshConfig()
        devices = list(devices) if devices is not None else jax.devices()
        n = len(devices)

        sizes = {
            PIPE_AXIS: config.pipe,
            DATA_AXIS: config.data,
            FSDP_AXIS: config.fsdp,
            EXPERT_AXIS: config.expert,
            SEQ_AXIS: config.seq,
            TENSOR_AXIS: config.tensor,
        }
        # normalize: <=0 means infer (at most one axis may infer; default data)
        infer = [a for a, s in sizes.items() if s is None or s <= 0]
        fixed = math.prod(s for a, s in sizes.items() if a not in infer)
        if len(infer) > 1:
            raise ValueError(f"Only one mesh axis may be inferred, got {infer}")
        if infer:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by product of fixed axes {fixed}")
            sizes[infer[0]] = n // fixed
        total = math.prod(sizes.values())
        if total != n:
            raise ValueError(
                f"Mesh axes {sizes} multiply to {total} but there are {n} devices")

        shape = tuple(sizes[a] for a in AXIS_ORDER)
        mesh_devices = _arrange_devices(devices, shape, config.devices_per_slice)
        mesh = Mesh(mesh_devices, AXIS_ORDER)
        topo = cls(mesh=mesh, axis_sizes=dict(sizes))
        log_dist(f"MeshTopology: {sizes} over {n} devices")
        return topo

    # ---- sizes -----------------------------------------------------------
    def size(self, axis: str) -> int:
        return self.axis_sizes[axis]

    @property
    def dp_world_size(self) -> int:
        """Number of ways the global batch is split (data × fsdp)."""
        return self.axis_sizes[DATA_AXIS] * self.axis_sizes[FSDP_AXIS]

    @property
    def device_count(self) -> int:
        return math.prod(self.axis_sizes.values())

    @property
    def pp_size(self) -> int:
        return self.axis_sizes[PIPE_AXIS]

    @property
    def tp_size(self) -> int:
        return self.axis_sizes[TENSOR_AXIS]

    @property
    def sp_size(self) -> int:
        return self.axis_sizes[SEQ_AXIS]

    @property
    def ep_size(self) -> int:
        return self.axis_sizes[EXPERT_AXIS]

    # ---- conventional shardings -----------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_spec(self, extra_seq: bool = False) -> P:
        """Spec for a [batch, seq, ...] input: batch split over data+fsdp,
        optionally sequence split over the seq axis."""
        if extra_seq and self.sp_size > 1:
            return P(BATCH_AXES, SEQ_AXIS)
        return P(BATCH_AXES)

    def batch_sharding(self, extra_seq: bool = False) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(extra_seq))

    def active_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if self.axis_sizes[a] > 1)


def _arrange_devices(devices: Sequence[jax.Device], shape: Tuple[int, ...],
                     devices_per_slice: int) -> np.ndarray:
    """Arrange devices into the mesh shape, ICI/DCN aware when possible."""
    n = len(devices)
    try:
        from jax.experimental import mesh_utils

        if devices_per_slice and devices_per_slice > 0 and n > devices_per_slice:
            # hybrid mesh: outer axes ride DCN between slices
            n_slices = n // devices_per_slice
            dcn_shape = _split_outer(shape, n_slices)
            ici_shape = tuple(s // d for s, d in zip(shape, dcn_shape))
            return mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception as e:
        # CPU emulation or exotic topologies: row-major is fine — but say
        # so; a silently degraded device order costs ICI bandwidth on TPU
        log_dist(f"mesh_utils arrangement unavailable ({type(e).__name__}: "
                 f"{e}); using row-major device order", level=logging.DEBUG)
        return np.asarray(devices).reshape(shape)


def _split_outer(shape: Tuple[int, ...], n_slices: int) -> Tuple[int, ...]:
    """Factor n_slices into the outermost mesh axes (greedy)."""
    out = []
    remaining = n_slices
    for s in shape:
        g = math.gcd(s, remaining)
        out.append(g)
        remaining //= g
    if remaining != 1:
        raise ValueError(f"Cannot split {n_slices} slices over mesh shape {shape}")
    return tuple(out)
