"""Tuner strategies: grid, random, model-based.

Reference: ``autotuning/tuner/index_based_tuner.py:11,27`` (GridSearch /
RandomTuner over the experiment list) and
``tuner/model_based_tuner.py:19`` + ``cost_model.py:14`` (XGBoost cost
model ranking unmeasured experiments).  Same staged flow here; the cost
model is a numpy ridge regression over step-time features (XGBoost isn't
in the image, and the feature design carries the value)."""

from __future__ import annotations

import random
from typing import Callable, List

import numpy as np

from .autotuner import Experiment


class BaseTuner:
    def __init__(self, space: List[Experiment],
                 run: Callable[[Experiment], Experiment]):
        self.space = list(space)
        self.run = run

    def tune(self, budget: int) -> List[Experiment]:
        raise NotImplementedError


class GridTuner(BaseTuner):
    """Measure the space in order until the budget is exhausted
    (reference: GridSearchTuner)."""

    def tune(self, budget: int) -> List[Experiment]:
        todo = self.space[:budget]
        return [self.run(e) for e in todo]


class RandomTuner(BaseTuner):
    """Uniformly sample the space (reference: RandomTuner)."""

    def __init__(self, space, run, seed: int = 0):
        super().__init__(space, run)
        self.rng = random.Random(seed)

    def tune(self, budget: int) -> List[Experiment]:
        todo = self.space[:]
        self.rng.shuffle(todo)
        return [self.run(e) for e in todo[:budget]]


def _features(e: Experiment) -> np.ndarray:
    o = e.overrides
    mesh = o["mesh"]
    from .autotuner import REMAT_CHOICES
    remat = o.get("remat_policy", "nothing")
    remat_idx = (REMAT_CHOICES.index(remat)
                 if remat in REMAT_CHOICES else len(REMAT_CHOICES))
    return np.array([
        1.0,
        float(o["zero_stage"]),
        np.log2(max(o["micro_batch"], 1)),
        float(remat_idx),
        np.log2(max(mesh.get("data", 1), 1)),
        np.log2(max(mesh.get("fsdp", 1), 1)),
        np.log2(max(mesh.get("tensor", 1), 1)),
    ])


class ModelBasedTuner(BaseTuner):
    """Seed-measure a diverse subset, fit a ridge cost model on step
    time, then spend the rest of the budget on the predicted-fastest
    candidates (reference: ModelBasedTuner.find_estimated_top_configs
    model_based_tuner.py)."""

    def __init__(self, space, run, seed_fraction: float = 0.4,
                 ridge: float = 1e-3, seed: int = 0):
        super().__init__(space, run)
        self.seed_fraction = seed_fraction
        self.ridge = ridge
        self.rng = random.Random(seed)

    def tune(self, budget: int) -> List[Experiment]:
        budget = min(budget, len(self.space))
        n_seed = min(budget, max(2, int(budget * self.seed_fraction)))
        todo = self.space[:]
        self.rng.shuffle(todo)
        measured = [self.run(e) for e in todo[:n_seed]]
        remaining = todo[n_seed:]
        left = budget - n_seed
        good = [e for e in measured if e.ok]
        if left > 0 and remaining:
            if len(good) >= 2:
                X = np.stack([_features(e) for e in good])
                y = np.log(np.array([e.step_time_s for e in good]))
                A = X.T @ X + self.ridge * np.eye(X.shape[1])
                w = np.linalg.solve(A, X.T @ y)
                preds = [(float(_features(e) @ w), e) for e in remaining]
                preds.sort(key=lambda p: p[0])
                chosen = [e for _, e in preds[:left]]
            else:       # not enough signal to fit — fall back to random
                chosen = remaining[:left]
            measured += [self.run(e) for e in chosen]
        return measured
