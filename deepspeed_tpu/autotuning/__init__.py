"""Autotuning: experiment-space search over ZeRO stage, micro-batch,
remat policy, and mesh factorization (reference: deepspeed/autotuning/)."""

from .autotuner import (Experiment, autotune, build_space,
                        estimate_state_bytes, evaluate,
                        mesh_factorizations, prune_by_memory)
from .tuner import GridTuner, ModelBasedTuner, RandomTuner

__all__ = [
    "Experiment", "autotune", "build_space", "estimate_state_bytes",
    "evaluate", "mesh_factorizations", "prune_by_memory",
    "GridTuner", "ModelBasedTuner", "RandomTuner",
]
