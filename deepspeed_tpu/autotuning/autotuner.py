"""Autotuner: search over ZeRO stage x micro-batch x remat x mesh.

TPU-native re-design of the reference autotuner
(``autotuning/autotuner.py:42`` — experiment generation from the config
space, ``scheduler.py`` ResourceManager launching experiments through the
launcher, ``tuner/index_based_tuner.py:11,27`` grid/random tuners,
``tuner/model_based_tuner.py:19`` + ``cost_model.py:14`` XGBoost cost
model).

What transfers and what doesn't:

* The reference explores (zero stage, micro-batch, misc flags) by
  launching whole training jobs per experiment and parsing their metric
  files.  Under jax there is no process boundary to cross: an experiment
  is ``Engine`` construction + a handful of timed ``train_batch`` calls
  in-process, and **compile-time signals** (HLO cost analysis, the
  compiler's own peak-memory estimate) are available before running a
  single step — a tier the reference cannot see at all.
* The experiment space gains the **mesh factorization** dimension
  (data x fsdp x tensor), which has no analog on the NCCL side and
  matters most on TPU (which axes ride ICI).
* The model-based tuner keeps the reference's staged flow (seed
  measurements -> fit cost model -> explore predicted-best) but fits a
  tiny ridge regression on step-time features instead of XGBoost (not in
  the image; the feature design is the point, not the regressor).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import log_dist, logger

REMAT_CHOICES = ("nothing", "dots_no_batch", "everything")


@dataclasses.dataclass
class Experiment:
    """One candidate configuration and its measured/estimated metrics."""
    overrides: Dict[str, Any]
    # filled by evaluation:
    step_time_s: Optional[float] = None
    compile_time_s: Optional[float] = None
    flops_per_step: Optional[float] = None
    peak_bytes: Optional[int] = None
    est_state_bytes: Optional[int] = None
    error: Optional[str] = None
    pruned: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None and self.pruned is None and \
            self.step_time_s is not None

    def label(self) -> str:
        o = self.overrides
        mesh = o.get("mesh", {})
        return (f"z{o.get('zero_stage', 0)}"
                f"_mb{o.get('micro_batch', '?')}"
                f"_{o.get('remat_policy', 'nothing')}"
                f"_d{mesh.get('data', 1)}f{mesh.get('fsdp', 1)}"
                f"t{mesh.get('tensor', 1)}")


def mesh_factorizations(n_devices: int,
                        max_tensor: Optional[int] = None) -> List[Dict[str, int]]:
    """All (data, fsdp, tensor) factorizations of ``n_devices``.

    The reference has nothing like this (its DP degree is fixed by the
    launcher); on TPU the factorization decides which collectives ride
    which ICI axes, so it is a first-class tuning dimension."""
    out = []
    for tensor in sorted({d for d in range(1, n_devices + 1)
                          if n_devices % d == 0}):
        if max_tensor is not None and tensor > max_tensor:
            continue
        rest = n_devices // tensor
        for fsdp in sorted({d for d in range(1, rest + 1) if rest % d == 0}):
            out.append({"data": rest // fsdp, "fsdp": fsdp,
                        "tensor": tensor})
    return out


def build_space(n_devices: int,
                stages: Sequence[int] = (0, 1, 2, 3),
                micro_batches: Sequence[int] = (1, 2, 4, 8),
                remat_policies: Sequence[str] = REMAT_CHOICES,
                meshes: Optional[Sequence[Dict[str, int]]] = None,
                max_tensor: Optional[int] = None) -> List[Experiment]:
    """Enumerate the experiment space (reference:
    Autotuner._generate_experiments autotuner.py — tuning_space product
    over zero stages and micro-batch candidates)."""
    meshes = list(meshes) if meshes is not None else \
        mesh_factorizations(n_devices, max_tensor=max_tensor)
    exps = []
    for stage, mb, remat, mesh in itertools.product(
            stages, micro_batches, remat_policies, meshes):
        if stage >= 1 and mesh["fsdp"] == 1 and mesh["data"] == 1:
            continue        # nothing to shard over
        exps.append(Experiment(overrides={
            "zero_stage": stage, "micro_batch": mb,
            "remat_policy": remat, "mesh": dict(mesh)}))
    return exps


# --------------------------------------------------------------------------
# analytic memory model (pre-compile pruning)
# --------------------------------------------------------------------------

def estimate_state_bytes(n_params: int, stage: int, mesh: Dict[str, int],
                         compute_bytes: int = 2,
                         moment_count: int = 2) -> int:
    """Per-device persistent-state bytes under a ZeRO stage — the analog
    of the reference's memory estimators
    (``runtime/zero/stage3.py`` estimate_zero3_model_states_mem_needs):
    compute params + fp32 master + moments, sharded per stage."""
    fsdp = max(mesh.get("fsdp", 1), 1)
    tensor = max(mesh.get("tensor", 1), 1)
    dp_shard = fsdp if stage >= 1 else 1
    param_shard = (fsdp * tensor) if stage >= 3 else tensor
    compute = n_params * compute_bytes // param_shard
    master = n_params * 4 // (dp_shard * tensor)
    moments = n_params * 4 * moment_count // (dp_shard * tensor)
    return compute + master + moments


def prune_by_memory(exps: List[Experiment], n_params: int,
                    hbm_bytes: Optional[int] = None,
                    headroom: float = 0.6) -> List[Experiment]:
    """Mark experiments whose *persistent state alone* exceeds the memory
    budget (activations still need the headroom).  Returns survivors."""
    if hbm_bytes is None:
        from ..platform import get_platform
        hbm_bytes = get_platform().total_memory() or 16 << 30
    budget = int(hbm_bytes * headroom)
    alive = []
    for e in exps:
        est = estimate_state_bytes(n_params, e.overrides["zero_stage"],
                                   e.overrides["mesh"])
        e.est_state_bytes = est
        if est > budget:
            e.pruned = (f"state {est/1e9:.2f} GB > budget "
                        f"{budget/1e9:.2f} GB")
        else:
            alive.append(e)
    return alive


# --------------------------------------------------------------------------
# experiment evaluation
# --------------------------------------------------------------------------

def _apply_overrides(base_config: Dict, ov: Dict[str, Any]) -> Dict:
    import copy
    cfg = copy.deepcopy(base_config)
    cfg.setdefault("zero_optimization", {})["stage"] = ov["zero_stage"]
    cfg["train_micro_batch_size_per_device"] = ov["micro_batch"]
    cfg.pop("train_batch_size", None)
    cfg["mesh"] = dict(ov["mesh"])
    return cfg


def evaluate(exp: Experiment, model_fn: Callable[[str], Any],
             base_config: Dict, batch_fn: Callable[[int], Any],
             steps: int = 3, warmup: int = 1) -> Experiment:
    """Run one experiment: build the engine, compile, time a few steps
    (reference: one launched run per exp + metric-file parse; here:
    in-process, plus compile-time HLO cost + peak-memory readings)."""
    import jax

    import deepspeed_tpu as ds

    cfg = _apply_overrides(base_config, exp.overrides)
    try:
        model = model_fn(exp.overrides["remat_policy"])
        t0 = time.perf_counter()
        eng = ds.initialize(model=model, config=cfg)
        # batch_fn receives the PER-PROCESS sample count: under
        # multi-host, shard_batch treats its input as this process's
        # slice of the global batch (engine.shard_batch contract)
        batch = batch_fn(eng.train_batch_size // jax.process_count())
        # stage once; reused for compile, analysis, and the timed loop
        # (shard_batch is idempotent and the step doesn't donate it)
        staged = eng.shard_batch(batch)
        m = eng.train_batch(staged)           # compile + step 1
        float(np.asarray(m["loss"]))
        exp.compile_time_s = time.perf_counter() - t0
        # compile-time signals (HLO flops + compiler peak-memory estimate)
        # — the pre-execution tier the reference's launch-and-parse design
        # cannot see.  Analyze against the STAGED batch — the avals the
        # step was compiled with (gas-reshaped, sharded); the raw host
        # dict would trigger a second full compile and fail under gas>1
        try:
            from ..profiling import analyze_fn
            stats = analyze_fn(eng._train_step_fn, eng.state, staged,
                               jax.random.PRNGKey(0))
            exp.flops_per_step = stats.get("flops")
            if stats.get("peak_bytes"):
                exp.peak_bytes = int(stats["peak_bytes"])
        except Exception as e:
            logger.debug("autotune: cost analysis failed (%r); "
                         "ranking on wall clock only", e)
        # timed region is device-only — host-side batch synthesis must
        # not distort the ranking
        for _ in range(max(warmup - 1, 0)):
            m = eng.train_batch(staged)
        float(np.asarray(m["loss"]))
        t0 = time.perf_counter()
        for _ in range(steps):
            m = eng.train_batch(staged)
        float(np.asarray(m["loss"]))
        exp.step_time_s = (time.perf_counter() - t0) / steps
    # recorded, not swallowed: the tuner loop log_dist's every FAILED
    # experiment with this error string
    except Exception as e:  # tpulint: disable=silent-except
        exp.error = f"{type(e).__name__}: {str(e).splitlines()[0][:160]}"
    return exp


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def autotune(model_fn: Callable[[str], Any],
             base_config: Dict,
             batch_fn: Callable[[int], Any],
             n_params: Optional[int] = None,
             space: Optional[List[Experiment]] = None,
             tuner: str = "model",
             budget: int = 12,
             steps: int = 3,
             hbm_bytes: Optional[int] = None,
             **space_kw) -> List[Experiment]:
    """Search the config space; returns experiments ranked by step time
    (fastest first), failed/pruned ones at the end.

    ``model_fn(remat_policy) -> model`` builds the model per candidate
    (remat is a model-construction choice here); ``batch_fn(n)``
    synthesizes ``n`` samples — ``n`` is the per-process share of the
    candidate's global batch.  ``budget`` caps the number of *measured*
    experiments — the tuner decides which candidates get measured
    (reference: Autotuner.tune autotuner.py + tuner hierarchy)."""
    import jax

    if space is None:
        space = build_space(len(jax.devices()), **space_kw)
    if n_params is not None:
        # marks .pruned in place; pruned entries stay in the returned
        # list (with the reason) but are never measured
        prune_by_memory(space, n_params, hbm_bytes=hbm_bytes)
    space_alive = [e for e in space if e.pruned is None]

    from .tuner import GridTuner, ModelBasedTuner, RandomTuner
    tuner_cls = {"grid": GridTuner, "random": RandomTuner,
                 "model": ModelBasedTuner}[tuner]
    run = lambda e: evaluate(e, model_fn, base_config, batch_fn,
                             steps=steps)
    tuner_obj = tuner_cls(space_alive, run)
    measured = tuner_obj.tune(budget)

    for e in measured:
        if e.ok:
            log_dist(f"autotune {e.label()}: {e.step_time_s*1e3:.1f} ms/step")
        elif e.error:
            log_dist(f"autotune {e.label()}: FAILED ({e.error})")

    ranked = sorted([e for e in measured if e.ok],
                    key=lambda e: e.step_time_s)
    rest = [e for e in space if not e.ok and e not in ranked]
    return ranked + rest
