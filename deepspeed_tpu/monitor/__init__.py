from .monitor import (CSVMonitor, Monitor, MonitorMaster, TensorBoardMonitor,
                      WandbMonitor)

__all__ = ["Monitor", "MonitorMaster", "CSVMonitor", "TensorBoardMonitor",
           "WandbMonitor"]
