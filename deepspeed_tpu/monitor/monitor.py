"""Experiment monitoring: fan-out scalar/event writers.

TPU-native equivalent of the reference monitor subsystem
(``monitor/monitor.py:30`` ``MonitorMaster`` fanning out to
TensorBoard/WandB/Comet/CSV writers in ``monitor/{tensorboard,wandb,
comet,csv_monitor}.py``; engine scalar events ``runtime/engine.py:2317``).

Only the process with ``jax.process_index() == 0`` writes (the reference
gates on rank, monitor/monitor.py) — under multi-host SPMD every process
sees identical replicated metrics, so one writer suffices.
"""

from __future__ import annotations

import csv
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from ..utils.logging import logger

# (name, value, step) triples — the reference's event tuple shape
Event = Tuple[str, float, int]


class Monitor:
    """Writer interface (reference: monitor/monitor.py Monitor ABC)."""

    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, events: Sequence[Event]) -> None:
        raise NotImplementedError

    def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:
        self.write_events([(k, float(v), step) for k, v in scalars.items()])

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CSVMonitor(Monitor):
    """One CSV file per metric name (reference: monitor/csv_monitor.py)."""

    def __init__(self, config):
        super().__init__(config)
        base = config.output_path or "csv_monitor"
        self.dir = os.path.join(base, config.job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files: Dict[str, Any] = {}

    def _writer(self, name: str):
        if name not in self._files:
            safe = name.replace("/", "_")
            f = open(os.path.join(self.dir, f"{safe}.csv"), "a", newline="")
            self._files[name] = (f, csv.writer(f))
        return self._files[name]

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            f, w = self._writer(name)
            w.writerow([step, value])
            f.flush()          # rows visible immediately (tail -f etc.)

    def flush(self) -> None:
        for f, _ in self._files.values():
            f.flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            f.close()
        self._files = {}


class TensorBoardMonitor(Monitor):
    """(reference: monitor/tensorboard.py — SummaryWriter wrapper)."""

    def __init__(self, config):
        super().__init__(config)
        from torch.utils.tensorboard import SummaryWriter  # torch is baked in

        path = os.path.join(config.output_path or "runs", config.job_name)
        self.writer = SummaryWriter(log_dir=path)

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            self.writer.add_scalar(name, value, step)

    def flush(self) -> None:
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()


class WandbMonitor(Monitor):
    """(reference: monitor/wandb.py)."""

    def __init__(self, config):
        super().__init__(config)
        import wandb  # optional; gated by caller

        self.wandb = wandb
        wandb.init(project=config.project, group=config.group,
                   entity=config.team)

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            self.wandb.log({name: value}, step=step)

    def close(self) -> None:
        self.wandb.finish()


class CometMonitor(Monitor):
    """(reference: monitor/comet.py CometMonitor — Experiment wrapper
    honoring samples_log_interval; comet_ml is an optional dependency,
    gated by MonitorMaster exactly like wandb)."""

    def __init__(self, config):
        super().__init__(config)
        import comet_ml  # optional; gated by caller

        kw = {k: v for k, v in dict(
            api_key=config.api_key, project_name=config.project,
            workspace=config.workspace,
            experiment_key=config.experiment_key or None).items()
            if v}
        if config.online:
            self.experiment = comet_ml.Experiment(**kw)
        else:
            self.experiment = comet_ml.OfflineExperiment(**kw)
        if config.experiment_name:
            self.experiment.set_name(config.experiment_name)
        self.samples_log_interval = max(1, config.samples_log_interval)

    def write_events(self, events: Sequence[Event]) -> None:
        for name, value, step in events:
            if step % self.samples_log_interval == 0:
                self.experiment.log_metric(name, value, step=step)

    def close(self) -> None:
        self.experiment.end()


class MonitorMaster(Monitor):
    """Builds every enabled writer and fans events out
    (reference: monitor/monitor.py:30)."""

    def __init__(self, config):
        # `config` is the top-level framework Config (or anything with
        # .tensorboard/.csv_monitor/.wandb sub-configs)
        self.writers: List[Monitor] = []
        self.enabled = False
        if jax.process_index() != 0:
            return
        specs = [
            (getattr(config, "csv_monitor", None), CSVMonitor),
            (getattr(config, "tensorboard", None), TensorBoardMonitor),
            (getattr(config, "wandb", None), WandbMonitor),
            (getattr(config, "comet", None), CometMonitor),
        ]
        for sub, cls in specs:
            if sub is None or not sub.enabled:
                continue
            try:
                self.writers.append(cls(sub))
            except Exception as e:  # missing optional dep — warn, continue
                logger.warning("monitor writer %s disabled (%s)",
                               cls.__name__, e)
        self.enabled = bool(self.writers)

    def write_events(self, events: Sequence[Event]) -> None:
        for w in self.writers:
            w.write_events(events)

    def flush(self) -> None:
        for w in self.writers:
            w.flush()

    def close(self) -> None:
        for w in self.writers:
            w.close()
