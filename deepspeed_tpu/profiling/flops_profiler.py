"""FLOPs / params / latency profiler.

TPU-native re-design of the reference flops profiler
(``profiling/flops_profiler/profiler.py`` — 1.2k LoC of nn.Module forward
hooks counting MACs per layer; engine hook ``runtime/engine.py:288,1850``).
Under XLA the compiler already knows the cost of the whole step: we read
``Compiled.cost_analysis()`` (exact flops/bytes for the optimized HLO) and
time real executions, instead of shadowing every module with a counting
hook.  The public helpers (``flops_to_string`` etc., ``get_model_profile``)
mirror the reference's API surface
(``profiling/flops_profiler/profiler.py`` bottom-of-file utilities).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import logger


# ---- formatting helpers (reference: flops_to_string / params_to_string) ---

def number_to_string(num: float, units: Optional[str] = None,
                     precision: int = 2) -> str:
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}
    if units is None:
        for units, s in scale.items():
            if abs(num) >= s and s > 1:
                break
        else:
            units = ""
    return f"{num / scale[units]:.{precision}f} {units}".rstrip()


def flops_to_string(flops: float, units=None, precision: int = 2) -> str:
    return number_to_string(flops, units, precision) + "FLOPs"


def params_to_string(n: float, units=None, precision: int = 2) -> str:
    return number_to_string(n, units, precision).rstrip() + ""


def macs_to_string(macs: float, units=None, precision: int = 2) -> str:
    return number_to_string(macs, units, precision) + "MACs"


def duration_to_string(seconds: float, precision: int = 2) -> str:
    if seconds >= 1:
        return f"{seconds:.{precision}f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.{precision}f} ms"
    return f"{seconds * 1e6:.{precision}f} us"


# ---- core measurement -----------------------------------------------------

def analyze_fn(fn: Callable, *args, static_argnums=()) -> Dict[str, float]:
    """Compile ``fn(*args)`` and read the optimized-HLO cost analysis.

    Returns flops / bytes accessed / peak (where the backend reports them).
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums)
    compiled = jitted.lower(*args).compile()
    out: Dict[str, float] = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        out["flops"] = float(cost.get("flops", 0.0))
        out["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
    except Exception as e:  # backends without cost analysis
        logger.warning("cost_analysis unavailable: %s", e)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["peak_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0) +
                getattr(mem, "argument_size_in_bytes", 0) +
                getattr(mem, "output_size_in_bytes", 0))
    except Exception as e:  # backends without memory analysis
        logger.debug("memory_analysis unavailable: %r", e)
    return out


def time_fn(fn: Callable, *args, warmup: int = 1, iters: int = 3) -> float:
    """Median wall-clock seconds of a blocked execution."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


class FlopsProfiler:
    """Profile one step function (reference: FlopsProfiler class;
    engine integration analog of engine.py:288,1850).

    Usage::

        prof = FlopsProfiler()
        stats = prof.profile(step_fn, state, batch)
        print(prof.report(stats))
    """

    def __init__(self, config=None):
        self.config = config

    def profile(self, fn: Callable, *args, params: Any = None,
                time_it: bool = True) -> Dict[str, float]:
        stats = analyze_fn(fn, *args)
        if params is not None:
            stats["params"] = float(sum(
                np.prod(np.shape(p)) for p in jax.tree.leaves(params)))
        if time_it:
            stats["latency_s"] = time_fn(fn, *args)
            if stats.get("flops"):
                stats["tflops_per_s"] = (
                    stats["flops"] / stats["latency_s"] / 1e12)
        return stats

    @staticmethod
    def report(stats: Dict[str, float], batch_size: Optional[int] = None,
               world_size: int = 1) -> str:
        lines = ["-" * 60, "DeepSpeed-TPU Flops Profiler", "-" * 60]
        if "params" in stats:
            lines.append(f"params:               "
                         f"{params_to_string(stats['params'])}")
        if "flops" in stats:
            lines.append(f"flops per step:       "
                         f"{flops_to_string(stats['flops'])}")
        if "bytes_accessed" in stats:
            lines.append(f"HBM bytes per step:   "
                         f"{number_to_string(stats['bytes_accessed'])}B")
        if "latency_s" in stats:
            lines.append(f"step latency:         "
                         f"{duration_to_string(stats['latency_s'])}")
        if "tflops_per_s" in stats:
            lines.append(f"achieved throughput:  "
                         f"{stats['tflops_per_s']:.2f} TFLOPS/device")
        if batch_size and "latency_s" in stats:
            sps = batch_size / stats["latency_s"]
            lines.append(f"samples/second:       {sps:.1f}")
        lines.append("-" * 60)
        return "\n".join(lines)


def get_model_profile(fn: Callable, args: Tuple = (), kwargs=None,
                      print_profile: bool = True,
                      as_string: bool = True):
    """Reference-parity helper (``profiling/flops_profiler`` public
    ``get_model_profile``): returns (flops, macs, params)."""
    kwargs = kwargs or {}
    prof = FlopsProfiler()
    stats = prof.profile(lambda *a: fn(*a, **kwargs), *args, time_it=False)
    flops = stats.get("flops", 0.0)
    macs = flops / 2
    params = stats.get("params", 0.0)
    if print_profile:
        logger.info("\n%s", prof.report(stats))
    if as_string:
        return (flops_to_string(flops), macs_to_string(macs),
                params_to_string(params))
    return flops, macs, params
