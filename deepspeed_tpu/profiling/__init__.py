from .flops_profiler import (FlopsProfiler, analyze_fn, duration_to_string,
                             flops_to_string, get_model_profile,
                             macs_to_string, number_to_string,
                             params_to_string, time_fn)

__all__ = ["FlopsProfiler", "analyze_fn", "time_fn", "get_model_profile",
           "flops_to_string", "macs_to_string", "params_to_string",
           "number_to_string", "duration_to_string"]
