"""JAX version compatibility shims.

The framework is written against the modern ``jax.shard_map`` API
(jax >= 0.6: ``check_vma``, partial-manual ``axis_names``).  Older
releases only ship ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling and the inverted ``auto=`` (axes NOT manual)
parameter.  Every ``shard_map`` in this package imports from here so the
whole tree runs unmodified on either API.
"""

from __future__ import annotations

import jax

try:                                    # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:                     # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

# the top-level export and the check_rep->check_vma rename did NOT land
# in the same release — key the kwarg spelling on the actual signature,
# not on where the function imported from
import inspect

_MODERN = "check_vma" in inspect.signature(_shard_map).parameters


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside traced code.
    ``jax.lax.axis_size`` where it exists; the classic constant-folded
    ``psum(1, axis)`` spelling elsewhere."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` with the modern keyword surface on any jax.

    ``axis_names``: the mesh axes the body is manual over (all axes when
    None) — translated to the legacy ``auto=`` complement on old jax.
    """
    kwargs = {}
    if _MODERN:
        kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
    else:
        kwargs["check_rep"] = check_vma
        if axis_names is not None:
            auto = (frozenset(mesh.axis_names) - frozenset(axis_names))
            # legacy partial-manual (`auto=`) is buggy: it silently
            # mis-reduces replicated outputs and CHECK-crashes (an
            # uncatchable process abort) on real auto sharding.  Size-1
            # axes shard nothing — drop them and run full-manual; a real
            # auto axis must refuse loudly HERE, not crash in XLA.
            auto = frozenset(a for a in auto if mesh.shape[a] > 1)
            if auto:
                raise NotImplementedError(
                    f"partial-manual shard_map (auto axes {sorted(auto)})"
                    " is unreliable on legacy jaxlib 0.4.x: it silently "
                    "mis-reduces or CHECK-crashes the compiler; upgrade "
                    "jax or make the region fully manual")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
