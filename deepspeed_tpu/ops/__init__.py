from .builder import AsyncIOBuilder, BuildError, OpBuilder
from .evoformer import evoformer_attention
from .flash_attention import flash_attention
from .paged_attention import paged_attention
from .sparse_attention import (BigBirdSparsityConfig,
                               BSLongformerSparsityConfig,
                               DenseSparsityConfig, FixedSparsityConfig,
                               VariableSparsityConfig,
                               block_sparse_attention,
                               make_block_sparse_attention)
from .spatial import (diffusers_transformer_block, geglu,
                      nhwc_group_norm, opt_bias_add, spatial_attention)
from .xla_attention import fused_attention

__all__ = [
    "AsyncIOBuilder", "BuildError", "OpBuilder",
    "evoformer_attention", "flash_attention", "paged_attention",
    "fused_attention",
    "BigBirdSparsityConfig", "BSLongformerSparsityConfig",
    "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "block_sparse_attention",
    "make_block_sparse_attention",
    "diffusers_transformer_block", "geglu", "nhwc_group_norm",
    "opt_bias_add", "spatial_attention",
]
