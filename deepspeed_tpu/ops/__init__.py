from .flash_attention import flash_attention
from .builder import AsyncIOBuilder, BuildError, OpBuilder

__all__ = ["flash_attention", "AsyncIOBuilder", "BuildError", "OpBuilder"]
