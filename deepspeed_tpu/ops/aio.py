"""Python handle over the native aio thread pool.

API parity with the reference's ``aio_handle``
(``csrc/aio/py_lib/py_ds_aio.cpp:15-80`` — block_size/queue_depth/
num_threads ctor; sync/async pread/pwrite; wait) consumed by the swap
machinery (``runtime/swap_tensor/partitioned_param_swapper.py:83``).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .builder import AsyncIOBuilder


class AsyncIOHandle:
    """Chunked, threaded file I/O for numpy buffers.

    ``queue_depth``/``single_submit``/``overlap_events`` exist for config
    parity with the reference handle only: the pool here is thread-based
    pread/pwrite (its submission queue is unbounded and always
    overlapped), so they change nothing and are merely recorded.
    """

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 128,
                 thread_count: int = 4, single_submit: bool = False,
                 overlap_events: bool = True):
        lib = AsyncIOBuilder().load()
        lib.aio_create.restype = ctypes.c_void_p
        lib.aio_create.argtypes = [ctypes.c_int, ctypes.c_long]
        lib.aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in ("aio_pread", "aio_pwrite", "aio_pwrite_trunc"):
            getattr(lib, fn).argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_long, ctypes.c_long]
        lib.aio_wait.argtypes = [ctypes.c_void_p]
        lib.aio_wait.restype = ctypes.c_int
        lib.aio_pending.argtypes = [ctypes.c_void_p]
        lib.aio_pending.restype = ctypes.c_int
        self._lib = lib
        self._h = lib.aio_create(thread_count, block_size)
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count
        self.single_submit = single_submit
        self.overlap_events = overlap_events

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.aio_destroy(h)
            self._h = None

    # ---- async (reference: async_pread/async_pwrite) --------------------
    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0):
        if not buffer.flags["C_CONTIGUOUS"]:
            raise ValueError("buffer must be C-contiguous")
        self._lib.aio_pread(self._h, os.fspath(path).encode(),
                            buffer.ctypes.data_as(ctypes.c_void_p),
                            buffer.nbytes, offset)

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0,
                     truncate: bool = False):
        """``truncate=True`` marks this a full-file rewrite: the file is
        truncated to ``offset + nbytes`` first so a smaller rewrite can't
        leave a stale tail behind.  Off by default — partial-write callers
        rely on surrounding bytes surviving."""
        if not buffer.flags["C_CONTIGUOUS"]:
            raise ValueError("buffer must be C-contiguous")
        fn = (self._lib.aio_pwrite_trunc if truncate
              else self._lib.aio_pwrite)
        fn(self._h, os.fspath(path).encode(),
           buffer.ctypes.data_as(ctypes.c_void_p),
           buffer.nbytes, offset)

    def wait(self) -> int:
        """Drain outstanding requests; returns number of failed chunks."""
        return self._lib.aio_wait(self._h)

    def pending(self) -> int:
        return self._lib.aio_pending(self._h)

    # ---- sync (reference: sync_pread/sync_pwrite) ------------------------
    def sync_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pread(buffer, path, offset)
        return self.wait()

    def sync_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0,
                    truncate: bool = False) -> int:
        self.async_pwrite(buffer, path, offset, truncate=truncate)
        return self.wait()
