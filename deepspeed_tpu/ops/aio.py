"""Python handle over the native aio thread pool.

API parity with the reference's ``aio_handle``
(``csrc/aio/py_lib/py_ds_aio.cpp:15-80`` — block_size/queue_depth/
num_threads ctor; sync/async pread/pwrite; wait) consumed by the swap
machinery (``runtime/swap_tensor/partitioned_param_swapper.py:83``).
"""

from __future__ import annotations

import ctypes
import os
import warnings
from typing import Optional

import numpy as np

from .builder import AsyncIOBuilder


class AioError(OSError):
    """Typed failure from the aio pool: a read against a missing or
    short file, or chunks the backend reported failed.  Callers that
    treat spill files as a cache (the KV tier, the swappers) catch this
    one type and fall back to recompute — a partial buffer must never
    be returned silently.

    ``path`` names the file, ``expected`` the bytes the caller needed,
    ``actual`` the bytes available (or failed-chunk count for a backend
    failure; ``None`` when the file is missing outright)."""

    def __init__(self, msg: str, path: Optional[str] = None,
                 expected: Optional[int] = None,
                 actual: Optional[int] = None):
        super().__init__(msg)
        self.path = path
        self.expected = expected
        self.actual = actual


class AsyncIOHandle:
    """Chunked, threaded file I/O for numpy buffers.

    All reference-handle knobs are consumed (semantics in
    ``native/aio.cpp``): ``queue_depth`` bounds in-flight chunks
    (submission backpressure), ``single_submit`` disables chunking (and
    therefore O_DIRECT — a whole unaligned request is buffered),
    ``overlap_events=False`` drains each submit before returning, and
    ``use_odirect`` routes 4096-aligned spans through O_DIRECT with
    pooled aligned bounce buffers (page-cache bypass — the path that
    scales on a real NVMe mount; tmpfs et al. fall back silently,
    ``odirect_ops()`` reports what actually happened)."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 128,
                 thread_count: int = 4, single_submit: bool = False,
                 overlap_events: bool = True, use_odirect: bool = False,
                 backend: str = "auto"):
        """``backend``: "uring" (io_uring — real kernel queue depth,
        registered O_DIRECT buffers), "threads" (pread/pwrite worker
        pool), or "auto" (io_uring when the kernel/sandbox allows it;
        silently falls back otherwise — ``self.backend`` reports what
        was actually built)."""
        assert backend in ("auto", "uring", "threads"), backend
        lib = AsyncIOBuilder().load()
        lib.aio_create3.restype = ctypes.c_void_p
        lib.aio_create3.argtypes = [ctypes.c_int, ctypes.c_long,
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int]
        lib.aio_backend.argtypes = [ctypes.c_void_p]
        lib.aio_backend.restype = ctypes.c_int
        lib.aio_uring_available.restype = ctypes.c_int
        lib.aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in ("aio_pread", "aio_pwrite", "aio_pwrite_trunc"):
            getattr(lib, fn).argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_long, ctypes.c_long]
        lib.aio_wait.argtypes = [ctypes.c_void_p]
        lib.aio_wait.restype = ctypes.c_int
        lib.aio_pending.argtypes = [ctypes.c_void_p]
        lib.aio_pending.restype = ctypes.c_int
        lib.aio_odirect_ops.argtypes = [ctypes.c_void_p]
        lib.aio_odirect_ops.restype = ctypes.c_long
        lib.aio_tasks_total.argtypes = [ctypes.c_void_p]
        lib.aio_tasks_total.restype = ctypes.c_long
        self._lib = lib
        want = {"auto": -1, "threads": 0, "uring": 1}[backend]
        self._h = lib.aio_create3(thread_count, block_size, queue_depth,
                                  int(single_submit), int(overlap_events),
                                  int(use_odirect), want)
        self.backend = ("uring" if lib.aio_backend(self._h) == 1
                        else "threads")
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count
        self.single_submit = single_submit
        self.overlap_events = overlap_events
        self.use_odirect = use_odirect

    @classmethod
    def from_config(cls, aio_cfg, **overrides) -> "AsyncIOHandle":
        """Build from a :class:`~deepspeed_tpu.config.config.AioConfig`
        (the reference reads the same block at
        partitioned_param_swapper.py:83)."""
        kw = dict(block_size=aio_cfg.block_size,
                  queue_depth=aio_cfg.queue_depth,
                  thread_count=aio_cfg.thread_count,
                  single_submit=aio_cfg.single_submit,
                  overlap_events=aio_cfg.overlap_events,
                  use_odirect=getattr(aio_cfg, "use_odirect", False),
                  backend=getattr(aio_cfg, "backend", "auto"))
        kw.update(overrides)
        return cls(**kw)

    def odirect_ops(self) -> int:
        """Chunks that actually went through O_DIRECT so far."""
        return int(self._lib.aio_odirect_ops(self._h))

    def tasks_total(self) -> int:
        return int(self._lib.aio_tasks_total(self._h))

    def __del__(self):
        h = getattr(self, "_h", None)
        lib = getattr(self, "_lib", None)
        if not h or lib is None:
            return
        leaked = int(lib.aio_pending(h))
        if leaked:
            # a handle dropped with ops still queued is a caller bug
            # (buffers may be freed while worker threads still target
            # them) — surface it, then drain so destruction is safe
            warnings.warn(
                f"AsyncIOHandle destroyed with {leaked} pending op(s); "
                "call wait() before dropping the handle", ResourceWarning,
                stacklevel=2)
            lib.aio_wait(h)
        lib.aio_destroy(h)
        self._h = None

    # ---- async (reference: async_pread/async_pwrite) --------------------
    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0):
        """Queue a read of exactly ``buffer.nbytes`` at ``offset``.

        Raises :class:`AioError` up front when the file is missing or
        shorter than the requested span — queueing would otherwise fill
        part of the buffer and leave the rest stale, and the failure
        would only surface as an aggregate failed-chunk count at
        ``wait()`` with no way to name the file."""
        if not buffer.flags["C_CONTIGUOUS"]:
            raise ValueError("buffer must be C-contiguous")
        p = os.fspath(path)
        need = offset + buffer.nbytes
        try:
            have = os.stat(p).st_size
        except OSError as e:
            raise AioError(f"async_pread: {p!r}: {e.strerror or e}",
                           path=p, expected=need) from e
        if have < need:
            raise AioError(
                f"async_pread: short file {p!r}: need {need} bytes, "
                f"file has {have} — refusing a partial read",
                path=p, expected=need, actual=have)
        self._lib.aio_pread(self._h, p.encode(),
                            buffer.ctypes.data_as(ctypes.c_void_p),
                            buffer.nbytes, offset)

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0,
                     truncate: bool = False):
        """``truncate=True`` marks this a full-file rewrite: the file is
        truncated to ``offset + nbytes`` first so a smaller rewrite can't
        leave a stale tail behind.  Off by default — partial-write callers
        rely on surrounding bytes surviving."""
        if not buffer.flags["C_CONTIGUOUS"]:
            raise ValueError("buffer must be C-contiguous")
        fn = (self._lib.aio_pwrite_trunc if truncate
              else self._lib.aio_pwrite)
        fn(self._h, os.fspath(path).encode(),
           buffer.ctypes.data_as(ctypes.c_void_p),
           buffer.nbytes, offset)

    def wait(self) -> int:
        """Drain outstanding requests; returns number of failed chunks."""
        return self._lib.aio_wait(self._h)

    def pending(self) -> int:
        return self._lib.aio_pending(self._h)

    # ---- sync (reference: sync_pread/sync_pwrite) ------------------------
    def sync_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        """Read and drain; raises :class:`AioError` when any chunk fails
        (a file that shrank or vanished after the up-front size check,
        an EIO from the device) instead of handing back a buffer that is
        silently part-stale.  Returns 0 on success, for API parity with
        the reference's failed-chunk count."""
        self.async_pread(buffer, path, offset)
        failed = self.wait()
        if failed:
            raise AioError(
                f"sync_pread: {failed} failed chunk(s) reading "
                f"{os.fspath(path)!r}", path=os.fspath(path),
                expected=offset + buffer.nbytes, actual=failed)
        return 0

    def sync_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0,
                    truncate: bool = False) -> int:
        self.async_pwrite(buffer, path, offset, truncate=truncate)
        return self.wait()
