"""Mixed-input GEMM: int8 weights x bf16 activations, dequant in VMEM.

TPU-native analog of the reference's mixed-input serving GEMMs
(``inference/v2/kernels/core_ops/cuda_linear/include/
weight_prepacking.cuh`` + ``fp6_linear.cu`` — FP6xFP16 GEMM that
dequantizes weight fragments in registers between the global-memory load
and the tensor-core MMA, so the weight read is quantized-sized).  Here
the quantized weight tile is DMA'd into VMEM int8-sized and widened to
bf16 *inside the kernel* right before the MXU dot — HBM traffic for the
weight is 1 byte/element instead of 2 (bf16) or 4 (the dequant-then-
matmul fallback when XLA fails to fuse).

Consumes the row-wise serving layout directly
(:func:`deepspeed_tpu.ops.quant.quantize_rowwise`: int8 payload in the
weight's own shape, fp32 scale per contraction row) — no repacking.

Like the flash kernel (ops/flash_attention.py), this is interpret-tested
everywhere and probe-gated at runtime: on this rig Mosaic kernels are
crippled through the axon tunnel (see ops/flash_attention.py:27), so the
serving engine times kernel-vs-XLA once post-compile and keeps the
winner.  The kernel exists for bare-metal TPUs where the weight-
bandwidth floor is the decode bound.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_bf16(w, interpret: bool):
    """Force the dequantized tile to MATERIALIZE as bf16.

    Interpret mode runs the kernel body as ordinary traced XLA ops, and
    XLA fuses the bf16 dequant multiply straight into the f32 dot —
    skipping the bf16 rounding the MXU feed applies on hardware.  An
    optimization barrier pins the intermediate, so interpret-tested
    numerics match the real kernel (and the bf16 XLA reference paths
    the engine probes against).  No-op on real TPUs."""
    return jax.lax.optimization_barrier(w) if interpret else w


def _mixed_kernel(x_ref, d_ref, s_ref, o_ref, acc_ref, *, interpret):
    """One (bm, bn) output tile; grid dim 2 walks the K blocks."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # dequant IN VMEM: int8 tile -> bf16, scaled per contraction row.
    # bf16 keeps the MXU on its native input width; the f32 accumulator
    # carries the precision.
    w = _round_bf16(d_ref[...].astype(jnp.bfloat16)
                    * s_ref[...].astype(jnp.bfloat16), interpret)
    acc_ref[...] += jax.lax.dot(
        x_ref[...].astype(jnp.bfloat16), w,
        preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(2) - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _tile_plan(x, Kb: int, N: int, block_m: int, block_n: int,
               block_k: int):
    """Shared tiling scaffold for the mixed-GEMM kernels: auto block_m
    (decode bursts are small — pad M up to a lane-friendly multiple),
    clamp K/N blocks, and reject non-dividing contractions rather than
    silently pad them.  ``Kb``: the kernel's K-walk extent (K for int8,
    K/2 packed rows for int4).  Returns (x_padded, M, Mp, block_m, bk,
    bn)."""
    M = x.shape[0]
    if block_m <= 0:
        block_m = min(128, max(8, 1 << (max(M - 1, 1)).bit_length()))
    bk = min(block_k, Kb)
    bn = min(block_n, N)
    if Kb % bk or N % bn:
        raise ValueError(f"K-extent={Kb}/N={N} must divide "
                         f"block_k={bk}/block_n={bn}")
    Mp = -(-M // block_m) * block_m
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    return x, M, Mp, block_m, bk, bn


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "block_k", "interpret",
                                             "out_dtype"))
def mixed_matmul_2d(x: jax.Array, data: jax.Array, scale: jax.Array,
                    *, block_m: int = 0, block_n: int = 512,
                    block_k: int = 512, out_dtype=jnp.bfloat16,
                    interpret: bool = False) -> jax.Array:
    """``x [M, K] @ (int8 data [K, N] * scale [K, 1]) -> [M, N]``."""
    M, K = x.shape
    K2, N = data.shape
    assert K == K2 and scale.shape[0] == K, (x.shape, data.shape,
                                             scale.shape)
    x, M, Mp, block_m, bk, bn = _tile_plan(x, K, N, block_m, block_n,
                                           block_k)
    scale2 = scale.reshape(K, 1)

    out = pl.pallas_call(
        functools.partial(_mixed_kernel, interpret=interpret),
        grid=(Mp // block_m, N // bn, K // bk),
        in_specs=[
            pl.BlockSpec((block_m, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((bk, 1), lambda i, j, k: (k, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, bn), jnp.float32)],
        interpret=interpret,
    )(x, data, scale2)
    return out[:M] if Mp != M else out


def _mixed4_kernel(x1_ref, x2_ref, d_ref, s1_ref, s2_ref, o_ref, acc_ref,
                   *, interpret):
    """Packed-int4 tile: the byte block unpacks IN VMEM into the two
    strided contraction halves (lo nibble = flat row j, hi = j + K/2 —
    ops/quant.quantize_rowwise4), each fed to its own MXU dot against
    the matching activation tile.  HBM streams 0.5 byte/weight."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    from .quant import unpack_nibbles
    lo, hi = unpack_nibbles(d_ref[...])
    w1 = _round_bf16(lo.astype(jnp.bfloat16)
                     * s1_ref[...].astype(jnp.bfloat16), interpret)
    w2 = _round_bf16(hi.astype(jnp.bfloat16)
                     * s2_ref[...].astype(jnp.bfloat16), interpret)
    acc_ref[...] += jax.lax.dot(
        x1_ref[...].astype(jnp.bfloat16), w1,
        preferred_element_type=jnp.float32)
    acc_ref[...] += jax.lax.dot(
        x2_ref[...].astype(jnp.bfloat16), w2,
        preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(2) - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "block_k", "interpret",
                                             "out_dtype"))
def mixed4_matmul_2d(x: jax.Array, data: jax.Array, scale: jax.Array,
                     *, block_m: int = 0, block_n: int = 512,
                     block_k: int = 512, out_dtype=jnp.bfloat16,
                     interpret: bool = False) -> jax.Array:
    """``x [M, K] @ unpack(int4 data [K/2, N], scale [K, 1]) -> [M, N]``.

    ``data`` byte row j packs flat contraction rows j (lo nibble) and
    j + K/2 (hi).  The x and scale operands are passed TWICE with offset
    index maps — one view per half — so the kernel needs no gather."""
    M, K = x.shape
    Kh, N = data.shape
    assert K == 2 * Kh and scale.shape[0] == K, (x.shape, data.shape,
                                                 scale.shape)
    x, M, Mp, block_m, bk, bn = _tile_plan(x, Kh, N, block_m, block_n,
                                           block_k)
    nk = Kh // bk
    scale2 = scale.reshape(K, 1)

    out = pl.pallas_call(
        functools.partial(_mixed4_kernel, interpret=interpret),
        grid=(Mp // block_m, N // bn, nk),
        in_specs=[
            pl.BlockSpec((block_m, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_m, bk),
                         lambda i, j, k, _nk=nk: (i, k + _nk)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((bk, 1), lambda i, j, k: (k, 0)),
            pl.BlockSpec((bk, 1), lambda i, j, k, _nk=nk: (k + _nk, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, bn), jnp.float32)],
        interpret=interpret,
    )(x, x, data, scale2, scale2)
    return out[:M] if Mp != M else out


def mixed_matmul(x: jax.Array, qt, *, contract_dims: int = 1,
                 interpret: bool = False, out_dtype=None) -> jax.Array:
    """``x @ dequant(qt)`` through the mixed-input kernel family.

    ``x``: [..., K]; ``qt``: a row-wise int8 (weight-shaped payload) or
    packed row-wise int4 ("rowwise4" flat [K/2, N])
    :class:`~deepspeed_tpu.ops.quant.QuantizedTensor` whose payload's
    first ``contract_dims`` dims flatten into the contraction (K) and
    the rest into N — e.g. an attention output projection [H, Dh, d]
    uses ``contract_dims=2``.  Scales on a coarser leading granularity
    than K (per-head for [H, Dh, d]) broadcast down to rows.
    """
    from .quant import is_rowwise_int4
    int4 = is_rowwise_int4(qt)
    assert int4 or (qt.bits == 8 and qt.zero is None), \
        "mixed_matmul consumes the row-wise int8/int4 symmetric layouts"
    if jax.default_backend() != "tpu":
        interpret = True        # CPU/virtual meshes: no Mosaic lowering
    wshape = tuple(qt.shape)
    K = int(np.prod(wshape[:contract_dims]))
    N = int(np.prod(wshape[contract_dims:]))
    lead = x.shape[:-1]
    M = int(np.prod(lead)) if lead else 1
    assert x.shape[-1] == K, (x.shape, wshape, contract_dims)
    s = qt.scale.reshape(-1)
    if s.size != K:
        assert K % s.size == 0, (qt.scale.shape, K)
        # leading-dim scales are constant over their trailing rows
        s = jnp.broadcast_to(s[:, None], (s.size, K // s.size))
    out_dtype = out_dtype or x.dtype
    if int4:
        # the flat packing fixed K at quantize time; a caller using a
        # different contraction split would reshape "successfully" into
        # garbage — reject loudly instead
        assert qt.data.shape[-2] == K // 2, \
            ("rowwise4 payload packed for a different contraction split",
             qt.data.shape, K)
        y = mixed4_matmul_2d(x.reshape(M, K), qt.data.reshape(K // 2, N),
                             s.reshape(K, 1), out_dtype=out_dtype,
                             interpret=interpret)
    else:
        y = mixed_matmul_2d(x.reshape(M, K), qt.data.reshape(K, N),
                            s.reshape(K, 1), out_dtype=out_dtype,
                            interpret=interpret)
    return y.reshape(*lead, *wshape[contract_dims:])


def dequant_matmul_reference(x: jax.Array, qt, out_dtype=None) -> jax.Array:
    """The XLA fallback this kernel races in the probe: bf16 fused
    dequantize (ops/quant.dequantize row-wise fast path) then matmul."""
    from .quant import dequantize
    out_dtype = out_dtype or x.dtype
    w = dequantize(qt, jnp.bfloat16)
    wshape = tuple(qt.shape)
    K = wshape[0]
    y = x.reshape(-1, K) @ w.reshape(K, -1)
    return y.astype(out_dtype).reshape(*x.shape[:-1], *wshape[1:])
