"""Paged (blocked) decode attention — Pallas TPU kernel.

TPU-native analog of the reference FastGen kernel family
(``inference/v2/kernels/ragged_ops/blocked_flash`` — flash attention over
a block table, ``atom_builder`` splitting sequences into fixed KV atoms).

Where the XLA formulation in ``inference/model.py:_paged_attention``
gathers every scheduled token's *entire* padded context
(``kv_layer[tables]`` → [T, max_blocks, bs, 2, Hkv, D]) through HBM and
then re-reads it for the attention einsums, this kernel streams each
token's KV blocks through VMEM once with an online softmax, keeping the
(m, l, acc) running state on-chip:

* grid (T, num_blocks): one step attends one token (all heads) to one KV
  block — the block carries every kv head so the trailing block dims are
  full-size (a Mosaic tiling requirement) and DMA count stays at T×nb;
* the block table and positions ride scalar prefetch
  (``PrefetchScalarGridSpec``) so the kv BlockSpec's index_map picks the
  DMA'd block dynamically — paged indirection happens in the DMA engine,
  not as a gather;
* blocks past a token's position are skipped (``pl.when``) — budget
  padding tokens and table padding (-1 → trash row) contribute nothing;
* GQA: a static (unrolled) loop over kv heads, one [rep, D]×[D, bs] MXU
  dot per kv head per block.

CPU tests run the same kernel in interpret mode.  ``InferenceEngine``
probes this kernel against the XLA formulations at build time and keeps
whichever is fastest on the running backend (Mosaic through the axon
tunnel is much slower than bare-metal, so the probe matters).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _kernel(tables_ref, pos_ref, q_ref, kv_ref, *rest,
            block_size: int, scale: float,
            num_kv_heads: int, rep: int, alibi: bool, kv_quant: bool):
    # optional trailing inputs (order: kv scales, alibi slopes) before
    # the output and scratch refs
    rest = list(rest)
    ks_ref = rest.pop(0) if kv_quant else None
    slopes_ref = rest.pop(0) if alibi else None
    o_ref, acc_ref, m_ref, l_ref = rest
    t = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    pos = pos_ref[t]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # the whole block is past this token's position → nothing to add
    @pl.when(j * block_size <= pos)
    def _compute():
        cols = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (rep, block_size), 1)
        keep = cols <= pos
        for h in range(num_kv_heads):          # static unroll (GQA groups)
            q = q_ref[0, h * rep:(h + 1) * rep, :]         # [rep, D]
            k = kv_ref[0, :, 0, h, :]                      # [bs, D]
            v = kv_ref[0, :, 1, h, :]                      # [bs, D]
            if kv_quant:    # in-VMEM dequant: HBM only streamed codes
                k = (k.astype(jnp.float32)
                     * ks_ref[0, :, 0, h][:, None]).astype(q.dtype)
                v = (v.astype(jnp.float32)
                     * ks_ref[0, :, 1, h][:, None]).astype(q.dtype)
            s = jax.lax.dot_general(
                q, k, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [rep, bs]
            if alibi:       # ALiBi: slope_h * absolute key position
                s = s + (slopes_ref[h, :][:, None]
                         * cols.astype(jnp.float32))
            s = jnp.where(keep, s, NEG_INF)
            sl = slice(h * rep, (h + 1) * rep)
            m_prev, l_prev = m_ref[sl, :], l_ref[sl, :]
            m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            m_ref[sl, :] = m_new
            l_ref[sl, :] = l_prev * corr + p.sum(axis=1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)          # [rep, D]
            acc_ref[sl, :] = acc_ref[sl, :] * corr + pv

    @pl.when(j == nb - 1)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def paged_attention(kv_layer, q, seq_slot, positions, block_tables,
                    block_size: int, max_blocks_per_seq: int, scale: float,
                    slopes=None):
    """kv_layer: [blocks+1, bs, 2, Hkv, D] (last row = trash), or a
    (data, scales) tuple for a quantized cache (scales
    [blocks+1, bs, 2, Hkv] f32; codes dequantized in VMEM so HBM only
    streams the 1-byte payloads);
    q: [T, H, D]; seq_slot/positions: [T] i32;
    block_tables: [max_seqs, max_blocks] i32 (-1 pad) → out [T, H, D].
    ``slopes``: optional ALiBi per-head slopes, any shape reshapeable to
    [Hkv, rep] in head order h = hkv*rep + r (reference analog: the alibi
    operand of the inference softmax kernels, csrc/transformer/inference/
    csrc/softmax.cu)."""
    kv_scales = None
    if isinstance(kv_layer, tuple):
        kv_layer, kv_scales = kv_layer
    T, H, D = q.shape
    nblocks, bs, _, Hkv, _ = kv_layer.shape
    rep = H // Hkv
    nb = max_blocks_per_seq

    tables = block_tables[seq_slot, :nb]                   # [T, nb]
    tables = jnp.where(tables < 0, nblocks - 1, tables).astype(jnp.int32)
    positions = positions.astype(jnp.int32)

    def _kv_index(t, j, tbl, pos):
        # clamp past-position block indices to the last needed block:
        # consecutive grid steps then revisit the same block and Pallas
        # skips the DMA entirely (the kernel skips the compute)
        jj = jnp.minimum(j, pos[t] // bs)
        return (tbl[t, jj], 0, 0, 0, 0)

    def _ks_index(t, j, tbl, pos):
        jj = jnp.minimum(j, pos[t] // bs)
        return (tbl[t, jj], 0, 0, 0)

    alibi = slopes is not None
    kv_quant = kv_scales is not None
    in_specs = [
        pl.BlockSpec((1, H, D),
                     lambda t, j, tbl, pos: (t, 0, 0)),
        pl.BlockSpec((1, bs, 2, Hkv, D), _kv_index),
    ]
    operands = [tables, positions, q, kv_layer]
    if kv_quant:
        in_specs.append(pl.BlockSpec((1, bs, 2, Hkv), _ks_index))
        operands.append(kv_scales)
    if alibi:
        in_specs.append(pl.BlockSpec((Hkv, rep),
                                     lambda t, j, tbl, pos: (0, 0)))
        operands.append(jnp.asarray(slopes, jnp.float32)
                        .reshape(Hkv, rep))

    grid = (T, nb)
    out = pl.pallas_call(
        functools.partial(_kernel, block_size=bs, scale=scale,
                          num_kv_heads=Hkv, rep=rep, alibi=alibi,
                          kv_quant=kv_quant),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, H, D),
                                   lambda t, j, tbl, pos: (t, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((H, D), jnp.float32),
                pltpu.VMEM((H, 1), jnp.float32),
                pltpu.VMEM((H, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((T, H, D), q.dtype),
        interpret=_use_interpret(),
    )(*operands)
    return out
