"""Group-wise quantization + quantized collectives (ZeRO++ primitives).

TPU-native equivalents of the reference quantization kernels
(``csrc/quantization/`` — ``pt_binding.cpp:270-297`` exports ``quantize``/
``dequantize`` grouped sym/asym with configurable bits, ``swizzle_quant``,
``quantized_reduction`` the qgZ dequant-reduce-requant primitive,
``quantize_intX.cu`` int4/int8; and the ZeRO++ comm paths
``runtime/zero/partition_parameters.py:753`` CUDAQuantizer int8 weight
all-gather, ``runtime/comm/coalesced_collectives.py`` all_to_all_quant_reduce).

Everything is jnp — XLA fuses quantize into the surrounding collectives'
pack/unpack.  The collectives are written for use **inside shard_map**
(manual axes) so the wire format really is int8/int4:

* ``quantized_all_gather``  — qwZ: 2x less all-gather traffic than bf16.
* ``quantized_psum_scatter`` — qgZ: all-to-all int8 chunks, dequant,
  local reduce (the single-hop formulation of qgZ's
  all-to-all-based gradient reduction).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compat import axis_size


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Grouped quantized representation: int data + per-group scale/zero.

    Registered as a pytree with (bits, shape, dtype) as STATIC aux data:
    quantized trees can then cross jit boundaries as ARGUMENTS (device
    buffers) instead of closure constants — a closed-over llama3-8b int8
    tree baked 7.5 GB of constants into the HLO and killed the compile."""

    __slots__ = ("data", "scale", "zero", "bits", "shape", "dtype",
                 "layout")

    def __init__(self, data, scale, zero, bits: int,
                 shape: Tuple[int, ...], dtype, layout: str = "grouped"):
        self.data = data           # int8 (packed nibbles when bits=4)
        self.scale = scale         # f32 [groups, 1]
        self.zero = zero           # f32 [groups, 1] (None when symmetric)
        self.bits = bits
        self.shape = tuple(shape)  # original shape
        self.dtype = dtype         # original dtype
        # "grouped": grouped-flat [G, gsz];  "rowwise": weight-shaped
        # int8 with leading-dim scales;  "rowwise4": flat [K/2, N] packed
        # nibbles over strided contraction halves (byte j = rows j and
        # j + K/2) with leading-dim scales — the serving GEMM layouts
        self.layout = layout

    def tree_flatten(self):
        return (self.data, self.scale, self.zero), \
            (self.bits, self.shape, jnp.dtype(self.dtype), self.layout)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scale, zero = children
        bits, shape, dtype, layout = aux
        return cls(data, scale, zero, bits, shape, dtype, layout)

    def __repr__(self):
        return (f"QuantizedTensor(bits={self.bits}, shape={self.shape}, "
                f"dtype={self.dtype}, layout={self.layout})")


def _group(x: jax.Array, num_groups: int) -> jax.Array:
    flat = x.reshape(-1)
    assert flat.size % num_groups == 0, \
        f"size {flat.size} not divisible into {num_groups} groups"
    return flat.reshape(num_groups, -1)


def default_groups(size: int, target_group_size: int = 2048) -> int:
    """Largest group count dividing ``size`` with groups >= the target
    group size (shared by every grouped-quant entry point)."""
    groups = max(1, size // target_group_size)
    while size % groups:
        groups -= 1
    return groups


def _pack_int4(q: jax.Array) -> jax.Array:
    """Two int4 values per int8 byte (reference: quantize_int4 layout)."""
    q = q.reshape(q.shape[0], -1, 2)
    lo = (q[..., 0] & 0x0F).astype(jnp.uint8)
    hi = (q[..., 1] & 0x0F).astype(jnp.uint8)
    return (lo | (hi << 4)).astype(jnp.int8)


def unpack_nibbles(p: jax.Array):
    """(lo, hi) int8 nibbles of a packed byte array, sign-extended from
    4-bit two's complement.  Pure jnp — shared by the grouped unpack,
    the rowwise4 dequant, and the Pallas mixed-GEMM kernel."""
    u = p.astype(jnp.uint8)
    lo = (u & 0x0F).astype(jnp.int8)
    hi = ((u >> 4) & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    return lo, hi


def _unpack_int4(p: jax.Array) -> jax.Array:
    lo, hi = unpack_nibbles(p)
    return jnp.stack([lo, hi], axis=-1).reshape(p.shape[0], -1)


def quantize(x: jax.Array, bits: int = 8, num_groups: Optional[int] = None,
             symmetric: bool = True,
             stochastic: bool = False,
             rng: Optional[jax.Array] = None) -> QuantizedTensor:
    """Group-wise quantization (reference: ds_quantize_* /
    ds_sr_quantize_* sym/asym families)."""
    assert bits in (4, 8), bits
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    if num_groups is None:
        num_groups = default_groups(x.size)
    g = _group(x.astype(jnp.float32), num_groups)
    qmax = float(2 ** (bits - 1) - 1)          # 127 / 7
    qmin = -qmax - 1
    if symmetric:
        scale = jnp.max(jnp.abs(g), axis=1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = None
        t = g / scale
    else:
        gmin = jnp.min(g, axis=1, keepdims=True)
        gmax = jnp.max(g, axis=1, keepdims=True)
        scale = (gmax - gmin) / (qmax - qmin)
        scale = jnp.where(scale == 0, 1.0, scale)
        zero = gmin - qmin * scale
        t = (g - zero) / scale
    if stochastic:
        # stochastic rounding (reference: ds_sr_quantize_*)
        assert rng is not None, "stochastic quantization needs rng"
        t = jnp.floor(t + jax.random.uniform(rng, t.shape))
    else:
        t = jnp.round(t)
    q = jnp.clip(t, qmin, qmax).astype(jnp.int8)
    if bits == 4:
        q = _pack_int4(q)
    return QuantizedTensor(q, scale, zero, bits, orig_shape, orig_dtype)


def quantize_rowwise(x: jax.Array, bits: int = 8) -> QuantizedTensor:
    """int8 quantization with per-FIRST-DIM scales and data kept in the
    WEIGHT'S OWN SHAPE (no grouped-flat relayout).

    This is the serving-weight layout: the grouped-flat form's
    dequantize chain profiles as convert → reshape → LAYOUT COPY →
    matmul on TPU (the [G, gsz] tiling never matches the matmul
    operand's), ~6x the int8 bytes of HBM traffic per use.  Row-wise,
    the scale broadcasts along the trailing dims and the int8→bf16
    convert+multiply fuses into the matmul operand load."""
    assert bits == 8, "row-wise layout is int8-only (int4 packs lanes)"
    return _quantize_leading(x, lead_dims=1)


def _quantize_leading(x: jax.Array, lead_dims: int) -> QuantizedTensor:
    """Row-wise quantization generalized to ``lead_dims`` leading scale
    dims (stacked [L, rows, ...] weights use lead_dims=2)."""
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    red = tuple(range(lead_dims, x.ndim))
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=red,
                    keepdims=True) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -128, 127)
    return QuantizedTensor(q.astype(jnp.int8), scale, None, 8,
                           orig_shape, orig_dtype, layout="rowwise")


def is_rowwise_int8(qt: "QuantizedTensor") -> bool:
    """The layout the int8 mixed-input GEMM consumes (ops/mixed_gemm.py):
    symmetric int8 payload kept in the weight's own shape with leading-
    dim scales — the single source of truth for eligibility checks."""
    return (qt.bits == 8 and qt.zero is None
            and tuple(qt.data.shape) == tuple(qt.shape))


def is_rowwise_int4(qt: "QuantizedTensor") -> bool:
    """The packed layout the int4 mixed-input GEMM consumes: flat
    [K/2, N] strided-half nibbles with leading-dim scales
    (:func:`quantize_rowwise4`)."""
    return qt.bits == 4 and qt.zero is None and qt.layout == "rowwise4"


def is_mixed_gemm_layout(qt: "QuantizedTensor") -> bool:
    """Any layout the mixed-input GEMM family consumes natively."""
    return is_rowwise_int8(qt) or is_rowwise_int4(qt)


def quantize_rowwise4(x: jax.Array, contract_dims: int = 1,
                      lead_dims: int = 0) -> QuantizedTensor:
    """Packed int4 serving layout (reference analog: the FP6/int4
    weight-only GEMM's prepacked storage,
    inference/v2/kernels/core_ops/cuda_linear/linear_kernels_cuda.cu —
    real 0.5-byte/weight storage AND bandwidth, not emulation).

    ``x``: [*lead, K..., N...] where the first ``contract_dims`` dims
    after ``lead_dims`` stack dims flatten into the contraction K.
    Symmetric per-(lead, first-K-dim-row) scales, values in [-7, 7],
    and the flat contraction packed as STRIDED HALVES: byte row j holds
    flat rows j (lo nibble) and j + K/2 (hi nibble).  The strided split
    means unpacking is two contiguous row blocks — no lane interleave —
    which both the XLA dequant and the Pallas kernel exploit."""
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    lead = orig_shape[:lead_dims]
    K = int(np.prod(orig_shape[lead_dims:lead_dims + contract_dims]))
    N = int(np.prod(orig_shape[lead_dims + contract_dims:]) or 1)
    assert K % 2 == 0, f"int4 packing needs an even contraction ({K})"
    red = tuple(range(lead_dims + 1, x.ndim))
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=red,
                    keepdims=False) / 7.0
    scale = jnp.where(scale == 0, 1.0, scale)       # [*lead, S]
    S = scale.shape[-1]
    sb = scale.reshape(*lead, S, *([1] * (x.ndim - lead_dims - 1)))
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / sb), -7, 7)
    q = q.astype(jnp.int8).reshape(*lead, K, N)
    lo, hi = q[..., : K // 2, :], q[..., K // 2:, :]
    packed = ((lo & 0x0F) | ((hi & 0x0F) << 4)).astype(jnp.int8)
    return QuantizedTensor(packed, scale.reshape(*lead, S, 1), None, 4,
                           orig_shape, orig_dtype, layout="rowwise4")


def dequantize_rowwise4(qt: QuantizedTensor, dtype=None) -> jax.Array:
    """Unpack a :func:`quantize_rowwise4` payload back to the original
    weight shape (the XLA fallback path; the kernel unpacks in VMEM)."""
    out_dt = dtype or qt.dtype
    lo, hi = unpack_nibbles(qt.data)                # [*lead, K/2, N]
    flat = jnp.concatenate([lo, hi], axis=-2)       # [*lead, K, N]
    K, N = flat.shape[-2], flat.shape[-1]
    s = qt.scale.reshape(*qt.scale.shape[:-1])      # [*lead, S]
    S = s.shape[-1]
    w = flat.reshape(*flat.shape[:-2], S, K // S, N).astype(out_dt) \
        * s[..., None, None].astype(out_dt)
    return w.reshape(qt.shape).astype(out_dt)


def dequantize(qt: QuantizedTensor, dtype=None) -> jax.Array:
    """(reference: dequantize / dequantize_int4_to_half_experimental)."""
    if qt.layout == "rowwise4":
        return dequantize_rowwise4(qt, dtype)
    out_dt = dtype or qt.dtype
    q = _unpack_int4(qt.data) if qt.bits == 4 else qt.data
    if qt.bits == 8 and qt.zero is None \
            and tuple(q.shape) == tuple(qt.shape):
        # row-wise layout: no reshape, scale broadcasts; computing in
        # the output dtype lets XLA fuse convert+mul into the consumer
        # instead of materializing an f32 copy of the whole weight
        return q.astype(out_dt) * qt.scale.astype(out_dt)
    g = q.astype(jnp.float32) * qt.scale
    if qt.zero is not None:
        g = g + qt.zero
    return g.reshape(qt.shape).astype(out_dt)


def quantized_reduction(qts, dtype=jnp.float32) -> jax.Array:
    """Dequantize-and-mean over a sequence of quantized tensors — the qgZ
    core primitive (reference: quant_reduce.cu ``quantized_reduction``)."""
    acc = dequantize(qts[0], jnp.float32)
    for qt in qts[1:]:
        acc = acc + dequantize(qt, jnp.float32)
    return (acc / len(qts)).astype(dtype)


# --------------------------------------------------------------------------
# Quantized collectives — call INSIDE shard_map (manual mesh axes)
# --------------------------------------------------------------------------

def quantized_all_gather(x: jax.Array, axis_name: str, bits: int = 8,
                         num_groups: Optional[int] = None,
                         gather_dim: int = 0) -> jax.Array:
    """qwZ: quantize the local shard, all-gather int data + scales,
    dequantize (reference: CUDAQuantizer gather path
    partition_parameters.py:753 + AllGatherCoalescedHandle.wait dequant
    partition_parameters.py:675).  Wire bytes: 1/2 (int8) or 1/4 (int4)
    of bf16."""
    qt = quantize(x, bits=bits, num_groups=num_groups)
    data = jax.lax.all_gather(qt.data, axis_name)          # [n, ...]
    scale = jax.lax.all_gather(qt.scale, axis_name)
    n = data.shape[0]
    parts = [dequantize(QuantizedTensor(data[i], scale[i], None, bits,
                                        qt.shape, qt.dtype))
             for i in range(n)]
    return jnp.concatenate(parts, axis=gather_dim)


def quantized_psum_scatter(x: jax.Array, axis_name: str, bits: int = 8,
                           num_groups: Optional[int] = None,
                           mean: bool = False,
                           pad: bool = False) -> jax.Array:
    """qgZ single-hop: split the local (unreduced) tensor into one chunk
    per rank along dim 0, quantize each, all-to-all, dequantize and reduce
    locally (reference: all_to_all_quant_reduce
    runtime/comm/coalesced_collectives.py + quant_reduce.cu).  Wire bytes:
    int8/int4 instead of fp32 — 4-8x less reduce traffic.

    ``pad``: a dim 0 the axis does not divide is zero-filled up to the
    next multiple of the axis size and the PADDED per-rank shard is
    returned (callers slice; ``quantized_all_reduce``'s padding path
    does).  Off, a non-divisible shape asserts — the historical
    contract, which keeps accidental layout changes loud."""
    n = axis_size(axis_name)
    if pad and x.shape[0] % n:
        pad_rows = (-x.shape[0]) % n
        x = jnp.concatenate(
            [x, jnp.zeros((pad_rows,) + x.shape[1:], x.dtype)])
    assert x.shape[0] % n == 0, (x.shape, n)
    if bits == 4:
        # packed nibbles need an even group size; fold the group count
        # (keeping it a divisor of the per-destination chunk — the
        # scale regrouping below depends on that) until it is
        per_chunk = x.size // n
        ng = num_groups if num_groups is not None \
            else default_groups(per_chunk)
        while ng > 1 and (per_chunk % ng or (per_chunk // ng) % 2):
            ng -= 1
        assert (per_chunk // ng) % 2 == 0, \
            f"int4 quantized scatter needs an even chunk size ({per_chunk})"
        num_groups = ng
    chunks = x.reshape(n, x.shape[0] // n, *x.shape[1:])
    if num_groups is None:
        # per-destination-chunk grouping at the shared default group size
        # (one scale per whole chunk would let a single outlier wipe the
        # rest of the chunk's signal — reference uses ~2048-elem groups)
        num_groups = default_groups(x.size // n)
    qt = quantize(chunks, bits=bits, num_groups=num_groups * n)
    # regroup so each destination's scales travel with its data
    data = qt.data.reshape(n, -1)
    scale = qt.scale.reshape(n, -1)
    data = jax.lax.all_to_all(data, axis_name, split_axis=0, concat_axis=0,
                              tiled=False)
    scale = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                               concat_axis=0, tiled=False)
    per_rank_shape = chunks.shape[1:]
    acc = jnp.zeros(per_rank_shape, jnp.float32)
    groups_per_rank = qt.scale.shape[0] // n
    for i in range(n):
        q_i = QuantizedTensor(
            data[i].reshape(groups_per_rank, -1),
            scale[i].reshape(groups_per_rank, 1), None, bits,
            per_rank_shape, jnp.float32)
        acc = acc + dequantize(q_i)
    if mean:
        acc = acc / n
    return acc.astype(x.dtype)


def quantized_psum_scatter_dim(x: jax.Array, axis_name: str, dim: int = 0,
                               bits: int = 8) -> jax.Array:
    """``quantized_psum_scatter`` along an arbitrary dimension (the qgZ
    reduce-scatter leg for a grad leaf whose sharded dim isn't 0)."""
    if dim != 0:
        x = jnp.moveaxis(x, dim, 0)
    out = quantized_psum_scatter(x, axis_name, bits=bits)
    if dim != 0:
        out = jnp.moveaxis(out, 0, dim)
    return out


def quantized_all_reduce(x: jax.Array, axis_name: str,
                         bits: int = 8, pad: bool = False) -> jax.Array:
    """Quantized-wire all-reduce: int reduce-scatter + int all-gather.
    2 int8 bytes per element on the wire instead of 4 fp32 (reference:
    the fallback ``all_to_all_quant_reduce`` path of
    coalesced_collectives.py for tensors every rank keeps whole).

    A dim 0 the axis does not divide falls back to plain psum by
    default (the historical qgZ contract: tiny leaves ride the exact
    wire and training numerics stay put) — with ``pad=True`` it
    instead runs the padding path: flatten, zero-fill to a multiple of
    the axis size, quantized reduce, slice back.  The serving
    activation path (comm/overlap.py) opts into padding so every
    eligible reduction really rides the quantized wire."""
    n = axis_size(axis_name)
    if x.ndim == 0 or n == 1:
        return jax.lax.psum(x, axis_name)
    # shapes the direct scatter cannot take: a dim 0 the axis does not
    # divide, or (int4 packs two codes per byte) an odd per-rank chunk
    awkward = x.shape[0] % n or (bits == 4 and (x.size // n) % 2)
    if awkward:
        if not pad:
            return jax.lax.psum(x, axis_name)
        flat = x.reshape(-1)
        mult = n * (2 if bits == 4 else 1)
        fill = (-flat.shape[0]) % mult
        if fill:
            flat = jnp.concatenate(
                [flat, jnp.zeros((fill,), flat.dtype)])
        red = quantized_psum_scatter(flat, axis_name, bits=bits,
                                     pad=True)
        out = quantized_all_gather(red, axis_name, bits=bits,
                                   gather_dim=0)
        return out[:x.size].reshape(x.shape).astype(x.dtype)
    red = quantized_psum_scatter(x, axis_name, bits=bits)
    return quantized_all_gather(red, axis_name, bits=bits, gather_dim=0)


_FP8_FORMATS = {
    "fp8_e4m3": (jnp.float8_e4m3fn, 448.0),
    "fp8_e5m2": (jnp.float8_e5m2, 57344.0),
}


def fp_quantize(x: jax.Array, fmt: str = "fp8_e4m3",
                num_groups: Optional[int] = None) -> QuantizedTensor:
    """Float-to-float quantization (reference: csrc/fp_quantizer/
    fp_quantize.cpp — FP6/FP8/FP12 ``quantize``/``get_scales``).  TPU has
    native fp8 dtypes; per-group scales stretch each group onto the
    format's dynamic range.  FP6/FP12 have no hardware type here — use
    grouped int quantization (``quantize``) for sub-byte widths."""
    if fmt not in _FP8_FORMATS:
        raise ValueError(f"unknown fp format {fmt!r}; "
                         f"known: {sorted(_FP8_FORMATS)}")
    dtype, fmax = _FP8_FORMATS[fmt]
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    if num_groups is None:
        num_groups = default_groups(x.size)
    g = _group(x.astype(jnp.float32), num_groups)
    scale = jnp.max(jnp.abs(g), axis=1, keepdims=True) / fmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = (g / scale).astype(dtype)
    return QuantizedTensor(q, scale, None, 8, orig_shape, orig_dtype)


def swizzle_quant(x: jax.Array, bits: int = 8,
                  num_groups: Optional[int] = None) -> QuantizedTensor:
    """Layout-compat shim (reference: swizzle_quant — an interleaved
    layout for hierarchical all-to-all on NVLink+IB topologies).  XLA owns
    collective layouts on TPU, so this is plain grouped quantization."""
    return quantize(x, bits=bits, num_groups=num_groups)


# --------------------------------------------------------------------------
# 1-bit collectives (reference: runtime/comm/nccl.py:16 compressed_allreduce
# — cupy sign packing + per-chunk scale; the wire format behind
# OnebitAdam/ZeroOneAdam/OnebitLamb's up-to-5x comm reduction,
# docs/_tutorials/onebit-adam.md:2)
# --------------------------------------------------------------------------

def pack_signs(x: jax.Array) -> jax.Array:
    """[n] floats -> [n/8] uint8 of sign bits (1 = non-negative)."""
    n = x.shape[0]
    assert n % 8 == 0, f"pack_signs needs n % 8 == 0, got {n}"
    bits = (x >= 0).astype(jnp.uint8).reshape(n // 8, 8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    return (bits << shifts).sum(axis=1).astype(jnp.uint8)


def unpack_signs(p: jax.Array) -> jax.Array:
    """[n/8] uint8 -> [n] float32 in {-1, +1}."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (p[:, None] >> shifts) & 1
    return jnp.where(bits.reshape(-1) > 0, 1.0, -1.0).astype(jnp.float32)


def onebit_all_reduce(x: jax.Array, axis_name, err: jax.Array
                      ) -> Tuple[jax.Array, jax.Array]:
    """Error-compensated 1-bit mean-allreduce.

    Each shard sends sign bits (1/32 of fp32) + one fp32 scale
    (mean |x + err|); the mean of the per-shard sign*scale
    reconstructions comes back, and the local compression residual
    becomes the next step's error feedback.  Place at the DP gradient /
    momentum reduction boundary under ``shard_map`` (the engine's manual
    reduce region or a custom training loop).

    Returns (mean_reduced, new_err)."""
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    if err is not None:
        flat = flat + err.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
    c = flat
    scale = jnp.mean(jnp.abs(c[:n])) if pad else jnp.mean(jnp.abs(c))
    packed = pack_signs(c)
    local_q = jnp.where(c >= 0, scale, -scale)
    new_err = (c - local_q)[:n].reshape(shape).astype(dtype)

    all_packed = jax.lax.all_gather(packed, axis_name)     # [W, n/8] u8
    all_scale = jax.lax.all_gather(scale, axis_name)       # [W]
    W = all_packed.shape[0]
    signs = jax.vmap(unpack_signs)(all_packed)             # [W, n]
    mean = (signs * all_scale[:, None]).mean(axis=0)
    return mean[:n].reshape(shape).astype(dtype), new_err


# --------------------------------------------------------------------------
# Emulated minifloat formats + selective dequantize (reference:
# csrc/fp_quantizer — FP6 e3m2 / FP12 quantize + selective_dequantize used
# to expand only the rows a step touches, e.g. routed MoE experts)
# --------------------------------------------------------------------------

import functools


@functools.lru_cache(maxsize=None)
def _minifloat_table(exp_bits: int, man_bits: int) -> np.ndarray:
    """All non-negative representable values of a (1, e, m) minifloat
    with IEEE-style subnormals, ascending."""
    bias = (1 << (exp_bits - 1)) - 1
    vals = []
    for e in range(1 << exp_bits):
        for m in range(1 << man_bits):
            if e == 0:
                v = (m / (1 << man_bits)) * 2.0 ** (1 - bias)
            else:
                v = (1 + m / (1 << man_bits)) * 2.0 ** (e - bias)
            vals.append(v)
    return np.asarray(vals, np.float32)


_MINIFLOAT_FORMATS = {
    # name: (exp_bits, man_bits, container dtype)
    "fp6_e3m2": (3, 2, jnp.int8),
    "fp12_e4m7": (4, 7, jnp.int16),
}

# the single source of truth for weight-quant format names (serving
# config strings), bit widths, and minifloat format ids
WEIGHT_QUANT_BITS = {"int8": 8, "int4": 4, "fp6": 6, "fp12": 12}
MINIFLOAT_BY_BITS = {6: "fp6_e3m2", 12: "fp12_e4m7"}


def dequantize_any(qt: "QuantizedTensor", dtype=None) -> jax.Array:
    """Dispatch on layout/bit width: packed row-wise fp6, emulated
    minifloat (6/12), or grouped/row-wise int (4/8)."""
    if qt.layout == "rowwise6":
        return dequantize_rowwise6(qt, dtype)
    if qt.layout == "rowwise12":
        return dequantize_rowwise12(qt, dtype)
    if qt.bits in MINIFLOAT_BY_BITS:
        return minifloat_dequantize(qt, dtype)
    return dequantize(qt, dtype)


def minifloat_quantize(x: jax.Array, fmt: str = "fp6_e3m2",
                       num_groups: Optional[int] = None) -> QuantizedTensor:
    """Emulated FP6/FP12 grouped quantization: per-group scale onto the
    format's dynamic range, then nearest representable value; codes are
    stored in the smallest integer container (1 byte for fp6, 2 for
    fp12 — the reference packs 6-bit lanes the same way on GPUs without
    native types)."""
    if fmt not in _MINIFLOAT_FORMATS:
        raise ValueError(f"unknown minifloat format {fmt!r}; "
                         f"known: {sorted(_MINIFLOAT_FORMATS)}")
    eb, mb, container = _MINIFLOAT_FORMATS[fmt]
    table = _minifloat_table(eb, mb)
    fmax = float(table[-1])
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    if num_groups is None:
        num_groups = default_groups(x.size)
    g = _group(x.astype(jnp.float32), num_groups)
    scale = jnp.max(jnp.abs(g), axis=1, keepdims=True) / fmax
    scale = jnp.where(scale == 0, 1.0, scale)
    t = g / scale
    mags = jnp.abs(t)
    tab = jnp.asarray(table)
    # nearest representable: searchsorted against midpoints
    mids = jnp.asarray((table[1:] + table[:-1]) / 2.0)
    code = jnp.searchsorted(mids, mags).astype(jnp.int32)
    signed = jnp.where(t < 0, -code - 1, code)     # sign folded into code
    qt = QuantizedTensor(signed.astype(container), scale, None,
                         eb + mb + 1, orig_shape, orig_dtype)
    return qt


def minifloat_dequantize(qt: QuantizedTensor, dtype=None) -> jax.Array:
    fmt = MINIFLOAT_BY_BITS[qt.bits]
    eb, mb, _ = _MINIFLOAT_FORMATS[fmt]
    tab = jnp.asarray(_minifloat_table(eb, mb))
    code = qt.data.astype(jnp.int32)
    mag = tab[jnp.where(code < 0, -code - 1, code)]
    val = jnp.where(code < 0, -mag, mag) * qt.scale
    return val.reshape(qt.shape).astype(dtype or qt.dtype)


def _pack_codes(u: jax.Array, per_word: int, bits: int) -> jax.Array:
    """[..., N] codes → packed bytes: ``per_word`` codes per 24-bit word
    (3 bytes), little-endian bit order.  Serves the fp6 (4×6b) and fp12
    (2×12b) layouts."""
    g = u.astype(jnp.uint32).reshape(*u.shape[:-1], -1, per_word)
    word = g[..., 0]
    for i in range(1, per_word):
        word = word | (g[..., i] << (bits * i))
    b = jnp.stack([word & 0xFF, (word >> 8) & 0xFF, (word >> 16) & 0xFF],
                  axis=-1).astype(jnp.uint8)
    return b.reshape(*u.shape[:-1], -1)


def _unpack_codes(p: jax.Array, per_word: int, bits: int) -> jax.Array:
    """[..., 3M] bytes → [..., per_word*M] codes."""
    b = p.astype(jnp.uint32).reshape(*p.shape[:-1], -1, 3)
    word = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
    mask = (1 << bits) - 1
    codes = jnp.stack([(word >> (bits * i)) & mask
                       for i in range(per_word)], axis=-1)
    return codes.reshape(*p.shape[:-1], -1).astype(jnp.int32)


# (fmt, codes per 24-bit word, code bits, layout tag)
_PACKED_MINIFLOAT = {
    "rowwise6": ("fp6_e3m2", 4, 6),
    "rowwise12": ("fp12_e4m7", 2, 12),
}


def _quantize_rowwise_minifloat(x: jax.Array, layout: str,
                                lead_dims: int = 0) -> QuantizedTensor:
    """REAL packed minifloat weight storage (reference:
    csrc/fp_quantizer/fp_quantize.cu + the cuda_linear FP6 GEMM's
    prepacked weights — the emulated :func:`minifloat_quantize` spends a
    whole integer container per value).  Sign-magnitude codes packed
    along the LAST dim, symmetric per-leading-row scales like the other
    serving layouts; fp6 = 0.75 and fp12 = 1.5 bytes/element."""
    fmt, per_word, bits = _PACKED_MINIFLOAT[layout]
    eb, mb, _ = _MINIFLOAT_FORMATS[fmt]
    table = _minifloat_table(eb, mb)
    fmax = float(table[-1])
    sign_bit = 1 << (bits - 1)
    orig_shape, orig_dtype = tuple(x.shape), x.dtype
    assert orig_shape[-1] % per_word == 0, (orig_shape, per_word)
    assert x.ndim > lead_dims + 1, (
        f"{layout} needs at least one data dim beyond the scale rows "
        f"(shape {orig_shape}, lead_dims={lead_dims})")
    red = tuple(range(lead_dims + 1, x.ndim))
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=red,
                    keepdims=False) / fmax
    scale = jnp.where(scale == 0, 1.0, scale)
    S = scale.shape[-1]
    sb = scale.reshape(*scale.shape, *([1] * (x.ndim - lead_dims - 1)))
    t = x.astype(jnp.float32) / sb
    mids = jnp.asarray((table[1:] + table[:-1]) / 2.0)
    mag = jnp.searchsorted(mids, jnp.abs(t)).astype(jnp.uint32)
    ucode = jnp.where(t < 0, mag | sign_bit, mag)
    return QuantizedTensor(_pack_codes(ucode, per_word, bits),
                           scale.reshape(*scale.shape[:lead_dims], S, 1),
                           None, eb + mb + 1, orig_shape, orig_dtype,
                           layout=layout)


def _dequantize_rowwise_minifloat(qt: QuantizedTensor,
                                  dtype=None) -> jax.Array:
    out_dt = dtype or qt.dtype
    fmt, per_word, bits = _PACKED_MINIFLOAT[qt.layout]
    eb, mb, _ = _MINIFLOAT_FORMATS[fmt]
    tab = jnp.asarray(_minifloat_table(eb, mb))
    sign_bit = 1 << (bits - 1)
    codes = _unpack_codes(qt.data, per_word, bits)
    mag = tab[codes & (sign_bit - 1)]
    val = jnp.where((codes & sign_bit) != 0, -mag, mag)
    s = qt.scale.reshape(*qt.scale.shape[:-1])       # [*lead, S]
    val = val.reshape(*s.shape, -1, codes.shape[-1])
    out = val * s[..., None, None]
    return out.reshape(qt.shape).astype(out_dt)


def quantize_rowwise6(x: jax.Array, lead_dims: int = 0) -> QuantizedTensor:
    return _quantize_rowwise_minifloat(x, "rowwise6", lead_dims)


def dequantize_rowwise6(qt: QuantizedTensor, dtype=None) -> jax.Array:
    return _dequantize_rowwise_minifloat(qt, dtype)


def quantize_rowwise12(x: jax.Array, lead_dims: int = 0) -> QuantizedTensor:
    return _quantize_rowwise_minifloat(x, "rowwise12", lead_dims)


def dequantize_rowwise12(qt: QuantizedTensor, dtype=None) -> jax.Array:
    return _dequantize_rowwise_minifloat(qt, dtype)


def selective_dequantize(qt: QuantizedTensor, rows: jax.Array,
                         dtype=None) -> jax.Array:
    """Dequantize only the selected first-dim rows of a grouped
    QuantizedTensor (reference: selective_dequantize fp_quantizer — the
    MoE path expands just the routed experts' weights).

    Requires the grouping to not straddle rows (row size a multiple of
    the group size), which ``default_groups`` guarantees whenever the
    first dim divides the group count."""
    n_rows = qt.shape[0]
    G = qt.data.shape[0]
    if G % n_rows:
        raise ValueError(
            f"groups ({G}) must align with rows ({n_rows}) for "
            "selective dequantize; quantize with num_groups a multiple "
            "of the first dim")
    gpr = G // n_rows                       # groups per row
    rows = jnp.asarray(rows, jnp.int32)
    gidx = (rows[:, None] * gpr + jnp.arange(gpr)[None, :]).reshape(-1)
    sub = QuantizedTensor(
        qt.data[gidx], qt.scale[gidx],
        None if qt.zero is None else qt.zero[gidx],
        qt.bits, (int(rows.shape[0]),) + tuple(qt.shape[1:]), qt.dtype)
    return dequantize_any(sub, dtype)
