"""Spatial / diffusers inference ops (stable-diffusion UNet family).

TPU-native analog of the reference's spatial suite
(``csrc/spatial/csrc/opt_bias_add.cu`` — the three fused NHWC bias/add
variants; ``ops/transformer/inference/diffusers_attention.py:34``
DeepSpeedDiffusersAttention — fused QKV self/cross attention over H·W
latent tokens; ``diffusers_transformer_block.py:35``
DeepSpeedDiffusersTransformerBlock — LN → self-attn → LN → cross-attn →
LN → GEGLU feed-forward, residuals throughout).

TPU-first notes: the CUDA fused-elementwise kernels exist because torch
would otherwise launch one kernel per add — XLA fuses the whole
elementwise chain into its producer for free, so :func:`opt_bias_add`
is the API-parity surface over a fusion the compiler already does.
Latent layout stays NHWC (TPU convs are channels-last native); attention
flattens H·W into the sequence dim and routes through the same flash /
XLA attention impls as the language models (non-causal).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..models import layers as L


def opt_bias_add(x: jax.Array, bias: Optional[jax.Array] = None,
                 other: Optional[jax.Array] = None,
                 other_bias: Optional[jax.Array] = None) -> jax.Array:
    """Fused bias/residual adds over NHWC activations.

    Covers the reference's three variants (opt_bias_add.cu:24,50,81):
    ``bias_add`` (x+b), ``bias_add_add`` (x+b+other) and
    ``bias_add_bias_add`` (x+b + other+ob).  One jitted expression —
    XLA emits a single fused loop either way."""
    out = x if bias is None else x + bias
    if other is not None:
        out = out + (other if other_bias is None else other + other_bias)
    return out


def geglu(x: jax.Array, w: jax.Array,
          bias: Optional[jax.Array] = None) -> jax.Array:
    """GEGLU feed-forward gate (diffusers' FeedForward): the projection
    doubles the hidden dim; half gates the other through gelu."""
    h = x @ w
    if bias is not None:
        h = h + bias
    u, g = jnp.split(h, 2, axis=-1)
    # exact erf gelu, matching diffusers' GEGLU (not the tanh approx)
    return u * jax.nn.gelu(g, approximate=False)


def spatial_attention(x: jax.Array, params: Dict[str, Any],
                      num_heads: int,
                      context: Optional[jax.Array] = None,
                      attention_fn=None) -> jax.Array:
    """Self / cross attention over latent tokens
    (reference: DeepSpeedDiffusersAttention.selfAttention_fp).

    ``x``: [B, H, W, C] (NHWC latents) or [B, T, C] (pre-flattened).
    ``context``: optional [B, Tc, Cc] text-encoder states — when given,
    K/V project from it (cross attention).  ``params``: wq/wk/wv/wo
    (+ optional bo).  Non-causal; flash kernel when shapes tile."""
    spatial = x.ndim == 4
    if spatial:
        B, H, W, C = x.shape
        h = x.reshape(B, H * W, C)
    else:
        h = x
    B, T, C = h.shape
    D = C // num_heads
    kv_src = h if context is None else context
    dt = h.dtype
    q = (h @ params["wq"].astype(dt)).reshape(B, T, num_heads, D)
    k = (kv_src @ params["wk"].astype(dt)).reshape(
        B, kv_src.shape[1], num_heads, D)
    v = (kv_src @ params["wv"].astype(dt)).reshape(
        B, kv_src.shape[1], num_heads, D)
    if attention_fn is None:
        attention_fn = L.causal_attention
    o = attention_fn(q, k, v, causal=False)
    o = o.reshape(B, T, C) @ params["wo"].astype(dt)
    if "bo" in params:
        o = o + params["bo"].astype(dt)
    return o.reshape(x.shape) if spatial else o


def diffusers_transformer_block(x: jax.Array, params: Dict[str, Any],
                                num_heads: int,
                                context: Optional[jax.Array] = None,
                                eps: float = 1e-5,
                                attention_fn=None) -> jax.Array:
    """One diffusers 2D transformer block over NHWC latents
    (reference: DeepSpeedDiffusersTransformerBlock.forward):
    LN → self-attn → LN → cross-attn (when context given) → LN → GEGLU
    FF, residual around each.

    ``params``: {"ln1","ln2","ln3": {scale, bias}, "attn1","attn2":
    spatial_attention params, "ff": {"wi","bi","wo","bo"}}."""
    B, H, W, C = x.shape
    h = x.reshape(B, H * W, C)

    def ln(p, v):
        return L.layernorm(p, v, eps=eps)

    h = h + spatial_attention(ln(params["ln1"], h), params["attn1"],
                              num_heads, attention_fn=attention_fn)
    if "attn2" in params:
        # like the reference block, attn2 always runs: with no encoder
        # states it degrades to self-attention
        h = h + spatial_attention(ln(params["ln2"], h), params["attn2"],
                                  num_heads, context=context,
                                  attention_fn=attention_fn)
    ff = params["ff"]
    g = geglu(ln(params["ln3"], h), ff["wi"].astype(h.dtype),
              ff.get("bi"))
    h = h + (g @ ff["wo"].astype(h.dtype)
             + (ff["bo"].astype(h.dtype) if "bo" in ff else 0.0))
    return h.reshape(B, H, W, C)


def nhwc_group_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                    num_groups: int = 32, eps: float = 1e-5,
                    bias: Optional[jax.Array] = None,
                    residual: Optional[jax.Array] = None) -> jax.Array:
    """GroupNorm over NHWC latents with the fused pre-add the reference's
    spatial kernels provide (bias/residual folded into the same pass —
    here one fused XLA expression): the UNet ResBlock entry op."""
    out_dt = x.dtype        # before bias/residual promotion
    x = opt_bias_add(x, bias, residual)
    B, H, W, C = x.shape
    g = x.reshape(B, H, W, num_groups, C // num_groups).astype(jnp.float32)
    mean = g.mean(axis=(1, 2, 4), keepdims=True)
    var = g.var(axis=(1, 2, 4), keepdims=True)
    n = ((g - mean) * jax.lax.rsqrt(var + eps)).reshape(B, H, W, C)
    return (n * gamma + beta).astype(out_dt)
