"""Evoformer (DS4Science) attention: biased multi-head attention for
AlphaFold-style pair/MSA stacks.

TPU-native analog of the reference's DS4Sci_EvoformerAttention
(``deepspeed/ops/deepspeed4science/evoformer_attn.py:88`` — CUTLASS
fused kernels behind ``EvoformerFusedAttention``): attention over the
last sequence dim with up to two additive biases,

    softmax(Q K^T / sqrt(d) + bias1 + bias2) V

* ``bias1`` [B, N, 1, 1, Sk] — the MSA/row mask bias (broadcast over
  heads and queries);
* ``bias2`` [B, 1, H, Sq, Sk] — the pair-representation bias (broadcast
  over the N dim).

Shapes follow the reference contract: Q/K/V are [B, N, Sq|Sk, H, D].
XLA fuses the bias adds into the softmax the same way the CUTLASS
kernel fuses them into the matmul epilogue; the flash-style LSE/delta
backward of ``ops/xla_attention.py`` applies verbatim and is reused via
the same single-exp recompute trick.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def _check_biases(Q, K, bias1, bias2):
    B, N, Sq, H, D = Q.shape
    Sk = K.shape[2]
    if bias1 is not None:
        if (bias1.ndim != 5 or bias1.shape[:2] != (B, N)
                or bias1.shape[2:4] != (1, 1)
                or bias1.shape[4] != Sk):
            raise ValueError(f"bias1 shape {tuple(bias1.shape)} != "
                             f"[B={B}, N={N}, 1, 1, Sk={Sk}]")
    if bias2 is not None:
        if (bias2.ndim != 5 or bias2.shape[0] != B or bias2.shape[1] != 1
                or bias2.shape[2] != H or bias2.shape[3] != Sq
                or bias2.shape[4] != Sk):
            raise ValueError(f"bias2 shape {tuple(bias2.shape)} != "
                             f"[B={B}, 1, H={H}, Sq={Sq}, Sk={Sk}]")


def _logits(Q, K, bias1, bias2, scale):
    # [B, N, Sq, H, D] x [B, N, Sk, H, D] -> [B, N, H, Sq, Sk]
    s = jnp.einsum("bnqhd,bnkhd->bnhqk", Q, K) * scale
    s = s.astype(jnp.float32)
    if bias1 is not None:
        # [B, N, 1, 1, Sk] broadcasts over (H, Sq)
        s = s + bias1.astype(jnp.float32)
    if bias2 is not None:
        # [B, 1, H, Sq, Sk] broadcasts over N
        s = s + bias2.astype(jnp.float32)
    return s


def _fwd(Q, K, V, bias1, bias2, scale):
    s = _logits(Q, K, bias1, bias2, scale)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None]).astype(Q.dtype)
    o = jnp.einsum("bnhqk,bnkhd->bnqhd", p, V)
    return o, lse


def _bwd_core(Q, K, V, bias1, bias2, o, lse, do, scale):
    delta = jnp.einsum("bnqhd,bnqhd->bnhq", do.astype(jnp.float32),
                       o.astype(jnp.float32))
    s = _logits(Q, K, bias1, bias2, scale)
    p = jnp.exp(s - lse[..., None]).astype(Q.dtype)
    dv = jnp.einsum("bnhqk,bnqhd->bnkhd", p, do)
    dp = jnp.einsum("bnqhd,bnkhd->bnhqk", do, V)
    ds = (p.astype(jnp.float32)
          * (dp.astype(jnp.float32) - delta[..., None]))
    dq = jnp.einsum("bnhqk,bnkhd->bnqhd",
                    (ds * scale).astype(Q.dtype), K)
    dk = jnp.einsum("bnhqk,bnqhd->bnkhd",
                    (ds * scale).astype(Q.dtype), Q)
    db1 = ds.sum(axis=(2, 3), keepdims=True) \
        if bias1 is not None else None              # [B, N, 1, 1, Sk]
    db2 = ds.sum(axis=1, keepdims=True) \
        if bias2 is not None else None              # [B, 1, H, Sq, Sk]
    return dq, dk, dv, db1, db2


def _make(variant: str):
    has1 = "1" in variant
    has2 = "2" in variant

    @jax.custom_vjp
    def attn(Q, K, V, *biases):
        b1 = biases[0] if has1 else None
        b2 = biases[-1] if has2 else None
        scale = 1.0 / math.sqrt(Q.shape[-1])
        o, _ = _fwd(Q, K, V, b1, b2, scale)
        return o

    def fwd(Q, K, V, *biases):
        b1 = biases[0] if has1 else None
        b2 = biases[-1] if has2 else None
        scale = 1.0 / math.sqrt(Q.shape[-1])
        o, lse = _fwd(Q, K, V, b1, b2, scale)
        return o, (Q, K, V, b1, b2, o, lse)

    def bwd(res, do):
        Q, K, V, b1, b2, o, lse = res
        scale = 1.0 / math.sqrt(Q.shape[-1])
        dq, dk, dv, db1, db2 = _bwd_core(Q, K, V, b1, b2, o, lse, do,
                                         scale)
        grads = [dq, dk, dv]
        if has1:
            grads.append(db1.astype(b1.dtype))
        if has2:
            grads.append(db2.astype(b2.dtype))
        return tuple(grads)

    attn.defvjp(fwd, bwd)
    return attn


_VARIANTS = {v: _make(v) for v in ("", "1", "2", "12")}


def evoformer_attention(Q, K, V,
                        biases: Optional[Sequence] = None) -> jax.Array:
    """Drop-in for ``DS4Sci_EvoformerAttention(Q, K, V, biases)``
    (reference: evoformer_attn.py:88): Q/K/V [B, N, S, H, D], up to two
    additive biases (see module docstring for their shapes)."""
    biases = [b for b in (biases or []) if b is not None]
    if len(biases) > 2:
        raise ValueError("at most two biases")
    b1 = b2 = None
    for b in biases:
        if b.ndim != 5:
            raise ValueError(
                f"bias rank {b.ndim} != 5; expected [B, N, 1, 1, Sk] "
                "(mask bias) or [B, 1, H, Sq, Sk] (pair bias)")
        if b.shape[2] == 1 and b.shape[3] == 1:
            if b1 is not None:
                raise ValueError("two mask-shaped ([B, N, 1, 1, Sk]) "
                                 "biases passed")
            b1 = b
        else:
            if b2 is not None:
                raise ValueError("two pair-shaped biases passed — one "
                                 "must be [B, N, 1, 1, Sk]")
            b2 = b
    _check_biases(Q, K, b1, b2)
    variant = ("1" if b1 is not None else "") + \
        ("2" if b2 is not None else "")
    args = [x for x in (b1, b2) if x is not None]
    return _VARIANTS[variant](Q, K, V, *args)
