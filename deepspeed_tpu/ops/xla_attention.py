"""Flash-style attention in pure XLA: hand-written VJP with an LSE residual.

The Pallas flash kernel (``ops/flash_attention.py``) is the right answer on
bare-metal TPUs, but XLA's stock softmax-attention autodiff is measurably
beatable *without* Mosaic too: the standard backward recomputes the
forward's full two-reduction softmax and forms ``rowsum(P * dP)`` — three
extra O(S^2) memory passes that a flash-style backward avoids by

* saving the per-row log-sum-exp (``lse`` — O(S), not O(S^2)) so the
  recomputed probabilities are one ``exp`` away (no max/sum re-reduction),
* computing the softmax-Jacobian row term as ``delta = rowsum(dO * O)``
  (O(S·D) traffic) instead of ``rowsum(P * dP)`` (O(S^2)).

On top of the VJP, causal attention runs BLOCK-CAUSAL: queries split
into ``_NUM_Q_BLOCKS`` blocks, each attending only to its visible key
prefix — the upper-triangle block quadrants are never computed, cutting
work to (NB+1)/(2NB) of the full square.

Measured on a v5e chip (B32 H12 S1024 D64, bf16): stock XLA autodiff
14.6 ms fwd+bwd -> 12.9 (custom VJP) -> 11.6 (block-causal, NB=8);
fwd alone 9.5 -> 5.7 ms.  GPT-2-small training throughput moved
83k -> 106k tok/s across the two changes.  Numerics identical to bf16
tolerance.  The same tricks are what the reference's fused kernels do
in CUDA (csrc/transformer softmax + mega-attention ops; the flash
paper's backward) — here XLA fuses the elementwise legs and the MXU
takes the matmuls.

Signature-compatible with ``models.layers.causal_attention`` (GQA via
grouped einsum, optional [B, Sk] padding mask, ``causal=`` flag) so it
plugs into ``TransformerConfig.attention_impl = "xla_flash"``.

Remat: the outputs are tagged ``checkpoint_name`` ``"attn_out"`` /
``"attn_lse"`` — the ``xla_flash`` remat policy saves exactly these so a
checkpointed layer's backward re-enters the custom VJP instead of
replaying the forward softmax.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

_NEG_INF = -1e30


def _group(q, Hkv):
    B, S, H, D = q.shape
    return q.reshape(B, S, Hkv, H // Hkv, D)


def _logits(qg, k, scale, mask, causal):
    """[B,Sq,Hkv,r,D] x [B,Sk,Hkv,D] -> fp32 masked logits [B,Hkv,r,Sq,Sk]."""
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k) * scale
    logits = logits.astype(jnp.float32)
    Sq, Sk = qg.shape[1], k.shape[1]
    if causal:
        keep = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(keep[None, None, None], logits, _NEG_INF)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, None, :].astype(bool),
                           logits, _NEG_INF)
    return logits


# q blocks for the block-causal decomposition: the upper-triangle block
# quadrants are never computed, cutting causal-attention work to
# (NB+1)/(2*NB) of the full square (NB=4 -> 62.5%).  Measured on a v5e
# (B32 H12 S1024 D64 bf16): fwd 9.5 -> 5.7 ms vs the full-square form.
_NUM_Q_BLOCKS = 8
# backward runs over (q-block, k-block) pairs with coarser blocks
_NUM_BWD_BLOCKS = 4


def _blocks(Sq: int, Sk: int):
    """Block size for the block-causal path, or None when inapplicable
    (self-attention with equal q/k lengths only — cross-length causal
    offsets stay on the general path)."""
    nb = _NUM_Q_BLOCKS
    if Sq != Sk or Sq % nb:
        return None
    return Sq // nb


def _block_logits(qi, kp, i, bs, scale):
    """fp32 masked logits of q-block i against a key prefix whose
    visible length is ``i * bs + <diagonal>`` — shared by the forward
    (full prefix) and the backward's diagonal pairs (i=0, single block)
    so the two sides' masking can never desynchronize."""
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qi, kp) * scale
    logits = logits.astype(jnp.float32)
    keep = jnp.tril(jnp.ones((bs, kp.shape[1]), bool), k=i * bs)
    return jnp.where(keep[None, None, None], logits, _NEG_INF)


def _attn_fwd(q, k, v, mask, scale, causal):
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    qg = _group(q, Hkv)
    bs = _blocks(S, k.shape[1]) if (causal and mask is None) else None
    if bs is None:
        logits = _logits(qg, k, scale, mask, causal)
        lse = jax.nn.logsumexp(logits, axis=-1)        # [B,Hkv,r,Sq]
        probs = jnp.exp(logits - lse[..., None]).astype(q.dtype)
        o = jnp.einsum("bhrqk,bkhd->bqhrd", probs, v).reshape(B, S, H, D)
    else:
        o_blocks, lse_blocks = [], []
        for i in range(_NUM_Q_BLOCKS):
            qi = qg[:, i * bs:(i + 1) * bs]
            # one merged pass over this q-block's visible prefix: the
            # causal mask only bites in the diagonal sub-block
            kp = k[:, :(i + 1) * bs]
            vp = v[:, :(i + 1) * bs]
            logits = _block_logits(qi, kp, i, bs, scale)
            l_i = jax.nn.logsumexp(logits, axis=-1)
            p_i = jnp.exp(logits - l_i[..., None]).astype(q.dtype)
            o_blocks.append(jnp.einsum("bhrqk,bkhd->bqhrd", p_i, vp))
            lse_blocks.append(l_i)
        o = jnp.concatenate(o_blocks, axis=1).reshape(B, S, H, D)
        lse = jnp.concatenate(lse_blocks, axis=-1)
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, lse


def _attn_bwd(q, k, v, mask, o, lse, do, scale, causal):
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    r = H // Hkv
    qg = _group(q, Hkv)
    dog = _group(do, Hkv)
    og = _group(o, Hkv)
    # softmax-Jacobian row term from O instead of P*dP: O(S*D), not O(S^2)
    delta = jnp.einsum("bqhrd,bqhrd->bhrq", dog.astype(jnp.float32),
                       og.astype(jnp.float32))
    bs = _blocks(S, k.shape[1]) if (causal and mask is None) else None
    if bs is None:
        # recompute P with one exp — no max/sum re-reduction
        logits = _logits(qg, k, scale, mask, causal)
        p = jnp.exp(logits - lse[..., None]).astype(q.dtype)
        dv = jnp.einsum("bhrqk,bqhrd->bkhd", p, dog)
        dp = jnp.einsum("bqhrd,bkhd->bhrqk", dog, v)
        ds = (p.astype(jnp.float32)
              * (dp.astype(jnp.float32) - delta[..., None])
              * scale).astype(q.dtype)
        dq = jnp.einsum("bhrqk,bkhd->bqhrd", ds, k).reshape(B, S, H, D)
        dk = jnp.einsum("bhrqk,bqhrd->bkhd", ds, qg)
        return dq, dk, dv

    # block-causal backward over (q-block i, k-block j) PAIRS, i >= j:
    # dk_j/dv_j accumulate block-sized partials and are written ONCE per
    # key block — the earlier per-i prefix formulation did
    # ``dk.at[:, :prefix].add`` 8x over full fp32 [B,S,Hkv,D] buffers,
    # ~2.8 GB/layer of read-modify-write HBM traffic that this removes.
    # Off-diagonal pairs are fully visible, so only the i == j diagonal
    # pays the causal mask.  Pairs use coarser blocks than the forward
    # (fewer, bigger matmuls — the MXU prefers them; measured on v5e
    # GPT-2s train: pair-blocks of S/4 beat S/8 by 3% and S/2 by 1.5%).
    bw_nb = _NUM_BWD_BLOCKS
    if S % bw_nb == 0 and (S // bw_nb) % bs == 0:
        bs = S // bw_nb
    nb = S // bs
    dq_acc = [None] * nb
    dk_parts, dv_parts = [], []
    for j in range(nb):
        kj = k[:, j * bs:(j + 1) * bs]
        vj = v[:, j * bs:(j + 1) * bs]
        dk_j = dv_j = None
        for i in range(j, nb):
            sl = slice(i * bs, (i + 1) * bs)
            qi, doi = qg[:, sl], dog[:, sl]
            li, di = lse[..., sl], delta[..., sl]
            if i == j:
                # diagonal pair: same shared mask helper as the forward
                # (prefix of one block), so fwd/bwd cannot desynchronize
                logits = _block_logits(qi, kj, 0, bs, scale)
            else:       # fully-visible off-diagonal pair: no mask
                logits = (jnp.einsum("bqhrd,bkhd->bhrqk", qi, kj)
                          * scale).astype(jnp.float32)
            p = jnp.exp(logits - li[..., None]).astype(q.dtype)
            # cross-pair partial sums accumulate in fp32 (the MXU already
            # accumulates within each einsum in fp32; bf16 adds between
            # partials would round 2^-8 per block)
            pv = jnp.einsum("bhrqk,bqhrd->bkhd", p, doi
                            ).astype(jnp.float32)
            dv_j = pv if dv_j is None else dv_j + pv
            dp = jnp.einsum("bqhrd,bkhd->bhrqk", doi, vj)
            ds = (p.astype(jnp.float32)
                  * (dp.astype(jnp.float32) - di[..., None])
                  * scale).astype(q.dtype)
            dq_i = jnp.einsum("bhrqk,bkhd->bqhrd", ds, kj
                              ).astype(jnp.float32)
            dq_acc[i] = dq_i if dq_acc[i] is None else dq_acc[i] + dq_i
            sq = jnp.einsum("bhrqk,bqhrd->bkhd", ds, qi
                            ).astype(jnp.float32)
            dk_j = sq if dk_j is None else dk_j + sq
        dk_parts.append(dk_j)
        dv_parts.append(dv_j)
    dq = jnp.concatenate(dq_acc, axis=1).reshape(B, S, H, D).astype(q.dtype)
    dk = jnp.concatenate(dk_parts, axis=1).astype(k.dtype)
    dv = jnp.concatenate(dv_parts, axis=1).astype(v.dtype)
    return dq, dk, dv


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _attn(q, k, v, scale, causal):
    o, _ = _attn_fwd(q, k, v, None, scale, causal)
    return o


def _attn_f(q, k, v, scale, causal):
    o, lse = _attn_fwd(q, k, v, None, scale, causal)
    return o, (q, k, v, o, lse)


def _attn_b(scale, causal, res, do):
    q, k, v, o, lse = res
    return _attn_bwd(q, k, v, None, o, lse, do, scale, causal)


_attn.defvjp(_attn_f, _attn_b)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _attn_masked(q, k, v, mask, scale, causal):
    o, _ = _attn_fwd(q, k, v, mask, scale, causal)
    return o


def _attn_masked_f(q, k, v, mask, scale, causal):
    o, lse = _attn_fwd(q, k, v, mask, scale, causal)
    return o, (q, k, v, mask, o, lse)


def _attn_masked_b(scale, causal, res, do):
    q, k, v, mask, o, lse = res
    dq, dk, dv = _attn_bwd(q, k, v, mask, o, lse, do, scale, causal)
    return dq, dk, dv, None


_attn_masked.defvjp(_attn_masked_f, _attn_masked_b)


def fused_attention(q, k, v, mask: Optional[jnp.ndarray] = None,
                    scale: Optional[float] = None, causal: bool = True):
    """Drop-in for ``layers.causal_attention`` with the flash-style VJP.

    q: [B, S, H, D]; k/v: [B, Sk, Hkv, D]; mask: optional [B, Sk] padding
    mask (1 = attend)."""
    D = q.shape[-1]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    if mask is None:
        return _attn(q, k, v, scale, causal)
    # bool mask: non-differentiable operand, bwd returns None for it
    return _attn_masked(q, k, v, mask.astype(bool), scale, causal)
