"""Flash attention — Pallas TPU kernels.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/softmax.cu`` + ``attention_softmax_context`` family,
the triton alternates in ``deepspeed/ops/transformer/inference/triton/``,
and the training-side fused softmax of ``csrc/transformer``).

Blockwise streaming-softmax attention (Flash-Attention-2 style) with the
KV stream expressed THROUGH THE GRID: the kv-block index is the
innermost grid dimension, so Mosaic double-buffers one [BK, D] K and V
tile at a time into VMEM while (m, l, acc) persist in VMEM scratch
across the sequential grid steps.  Nothing is ever wholly pinned —
VMEM holds O(BQ·D + BK·D) regardless of S, so the kernel runs at 32k+
context where the earlier whole-KV-resident variant fell back to XLA.

- forward: grid (B, H, Sq/BQ, S/BK); fp32 accumulation, bf16 MXU
  matmuls; per-row LSE saved for the backward.
- backward: recomputation-based two-pass — a dq kernel on the same grid,
  and a dkv kernel on grid (B, Hkv, S/BK, rep·Sq/BQ) streaming the GQA
  query-head group's q/do blocks while dk/dv accumulate in scratch,
  with delta = rowsum(dO·O) precomputed.

Causal skipping: fully-masked block pairs skip their compute via
``pl.when`` (their DMA still runs — grids are static); the diagonal
applies the triangular mask.

Measured 2026-07-31, S=8192 B2 H8 D64 bf16 fwd+bwd on the tunneled v5e:
104 ms (~9.6 TF/s) vs 40 ms for the XLA flash-style path — the gap is
the documented Mosaic-through-axon handicap (Mosaic matmuls measure
1-15 TF/s on this rig, see bench.py notes), not kernel structure; on
bare-metal TPU the streaming kernel is the intended long-context path.
Numerics match XLA to bf16 tolerance at every tested S (128..8192).

Falls back to the XLA softmax-attention path for padding masks, ragged
block sizes, or non-TPU backends (interpret mode covers CPU tests).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.layers import causal_attention

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _causal_j_last(i, block_q: int, block_k: int, n_k: int):
    """Last kv-block index (inclusive) visible to q block ``i``."""
    return jnp.minimum(
        jax.lax.div((i + 1) * block_q - 1, block_k), n_k - 1)


def _causal_mask(s, i, j, block_q: int, block_k: int):
    rows = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows >= cols, s, NEG_INF)


# ==========================================================================
# forward
# ==========================================================================

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *,
                block_q: int, block_k: int, n_k: int,
                scale: float, causal: bool):
    i = pl.program_id(2)
    j = pl.program_id(3)
    j_last = _causal_j_last(i, block_q, block_k, n_k) if causal \
        else n_k - 1

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j <= j_last)
    def _compute():
        q = q_ref[0, 0]                                    # [BQ, D] bf16
        k = k_ref[0, 0]                                    # [BK, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [BQ, BK]
        if causal:
            s = _causal_mask(s, i, j, block_q, block_k)
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BQ, D]
        acc_ref[...] = acc_ref[...] * corr + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == j_last)
    def _emit():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # slim [BQ, 1] column (trailing singleton keeps the block
        # tile-legal for Mosaic at 1/128th of a lane broadcast)
        lse_ref[0, 0] = m_ref[:, :1] + jnp.log(
            jnp.maximum(l_ref[:, :1], 1e-30))


def _fwd(q, k, v, scale: float, causal: bool,
         block_q: int, block_k: int):
    """q: [B,H,S,D]; k/v: [B,Hkv,S,D] → (o [B,H,S,D], lse [B,H,S,1])."""
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    n_k = S // block_k
    grid = (B, H, S // block_q, n_k)

    kv_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, i, j: (b, h // rep, j, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                          n_k=n_k, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            kv_spec, kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v)
    return out[0], out[1]


# ==========================================================================
# backward
# ==========================================================================

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, block_q: int, block_k: int, n_k: int,
               scale: float, causal: bool):
    i = pl.program_id(2)
    j = pl.program_id(3)
    j_last = _causal_j_last(i, block_q, block_k, n_k) if causal \
        else n_k - 1

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j <= j_last)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                # [BQ, 1]
        delta = delta_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i, j, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        acc_ref[...] += jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == j_last)
    def _emit():
        dq_ref[0, 0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                block_q: int, block_k: int, n_q: int,
                scale: float, causal: bool, rep: int):
    j = pl.program_id(2)
    t = pl.program_id(3)                 # flat (r, i) stream
    i = jax.lax.rem(t, n_q)
    n_t = rep * n_q

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly above this kv block contribute nothing
    active = jnp.logical_or(
        jnp.logical_not(causal),
        (i + 1) * block_q - 1 >= j * block_k)

    @pl.when(active)
    def _compute():
        k = k_ref[0, 0]                                    # [BK, D]
        v = v_ref[0, 0]
        q = q_ref[0, 0, 0]                                 # [BQ, D]
        do = do_ref[0, 0, 0]
        lse = lse_ref[0, 0, 0]                             # [BQ, 1]
        delta = delta_ref[0, 0, 0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [BQ, BK]
        if causal:
            s = _causal_mask(s, i, j, block_q, block_k)
        p = jnp.exp(s - lse)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _emit():
        # s = scale·qkᵀ ⇒ dk = scale·dsᵀq (q enters the matmul unscaled)
        dk_ref[0, 0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, scale: float, causal: bool,
         block_q: int, block_k: int):
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    n_q = S // block_q
    n_k = S // block_k
    delta = (do.astype(jnp.float32)
             * o.astype(jnp.float32)).sum(-1, keepdims=True)

    q_spec = pl.BlockSpec((1, 1, block_q, D),
                          lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, block_k, D),
                           lambda b, h, i, j: (b, h // rep, j, 0),
                           memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=block_q, block_k=block_k,
                          n_k=n_k, scale=scale, causal=causal),
        grid=(B, H, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, vec_spec, vec_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, S, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)[0]

    # dkv: kv block owns the scratch; the GQA group's (r, i) q blocks
    # stream through the innermost grid dim
    qg = q.reshape(B, Hkv, rep, S, D)
    dog = do.reshape(B, Hkv, rep, S, D)
    lseg = lse.reshape(B, Hkv, rep, S, 1)
    deltag = delta.reshape(B, Hkv, rep, S, 1)

    def qg_index(b, h, j, t):
        return (b, h, t // n_q, t % n_q, 0)

    kv_blk_spec = pl.BlockSpec((1, 1, block_k, D),
                               lambda b, h, j, t: (b, h, j, 0),
                               memory_space=pltpu.VMEM)
    qg_spec = pl.BlockSpec((1, 1, 1, block_q, D), qg_index,
                           memory_space=pltpu.VMEM)
    vg_spec = pl.BlockSpec((1, 1, 1, block_q, 1), qg_index,
                           memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, block_k=block_k,
                          n_q=n_q, scale=scale, causal=causal, rep=rep),
        grid=(B, Hkv, n_k, rep * n_q),
        in_specs=[qg_spec, kv_blk_spec, kv_blk_spec, qg_spec, vg_spec,
                  vg_spec],
        out_specs=[kv_blk_spec, kv_blk_spec],
        out_shape=[jax.ShapeDtypeStruct((B, Hkv, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B, Hkv, S, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=_use_interpret(),
    )(qg, k, v, dog, lseg, deltag)
    return dq, dk, dv


# ==========================================================================
# public API (custom VJP)
# ==========================================================================

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask: Optional[jnp.ndarray] = None,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    causal: bool = True):
    """Drop-in ``attention_fn`` ([B, S, H, D] layout, GQA k/v allowed).

    KV streams through the grid, so VMEM use is O(block) and independent
    of S — no sequence-length cap.  Falls back to the XLA path when a
    padding mask is supplied or the sequence doesn't tile evenly (the
    reference keeps an unfused python softmax path the same way)."""
    B, S, H, D = q.shape
    bq, bk = min(block_q, S), min(block_k, S)
    # cross-length attention (Sk != Sq, e.g. diffusers cross-attn) stays
    # on the XLA path: the kernels assume one shared S
    if (mask is not None or k.shape[1] != S or S % bq or S % bk
            or (H % k.shape[2])):
        return causal_attention(q, k, v, mask=mask, scale=scale,
                                causal=causal)
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qt = q.transpose(0, 2, 1, 3)                   # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _flash(qt, kt, vt, float(scale), causal, bq, bk)
    # named so the 'flash' remat policy saves it: flash's custom VJP already
    # recomputes attention internally — replaying the forward kernel under
    # jax.checkpoint would recompute it twice
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_out")
    return o.transpose(0, 2, 1, 3)
