"""Flash attention — Pallas TPU kernels.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/softmax.cu`` + ``attention_softmax_context`` family,
the triton alternates in ``deepspeed/ops/transformer/inference/triton/``,
and the training-side fused softmax of ``csrc/transformer``).

Blockwise streaming-softmax attention (Flash-Attention-2 style):
- forward: grid (B, H, Sq/BQ); per q-block, fori_loop over kv blocks with
  the causal upper bound, (m, l, o) carried in registers/VMEM, fp32
  accumulation, bf16 MXU matmuls; saves per-row LSE for backward.
- backward: recomputation-based two-pass — a dq kernel (grid over
  q-blocks) and a dkv kernel (grid over kv-blocks, accumulating over the
  GQA query-head group), with delta = rowsum(dO*O) precomputed.

Memory: O(S·D) per (batch, head) instead of O(S²) — the whole point; the
attention-probability tensor that forced remat in the XLA path never
materializes.

Falls back to the XLA softmax-attention path for padding masks, ragged
block sizes, or non-TPU backends (interpret mode covers CPU tests).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.layers import causal_attention

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ==========================================================================
# forward
# ==========================================================================

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                block_q: int, block_k: int, scale: float, causal: bool):
    i = pl.program_id(2)
    q = q_ref[0, 0]                                        # [BQ, D] bf16
    S = k_ref.shape[2]
    n_k = S // block_k
    if causal:
        # blocks whose start <= this q block's last row
        jmax = jax.lax.div((i + 1) * block_q + block_k - 1, block_k)
        jmax = jnp.minimum(jmax, n_k)
    else:
        jmax = n_k

    D = q_ref.shape[3]

    def body(j, carry):
        o, m, l = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]    # [BK, D]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        # bf16 MXU matmul with fp32 accumulation
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [BQ, BK]
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                              # [BQ, BK]
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BQ, D]
        o_new = o * corr + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, jmax, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    # 128-lane broadcast keeps the block tileable (Mosaic needs the last
    # two block dims (8k, 128) or full-size)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (block_q, 128))


def _fwd(q, k, v, scale: float, causal: bool,
         block_q: int, block_k: int):
    """q: [B,H,S,D]; k/v: [B,Hkv,S,D] → (o [B,H,S,D], lse [B,H,S])."""
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    grid = (B, H, S // block_q)

    kv_spec = pl.BlockSpec((1, 1, S, D),
                           lambda b, h, i: (b, h // rep, 0, 0),
                           memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            kv_spec, kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 128), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v)
    return out[0], out[1]


# ==========================================================================
# backward
# ==========================================================================

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_q: int, block_k: int, scale: float, causal: bool):
    i = pl.program_id(2)
    q = q_ref[0, 0]                                        # [BQ, D] bf16
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]                             # [BQ, 1] f32
    delta = delta_ref[0, 0][:, :1]
    S = k_ref.shape[2]
    n_k = S // block_k
    if causal:
        jmax = jnp.minimum(
            jax.lax.div((i + 1) * block_q + block_k - 1, block_k), n_k)
    else:
        jmax = n_k

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                               # [BQ, BK]
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    D = q_ref.shape[3]
    dq = jax.lax.fori_loop(0, jmax,
                           body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, block_k: int,
                scale: float, causal: bool, rep: int):
    j = pl.program_id(2)
    k = k_ref[0, 0]                                        # [BK, D] bf16
    v = v_ref[0, 0]
    Sq = q_ref.shape[3]                                    # q_ref [1,1,rep,S,D]
    n_q = Sq // block_q
    D = k_ref.shape[3]

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)

    def head_loop(r, carry):
        dk, dv = carry
        if causal:
            imin = jax.lax.div(j * block_k, block_q)
        else:
            imin = 0

        def body(i, carry):
            dk, dv = carry
            q = q_ref[0, 0, r, pl.ds(i * block_q, block_q), :]  # [BQ, D]
            do = do_ref[0, 0, r, pl.ds(i * block_q, block_q), :]
            lse = lse_ref[0, 0, r, pl.ds(i * block_q, block_q), :1]
            delta = delta_ref[0, 0, r, pl.ds(i * block_q, block_q), :1]
            s = jax.lax.dot_general(
                q, k, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [BQ, BK]
            if causal:
                rows = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                cols = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(rows >= cols, s, NEG_INF)
            p = jnp.exp(s - lse)
            dv = dv + jax.lax.dot_general(
                p.astype(do.dtype), do,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # [BK, D]
            dp = jax.lax.dot_general(
                do, v, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [BQ, BK]
            ds = (p * (dp - delta)).astype(q.dtype)
            dk = dk + jax.lax.dot_general(
                ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk, dv

        return jax.lax.fori_loop(imin, n_q, body, (dk, dv))

    dk, dv = jax.lax.fori_loop(0, rep, head_loop, (dk0, dv0))
    # s = scale·qkᵀ ⇒ dk = scale·dsᵀq (q enters the matmul unscaled)
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, scale: float, causal: bool,
         block_q: int, block_k: int):
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    delta = jnp.broadcast_to(delta[..., None], (B, H, S, 128))

    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // rep, 0, 0),
                           memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, 1, block_q, 128), lambda b, h, i: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal),
        grid=(B, H, S // block_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, vec_spec, vec_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, S, D), q.dtype)],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)[0]

    # dkv: grid over kv blocks; q/do/lse/delta views grouped by kv head
    qg = q.reshape(B, Hkv, rep, S, D)
    dog = do.reshape(B, Hkv, rep, S, D)
    lseg = lse.reshape(B, Hkv, rep, S, 128)
    deltag = delta.reshape(B, Hkv, rep, S, 128)

    kv_blk_spec = pl.BlockSpec((1, 1, block_k, D),
                               lambda b, h, j: (b, h, j, 0),
                               memory_space=pltpu.VMEM)
    qg_spec = pl.BlockSpec((1, 1, rep, S, D),
                           lambda b, h, j: (b, h, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    vg_spec = pl.BlockSpec((1, 1, rep, S, 128),
                           lambda b, h, j: (b, h, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, rep=rep),
        grid=(B, Hkv, S // block_k),
        in_specs=[qg_spec, kv_blk_spec, kv_blk_spec, qg_spec, vg_spec,
                  vg_spec],
        out_specs=[kv_blk_spec, kv_blk_spec],
        out_shape=[jax.ShapeDtypeStruct((B, Hkv, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B, Hkv, S, D), v.dtype)],
        interpret=_use_interpret(),
    )(qg, k, v, dog, lseg, deltag)
    return dq, dk, dv


# ==========================================================================
# public API (custom VJP)
# ==========================================================================

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    # residual slimmed to [B,H,S,1]: the kernel emits a 128-lane broadcast
    # (Mosaic tiling), but keeping it as a VJP residual would cost 128x the
    # needed memory (hundreds of MB at GPT-2-scale batches)
    return o, (q, k, v, o, lse[..., :1])


def _flash_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse1 = res
    lse = jnp.broadcast_to(lse1, (*lse1.shape[:-1], 128))
    dq, dk, dv = _bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, mask: Optional[jnp.ndarray] = None,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    causal: bool = True):
    """Drop-in ``attention_fn`` ([B, S, H, D] layout, GQA k/v allowed).

    Falls back to the XLA path when a padding mask is supplied or the
    sequence doesn't tile evenly (the reference keeps an unfused python
    softmax path the same way)."""
    B, S, H, D = q.shape
    bq, bk = min(block_q, S), min(block_k, S)
    # VMEM guard: the current kernels pin K/V (and the dkv pass q/do per
    # GQA group) wholly in VMEM; beyond ~10MB fall back to XLA.  The
    # blocked-KV-through-grid variant lifts this cap (planned).
    rep = H // k.shape[2] if k.shape[2] else 1
    itemsize = jnp.dtype(q.dtype).itemsize
    vmem_est = (2 + 2 * rep) * S * D * itemsize
    if (mask is not None or S % bq or S % bk or (H % k.shape[2])
            or vmem_est > 10 * 1024 * 1024):
        return causal_attention(q, k, v, mask=mask, scale=scale,
                                causal=causal)
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qt = q.transpose(0, 2, 1, 3)                   # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _flash(qt, kt, vt, float(scale), causal, bq, bk)
    # named so the 'flash' remat policy saves it: flash's custom VJP already
    # recomputes attention internally — replaying the forward kernel under
    # jax.checkpoint would recompute it twice
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_out")
    return o.transpose(0, 2, 1, 3)
