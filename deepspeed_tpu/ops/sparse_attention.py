"""Block-sparse attention: sparsity layouts + a static-gather kernel.

TPU-native analog of the reference's sparse-attention stack
(``deepspeed/ops/sparse_attention/`` — ``sparsity_config.py`` Fixed/
BigBird/BSLongformer/Variable/Dense layout builders,
``matmul.py``/``softmax.py`` triton block-sparse kernels,
``sparse_self_attention.py``; ``csrc/sparse_attention/utils.cpp``).

The reference JIT-compiles triton kernels around a [heads, nQ, nK] block
layout.  The TPU redesign leans on the layout being STATIC: the active
(q-block, k-block) pairs are known at trace time, so each q-block's
active k-blocks become a numpy gather index and the whole computation is
dense einsums over ``[.., nQ, A, block, block]`` — work and memory scale
with ACTIVE blocks (A = max active per row), XLA tiles the block matmuls
onto the MXU, and there is no dynamic control flow.  (A Pallas
splash-style kernel can drop in behind the same layout; on virtualized
chips the XLA form wins — see ops/flash_attention.py notes.)

Measured (v5e, B2 H8 D64 bf16): S=8192 longformer window-3 at 12%
density runs the forward 2.9x faster than dense causal attention
(6.9 vs 19.8 ms); the gap widens with sequence length.

Layout semantics follow the reference configs:

* :class:`FixedSparsityConfig` — local block windows; each window's last
  ``num_global_blocks`` are visible to every later query block
  (fixed.py of the Sparse Transformers family).
* :class:`BSLongformerSparsityConfig` — sliding window + designated
  leading global blocks (bidirectional globals made causal here).
* :class:`BigBirdSparsityConfig` — sliding window + leading globals +
  per-row random blocks (seeded, static).
* :class:`VariableSparsityConfig` — user-chosen local windows + global
  block ids.
* :class:`DenseSparsityConfig` — all blocks active (debug/reference).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


# --------------------------------------------------------------------------
# layouts (reference: sparsity_config.py)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class SparsityConfig:
    block: int = 16

    def make_layout(self, num_blocks: int) -> np.ndarray:
        """[nQ, nK] bool, lower-triangular (causal) at block level."""
        raise NotImplementedError

    def _causal(self, layout: np.ndarray) -> np.ndarray:
        return np.tril(layout)


@dataclasses.dataclass
class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, num_blocks: int) -> np.ndarray:
        return self._causal(np.ones((num_blocks, num_blocks), bool))


@dataclasses.dataclass
class FixedSparsityConfig(SparsityConfig):
    num_local_blocks: int = 4
    num_global_blocks: int = 1

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        L = self.num_local_blocks
        for q in range(n):
            w0 = (q // L) * L
            lay[q, w0:q + 1] = True                 # local window
            # last num_global_blocks of every previous window are global
            for base in range(0, w0, L):
                lo = base + L - self.num_global_blocks
                lay[q, max(base, lo):base + L] = True
        return self._causal(lay)


@dataclasses.dataclass
class BSLongformerSparsityConfig(SparsityConfig):
    num_sliding_window_blocks: int = 3
    global_block_indices: Sequence[int] = (0,)

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks
        for q in range(n):
            lay[q, max(0, q - w + 1):q + 1] = True
        for g in self.global_block_indices:
            if g < n:
                lay[:, g] = True                    # everyone sees global
                lay[g, :] = True                    # global sees everyone
        return self._causal(lay)


@dataclasses.dataclass
class BigBirdSparsityConfig(SparsityConfig):
    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        w = self.num_sliding_window_blocks
        r = np.random.RandomState(self.seed)
        for q in range(n):
            lay[q, max(0, q - w + 1):q + 1] = True
            if q > 0 and self.num_random_blocks:
                pick = r.choice(q, min(self.num_random_blocks, q),
                                replace=False)
                lay[q, pick] = True
        g = self.num_global_blocks
        lay[:, :g] = True
        lay[:g, :] = True
        return self._causal(lay)


@dataclasses.dataclass
class VariableSparsityConfig(SparsityConfig):
    num_local_blocks: int = 4
    global_block_indices: Sequence[int] = (0,)

    def make_layout(self, n: int) -> np.ndarray:
        lay = np.zeros((n, n), bool)
        L = self.num_local_blocks
        for q in range(n):
            lay[q, max(0, q - L + 1):q + 1] = True
        for g in self.global_block_indices:
            if g < n:
                lay[:, g] = True
                lay[g, :] = True
        return self._causal(lay)


# --------------------------------------------------------------------------
# kernel (static-gather XLA formulation)
# --------------------------------------------------------------------------

def block_sparse_attention(q, k, v, layout: np.ndarray, block: int,
                           scale: Optional[float] = None):
    """q: [B, S, H, D]; k/v: [B, S, Hkv, D]; layout: static [nQ, nK]
    bool (block-causal).  Causal masking applies inside diagonal blocks;
    work scales with the active block count."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    n = S // block
    assert layout.shape == (n, n), (layout.shape, n)
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    # static per-row gather: pad every row to the max active count
    rows = [np.flatnonzero(layout[i]) for i in range(n)]
    A = max(1, max(len(r) for r in rows))
    idx = np.zeros((n, A), np.int32)
    act = np.zeros((n, A), bool)
    for i, r in enumerate(rows):
        idx[i, :len(r)] = r
        act[i, :len(r)] = True

    qb = q.reshape(B, n, block, Hkv, rep, D)
    kb = k.reshape(B, n, block, Hkv, D)
    vb = v.reshape(B, n, block, Hkv, D)
    ks = kb[:, idx]                                  # [B, n, A, blk, Hkv, D]
    vs = vb[:, idx]

    s = jnp.einsum("bnqhrd,bnakhd->bnhrqak", qb, ks) * scale
    s = s.astype(jnp.float32)

    # causal + active-block mask (all static numpy, baked as a constant)
    grow = np.arange(n)[:, None] * block + np.arange(block)[None, :]
    gcol = idx[:, :, None] * block + np.arange(block)[None, None, :]
    keep = (gcol[:, None, :, :] <= grow[:, :, None, None]) & \
        act[:, None, :, None]                        # [n, blk, A, blk]
    s = jnp.where(jnp.asarray(keep)[None, :, None, None], s, NEG_INF)

    sf = s.reshape(*s.shape[:-2], A * block)
    p = jax.nn.softmax(sf, axis=-1).astype(q.dtype)
    p = p.reshape(s.shape)
    o = jnp.einsum("bnhrqak,bnakhd->bnqhrd", p, vs)
    return o.reshape(B, S, H, D)


def make_block_sparse_attention(config: SparsityConfig):
    """attention_fn factory for ``TransformerConfig`` /
    ``Model(attention_fn=...)`` (reference: SparseSelfAttention /
    SparseAttentionUtils wrapping)."""

    def attn(q, k, v, mask=None, scale=None, causal=True):
        if mask is not None:
            raise NotImplementedError(
                "block-sparse attention does not support padding masks")
        if not causal:
            raise NotImplementedError(
                "block-sparse attention is causal-only")
        S = q.shape[1]
        if S % config.block:
            raise ValueError(f"sequence {S} not divisible by "
                             f"block {config.block}")
        layout = config.make_layout(S // config.block)
        return block_sparse_attention(q, k, v, layout, config.block,
                                      scale=scale)

    return attn


def density(layout: np.ndarray) -> float:
    """Active fraction vs the full causal lower triangle."""
    n = layout.shape[0]
    return float(layout.sum()) / (n * (n + 1) / 2)
