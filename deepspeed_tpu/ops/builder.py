"""JIT builder for native C++ extensions.

TPU-native analog of the reference's op-builder subsystem
(``op_builder/builder.py:109`` OpBuilder ABC, ``jit_load`` :513/:532 via
torch cpp_extension/ninja): compiles C++ sources under
``deepspeed_tpu/native/`` to shared objects with g++ at first use, caches
by source hash, and loads them through ``ctypes`` (pybind11 is not in the
image; a C ABI + ctypes is the stable boundary).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Dict, List, Optional

from ..utils.logging import logger

NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
CACHE_DIR = Path(os.environ.get(
    "DEEPSPEED_TPU_CACHE", os.path.expanduser("~/.cache/deepspeed_tpu")))

_lock = threading.Lock()
_loaded: Dict[str, ctypes.CDLL] = {}


class BuildError(RuntimeError):
    pass


class OpBuilder:
    """One native extension = sources + flags (reference: OpBuilder)."""

    name: str = ""
    sources: List[str] = []
    extra_flags: List[str] = []

    def source_paths(self) -> List[Path]:
        return [NATIVE_DIR / s for s in self.sources]

    def is_compatible(self) -> bool:
        """Whether this op can build on the current host
        (reference: OpBuilder.is_compatible)."""
        from shutil import which

        return which("g++") is not None

    def _hash(self) -> str:
        h = hashlib.sha256()
        for p in self.source_paths():
            h.update(p.read_bytes())
        for s in getattr(self, "hash_extra_sources", []):
            h.update((NATIVE_DIR / s).read_bytes())
        h.update(" ".join(self.extra_flags).encode())
        return h.hexdigest()[:16]

    def load(self) -> ctypes.CDLL:
        """Compile (if needed) and dlopen (reference: OpBuilder.load)."""
        with _lock:
            if self.name in _loaded:
                return _loaded[self.name]
            so = self._build()
            lib = ctypes.CDLL(str(so))
            _loaded[self.name] = lib
            return lib

    def _build(self) -> Path:
        CACHE_DIR.mkdir(parents=True, exist_ok=True)
        so = CACHE_DIR / f"{self.name}_{self._hash()}.so"
        if so.exists():
            return so
        if not self.is_compatible():
            raise BuildError(f"No g++ available to build {self.name}")
        cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
               *self.extra_flags,
               *[str(p) for p in self.source_paths()], "-o", str(so)]
        logger.info("building native op %s: %s", self.name, " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise BuildError(
                f"build of {self.name} failed:\n{proc.stderr[:4000]}")
        return so


class AsyncIOBuilder(OpBuilder):
    """(reference: op_builder/async_io.py)."""
    name = "aio"
    sources = ["aio.cpp"]
    # headers participate in the source hash so an edit rebuilds the .so
    hash_extra_sources = ["uring.h"]
