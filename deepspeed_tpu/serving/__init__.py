"""Fleet serving: a multi-replica front-end over hardened inference
engines (docs/SERVING.md "Fleet: routing, failover, migration").

The scheduler/engine boundary split makes every placement and
migration decision portable: prompts and replicas share one
engine-independent affinity key (`placement.prompt_digests` vs
``StateManager.prefix_digests()``), and open work moves between
replicas as restore()-compatible per-request records
(``engine.snapshot_requests`` / ``migrate_out`` /
``load_snapshot(merge=True)``)."""

from .autoscaler import (Autoscaler, AutoscalerConfig,
                         WeightStreamColdStart)
from .fleet_telemetry import (FLEET_DUMP_VERSION, FleetRegistry,
                              FleetTelemetry, FleetTelemetryConfig,
                              default_fleet_detectors,
                              fleet_request_metrics,
                              fleet_request_records,
                              reconciled_terminal_statuses,
                              validate_fleet_dump)
from .placement import (PLACEMENT_POLICIES, REPLICA_ROLES,
                        affinity_chain_len, prompt_digests,
                        rank_replicas, split_by_pool)
from .replica import CircuitBreaker, ReplicaHandle
from .router import FleetConfig, FleetRouter

__all__ = ["FleetConfig", "FleetRouter", "ReplicaHandle",
           "CircuitBreaker", "PLACEMENT_POLICIES", "REPLICA_ROLES",
           "prompt_digests", "affinity_chain_len", "rank_replicas",
           "split_by_pool",
           "Autoscaler", "AutoscalerConfig", "WeightStreamColdStart",
           "FleetTelemetry", "FleetTelemetryConfig", "FleetRegistry",
           "default_fleet_detectors", "fleet_request_metrics",
           "fleet_request_records", "reconciled_terminal_statuses",
           "validate_fleet_dump", "FLEET_DUMP_VERSION"]
