"""Replica handle + per-replica circuit breaker for the fleet router
(docs/SERVING.md "Fleet: routing, failover, migration").

A :class:`ReplicaHandle` wraps one hardened
:class:`~deepspeed_tpu.inference.InferenceEngine` behind the
per-replica contract PR 8 built — ``health()`` / ``drain()`` /
``snapshot()`` / ``restore()`` — plus the two things only the fleet
layer needs: the live prefix-digest set (the cache-affinity placement
key) and a :class:`CircuitBreaker` fed from the engine's own failure
counters.

The breaker is **step-counted and deterministic** (no wall clocks —
the same discipline as the engine's retry backoff, so chaos replays
are machine-independent):

    closed --(threshold consecutive failing steps)--> open
    open --(probe_interval router steps)--> half_open
    half_open --(one clean dispatched step: the probe)--> closed
    half_open --(a failing step)--> open          (re-quarantined)
    any --(replica death / drain-to-scale-down)--> dead   (sticky)

``open`` quarantines the replica from NEW placements only: the router
keeps stepping it so its live requests finish and its clean steps make
the eventual probe meaningful.  Failure evidence is the engine's own
``serving_step_retries_total`` counter delta — the classifier already
decided those steps failed; the breaker just watches the ledger, and
idle rounds (backoff, empty queue: no ``steps`` delta) are neither
success nor failure, so a retry-backoff window cannot launder a sick
replica back to closed.
"""

from __future__ import annotations

from typing import Dict, Optional

from .placement import REPLICA_ROLES, CombinedDigestIndex


class CircuitBreaker:
    """Per-replica quarantine state machine (module docstring above).
    All transitions are driven by the router's step counter — never a
    clock."""

    def __init__(self, threshold: int = 2, probe_interval: int = 8):
        if threshold < 1:
            raise ValueError("failure threshold must be >= 1")
        if probe_interval < 1:
            raise ValueError("probe_interval must be >= 1")
        self.threshold = threshold
        self.probe_interval = probe_interval
        self.state = "closed"
        self.failures = 0            # consecutive failing steps
        self.opened_step = 0
        self.probes = 0              # half-open probe windows entered
        self.quarantines = 0         # closed/half_open -> open trips
        self.readmissions = 0        # half_open -> closed (clean probe)

    @property
    def routable(self) -> bool:
        """Fully closed — the strict form.  The ROUTING predicate is
        :meth:`ReplicaHandle.routable`, which additionally admits
        half-open (one last-resort placement IS the probe, ranked
        after every closed replica)."""
        return self.state == "closed"

    def record_failure(self, step: int) -> bool:
        """One failing engine step (a step-retry delta).  Returns True
        when this failure OPENED the breaker (the router counts the
        quarantine)."""
        if self.state == "dead":
            return False
        self.failures += 1
        if self.state == "half_open" or (
                self.state == "closed"
                and self.failures >= self.threshold):
            self.state = "open"
            self.opened_step = step
            self.quarantines += 1
            return True
        return False

    def record_success(self) -> bool:
        """One clean DISPATCHED engine step (idle rounds don't call
        this).  Returns True when it was the half-open probe that
        re-admitted the replica."""
        if self.state == "closed":
            self.failures = 0
            return False
        if self.state == "half_open":
            self.state = "closed"
            self.failures = 0
            self.readmissions += 1
            return True
        return False                 # open: quarantined steps don't close

    def tick(self, step: int) -> None:
        """Router-step clock: an open breaker becomes half-open (probe
        window) after ``probe_interval`` steps in quarantine."""
        if self.state == "open" \
                and step - self.opened_step >= self.probe_interval:
            self.state = "half_open"
            self.probes += 1

    def kill(self) -> None:
        """Sticky terminal state: a dead or drained-away replica never
        re-admits."""
        self.state = "dead"


class ReplicaHandle:
    """One engine replica as the router sees it: identity, breaker,
    placement inputs (digest set + load), and the counter-delta
    bookkeeping that feeds the breaker after every stepped round."""

    def __init__(self, name: str, engine, threshold: int = 2,
                 probe_interval: int = 8, role: str = "mixed"):
        if role not in REPLICA_ROLES:
            raise ValueError(f"role={role!r}: expected one of "
                             f"{REPLICA_ROLES}")
        self.name = name
        self.engine = engine
        self.role = role
        self.breaker = CircuitBreaker(threshold, probe_interval)
        self._last_retries = int(engine.timings["step_retries"])
        self._last_steps = int(engine.timings["steps"])
        # warm placement digests seeded from a PRIOR router generation's
        # snapshot (router.restore_prefix_index): bytes digests that
        # score affinity so a restarted fleet routes each prefix family
        # back to its old replica — the engine re-prefills the first
        # visit, every later one hits the rebuilt cache.  Advertised to
        # placement only, never re-exported as real cache content
        self.warm_digests: set = set()

    @property
    def dead(self) -> bool:
        return self.breaker.state == "dead"

    def prefix_digests(self) -> frozenset:
        """The replica's LIVE cache-affinity key (hex digest set) —
        same key space as ``snapshot()["prefix_index"]``.  With the KV
        tier on, TIERED chains are advertised too: a spilled chain is
        still servable (restage beats re-prefill), so it must still
        attract its stream (docs/KV_TIERING.md)."""
        base = self.engine.state.prefix_digests()
        tier = getattr(self.engine.state, "tier", None)
        if tier is not None and len(tier):
            base = base | frozenset(h.hex() for h in tier.digests())
        return base

    def digest_index(self):
        """The live BYTES-digest membership view the router scores
        against per placement — the index dict itself, so scoring a
        prompt costs dict lookups only (no per-placement set build or
        hex conversion; read-only by contract), or the resident+tier
        :class:`~.placement.CombinedDigestIndex` when the engine's KV
        tier is on (two lookups — tiered chains score like resident
        ones).  :meth:`prefix_digests` is the exportable hex form."""
        tier = getattr(self.engine.state, "tier", None)
        base = self.engine.state._hash_index
        if tier is not None:
            base = CombinedDigestIndex(base, tier)
        if self.warm_digests:
            base = CombinedDigestIndex(base, self.warm_digests)
        return base

    def load(self) -> int:
        """Live sequences + requests still waiting for first admission
        — the least-loaded tiebreak (ints: exact, deterministic)."""
        eng = self.engine
        return len(eng.state.seqs) + sum(
            1 for uid, t in eng._pending.items()
            if t and uid not in eng.state.seqs)

    def routable(self) -> bool:
        """Placeable for NEW work: breaker closed — or half-open, where
        one placement IS the probe (an idle quarantined replica has no
        backlog left to certify itself with; classic half-open admits
        limited traffic) — and the engine still admits (not draining,
        not dead)."""
        return self.breaker.state in ("closed", "half_open") \
            and not self.engine._draining \
            and self.engine._health != "dead"

    def health(self) -> Dict:
        return self.engine.health()

    def observe(self, router_step: int) -> Optional[str]:  # tpulint: serving-loop
        """Post-step breaker bookkeeping from the engine's own counter
        deltas: a ``step_retries`` delta is a failing step, a ``steps``
        delta without one is a clean dispatched step, neither is an
        idle round (no evidence either way).  Returns the breaker event
        — ``"opened"`` / ``"readmitted"`` / ``"failure"`` / ``"clean"``
        — or None on idle."""
        tm = self.engine.timings
        retries = int(tm["step_retries"])
        steps = int(tm["steps"])
        if retries < self._last_retries or steps < self._last_steps:
            # the counters were reset underneath us (reset_metrics
            # between bench legs): resync the baselines — a stale
            # higher baseline would blind the breaker to every failure
            # until the counter re-exceeded it
            self._last_retries = retries
            self._last_steps = steps
            return None
        ev = None
        if retries > self._last_retries:
            ev = "opened" if self.breaker.record_failure(router_step) \
                else "failure"
        elif steps > self._last_steps:
            ev = "readmitted" if self.breaker.record_success() \
                else "clean"
        self._last_retries = retries
        self._last_steps = steps
        return ev
