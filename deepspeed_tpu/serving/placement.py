"""Placement policy for the multi-replica serving front-end
(docs/SERVING.md "Fleet: routing, failover, migration").

Pure host-side scoring over engine-independent keys — the scheduler/
engine boundary split made both sides of a placement decision portable:

* the **prompt side** is :func:`prompt_digests` — the rolling chain
  digests of the prompt's full block-aligned prefixes
  (``ragged.state.prefix_chain_digests``, the SAME function
  ``match_prefix`` consumes, so router-side scoring and engine-side
  matching can never disagree on the key);
* the **replica side** is a digest set — ``StateManager.
  prefix_digests()`` live, or ``engine.snapshot()["prefix_index"]``
  from a replica's last snapshot.

``affinity_chain_len`` is deliberately a *leading-run* match, not a set
intersection: the engine can only alias a cached prefix whose every
ancestor block is resident (``match_prefix`` stops at the first miss),
so a mid-stream hit is worth nothing to prefill and must score nothing
to placement.

Everything here is pure functions over small sequences — no device
work, no engine references — so the router, the load harness, and the
tests all score placements the same way.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..inference.ragged.state import prefix_chain_digests

PLACEMENT_POLICIES = ("affinity", "least_loaded", "round_robin")

# per-replica roles for disaggregated serving (docs/SERVING.md
# "Disaggregated pools & elasticity"): a "prefill" replica runs
# chunk-free prompt ingestion and hands finished prefills off, a
# "decode" replica hosts the token loops, "mixed" serves both (the
# colocated default — a fleet of only mixed replicas behaves exactly
# as before roles existed)
REPLICA_ROLES = ("prefill", "decode", "mixed")


def split_by_pool(order: Sequence[str], roles: Dict[str, str],
                  pool: Optional[str]) -> List[str]:
    """Stable-partition an already-ranked replica order for a pool-
    targeted placement: replicas serving ``pool`` (their role IS the
    pool, or ``mixed``) keep their rank ahead of everything else, and
    the rest stay as a ranked FALLBACK — a pool with no capacity must
    degrade to colocated placement, never to a lost request.
    ``pool=None`` (no split: pre-disaggregation behavior) returns the
    order unchanged."""
    if pool is None:
        return list(order)
    want = (pool, "mixed")
    pref = [n for n in order if roles.get(n, "mixed") in want]
    rest = [n for n in order if roles.get(n, "mixed") not in want]
    return pref + rest


class CombinedDigestIndex:
    """Membership view over a replica's RESIDENT digest index plus its
    KV tier (docs/KV_TIERING.md "The tier as a fleet asset"): a tiered
    chain scores placement affinity exactly like a resident one,
    because the engine's ``match_prefix`` revive path can serve it —
    restaging a spilled chain is far cheaper than re-prefilling it on a
    cold replica.  Pure membership composition (two ``in``-supporting
    containers), so it stays inside this module's no-engine-references
    contract; :meth:`~.replica.ReplicaHandle.digest_index` builds it.
    ``__len__`` is an upper bound (a digest resident AND tiered counts
    twice) — ranking only uses ``in``."""

    __slots__ = ("resident", "tier")

    def __init__(self, resident, tier):
        self.resident = resident
        self.tier = tier

    def __contains__(self, h) -> bool:
        return h in self.resident or h in self.tier

    def __len__(self) -> int:
        return len(self.resident) + len(self.tier)


def prompt_digests(tokens: Sequence[int], block_size: int,
                   max_blocks: Optional[int] = None) -> List[str]:
    """Hex chain digests of the prompt's full block-aligned prefixes —
    directly comparable against a replica's
    ``StateManager.prefix_digests()`` or its snapshot's
    ``prefix_index`` list."""
    return [h.hex() for h in prefix_chain_digests(tokens, block_size,
                                                  max_blocks)]


def affinity_chain_len(digests: Sequence, index) -> int:
    """Longest cached-chain match: the number of LEADING prompt digests
    present in ``index`` (any container supporting ``in`` — the router
    scores bytes digests against a replica's live index dict; hex
    digests score against a snapshot's ``prefix_index`` list.  Both
    sides must use the same encoding).  The run stops at the first
    miss — blocks past a gap are unreachable to ``match_prefix`` and
    score nothing."""
    n = 0
    for h in digests:
        if h not in index:
            break
        n += 1
    return n


def rank_replicas(policy: str, digests: Sequence,
                  candidates: Sequence[Tuple[str, object, int]],
                  rr_offset: int = 0,
                  scores: Optional[Dict[str, int]] = None,
                  ) -> Tuple[List[str], Dict[str, int]]:
    """Order candidate replicas best-first for one placement.

    ``candidates``: ``(name, digest_index, load)`` per routable replica
    — ``digest_index`` is the replica's resident prefix-digest set,
    ``load`` its live+queued request count (an int, so ordering is
    exact and deterministic).  Returns ``(ordered_names, scores)`` with
    ``scores[name]`` the affinity chain length (computed for every
    policy — it is the placement-hit telemetry even when the policy
    ignores it).  Callers scoring many candidates against one prompt
    may pass precomputed ``scores`` (the router's lazy shared-stream
    scorer); ``digests`` is then ignored.

    * ``affinity`` — longest cached-chain match first, then least
      loaded, then name (stable across runs);
    * ``least_loaded`` — load ascending, then name;
    * ``round_robin`` — registration order rotated by ``rr_offset``
      (the bench baseline the affinity bar is measured against).
    """
    if policy not in PLACEMENT_POLICIES:
        raise ValueError(f"placement={policy!r}: expected one of "
                         f"{PLACEMENT_POLICIES}")
    if scores is None:
        scores = {name: affinity_chain_len(digests, idx)
                  for name, idx, _ in candidates}
    names = [name for name, _, _ in candidates]
    if not names:
        return [], scores
    if policy == "round_robin":
        k = rr_offset % len(names)
        return names[k:] + names[:k], scores
    if policy == "affinity":
        order = sorted(candidates,
                       key=lambda c: (-scores[c[0]], c[2], c[0]))
    else:                                    # least_loaded
        order = sorted(candidates, key=lambda c: (c[2], c[0]))
    return [c[0] for c in order], scores
