"""Multi-replica serving front-end: cache-affinity routing, failover,
live request migration (docs/SERVING.md "Fleet: routing, failover,
migration"; ROADMAP item: multi-replica serving — the fleet analogue of
data-parallel replication, where availability is won at the replica
boundary).

The :class:`FleetRouter` stands where one hardened engine used to be
and speaks the same request API (``put`` / ``step`` / ``flush`` /
``cancel`` / ``query``), so the load harness and any future gateway
drive a fleet exactly like an engine.  It composes contracts earlier
PRs already built — nothing here invents a new one:

* **placement** — each NEW request is scored by prefix-cache affinity
  (longest cached-chain match of the prompt's chain digests against
  every replica's live ``prefix_digests()``; PR 4's content hashes are
  the key), falling back to least-loaded; ``round_robin`` exists as
  the measured baseline.
* **health & quarantine** — replicas are watched through the PR-8
  health ladder and their own failure counters; a per-replica
  :class:`~.replica.CircuitBreaker` quarantines a replica after
  consecutive failing steps (NEW placements avoid it; its open work
  keeps stepping) and re-admits it after a clean probe.
* **failover** — a replica death mid-traffic (:class:`EngineDeadError`)
  loses zero requests: the dead engine's ``snapshot()`` (host truth,
  valid on a dead backend) yields restore()-compatible per-request
  records that migrate onto surviving replicas via
  ``load_snapshot(..., merge=True)``, with bounded retry + step-counted
  exponential backoff while the fleet is unplaceable.  The
  (uid, position)-folded sampling keys make migrated streams
  token-identical to an undisturbed run.
* **live migration & scale-down** — ``migrate()`` moves a chosen
  subset of open work between live replicas (``engine.migrate_out``);
  ``scale_down()`` drains a replica and re-places exactly its
  ``shed_uids``.
* **fleet-level shed** — a request is rejected only when EVERY
  routable replica's own admission bound shed it (the 429-equivalent);
  one replica's backpressure is the next replica's placement.

Everything is step-counted and host-side: no wall-clock waits, no
polling loops — the router's only clock is its own step counter, so
chaos replays stay machine-independent (the same discipline tpulint's
``serving-wait`` rule enforces on the marked methods below).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..inference import SamplingParams
from ..inference.engine import InferenceEngine
from ..inference.failures import EngineDeadError
from ..inference.overload import AdmissionVerdict
from ..inference.ragged.state import iter_prefix_chain_digests
from ..telemetry import MetricsRegistry
from ..utils.logging import logger
from .placement import PLACEMENT_POLICIES, rank_replicas
from .replica import ReplicaHandle

# fleet-level view of engine health states, exported per replica as
# the serving_fleet_replica_health gauge (same 0-3 code space as the
# engine's own serving_health_state; 4 = router-quarantined)
_HEALTH_CODE = {"healthy": 0, "degraded": 1, "draining": 2, "dead": 3}


@dataclasses.dataclass
class FleetConfig:
    """Knobs for the fleet router."""
    # placement policy for NEW requests: "affinity" (longest cached-
    # chain match, least-loaded tiebreak), "least_loaded", or
    # "round_robin" (the bench baseline the affinity bar beats)
    placement: str = "affinity"
    # circuit breaker: consecutive failing steps before a replica is
    # quarantined from new placements, and how many router steps the
    # quarantine lasts before the half-open probe
    failure_threshold: int = 2
    probe_interval_steps: int = 8
    # migration placement: bounded retries with step-counted
    # exponential backoff (base * 2^attempt, capped) while no replica
    # is routable; exhausted retries close the request "shed" at the
    # fleet level rather than parking it forever
    max_migration_retries: int = 8
    migration_backoff_steps: int = 1

    def __post_init__(self):
        if self.placement not in PLACEMENT_POLICIES:
            raise ValueError(f"placement={self.placement!r}: expected "
                             f"one of {PLACEMENT_POLICIES}")
        if self.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if self.probe_interval_steps < 1:
            raise ValueError("probe_interval_steps must be >= 1")
        if self.max_migration_retries < 0:
            raise ValueError("max_migration_retries must be >= 0")
        if self.migration_backoff_steps < 1:
            raise ValueError("migration_backoff_steps must be >= 1")


@dataclasses.dataclass
class _Migration:
    """One request record waiting for re-placement (failover, live
    migration, or scale-down hand-off)."""
    rec: Dict
    source: str
    attempts: int = 0
    next_step: int = 0


class FleetRouter:
    """N engine replicas behind one engine-shaped front-end (module
    docstring).  ``replicas``: ``{name: InferenceEngine}`` (insertion
    order is the deterministic rank tiebreak) or a sequence of engines
    auto-named ``r0, r1, ...``."""

    def __init__(self, replicas, cfg: Optional[FleetConfig] = None):
        self.cfg = cfg or FleetConfig()
        self._reps: Dict[str, ReplicaHandle] = {}
        self._block_size: Optional[int] = None
        self._max_blocks = 1      # hash budget: fleet max blocks/seq
        self._owner: Dict[int, str] = {}      # open uid -> replica name
        self._closed: Dict[int, str] = {}     # fleet-terminal statuses
        self._reaped: set = set()             # fleet closures to report
        self._migrations: List[_Migration] = []
        self._steps = 0
        self._rr = 0                          # round-robin cursor
        self._setup_metrics()
        items = replicas.items() if isinstance(replicas, dict) \
            else ((f"r{i}", e) for i, e in enumerate(replicas))
        for name, eng in items:
            self.add_replica(name, eng)
        if not self._reps:
            raise ValueError("FleetRouter needs at least one replica")

    def _setup_metrics(self) -> None:
        """Fleet gauges/counters (docs/OBSERVABILITY.md "Fleet
        gauges") — host counter bumps only, exported through the same
        registry/exposition machinery the engines use."""
        self.metrics = MetricsRegistry()
        reg = self.metrics
        self._c_placements = reg.counter(
            "serving_fleet_placements_total",
            "new requests placed on a replica (label policy=)",
            int_valued=True)
        self._c_place_hits = reg.counter(
            "serving_fleet_placement_affinity_hits_total",
            "placements whose chosen replica held a nonzero cached "
            "chain for the prompt", int_valued=True)
        self._c_shed = reg.counter(
            "serving_fleet_shed_total",
            "requests shed at the FLEET level (every routable replica "
            "rejected, or migration retries exhausted) — the "
            "429-equivalent", int_valued=True)
        self._c_failovers = reg.counter(
            "serving_fleet_failovers_total",
            "replica deaths answered by snapshot migration",
            int_valued=True)
        self._c_migrations = reg.counter(
            "serving_fleet_migrations_total",
            "request records re-placed onto a surviving replica",
            int_valued=True)
        self._c_migration_retries = reg.counter(
            "serving_fleet_migration_retries_total",
            "migration placements deferred by backoff (no routable "
            "replica at that step)", int_valued=True)
        self._c_quarantines = reg.counter(
            "serving_fleet_quarantines_total",
            "circuit-breaker trips (replica quarantined from new "
            "placements)", int_valued=True)
        self._c_readmissions = reg.counter(
            "serving_fleet_readmissions_total",
            "quarantined replicas re-admitted after a clean probe",
            int_valued=True)
        self._c_failed = reg.counter(
            "serving_fleet_requests_failed_total",
            "requests closed 'failed' at the fleet level (inexact "
            "records whose device-side tokens died with a replica)",
            int_valued=True)
        self._g_replicas = reg.gauge(
            "serving_fleet_replicas", "replicas registered (incl. dead)")
        self._g_routable = reg.gauge(
            "serving_fleet_replicas_routable",
            "replicas currently accepting new placements")
        self._g_rep_health = reg.gauge(
            "serving_fleet_replica_health",
            "per-replica health (label replica=): 0 healthy 1 degraded "
            "2 draining 3 dead 4 quarantined")
        reg.gauge_fn("serving_fleet_requests_migrating",
                     lambda: len(self._migrations),
                     "request records waiting for re-placement")
        reg.gauge_fn("serving_fleet_placement_hit_rate",
                     self._placement_hit_rate,
                     "affinity-hit placements / placements (absent "
                     "before the first placement)")

    def _placement_hit_rate(self) -> Optional[float]:
        total = sum(v for _, v in self._c_placements.series())
        if not total:
            return None
        return self._c_place_hits.value() / total

    # ------------------------------------------------------------------
    # fleet membership
    # ------------------------------------------------------------------
    def add_replica(self, name: str, engine: InferenceEngine) -> None:
        """Register a replica (scale-up).  Fleets must share one KV
        block size — the chain digest is block-aligned, so a
        heterogeneous fleet could never compare affinity keys."""
        if name in self._reps:
            raise ValueError(f"replica {name!r} already registered")
        bs = engine.icfg.kv_block_size
        if self._block_size is None:
            self._block_size = bs
        elif bs != self._block_size:
            raise ValueError(
                f"replica {name!r} has kv_block_size={bs}, fleet uses "
                f"{self._block_size}: affinity digests are block-"
                "aligned and cannot mix sizes")
        self._max_blocks = max(self._max_blocks,
                               engine.max_blocks_per_seq)
        self._reps[name] = ReplicaHandle(
            name, engine, threshold=self.cfg.failure_threshold,
            probe_interval=self.cfg.probe_interval_steps)

    def replica(self, name: str) -> ReplicaHandle:
        return self._reps[name]

    @property
    def replica_names(self) -> List[str]:
        return list(self._reps)

    def _routable(self) -> List[ReplicaHandle]:
        return [r for r in self._reps.values() if r.routable()]

    def _score_candidates(self, tokens, cands) -> Dict[str, int]:
        """Leading-run affinity scores for one prompt against every
        candidate's LIVE index dict, from one shared LAZY digest
        stream: hashing stops at the block where every candidate's run
        has missed (a fleet-wide cache-miss prompt hashes ONE block —
        the same discipline as ``match_prefix``) and is capped at the
        fleet's max blocks/seq (blocks past it can never be cached)."""
        scores = {name: 0 for name, _, _ in cands}
        alive = {name: idx for name, idx, _ in cands}
        if alive:
            for h in iter_prefix_chain_digests(
                    tokens, self._block_size, self._max_blocks):
                for name in list(alive):
                    if h in alive[name]:
                        scores[name] += 1
                    else:
                        del alive[name]
                if not alive:
                    break
        return scores

    def _rank(self, tokens) -> Tuple[List[str], Dict[str, int]]:
        """Rank routable replicas for one placement.  Half-open
        (probing) replicas rank strictly AFTER every closed one
        whatever their affinity — quarantine means minimal traffic, so
        they only receive work when no closed replica can take it (and
        that one placement is the probe)."""
        closed = [(rep.name, rep.digest_index(), rep.load())
                  for rep in self._routable()
                  if rep.breaker.state == "closed"]
        probing = [(rep.name, rep.digest_index(), rep.load())
                   for rep in self._routable()
                   if rep.breaker.state == "half_open"]
        scores = self._score_candidates(tokens, closed + probing)
        order, _ = rank_replicas(self.cfg.placement, (), closed,
                                 rr_offset=self._rr, scores=scores)
        if probing:
            p_order, _ = rank_replicas(
                self.cfg.placement, (), probing,
                rr_offset=self._rr, scores=scores)
            order = order + p_order
        return order, scores

    # ------------------------------------------------------------------
    # the engine-shaped request API
    # ------------------------------------------------------------------
    def put(self, uid: int, tokens: Sequence[int], priority: int = 0,
            deadline_ms: Optional[float] = None) -> AdmissionVerdict:  # tpulint: serving-loop
        """Route a request.  Continuations forward to the owning
        replica (or join the request's queued migration record — the
        fed-back token is simply the next stream token).  NEW requests
        are placed by the configured policy; a replica's shed verdict
        sends the request to the NEXT candidate, and only when every
        routable replica sheds does the fleet shed (``replica=None`` on
        the verdict — the 429-equivalent)."""
        owner = self._owner.get(uid)
        if owner is not None:
            v = self._reps[owner].engine.put(uid, tokens,
                                             priority=priority,
                                             deadline_ms=deadline_ms)
            return v._replace(replica=owner)
        for m in self._migrations:
            if m.rec["uid"] == uid:
                m.rec["tokens"].extend(int(t) for t in tokens)
                return AdmissionVerdict(True, "continued",
                                        reason="joined migration record")
        order, scores = self._rank(tokens)
        if self.cfg.placement == "round_robin" and order:
            # the rotation cursor advances per ARRIVAL, here only —
            # migration placements also rank (in _place_record) and
            # must not skew the baseline's rotation over new requests
            self._rr += 1
        for name in order:
            v = self._reps[name].engine.put(uid, tokens,
                                            priority=priority,
                                            deadline_ms=deadline_ms)
            for eu in v.evicted_uids:
                # evict-lowest backpressure shed a queued request on
                # that replica: terminal at the fleet level too
                self._closed[eu] = "shed"
                self._owner.pop(eu, None)
                self._reaped.add(eu)
            if v.admitted:
                self._owner[uid] = name
                # a terminal uid that returns lives a full new life —
                # the engine's own reuse semantics, mirrored.  The
                # stale reaped entry goes too: a driver draining later
                # must not drop the now-live request as closed
                self._closed.pop(uid, None)
                self._reaped.discard(uid)
                self._c_placements.inc(policy=self.cfg.placement)
                if scores.get(name, 0) > 0:
                    self._c_place_hits.inc()
                return v._replace(replica=name)
        self._c_shed.inc()
        self._closed[uid] = "shed"
        self._reaped.add(uid)
        return AdmissionVerdict(
            False, "shed",
            reason="fleet saturated: every routable replica shed the "
                   "request" if order else "no routable replica")

    def step(self, rng=None,
             sampling: SamplingParams = SamplingParams()
             ) -> Dict[int, int]:  # tpulint: serving-loop
        """One fleet step: every live replica runs one engine step
        (quarantined replicas included — their open work must finish,
        and their clean steps are what the probe eventually certifies),
        breaker bookkeeping folds in each replica's outcome, a replica
        that died mid-step fails over, and the migration queue pumps.
        Returns the merged ``{uid: token}`` emissions — uids are
        disjoint across replicas because each open request is owned by
        exactly one."""
        self._steps += 1
        outs: Dict[int, int] = {}
        for name in list(self._reps):
            rep = self._reps[name]
            if rep.dead:
                continue
            rep.breaker.tick(self._steps)
            try:
                o = rep.engine.step(rng=rng, sampling=sampling)
            except EngineDeadError:
                self._failover(name)
                continue
            ev = rep.observe(self._steps)
            if ev == "opened":
                self._c_quarantines.inc()
                logger.warning(
                    "fleet: replica %s quarantined after %d consecutive "
                    "failing steps (probe in %d steps)", name,
                    rep.breaker.failures, self.cfg.probe_interval_steps)
            elif ev == "readmitted":
                self._c_readmissions.inc()
                logger.warning(
                    "fleet: replica %s re-admitted after a clean probe",
                    name)
            for uid in rep.engine._drain_reaped():
                self._note_engine_close(rep, uid)
            outs.update(o)
        self._pump_migrations()
        self._refresh_gauges()
        return outs

    def flush(self, uid: int) -> None:
        """Client-side completion — forwards to the owner and records
        the fleet-terminal status.  A uid waiting in the migration
        queue settles HERE: the client is done with it, and a record
        left in the queue would re-run on a survivor as an orphan
        nobody ever drives or flushes."""
        for i, m in enumerate(self._migrations):
            if m.rec["uid"] == uid:
                del self._migrations[i]
                self._closed[uid] = "finished"
                return
        owner = self._owner.pop(uid, None)
        if owner is None:
            return
        self._reps[owner].engine.flush(uid)
        self._closed[uid] = "finished"

    def cancel(self, uid: int) -> None:
        """Client abort, wherever the request is: owned by a replica,
        waiting in the migration queue, or already gone (no-op)."""
        for i, m in enumerate(self._migrations):
            if m.rec["uid"] == uid:
                del self._migrations[i]
                self._closed[uid] = "cancelled"
                self._reaped.add(uid)
                return
        owner = self._owner.pop(uid, None)
        if owner is None:
            return
        rep = self._reps[owner]
        rep.engine.cancel(uid)
        for ru in rep.engine._drain_reaped():
            if ru != uid:          # other staged closures still surface
                self._note_engine_close(rep, ru)
        self._closed[uid] = "cancelled"
        self._reaped.add(uid)

    def query(self, uid: int) -> Dict:
        """Fleet-level request status: the owning replica's ``query()``
        plus ``replica``; ``migrating`` while a record waits for
        re-placement; the fleet-terminal status after closure."""
        if uid in self._closed:
            return {"status": self._closed[uid], "replica": None}
        for m in self._migrations:
            if m.rec["uid"] == uid:
                return {"status": "migrating", "replica": None,
                        "generated": list(m.rec.get("generated", []))}
        owner = self._owner.get(uid)
        if owner is not None:
            d = self._reps[owner].engine.query(uid)
            d["replica"] = owner
            return d
        return {"status": "unknown", "replica": None}

    def drain_reaped(self) -> set:
        """Uids the FLEET terminally closed since the last call
        (replica-side closures, fleet sheds, failed migrations) — the
        driver drops them from its active set, exactly like
        ``engine._drain_reaped``."""
        out = self._reaped
        self._reaped = set()
        return out

    def _note_engine_close(self, rep: ReplicaHandle, uid: int) -> None:
        """An engine-side terminal closure surfaced through that
        replica's reaped set.  ``migrated`` is NOT a fleet closure —
        the record is in flight to another replica.  A STALE report is
        ignored: a uid shed on this replica and then re-admitted on
        another before the reaped set drained is live THERE — closing
        it here would orphan the revived request."""
        own = self._owner.get(uid)
        if own is not None and own != rep.name:
            return
        s = rep.engine.query(uid)["status"]
        if s == "migrated":
            return
        if s in ("queued", "running"):
            # the engine reaps only at terminal close, so a LIVE status
            # means the uid was re-admitted on this replica after the
            # reap was staged (same revival race, same-replica form)
            return
        if s in ("unknown", "forgotten"):
            s = "released"
        self._closed[uid] = s
        self._owner.pop(uid, None)
        self._reaped.add(uid)

    # ------------------------------------------------------------------
    # failover, migration, scale-down
    # ------------------------------------------------------------------
    def _failover(self, name: str) -> None:  # tpulint: serving-loop
        """A replica died mid-step.  Zero lost requests: its
        ``snapshot()`` (host truth — valid on the dead backend) yields
        per-request records that enter the migration queue; inexact
        records (device-side tokens died with the replica) close
        ``failed`` honestly."""
        rep = self._reps[name]
        rep.breaker.kill()
        self._c_failovers.inc()
        # closures the engine staged in its dying step (deadline
        # reaps, sheds) must still surface as fleet closures — the
        # step that would have delivered them raised instead
        for uid in rep.engine._drain_reaped():
            self._note_engine_close(rep, uid)
        snap = rep.engine.snapshot()
        n = 0
        for rec in snap["requests"]:
            self._owner.pop(int(rec["uid"]), None)
            n += self._enqueue_migration(rec, source=name)
        logger.warning(
            "fleet: replica %s died; %d open request(s) queued for "
            "migration, %d inexact record(s) closed failed", name, n,
            len(snap["requests"]) - n)

    def _enqueue_migration(self, rec: Dict, source: str) -> int:
        uid = int(rec["uid"])
        if not rec.get("exact", True) or not rec.get("tokens"):
            self._closed[uid] = "failed"
            self._reaped.add(uid)
            self._c_failed.inc()
            return 0
        self._migrations.append(
            _Migration(rec=rec, source=source, next_step=self._steps))
        return 1

    def _pump_migrations(self) -> None:  # tpulint: serving-loop
        """Place queued migration records on surviving replicas.  A
        record that cannot place (no routable replica right now)
        retries with step-counted exponential backoff, bounded by
        ``max_migration_retries`` — exhausted retries shed at the
        fleet level instead of parking forever."""
        if not self._migrations:
            return
        still: List[_Migration] = []
        for m in self._migrations:
            if m.next_step > self._steps:
                still.append(m)
                continue
            name = self._place_record(m.rec, exclude=m.source)
            if name is not None:
                self._owner[m.rec["uid"]] = name
                self._c_migrations.inc()
                continue
            m.attempts += 1
            self._c_migration_retries.inc()
            if m.attempts > self.cfg.max_migration_retries:
                # last resort before destroying the work: going HOME
                # beats shedding — the source may be alive again (a
                # quarantined-then-readmitted replica); only a record
                # with nowhere at all left sheds
                name = self._place_record(m.rec)
                if name is not None:
                    self._owner[m.rec["uid"]] = name
                    self._c_migrations.inc()
                    continue
                self._closed[m.rec["uid"]] = "shed"
                self._reaped.add(m.rec["uid"])
                self._c_shed.inc()
                logger.warning(
                    "fleet: migration of uid %d exhausted %d retries "
                    "with no routable replica — shed",
                    m.rec["uid"], m.attempts - 1)
                continue
            m.next_step = self._steps + self.cfg.migration_backoff_steps \
                * (1 << min(m.attempts - 1, 6))
            still.append(m)
        self._migrations = still

    def _place_record(self, rec: Dict,
                      exclude: Optional[str] = None) -> Optional[str]:
        """Place one migration record by the same affinity ranking new
        requests get (its stream's cached chain may still be resident
        somewhere).  The SOURCE replica is excluded — its cached-free
        chain makes it the top affinity score for its own evictee, and
        a migration that lands back home moved nothing.
        ``load_snapshot(merge=True)`` bypasses admission bounds — the
        request was admitted by the fleet once; shedding it again
        would double-charge the client."""
        order, _ = self._rank(rec.get("tokens") or ())
        for name in order:
            if name == exclude:
                continue
            rep = self._reps[name]
            try:
                rep.engine.load_snapshot(
                    {"version": InferenceEngine.SNAPSHOT_VERSION,
                     "partial": True, "requests": [rec]}, merge=True)
            except ValueError:
                continue          # uid collision: try the next replica
            # no placement-hit bump here: migrations are not counted
            # in the placements denominator, and the MEASURED hit rate
            # (engine cached/prompt counters) covers them anyway
            return name
        return None

    def migrate(self, uids: Sequence[int], source: str) -> int:
        """Live request migration: extract the given OPEN requests from
        ``source`` (``engine.migrate_out`` — closes them ``migrated``
        there, releasing their KV) and re-place them by affinity on the
        rest of the fleet.  Returns the number of records that entered
        the migration queue.  With no routable destination besides the
        source, nothing is extracted (0) — a migration that could only
        end in retry-exhaustion must not destroy requests the source
        is serving fine."""
        if not any(rep.routable() for rep in self._reps.values()
                   if rep.name != source):
            return 0
        rep = self._reps[source]
        part = rep.engine.migrate_out(uids)
        n = 0
        for rec in part["requests"]:
            self._owner.pop(int(rec["uid"]), None)
            n += self._enqueue_migration(rec, source=source)
        for uid in rep.engine._drain_reaped():
            self._note_engine_close(rep, uid)  # "migrated" returns early
        self._pump_migrations()
        return n

    def scale_down(self, name: str,
                   deadline_ms: Optional[float] = None,
                   sampling: SamplingParams = SamplingParams(),
                   rng=None) -> Dict:
        """Drain-to-scale-down: ``engine.drain()`` the replica, then
        re-place exactly its ``shed_uids`` records (the drain's
        completed set stays settled — re-placing it would double-run).
        The replica leaves the routable set permanently; returns the
        drain's snapshot."""
        rep = self._reps[name]
        snap = rep.engine.drain(deadline_ms=deadline_ms,
                                sampling=sampling, rng=rng)
        rep.breaker.kill()
        recs = {int(r["uid"]): r for r in snap["requests"]}
        shed = set(snap["shed_uids"])
        for uid in snap["shed_uids"]:
            if uid in recs:
                self._owner.pop(uid, None)
                self._enqueue_migration(recs[uid], source=name)
        for uid in rep.engine._drain_reaped():
            if uid in shed:
                continue          # re-placing, not closing
            self._note_engine_close(rep, uid)
        self._pump_migrations()
        return snap

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def _refresh_gauges(self) -> None:
        # health_state(), not health(): the full probe is a phase
        # boundary (it polls device memory under device_telemetry) and
        # must not run per replica per router step
        self._g_replicas.set(len(self._reps))
        self._g_routable.set(len(self._routable()))
        for name, rep in self._reps.items():
            if rep.breaker.state in ("open", "half_open"):
                code = 4
            else:
                code = _HEALTH_CODE.get(rep.engine.health_state(), 3)
            self._g_rep_health.set(code, replica=name)

    def health(self) -> Dict:
        """Fleet health summary — the gateway's ``/healthz`` payload:
        per-replica engine state + breaker state + load, and the
        fleet-level tallies."""
        self._refresh_gauges()
        reps = {}
        for name, rep in self._reps.items():
            reps[name] = {
                "state": rep.engine.health()["state"],
                "breaker": rep.breaker.state,
                "load": rep.load(),
                "quarantines": rep.breaker.quarantines,
                "readmissions": rep.breaker.readmissions,
            }
        return {
            "replicas": reps,
            "routable": len(self._routable()),
            "migrating": len(self._migrations),
            "steps": self._steps,
            "failovers": int(self._c_failovers.value()),
            "migrations": int(self._c_migrations.value()),
            "fleet_shed": int(self._c_shed.value()),
        }

    def metrics_snapshot(self) -> Dict:
        """JSON-able snapshot of the fleet gauges/counters (the
        replicas' own registries are separate — scrape them per
        replica)."""
        return self.metrics.snapshot()

    def request_metrics(self) -> Dict:
        """Fleet-wide per-request aggregate: each replica's lifecycle
        aggregate keyed by replica name (a migrated request has one
        open record fleet-wide; its prior replicas hold closed
        ``migrated``/``shed`` records by design)."""
        return {name: rep.engine.request_metrics()["aggregate"]
                for name, rep in self._reps.items()}
