"""Multi-replica serving front-end: cache-affinity routing, failover,
live request migration (docs/SERVING.md "Fleet: routing, failover,
migration"; ROADMAP item: multi-replica serving — the fleet analogue of
data-parallel replication, where availability is won at the replica
boundary).

The :class:`FleetRouter` stands where one hardened engine used to be
and speaks the same request API (``put`` / ``step`` / ``flush`` /
``cancel`` / ``query``), so the load harness and any future gateway
drive a fleet exactly like an engine.  It composes contracts earlier
PRs already built — nothing here invents a new one:

* **placement** — each NEW request is scored by prefix-cache affinity
  (longest cached-chain match of the prompt's chain digests against
  every replica's live ``prefix_digests()``; PR 4's content hashes are
  the key), falling back to least-loaded; ``round_robin`` exists as
  the measured baseline.
* **health & quarantine** — replicas are watched through the PR-8
  health ladder and their own failure counters; a per-replica
  :class:`~.replica.CircuitBreaker` quarantines a replica after
  consecutive failing steps (NEW placements avoid it; its open work
  keeps stepping) and re-admits it after a clean probe.
* **failover** — a replica death mid-traffic (:class:`EngineDeadError`)
  loses zero requests: the dead engine's ``snapshot()`` (host truth,
  valid on a dead backend) yields restore()-compatible per-request
  records that migrate onto surviving replicas via
  ``load_snapshot(..., merge=True)``, with bounded retry + step-counted
  exponential backoff while the fleet is unplaceable.  The
  (uid, position)-folded sampling keys make migrated streams
  token-identical to an undisturbed run.
* **live migration & scale-down** — ``migrate()`` moves a chosen
  subset of open work between live replicas (``engine.migrate_out``);
  ``scale_down()`` drains a replica and re-places exactly its
  ``shed_uids``.
* **fleet-level shed** — a request is rejected only when EVERY
  routable replica's own admission bound shed it (the 429-equivalent);
  one replica's backpressure is the next replica's placement.
* **disaggregated pools** — replicas carry roles (``prefill`` /
  ``decode`` / ``mixed``): with both pools present, new arrivals place
  SLO-class-aware (interactive prompts onto chunk-free prefill
  replicas, batch streams straight onto the decode pool), and a
  request that finishes prefill on a pure-prefill replica is shipped
  to a decode replica as routing plus block transfer
  (``engine.handoff_out`` → ``load_snapshot(merge=True)``, the KV
  chain riding the same ``export_tier_chain`` path an affinity-miss
  restage uses) — docs/SERVING.md "Disaggregated pools & elasticity".

Everything is step-counted and host-side: no wall-clock waits, no
polling loops — the router's only clock is its own step counter, so
chaos replays stay machine-independent (the same discipline tpulint's
``serving-wait`` rule enforces on the marked methods below).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..inference import SamplingParams
from ..inference.engine import InferenceEngine
from ..inference.failures import EngineDeadError
from ..inference.overload import AdmissionVerdict
from ..inference.ragged.state import iter_prefix_chain_digests
from ..telemetry import (FlightRecorder, MetricsRegistry,
                         config_fingerprint, merge_scorecards)
from ..utils.logging import logger
from .fleet_telemetry import (FLEET_DUMP_VERSION, NOOP_CTX, FleetRegistry,
                              FleetTelemetry, FleetTelemetryConfig,
                              fleet_request_metrics)
from .placement import (PLACEMENT_POLICIES, REPLICA_ROLES, rank_replicas,
                        split_by_pool)
from .replica import ReplicaHandle

# fleet-level view of engine health states, exported per replica as
# the serving_fleet_replica_health gauge (same 0-3 code space as the
# engine's own serving_health_state; 4 = router-quarantined)
_HEALTH_CODE = {"healthy": 0, "degraded": 1, "draining": 2, "dead": 3}


@dataclasses.dataclass
class FleetConfig:
    """Knobs for the fleet router."""
    # placement policy for NEW requests: "affinity" (longest cached-
    # chain match, least-loaded tiebreak), "least_loaded", or
    # "round_robin" (the bench baseline the affinity bar beats)
    placement: str = "affinity"
    # circuit breaker: consecutive failing steps before a replica is
    # quarantined from new placements, and how many router steps the
    # quarantine lasts before the half-open probe
    failure_threshold: int = 2
    probe_interval_steps: int = 8
    # migration placement: bounded retries with step-counted
    # exponential backoff (base * 2^attempt, capped) while no replica
    # is routable; exhausted retries close the request "shed" at the
    # fleet level rather than parking it forever
    max_migration_retries: int = 8
    migration_backoff_steps: int = 1
    # fleet observability plane (docs/OBSERVABILITY.md "Fleet
    # observability"): "on" constructs the FleetTelemetry object
    # (journeys, router spans, fleet anomaly detectors, capture
    # budget); "off" constructs NOTHING and adds zero clock reads per
    # router step (the counted PR-10 bar).  "auto" resolves OFF until
    # a signal consumer flips it: attaching the autoscaling actuator
    # (serving/autoscaler.py) calls router.enable_telemetry(), exactly
    # like the engines' anomaly/device_telemetry gates
    telemetry: str = "auto"
    telemetry_cfg: Optional[FleetTelemetryConfig] = None
    # fleet post-mortem bundles: router.debug_dump() target for the
    # failover/quarantine/fleet-shed auto-dumps (None = no auto-dumps),
    # bounded by max_autodumps per router generation
    flight_dir: Optional[str] = None
    max_autodumps: int = 8

    def __post_init__(self):
        if self.placement not in PLACEMENT_POLICIES:
            raise ValueError(f"placement={self.placement!r}: expected "
                             f"one of {PLACEMENT_POLICIES}")
        if self.telemetry not in ("auto", "on", "off"):
            raise ValueError(f"telemetry={self.telemetry!r}: expected "
                             "'auto', 'on', or 'off'")
        if self.max_autodumps < 0:
            raise ValueError("max_autodumps must be >= 0")
        if self.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if self.probe_interval_steps < 1:
            raise ValueError("probe_interval_steps must be >= 1")
        if self.max_migration_retries < 0:
            raise ValueError("max_migration_retries must be >= 0")
        if self.migration_backoff_steps < 1:
            raise ValueError("migration_backoff_steps must be >= 1")


@dataclasses.dataclass
class _Migration:
    """One request record waiting for re-placement (failover, live
    migration, scale-down, or a prefill→decode handoff).  ``pool``
    targets the placement at one pool (ranked fallback to the rest —
    a full pool degrades to colocated placement, never to a lost
    request); ``via`` labels the journey's "placed" event."""
    rec: Dict
    source: str
    attempts: int = 0
    next_step: int = 0
    pool: Optional[str] = None
    via: str = "migration"


class FleetRouter:
    """N engine replicas behind one engine-shaped front-end (module
    docstring).  ``replicas``: ``{name: InferenceEngine}`` (insertion
    order is the deterministic rank tiebreak) or a sequence of engines
    auto-named ``r0, r1, ...``."""

    def __init__(self, replicas, cfg: Optional[FleetConfig] = None,
                 roles: Optional[Dict[str, str]] = None):
        self.cfg = cfg or FleetConfig()
        self._reps: Dict[str, ReplicaHandle] = {}
        self._block_size: Optional[int] = None
        self._max_blocks = 1      # hash budget: fleet max blocks/seq
        self._owner: Dict[int, str] = {}      # open uid -> replica name
        self._closed: Dict[int, str] = {}     # fleet-terminal statuses
        self._reaped: set = set()             # fleet closures to report
        self._migrations: List[_Migration] = []
        self._steps = 0
        self._rr = 0                          # round-robin cursor
        # uids already handed off prefill→decode once this life: a
        # fallback placement that lands one back on a prefill replica
        # must not re-extract it (no ping-pong); size-bounded
        self._handed: set = set()
        # the attached scaling actuator (serving/autoscaler.py); the
        # router drives it once per step, after telemetry feeds
        self._autoscaler = None
        # reconciliation ledgers (docs/OBSERVABILITY.md "Fleet
        # observability"): per-(uid, replica) phantom-shed counts
        # (engine shed closures that were fleet routing retries —
        # bounded FIFO), fleet-level closures that left NO engine
        # terminal, and record-gap closures that left NO engine record
        # at all (fleet_request_metrics adds them to its tally)
        self._phantoms: Dict[Tuple[int, str], int] = {}
        self._fleet_closures: Dict[str, int] = {}
        self._record_gaps: Dict[str, int] = {}
        self._setup_metrics()
        # the black box is ALWAYS constructed (engine discipline: the
        # happy path never touches it; the failure path's breadcrumbs
        # must exist before the incident someone debugs).  Placement
        # decisions are noted only when the telemetry plane is on
        self.flight = FlightRecorder()
        self._autodumps = 0
        tmode = self.cfg.telemetry
        # "auto" resolves OFF until a consumer flips it — attaching
        # the autoscaler calls enable_telemetry(), like the engines'
        # anomaly/device_telemetry gates
        self._ftel: Optional[FleetTelemetry] = FleetTelemetry(
            self.cfg.telemetry_cfg, self.metrics) \
            if tmode == "on" else None
        # the fleet-wide exposition view; pull-only, so constructing
        # it costs nothing on the serving path
        self.fleet_registry = FleetRegistry(self)
        items = replicas.items() if isinstance(replicas, dict) \
            else ((f"r{i}", e) for i, e in enumerate(replicas))
        for name, eng in items:
            self.add_replica(name, eng,
                             role=(roles or {}).get(name, "mixed"))
        if not self._reps:
            raise ValueError("FleetRouter needs at least one replica")

    def _setup_metrics(self) -> None:
        """Fleet gauges/counters (docs/OBSERVABILITY.md "Fleet
        gauges") — host counter bumps only, exported through the same
        registry/exposition machinery the engines use."""
        self.metrics = MetricsRegistry()
        reg = self.metrics
        self._c_placements = reg.counter(
            "serving_fleet_placements_total",
            "new requests placed on a replica (label policy=)",
            int_valued=True)
        self._c_place_hits = reg.counter(
            "serving_fleet_placement_affinity_hits_total",
            "placements whose chosen replica held a nonzero cached "
            "chain for the prompt", int_valued=True)
        self._c_shed = reg.counter(
            "serving_fleet_shed_total",
            "requests shed at the FLEET level (every routable replica "
            "rejected, or migration retries exhausted) — the "
            "429-equivalent", int_valued=True)
        self._c_failovers = reg.counter(
            "serving_fleet_failovers_total",
            "replica deaths answered by snapshot migration",
            int_valued=True)
        self._c_migrations = reg.counter(
            "serving_fleet_migrations_total",
            "request records re-placed onto a surviving replica",
            int_valued=True)
        self._c_migration_retries = reg.counter(
            "serving_fleet_migration_retries_total",
            "migration placements deferred by backoff (no routable "
            "replica at that step)", int_valued=True)
        self._c_quarantines = reg.counter(
            "serving_fleet_quarantines_total",
            "circuit-breaker trips (replica quarantined from new "
            "placements)", int_valued=True)
        self._c_readmissions = reg.counter(
            "serving_fleet_readmissions_total",
            "quarantined replicas re-admitted after a clean probe",
            int_valued=True)
        self._c_failed = reg.counter(
            "serving_fleet_requests_failed_total",
            "requests closed 'failed' at the fleet level (inexact "
            "records whose device-side tokens died with a replica)",
            int_valued=True)
        self._c_phantom = reg.counter(
            "serving_fleet_replica_shed_retries_total",
            "engine-level shed closures that were fleet routing "
            "retries — phantom terminals the reconciled fleet rollups "
            "subtract back out", int_valued=True)
        self._c_tier_fetches = reg.counter(
            "serving_fleet_tier_fetches_total",
            "cross-replica KV tier chain fetches: a spilled chain "
            "pulled from a peer replica's tier into the chosen "
            "replica's (docs/KV_TIERING.md)", int_valued=True)
        self._c_tier_fetch_blocks = reg.counter(
            "serving_fleet_tier_fetch_blocks_total",
            "KV blocks moved by cross-replica tier fetches",
            int_valued=True)
        self._c_tier_fetch_rejects = reg.counter(
            "serving_fleet_tier_fetch_rejects_total",
            "tier-fetch payloads rejected by digest/checksum "
            "verification on arrival (the chosen replica re-prefills "
            "instead)", int_valued=True)
        self._c_handoffs = reg.counter(
            "serving_fleet_handoffs_total",
            "prefill→decode handoffs: requests extracted from a "
            "prefill replica after first token and re-placed on the "
            "decode pool (docs/SERVING.md \"Disaggregated pools & "
            "elasticity\")", int_valued=True)
        self._c_scale_ups = reg.counter(
            "serving_fleet_scale_ups_total",
            "replicas added by the autoscaling actuator (label "
            "pool=)", int_valued=True)
        self._c_scale_downs = reg.counter(
            "serving_fleet_scale_downs_total",
            "replicas drained away by the autoscaling actuator "
            "(label pool=)", int_valued=True)
        self._g_replicas = reg.gauge(
            "serving_fleet_replicas", "replicas registered (incl. dead)")
        self._g_routable = reg.gauge(
            "serving_fleet_replicas_routable",
            "replicas currently accepting new placements")
        self._g_rep_health = reg.gauge(
            "serving_fleet_replica_health",
            "per-replica health (label replica=): 0 healthy 1 degraded "
            "2 draining 3 dead 4 quarantined")
        self._g_pool_replicas = reg.gauge(
            "serving_fleet_pool_replicas",
            "live replicas serving a pool (label pool=; mixed "
            "replicas serve both, so the pools may overlap)")
        self._g_pool_load = reg.gauge(
            "serving_fleet_pool_load",
            "summed live+queued requests across a pool's replicas "
            "(label pool=) — the depth/width signal the autoscaler "
            "sizes each pool by")
        reg.gauge_fn("serving_fleet_requests_migrating",
                     lambda: len(self._migrations),
                     "request records waiting for re-placement")
        reg.gauge_fn("serving_fleet_placement_hit_rate",
                     self._placement_hit_rate,
                     "affinity-hit placements / placements (absent "
                     "before the first placement)")

    def _placement_hit_rate(self) -> Optional[float]:
        total = self.metrics.series_sum("serving_fleet_placements_total")
        if not total:
            return None
        return self._c_place_hits.value() / total

    # ------------------------------------------------------------------
    # fleet membership
    # ------------------------------------------------------------------
    def add_replica(self, name: str, engine: InferenceEngine,
                    role: str = "mixed") -> None:
        """Register a replica (scale-up).  Fleets must share one KV
        block size — the chain digest is block-aligned, so a
        heterogeneous fleet could never compare affinity keys.
        ``role`` joins it to a pool (docs/SERVING.md "Disaggregated
        pools & elasticity"); a ``prefill`` replica's prompt ingestion
        is made chunk-free here — the chunk cap exists to protect
        decode TPOT on a replica that also decodes, which a pure
        prefill replica never does."""
        if name in self._reps:
            raise ValueError(f"replica {name!r} already registered")
        bs = engine.icfg.kv_block_size
        if self._block_size is None:
            self._block_size = bs
        elif bs != self._block_size:
            raise ValueError(
                f"replica {name!r} has kv_block_size={bs}, fleet uses "
                f"{self._block_size}: affinity digests are block-"
                "aligned and cannot mix sizes")
        self._max_blocks = max(self._max_blocks,
                               engine.max_blocks_per_seq)
        if role == "prefill" and engine.ocfg.prefill_chunk is not None:
            engine.ocfg = dataclasses.replace(engine.ocfg,
                                              prefill_chunk=None)
            logger.info("fleet: replica %s joins the prefill pool "
                        "chunk-free (prefill_chunk cleared)", name)
        self._reps[name] = ReplicaHandle(
            name, engine, threshold=self.cfg.failure_threshold,
            probe_interval=self.cfg.probe_interval_steps, role=role)

    def replica(self, name: str) -> ReplicaHandle:
        return self._reps[name]

    @property
    def replica_names(self) -> List[str]:
        return list(self._reps)

    def _routable(self) -> List[ReplicaHandle]:
        return [r for r in self._reps.values() if r.routable()]

    def _roles(self) -> Dict[str, str]:
        return {name: rep.role for name, rep in self._reps.items()}

    def _disaggregated(self) -> bool:
        """Pools are ACTIVE: at least one live prefill replica and at
        least one live replica that can decode (decode or mixed).  An
        all-mixed fleet — every pre-roles caller — never splits."""
        has_prefill = has_decode = False
        for rep in self._reps.values():
            if rep.dead:
                continue
            if rep.role == "prefill":
                has_prefill = True
            else:
                has_decode = True
        return has_prefill and has_decode

    def pool_members(self, pool: str) -> List[ReplicaHandle]:
        """Live replicas serving ``pool`` — dedicated-role replicas
        when the pool has any, else the mixed replicas standing in
        for it (an all-mixed fleet IS both pools)."""
        live = [r for r in self._reps.values() if not r.dead]
        exact = [r for r in live if r.role == pool]
        if exact:
            return exact
        return [r for r in live if r.role == "mixed"]

    def _arrival_pool(self, slo_class: Optional[str]) -> Optional[str]:
        """Which pool a NEW arrival targets.  None (no split) while
        the fleet isn't disaggregated.  Batch-class streams place
        straight onto the decode pool — their TTFT is not the SLO, and
        keeping them off the prefill replicas keeps prefill-pool depth
        (= interactive TTFT) low; everything else ingests chunk-free
        on the prefill pool and hands off after first token."""
        if not self._disaggregated():
            return None
        return "decode" if slo_class == "batch" else "prefill"

    def enable_telemetry(self) -> None:
        """Flip the ``telemetry="auto"`` fleet observability plane ON
        — the autoscaler's attach path: the actuator is the signal
        consumer "auto" was waiting for, exactly like the engines'
        anomaly/device_telemetry gates.  Idempotent; a hard "off" is
        respected (the operator said no)."""
        if self._ftel is None and self.cfg.telemetry != "off":
            self._ftel = FleetTelemetry(self.cfg.telemetry_cfg,
                                        self.metrics)

    def _score_candidates(self, tokens, cands) -> Dict[str, int]:
        """Leading-run affinity scores for one prompt against every
        candidate's LIVE index dict, from one shared LAZY digest
        stream: hashing stops at the block where every candidate's run
        has missed (a fleet-wide cache-miss prompt hashes ONE block —
        the same discipline as ``match_prefix``) and is capped at the
        fleet's max blocks/seq (blocks past it can never be cached)."""
        scores = {name: 0 for name, _, _ in cands}
        alive = {name: idx for name, idx, _ in cands}
        if alive:
            for h in iter_prefix_chain_digests(
                    tokens, self._block_size, self._max_blocks):
                for name in list(alive):
                    if h in alive[name]:
                        scores[name] += 1
                    else:
                        del alive[name]
                if not alive:
                    break
        return scores

    def _rank(self, tokens,
              pool: Optional[str] = None
              ) -> Tuple[List[str], Dict[str, int]]:
        """Rank routable replicas for one placement.  Half-open
        (probing) replicas rank strictly AFTER every closed one
        whatever their affinity — quarantine means minimal traffic, so
        they only receive work when no closed replica can take it (and
        that one placement is the probe).  ``pool`` stable-partitions
        each group so pool-serving replicas keep their rank ahead of
        the rest (a ranked fallback, never a hard filter — a full pool
        degrades to colocated placement, not a lost request)."""
        closed = [(rep.name, rep.digest_index(), rep.load())
                  for rep in self._routable()
                  if rep.breaker.state == "closed"]
        probing = [(rep.name, rep.digest_index(), rep.load())
                   for rep in self._routable()
                   if rep.breaker.state == "half_open"]
        scores = self._score_candidates(tokens, closed + probing)
        roles = self._roles() if pool is not None else {}
        order, _ = rank_replicas(self.cfg.placement, (), closed,
                                 rr_offset=self._rr, scores=scores)
        order = split_by_pool(order, roles, pool)
        if probing:
            p_order, _ = rank_replicas(
                self.cfg.placement, (), probing,
                rr_offset=self._rr, scores=scores)
            order = order + split_by_pool(p_order, roles, pool)
        return order, scores

    def _tier_fetch(self, uid: int, name: str, tokens) -> None:  # tpulint: serving-loop
        """Cross-replica KV tier fetch (docs/KV_TIERING.md "The tier as
        a fleet asset").  After placing ``uid`` on replica ``name``,
        find the prompt-chain CONTINUATION the chosen replica cannot
        serve locally (neither resident nor tiered) but some peer still
        holds in ITS tier, and move that leading run over the
        snapshot-v2 record path — ``export_tier_chain`` (checksum-
        verified on the way out) into ``load_snapshot(merge=True)``
        (digest+checksum re-verified on arrival; a rejected payload
        leaves the destination untouched and the stream simply
        re-prefills).  Host-side bytes only — no device work; the
        destination engine restages the blocks through its own
        dispatch-overlapped revive path."""
        dst = self._reps[name].engine
        if getattr(dst.state, "tier", None) is None or len(self._reps) < 2:
            return
        peers = [(p, tier)
                 for p, rep in self._reps.items()
                 if p != name and not rep.dead
                 and (tier := getattr(rep.engine.state, "tier",
                                      None)) is not None
                 and len(tier)]
        if not peers:
            return
        local = self._reps[name].digest_index()
        digests = list(iter_prefix_chain_digests(
            tokens, self._block_size, self._max_blocks))
        n = 0
        for h in digests:
            if h not in local:
                break              # match_prefix stops here too
            n += 1
        rest = digests[n:]
        if not rest:
            return
        best, best_len = None, 0
        for peer, tier in peers:
            k = 0
            for h in rest:
                if h not in tier:
                    break          # only a leading run is restageable
                k += 1
            if k > best_len:
                best, best_len = peer, k
        if best is None:
            return
        payload = self._reps[best].engine.export_tier_chain(
            rest[:best_len])
        if payload is None:
            return                 # peer's copy vanished or failed export
        try:
            dst.load_snapshot(payload, merge=True)
        except ValueError as e:
            # verification rejected the payload on arrival: count it,
            # keep the placement — the request re-prefills normally
            self._c_tier_fetch_rejects.inc()
            self.flight.note("tier_fetch_reject", uid=int(uid),
                             src=best, dst=name)
            logger.warning("fleet: tier fetch %s -> %s rejected (%s)",
                           best, name, e)
            return
        nblk = len(payload["tier_blocks"])
        self._c_tier_fetches.inc()
        self._c_tier_fetch_blocks.inc(nblk)
        if self._ftel is not None:
            self._ftel.journey_event(uid, "tier_fetch", self._steps,
                                     replica=name, src=best,
                                     blocks=nblk)

    # ------------------------------------------------------------------
    # the engine-shaped request API
    # ------------------------------------------------------------------
    def put(self, uid: int, tokens: Sequence[int], priority: int = 0,
            deadline_ms: Optional[float] = None,
            slo_class: Optional[str] = None) -> AdmissionVerdict:  # tpulint: serving-loop
        """Route a request.  Continuations forward to the owning
        replica (or join the request's queued migration record — the
        fed-back token is simply the next stream token).  NEW requests
        are placed by the configured policy; a replica's shed verdict
        sends the request to the NEXT candidate, and only when every
        routable replica sheds does the fleet shed (``replica=None`` on
        the verdict — the 429-equivalent).  ``slo_class`` (the
        gateway's resolved ``x-slo-class``) steers the placement's pool
        on a disaggregated fleet; it never changes admission."""
        owner = self._owner.get(uid)
        if owner is not None:
            v = self._reps[owner].engine.put(uid, tokens,
                                             priority=priority,
                                             deadline_ms=deadline_ms,
                                             slo_class=slo_class)
            return v._replace(replica=owner)
        for m in self._migrations:
            if m.rec["uid"] == uid:
                m.rec["tokens"].extend(int(t) for t in tokens)
                return AdmissionVerdict(True, "continued",
                                        reason="joined migration record")
        ft = self._ftel
        if ft is not None:
            # a revived uid (fleet-shed then re-admitted) gets a FRESH
            # journey — the dead life's story must not leak into it
            ft.begin_journey(uid)
        pool = self._arrival_pool(slo_class)
        with (ft.span("placement", uid=int(uid)) if ft is not None
              else NOOP_CTX):
            order, scores = self._rank(tokens, pool=pool)
            if self.cfg.placement == "round_robin" and order:
                # the rotation cursor advances per ARRIVAL, here only —
                # migration placements also rank (in _place_record) and
                # must not skew the baseline's rotation over new requests
                self._rr += 1
            for name in order:
                v = self._reps[name].engine.put(uid, tokens,
                                                priority=priority,
                                                deadline_ms=deadline_ms,
                                                slo_class=slo_class)
                for eu in v.evicted_uids:
                    # evict-lowest backpressure shed a queued request on
                    # that replica: terminal at the fleet level too
                    self._closed[eu] = "shed"
                    self._owner.pop(eu, None)
                    self._reaped.add(eu)
                    if ft is not None:
                        ft.journey_event(eu, "closed", self._steps,
                                         replica=name, status="shed",
                                         reason="evicted by backpressure")
                if v.admitted:
                    self._owner[uid] = name
                    # a terminal uid that returns lives a full new life —
                    # the engine's own reuse semantics, mirrored.  The
                    # stale reaped entry goes too: a driver draining later
                    # must not drop the now-live request as closed
                    self._closed.pop(uid, None)
                    self._reaped.discard(uid)
                    self._handed.discard(uid)
                    self._c_placements.inc(policy=self.cfg.placement)
                    if scores.get(name, 0) > 0:
                        self._c_place_hits.inc()
                    if ft is not None:
                        ft.last_placed = name
                        extra = {}
                        if pool is not None:
                            extra = {"pool": pool,
                                     "slo": slo_class or "standard"}
                        ft.journey_event(
                            uid, "placed", self._steps, replica=name,
                            via="arrival", policy=self.cfg.placement,
                            score=int(scores.get(name, 0)), **extra)
                    # the chosen replica may be missing part of the
                    # prompt's chain that a PEER spilled to its tier:
                    # fetch it now, before first admission, so the
                    # engine's match sees it and restages instead of
                    # re-prefilling (docs/KV_TIERING.md)
                    self._tier_fetch(uid, name, tokens)
                    return v._replace(replica=name)
                # this replica shed a put the fleet will retry
                # elsewhere: its engine-side terminal is a PHANTOM the
                # reconciled fleet accounting subtracts back out
                self._note_phantom(uid, name)
                if ft is not None:
                    ft.journey_event(uid, "replica_shed", self._steps,
                                     replica=name, reason=v.reason)
        self._c_shed.inc()
        self._fleet_closures["shed"] = \
            self._fleet_closures.get("shed", 0) + 1
        # a saturation shed leaves NO fleet-visible record (every
        # engine record was a phantom): the record view adds it back
        self._note_record_gap(uid, "shed")
        self._closed[uid] = "shed"
        self._reaped.add(uid)
        self.flight.note("fleet_shed", uid=int(uid),
                         routable=len(order))
        if ft is not None:
            ft.journey_event(uid, "closed", self._steps, status="shed",
                             reason="fleet saturated" if order
                             else "no routable replica")
        self._autodump("fleet_shed")
        return AdmissionVerdict(
            False, "shed",
            reason="fleet saturated: every routable replica shed the "
                   "request" if order else "no routable replica")

    def _life_has_hop(self, uid: int) -> bool:
        """Whether ``uid``'s CURRENT fleet life still ends in a hop
        record somewhere — a ``migrated`` close or a dead replica's
        open record that the merged-record view will resolve with the
        fleet status.  A fleet-level closure of a life WITH a hop is
        already visible to ``fleet_request_metrics``; one WITHOUT (all
        its engine records were phantom routing-retry sheds, or it
        never held one) must be tallied in the record-gap ledger or
        the record view undercounts.  Walks the same phantom-dropped
        record chain the merged view builds (failure-path only — never
        per step)."""
        items = []
        for name, rep in self._reps.items():
            dead = rep.dead
            for rec in rep.engine.requests.records():
                if rec.uid == uid:
                    items.append((rec.t_arrival, name, rec, dead))
        items.sort(key=lambda e: e[0])
        budget = {k: v for k, v in self._phantoms.items()
                  if k[0] == uid}
        last = None
        for t, name, rec, dead in items:
            if rec.status == "shed" and budget.get((uid, name), 0) > 0:
                budget[(uid, name)] -= 1
                continue
            last = (rec, dead)
        if last is None:
            return False
        rec, dead = last
        return rec.status in ("migrated", "handed_off") \
            or (dead and rec.status == "open")

    def _note_record_gap(self, uid: int, status: str) -> None:
        """Tally a fleet-level closure the merged-record view cannot
        see (no surviving record for the life)."""
        if not self._life_has_hop(uid):
            self._record_gaps[status] = \
                self._record_gaps.get(status, 0) + 1

    def _note_phantom(self, uid: int, name: str) -> None:
        """One engine-level shed closure that was a fleet routing
        retry, not a fleet terminal (put retried the next candidate, or
        scale-down re-placed the drain's shed set).  Counted for the
        reconciled rollups and remembered per (uid, replica) so the
        merged record view can drop exactly those records; the map is
        FIFO-bounded like the lifecycle tracker's forgotten set."""
        self._c_phantom.inc()
        key = (int(uid), name)
        self._phantoms[key] = self._phantoms.get(key, 0) + 1
        while len(self._phantoms) > 8192:
            self._phantoms.pop(next(iter(self._phantoms)))

    def step(self, rng=None,
             sampling: SamplingParams = SamplingParams()
             ) -> Dict[int, int]:  # tpulint: serving-loop
        """One fleet step: every live replica runs one engine step
        (quarantined replicas included — their open work must finish,
        and their clean steps are what the probe eventually certifies),
        breaker bookkeeping folds in each replica's outcome, a replica
        that died mid-step fails over, and the migration queue pumps.
        Returns the merged ``{uid: token}`` emissions — uids are
        disjoint across replicas because each open request is owned by
        exactly one."""
        self._steps += 1
        outs: Dict[int, int] = {}
        for name in list(self._reps):
            rep = self._reps[name]
            if rep.dead:
                continue
            rep.breaker.tick(self._steps)
            try:
                o = rep.engine.step(rng=rng, sampling=sampling)
            except EngineDeadError:
                self._failover(name)
                continue
            ev = rep.observe(self._steps)
            if ev == "opened":
                self._c_quarantines.inc()
                self.flight.note("quarantine", replica=name,
                                 failures=rep.breaker.failures,
                                 step=self._steps)
                if self._ftel is not None:
                    # every request riding the quarantined replica
                    # carries the detour in its journey
                    with self._ftel.span("quarantine", replica=name):
                        for juid, own in self._owner.items():
                            if own == name:
                                self._ftel.journey_event(
                                    juid, "quarantined", self._steps,
                                    replica=name)
                logger.warning(
                    "fleet: replica %s quarantined after %d consecutive "
                    "failing steps (probe in %d steps)", name,
                    rep.breaker.failures, self.cfg.probe_interval_steps)
                self._autodump("quarantine")
            elif ev == "readmitted":
                self._c_readmissions.inc()
                self.flight.note("readmitted", replica=name,
                                 step=self._steps)
                logger.warning(
                    "fleet: replica %s re-admitted after a clean probe",
                    name)
            for uid in rep.engine._drain_reaped():
                self._note_engine_close(rep, uid)
            outs.update(o)
        # handoffs enqueue BEFORE the migration pump so extraction and
        # re-placement land in the SAME router step: the decode
        # replica admits the record at its next schedule pass, inside
        # its depth-2 dispatch-ahead window — arrival overlaps the
        # step already in flight and TPOT never stalls on it
        self._pump_handoffs(outs)
        self._pump_migrations()
        self._refresh_gauges()
        if self._ftel is not None:
            # fleet anomaly signals ride the counters and integer
            # loads this step already produced — no added clock reads
            self._ftel.feed_step(self)
        if self._autoscaler is not None:
            # the actuator reads the gauges/anomalies this step just
            # refreshed and may add_replica/scale_down — membership
            # changes take effect at the NEXT step's replica loop
            self._autoscaler.on_router_step()
        return outs

    def _pump_handoffs(self, outs: Dict[int, int]) -> None:  # tpulint: serving-loop
        """Ship every request that finished prefill this step on a
        pure-prefill replica to the decode pool (docs/SERVING.md
        "Disaggregated pools & elasticity").  A uid emitting a token
        on a prefill replica IS the prefill-done signal — its prompt
        is fully ingested.  ``engine.handoff_out`` closes it there
        (``handed_off``) with its KV chain staged into the source
        tier; the record enqueues for decode-pool placement and the
        migration pump places it within this same step, after which
        the chain rides ``_tier_fetch`` to the destination.  The
        driver's fed-back token joins the queued record exactly like
        a migration continuation."""
        if not self._disaggregated():
            return
        by_src: Dict[str, List[int]] = {}
        for uid in outs:
            name = self._owner.get(uid)
            if name is None or uid in self._handed:
                continue
            rep = self._reps.get(name)
            if rep is None or rep.dead or rep.role != "prefill":
                continue
            by_src.setdefault(name, []).append(uid)
        for name, uids in by_src.items():
            rep = self._reps[name]
            with (self._ftel.span("handoff", replica=name,
                                  n=len(uids))
                  if self._ftel is not None else NOOP_CTX):
                part = rep.engine.handoff_out(uids)
                for rec in part["requests"]:
                    uid = int(rec["uid"])
                    self._owner.pop(uid, None)
                    self._handed.add(uid)
                    self._c_handoffs.inc()
                    if self._ftel is not None:
                        self._ftel.journey_event(
                            uid, "handed_off", self._steps,
                            replica=name, via="prefill_done")
                    self._migrations.append(_Migration(
                        rec=rec, source=name, next_step=self._steps,
                        pool="decode", via="handoff"))
            for uid in rep.engine._drain_reaped():
                self._note_engine_close(rep, uid)  # "handed_off": early out
        while len(self._handed) > 8192:
            self._handed.pop()

    def flush(self, uid: int) -> None:
        """Client-side completion — forwards to the owner and records
        the fleet-terminal status.  A uid waiting in the migration
        queue settles HERE: the client is done with it, and a record
        left in the queue would re-run on a survivor as an orphan
        nobody ever drives or flushes."""
        for i, m in enumerate(self._migrations):
            if m.rec["uid"] == uid:
                del self._migrations[i]
                self._close_queued(m, "finished")
                return
        owner = self._owner.pop(uid, None)
        if owner is None:
            return
        self._reps[owner].engine.flush(uid)
        self._closed[uid] = "finished"
        if self._ftel is not None:
            self._ftel.journey_event(uid, "closed", self._steps,
                                     replica=owner, status="finished")

    def _close_queued(self, m: _Migration, status: str) -> None:
        """A record settled while waiting in the migration queue: the
        fleet closure has no engine terminal (the source closed it
        ``migrated`` — or, for a scale-down record, a reconciled-away
        ``shed``), so both reconciliation ledgers take it here."""
        uid = int(m.rec["uid"])
        self._closed[uid] = status
        self._fleet_closures[status] = \
            self._fleet_closures.get(status, 0) + 1
        self._note_record_gap(uid, status)
        if self._ftel is not None:
            self._ftel.journey_event(uid, "closed", self._steps,
                                     status=status,
                                     reason="settled in migration queue")

    def cancel(self, uid: int) -> None:
        """Client abort, wherever the request is: owned by a replica,
        waiting in the migration queue, or already gone (no-op)."""
        for i, m in enumerate(self._migrations):
            if m.rec["uid"] == uid:
                del self._migrations[i]
                self._close_queued(m, "cancelled")
                self._reaped.add(uid)
                return
        owner = self._owner.pop(uid, None)
        if owner is None:
            return
        rep = self._reps[owner]
        rep.engine.cancel(uid)
        for ru in rep.engine._drain_reaped():
            if ru != uid:          # other staged closures still surface
                self._note_engine_close(rep, ru)
        self._closed[uid] = "cancelled"
        self._reaped.add(uid)
        if self._ftel is not None:
            self._ftel.journey_event(uid, "closed", self._steps,
                                     replica=owner, status="cancelled")

    def query(self, uid: int) -> Dict:
        """Fleet-level request status: the owning replica's ``query()``
        plus ``replica``; ``migrating`` while a record waits for
        re-placement; the fleet-terminal status after closure.  With
        the telemetry plane on, the request's JOURNEY (its placed /
        quarantined / migrated / failed-over hops) rides along under
        ``"journey"``."""
        d = self._query_status(uid)
        if self._ftel is not None:
            j = self._ftel.journey(uid)
            if j is not None:
                d["journey"] = j
        return d

    def _query_status(self, uid: int) -> Dict:
        if uid in self._closed:
            return {"status": self._closed[uid], "replica": None}
        for m in self._migrations:
            if m.rec["uid"] == uid:
                return {"status": "migrating", "replica": None,
                        "generated": list(m.rec.get("generated", []))}
        owner = self._owner.get(uid)
        if owner is not None:
            d = self._reps[owner].engine.query(uid)
            d["replica"] = owner
            return d
        return {"status": "unknown", "replica": None}

    def request_journey(self, uid: int) -> Optional[List[Dict]]:
        """The request's fleet journey — placed → (quarantined |
        migrated | failed-over)* → terminal, step-counter timestamps
        and reasons (docs/OBSERVABILITY.md "Fleet observability").
        None when the telemetry plane is off or the uid is unknown."""
        if self._ftel is None:
            return None
        return self._ftel.journey(uid)

    def _fleet_status_of(self, uid: int) -> str:
        """Where a record with no live engine tail ended up, fleet-
        side: queued for re-placement, fleet-closed, or (conservative
        fallback) still open — the merged-record view's trailing-hop
        resolver."""
        for m in self._migrations:
            if m.rec["uid"] == uid:
                return "migrating"
        return self._closed.get(uid, "open")

    def drain_reaped(self) -> set:
        """Uids the FLEET terminally closed since the last call
        (replica-side closures, fleet sheds, failed migrations) — the
        driver drops them from its active set, exactly like
        ``engine._drain_reaped``."""
        out = self._reaped
        self._reaped = set()
        return out

    def _note_engine_close(self, rep: ReplicaHandle, uid: int) -> None:
        """An engine-side terminal closure surfaced through that
        replica's reaped set.  ``migrated`` / ``handed_off`` are NOT
        fleet closures — the record is in flight to another replica.
        A STALE report is ignored: a uid shed on this replica and then
        re-admitted on another before the reaped set drained is live
        THERE — closing it here would orphan the revived request."""
        own = self._owner.get(uid)
        if own is not None and own != rep.name:
            return
        s = rep.engine.query(uid)["status"]
        if s in ("migrated", "handed_off"):
            return
        if s in ("queued", "running"):
            # the engine reaps only at terminal close, so a LIVE status
            # means the uid was re-admitted on this replica after the
            # reap was staged (same revival race, same-replica form)
            return
        if s in ("unknown", "forgotten"):
            s = "released"
        self._closed[uid] = s
        self._owner.pop(uid, None)
        self._reaped.add(uid)
        if self._ftel is not None:
            self._ftel.journey_event(uid, "closed", self._steps,
                                     replica=rep.name, status=s)

    # ------------------------------------------------------------------
    # failover, migration, scale-down
    # ------------------------------------------------------------------
    def _failover(self, name: str) -> None:  # tpulint: serving-loop
        """A replica died mid-step.  Zero lost requests: its
        ``snapshot()`` (host truth — valid on the dead backend) yields
        per-request records that enter the migration queue; inexact
        records (device-side tokens died with the replica) close
        ``failed`` honestly."""
        rep = self._reps[name]
        rep.breaker.kill()
        self._c_failovers.inc()
        self.flight.note("failover", replica=name, step=self._steps)
        # closures the engine staged in its dying step (deadline
        # reaps, sheds) must still surface as fleet closures — the
        # step that would have delivered them raised instead
        for uid in rep.engine._drain_reaped():
            self._note_engine_close(rep, uid)
        snap = rep.engine.snapshot()
        n = 0
        with (self._ftel.span("failover", replica=name)
              if self._ftel is not None else NOOP_CTX):
            for rec in snap["requests"]:
                uid = int(rec["uid"])
                self._owner.pop(uid, None)
                if self._ftel is not None:
                    self._ftel.journey_event(uid, "failed_over",
                                             self._steps, replica=name)
                n += self._enqueue_migration(rec, source=name)
        self.flight.note("failover_migrations", replica=name,
                         queued=n, failed=len(snap["requests"]) - n)
        logger.warning(
            "fleet: replica %s died; %d open request(s) queued for "
            "migration, %d inexact record(s) closed failed", name, n,
            len(snap["requests"]) - n)
        self._autodump("failover")

    def _enqueue_migration(self, rec: Dict, source: str) -> int:
        uid = int(rec["uid"])
        if not rec.get("exact", True) or not rec.get("tokens"):
            self._closed[uid] = "failed"
            self._reaped.add(uid)
            self._c_failed.inc()
            self._fleet_closures["failed"] = \
                self._fleet_closures.get("failed", 0) + 1
            self._note_record_gap(uid, "failed")
            if self._ftel is not None:
                self._ftel.journey_event(
                    uid, "closed", self._steps, status="failed",
                    reason="record not replayable (device-side tokens "
                           "lost)")
            return 0
        self._migrations.append(
            _Migration(rec=rec, source=source, next_step=self._steps))
        return 1

    def _pump_migrations(self) -> None:  # tpulint: serving-loop
        """Place queued migration records on surviving replicas.  A
        record that cannot place (no routable replica right now)
        retries with step-counted exponential backoff, bounded by
        ``max_migration_retries`` — exhausted retries shed at the
        fleet level instead of parking forever."""
        if not self._migrations:
            return
        still: List[_Migration] = []
        for m in self._migrations:
            uid = int(m.rec["uid"])
            if m.next_step > self._steps:
                still.append(m)
                continue
            name = self._place_record(m.rec, exclude=m.source,
                                      pool=m.pool)
            if name is not None:
                self._owner[uid] = name
                self._c_migrations.inc()
                if self._ftel is not None:
                    self._ftel.last_migration_dest = name
                    self._ftel.journey_event(uid, "placed", self._steps,
                                             replica=name,
                                             via=m.via)
                if m.pool is not None:
                    # handoff arrival: pull the chain the source just
                    # staged into its tier (plus anything other peers
                    # hold) so the destination restages the prefilled
                    # KV instead of re-prefilling the prompt
                    self._tier_fetch(uid, name,
                                     m.rec.get("tokens") or ())
                continue
            m.attempts += 1
            self._c_migration_retries.inc()
            if self._ftel is not None:
                self._ftel.journey_event(uid, "migration_retry",
                                         self._steps,
                                         attempts=m.attempts)
            if m.attempts > self.cfg.max_migration_retries:
                # last resort before destroying the work: going HOME
                # beats shedding — the source may be alive again (a
                # quarantined-then-readmitted replica); only a record
                # with nowhere at all left sheds
                name = self._place_record(m.rec)
                if name is not None:
                    self._owner[uid] = name
                    self._c_migrations.inc()
                    if self._ftel is not None:
                        self._ftel.last_migration_dest = name
                        self._ftel.journey_event(uid, "placed",
                                                 self._steps,
                                                 replica=name,
                                                 via="home")
                    continue
                self._closed[uid] = "shed"
                self._reaped.add(uid)
                self._c_shed.inc()
                self._fleet_closures["shed"] = \
                    self._fleet_closures.get("shed", 0) + 1
                self._note_record_gap(uid, "shed")
                self.flight.note("migration_exhausted", uid=uid,
                                 attempts=m.attempts - 1)
                if self._ftel is not None:
                    self._ftel.journey_event(
                        uid, "closed", self._steps, status="shed",
                        reason=f"migration exhausted after "
                               f"{m.attempts - 1} retries")
                logger.warning(
                    "fleet: migration of uid %d exhausted %d retries "
                    "with no routable replica — shed",
                    uid, m.attempts - 1)
                self._autodump("fleet_shed")
                continue
            m.next_step = self._steps + self.cfg.migration_backoff_steps \
                * (1 << min(m.attempts - 1, 6))
            still.append(m)
        self._migrations = still

    def _place_record(self, rec: Dict,
                      exclude: Optional[str] = None,
                      pool: Optional[str] = None) -> Optional[str]:
        """Place one migration record by the same affinity ranking new
        requests get (its stream's cached chain may still be resident
        somewhere).  The SOURCE replica is excluded — its cached-free
        chain makes it the top affinity score for its own evictee, and
        a migration that lands back home moved nothing.  ``pool``
        ranks that pool's replicas first (handoffs target decode).
        ``load_snapshot(merge=True)`` bypasses admission bounds — the
        request was admitted by the fleet once; shedding it again
        would double-charge the client."""
        order, _ = self._rank(rec.get("tokens") or (), pool=pool)
        for name in order:
            if name == exclude:
                continue
            rep = self._reps[name]
            try:
                rep.engine.load_snapshot(
                    {"version": InferenceEngine.SNAPSHOT_VERSION,
                     "partial": True, "requests": [rec]}, merge=True)
            except ValueError:
                continue          # uid collision: try the next replica
            # no placement-hit bump here: migrations are not counted
            # in the placements denominator, and the MEASURED hit rate
            # (engine cached/prompt counters) covers them anyway
            return name
        return None

    def migrate(self, uids: Sequence[int], source: str) -> int:
        """Live request migration: extract the given OPEN requests from
        ``source`` (``engine.migrate_out`` — closes them ``migrated``
        there, releasing their KV) and re-place them by affinity on the
        rest of the fleet.  Returns the number of records that entered
        the migration queue.  With no routable destination besides the
        source, nothing is extracted (0) — a migration that could only
        end in retry-exhaustion must not destroy requests the source
        is serving fine."""
        if not any(rep.routable() for rep in self._reps.values()
                   if rep.name != source):
            return 0
        rep = self._reps[source]
        with (self._ftel.span("migrate", replica=source)
              if self._ftel is not None else NOOP_CTX):
            part = rep.engine.migrate_out(uids)
            n = 0
            for rec in part["requests"]:
                uid = int(rec["uid"])
                self._owner.pop(uid, None)
                if self._ftel is not None:
                    self._ftel.journey_event(uid, "migrated",
                                             self._steps,
                                             replica=source,
                                             via="migrate")
                n += self._enqueue_migration(rec, source=source)
        for uid in rep.engine._drain_reaped():
            self._note_engine_close(rep, uid)  # "migrated" returns early
        self._pump_migrations()
        return n

    def scale_down(self, name: str,
                   deadline_ms: Optional[float] = None,
                   sampling: SamplingParams = SamplingParams(),
                   rng=None) -> Dict:
        """Drain-to-scale-down: ``engine.drain()`` the replica, then
        re-place exactly its ``shed_uids`` records (the drain's
        completed set stays settled — re-placing it would double-run).
        The replica leaves the routable set permanently; returns the
        drain's snapshot."""
        rep = self._reps[name]
        snap = rep.engine.drain(deadline_ms=deadline_ms,
                                sampling=sampling, rng=rng)
        rep.breaker.kill()
        recs = {int(r["uid"]): r for r in snap["requests"]}
        shed = set(snap["shed_uids"])
        for uid in snap["shed_uids"]:
            if uid in recs:
                self._owner.pop(uid, None)
                # the drain closed this request "shed" on the replica,
                # but the fleet is RE-PLACING it: that engine terminal
                # is a phantom the reconciled accounting subtracts out
                self._note_phantom(uid, name)
                if self._ftel is not None:
                    self._ftel.journey_event(uid, "migrated",
                                             self._steps, replica=name,
                                             via="scale_down")
                self._enqueue_migration(recs[uid], source=name)
        for uid in rep.engine._drain_reaped():
            if uid in shed:
                continue          # re-placing, not closing
            self._note_engine_close(rep, uid)
        self._pump_migrations()
        return snap

    def drain(self, deadline_ms: Optional[float] = None,
              sampling: SamplingParams = SamplingParams(),
              rng=None) -> Dict:
        """Fleet-wide graceful drain — the seam verb the single engine
        already speaks, so a front-end (the gateway's SIGTERM path)
        shuts either backend down through one code path.  Every live
        replica runs its own ``engine.drain()`` (step-bounded, splits
        any ``deadline_ms`` across replicas), records still waiting in
        the migration queue close ``shed`` fleet-side (there is no
        surviving replica to re-place onto — the whole fleet is going
        away), and the merged final :meth:`snapshot` is the warm-
        restart hand-off — every shed record rides along in
        ``requests`` tagged ``replica: None``, exactly like the
        engine-level drain keeps its shed records restorable.  Unlike
        :meth:`scale_down` nothing is re-placed: a fleet drain ends
        the fleet's serving life."""
        t0 = time.perf_counter()
        live = [rep for rep in self._reps.values() if not rep.dead]
        shed: set = set()
        completed: set = set()
        shed_records: List[Dict] = []
        for i, rep in enumerate(live):
            per_rep = None
            if deadline_ms is not None:
                left = deadline_ms - (time.perf_counter() - t0) * 1e3
                per_rep = max(0.0, left)
            try:
                part = rep.engine.drain(deadline_ms=per_rep,
                                        sampling=sampling, rng=rng)
            except EngineDeadError:
                continue
            # the engine's hand-off snapshot carries the shed records
            # (taken before the close); keep them — the merged fleet
            # snapshot below is built AFTER every breaker dies, so it
            # cannot see them on its own
            by_uid = {int(r["uid"]): r for r in part["requests"]}
            for u in part.get("shed_uids", ()):
                shed.add(int(u))
                rec = by_uid.get(int(u))
                if rec is not None:
                    rec = dict(rec)
                    rec["replica"] = None
                    shed_records.append(rec)
            completed.update(int(u)
                             for u in part.get("completed_uids", ()))
            rep.breaker.kill()
            for uid in rep.engine._drain_reaped():
                self._note_engine_close(rep, uid)
        # queued migrations have no destination anymore: fleet-shed
        while self._migrations:
            m = self._migrations.pop()
            self._close_queued(m, "shed")
            # surfaces through drain_reaped() like every other fleet
            # shed (cancel, retry exhaustion) — a driver still watching
            # its active set must see the closure
            self._reaped.add(int(m.rec["uid"]))
            shed.add(int(m.rec["uid"]))
            rec = dict(m.rec)
            rec["replica"] = None
            shed_records.append(rec)
        snap = self.snapshot()
        snap["requests"] = snap["requests"] + shed_records
        snap["shed_uids"] = sorted(shed)
        snap["completed_uids"] = sorted(
            u for u in completed if u not in shed)
        return snap

    def snapshot(self) -> Dict:
        """Fleet-merged host truth, schema-compatible with
        ``engine.snapshot()`` (seam verb): every live replica's open
        request records (tagged ``replica``), records in flight in the
        migration queue (tagged ``replica: None``), summed engine
        counters, and the union prefix-cache index.  Like the engine's,
        it is valid with dead replicas in the fleet — their open work
        is whatever failover already queued."""
        from .. import __version__
        reqs: List[Dict] = []
        counters: Dict[str, int] = {}
        prefix: set = set()
        for name, rep in self._reps.items():
            if rep.dead:
                continue
            part = rep.engine.snapshot()
            for rec in part["requests"]:
                rec = dict(rec)
                rec["replica"] = name
                reqs.append(rec)
            for k, v in part["counters"].items():
                counters[k] = counters.get(k, 0) + v
            prefix.update(part["prefix_index"])
        for m in self._migrations:
            rec = dict(m.rec)
            rec["replica"] = None
            reqs.append(rec)
        return {
            "version": InferenceEngine.SNAPSHOT_VERSION,
            "engine_version": __version__,
            "health": self.health_state(),
            "counters": counters,
            "requests": reqs,
            "prefix_index": sorted(prefix),
            # per-replica attribution (resident ∪ tiered digests) —
            # what restore_prefix_index() needs to route each prefix
            # family back to its old replica after a router restart;
            # the union above keeps its pre-roles schema
            "replica_prefix_index": {
                name: sorted(rep.prefix_digests())
                for name, rep in self._reps.items() if not rep.dead},
            "roles": {name: rep.role
                      for name, rep in self._reps.items()
                      if not rep.dead},
            "replicas": sorted(name for name, rep in self._reps.items()
                               if not rep.dead),
        }

    def restore_prefix_index(self, snap: Dict) -> int:
        """Seed placement affinity from a PRIOR router generation's
        :meth:`snapshot` (ROADMAP 1b: cache affinity survives a
        restart).  Each named replica's digests load as warm
        placement-only entries (``ReplicaHandle.warm_digests``):
        affinity scoring sees them immediately, so the restarted fleet
        routes every prefix family back to the replica that served it
        — the first visit re-prefills honestly, every later one hits
        the rebuilt cache.  Replicas the snapshot doesn't name (or
        that no longer exist) are skipped; falls back to the fleet
        union for pre-``replica_prefix_index`` snapshots.  Returns
        the number of digests seeded."""
        per = snap.get("replica_prefix_index")
        if per is None:
            union = snap.get("prefix_index") or ()
            per = {name: union for name in self._reps}
        n = 0
        for name, hexes in per.items():
            rep = self._reps.get(name)
            if rep is None or rep.dead:
                continue
            for h in hexes:
                rep.warm_digests.add(bytes.fromhex(h))
                n += 1
        return n

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def _refresh_gauges(self) -> None:
        # health_state(), not health(): the full probe is a phase
        # boundary (it polls device memory under device_telemetry) and
        # must not run per replica per router step
        self._g_replicas.set(len(self._reps))
        self._g_routable.set(len(self._routable()))
        for name, rep in self._reps.items():
            if rep.breaker.state in ("open", "half_open"):
                code = 4
            else:
                code = _HEALTH_CODE.get(rep.engine.health_state(), 3)
            self._g_rep_health.set(code, replica=name)
        for pool in ("prefill", "decode"):
            members = self.pool_members(pool)
            self._g_pool_replicas.set(len(members), pool=pool)
            self._g_pool_load.set(sum(r.load() for r in members),
                                  pool=pool)

    def health_state(self) -> str:
        """The fleet's cheap health-LADDER read, mirroring
        ``engine.health_state()`` so a front-end (the network gateway)
        probes either backend shape through one seam: ``dead`` when no
        replica is alive, ``degraded`` when nothing is routable or any
        live replica is degraded/quarantined, else ``healthy``.  No
        gauge writes, no memory polls — :meth:`health` is the
        phase-boundary probe."""
        live = [rep for rep in self._reps.values() if not rep.dead]
        if not live:
            return "dead"
        if not self._routable():
            return "degraded"
        if any(rep.engine.health_state() != "healthy"
               or rep.breaker.state != "closed" for rep in live):
            return "degraded"
        return "healthy"

    def health(self) -> Dict:
        """Fleet health summary — the gateway's ``/healthz`` payload:
        per-replica engine state + breaker state + load, and the
        fleet-level tallies."""
        self._refresh_gauges()
        reps = {}
        for name, rep in self._reps.items():
            reps[name] = {
                "state": rep.engine.health()["state"],
                "breaker": rep.breaker.state,
                "load": rep.load(),
                "quarantines": rep.breaker.quarantines,
                "readmissions": rep.breaker.readmissions,
            }
        return {
            "replicas": reps,
            "routable": len(self._routable()),
            "migrating": len(self._migrations),
            "steps": self._steps,
            "failovers": int(self._c_failovers.value()),
            "migrations": int(self._c_migrations.value()),
            "fleet_shed": int(self._c_shed.value()),
        }

    def metrics_snapshot(self) -> Dict:
        """JSON-able snapshot of the fleet gauges/counters; the whole
        fleet's series (every replica's registry under ``replica=``
        labels, plus rollups) live on ``router.fleet_registry``."""
        return self.metrics.snapshot()

    def request_metrics(self) -> Dict:
        """Fleet-level request metrics, migration-deduped (docs/
        OBSERVABILITY.md "Fleet observability"): ``{"aggregate": the
        exact fleet tally, "replicas": {name: per-replica aggregate},
        "requests": [merged records]}`` — a migrated uid yields ONE
        record attributed to its finishing replica, with token sums
        equal to the sum of the per-replica engine counters."""
        return fleet_request_metrics(self)

    def anomaly_summary(self) -> Optional[Dict]:
        """Fleet anomaly tally + anomaly-armed capture records; None
        while the telemetry plane is off."""
        if self._ftel is None:
            return None
        return self._ftel.summary()

    def slo_scorecard(self) -> Dict:
        """The FLEET SLO scorecard (telemetry/slo.py): per-replica
        engine scorecards merged by ``merge_scorecards`` — counter
        pairs sum (the fleet attainment is the quotient of the summed
        exported counters, exactly what the ``serving_fleet_slo_*``
        rollups scrape), burn rates take the worst replica.  Replicas
        with SLO tracking off merge as disabled; an all-off fleet
        reports ``{"enabled": False}``."""
        return merge_scorecards(
            {name: rep.engine.slo_scorecard()
             for name, rep in self._reps.items()})

    def arm_budgeted_capture(self, reason: str = "ops",
                             replica: Optional[str] = None
                             ) -> Optional[Dict]:
        """Arm ONE budgeted capture window through the fleet-telemetry
        capture budget (``FleetTelemetryConfig.max_captures`` — the
        same budget anomaly-armed captures draw from), on ``replica``
        or the busiest routable one.  The gateway ``POST
        /debug/capture`` seam: returns ``{"replica", "dir"}`` or None
        when telemetry is off, the budget is exhausted, no directory
        is configured, or no replica can take the window."""
        if self._ftel is None:
            return None
        return self._ftel.ops_capture(self, reason=reason,
                                      replica=replica)

    def ops_dump(self) -> Optional[str]:
        """The gateway ``POST /debug/dump`` seam: one budgeted fleet
        bundle through the ``_autodump`` path (``FleetConfig.
        max_autodumps`` per router generation, into ``flight_dir``).
        Returns the bundle directory, or None when the budget is
        exhausted or no flight_dir is configured — a wire client can
        name neither the path nor the budget."""
        return self._autodump("ops")

    def reset_metrics(self) -> None:
        """Reset the ROUTER-side telemetry: fleet counters/gauges, the
        reconciliation ledgers that ride them, the flight-event ring,
        journeys, detector baselines, and the capture budget.  The
        replicas' own registries are theirs to reset — reconciled
        views are only exact when both sides reset together (the bench
        legs reset engines before building the router)."""
        self.metrics.reset()
        self._phantoms.clear()
        self._fleet_closures.clear()
        self._record_gaps.clear()
        self.flight.clear()
        if self._ftel is not None:
            self._ftel.reset()

    def request_journeys(self) -> Dict[int, List[Dict]]:
        """Every live journey (uid -> event list); empty when the
        telemetry plane is off."""
        if self._ftel is None:
            return {}
        return {uid: list(j)
                for uid, j in self._ftel._journeys.items()}

    def capture(self, steps: Optional[int] = None, replicas=None,
                out_dir: Optional[str] = None,
                reason: str = "manual") -> Dict[str, Optional[str]]:
        """Arm a deep-capture window on the given replicas (default:
        every live one) through the engines' existing ProfilerCapture
        seam; windows begin at each engine's next step boundary and the
        artifacts land under ``<dir>/captures/<replica>/``.  Returns
        {replica: capture dir or None (refused)}.  Raises without a
        resolvable directory — an explicit capture with nowhere to
        write is a caller error (the ANOMALY path degrades instead)."""
        tcfg = self._ftel.cfg if self._ftel is not None \
            else FleetTelemetryConfig()
        d = out_dir or tcfg.capture_dir or self.cfg.flight_dir
        if not d:
            raise ValueError(
                "no capture directory: pass out_dir=, or set "
                "FleetTelemetryConfig.capture_dir / "
                "FleetConfig.flight_dir")
        names = list(replicas) if replicas is not None else \
            [n for n, r in self._reps.items() if not r.dead]
        out = {}
        for n in names:
            out[n] = self._reps[n].engine.capture(
                steps or tcfg.capture_steps, reason=f"fleet_{reason}",
                out_dir=os.path.join(d, "captures", n))
        return out

    # ------------------------------------------------------------------
    # fleet post-mortems
    # ------------------------------------------------------------------
    def debug_dump(self, path: str, reason: str = "debug") -> Dict:
        """Write the fleet post-mortem BUNDLE (docs/OBSERVABILITY.md
        "Fleet observability") into directory ``path``::

            path/
                fleet.json            router events + journeys + fleet
                                      metrics/rollups + deduped request
                                      metrics (validate_fleet_dump)
                router_trace.json     router span ring (telemetry on)
                replicas/<name>/flight.json   each replica's own
                                      debug_dump (valid on a DEAD one)

        Returns the fleet dump dict.  ``tools/tracemerge.py --fleet``
        merges the bundle (router trace + each replica's capture
        artifacts) onto one Perfetto timeline."""
        try:
            os.makedirs(path, exist_ok=True)
        except OSError as e:
            logger.warning("fleet dump dir %r unusable (%s)", path, e)
        replicas: Dict[str, Dict] = {}
        for name, rep in self._reps.items():
            rdir = os.path.join(path, "replicas", name)
            try:
                os.makedirs(rdir, exist_ok=True)
            except OSError as e:
                logger.warning("fleet dump: replica dir %r unusable "
                               "(%s)", rdir, e)
            rep.engine.debug_dump(os.path.join(rdir, "flight.json"),
                                  reason=f"fleet_{reason}")
            replicas[name] = {
                "flight": os.path.join("replicas", name, "flight.json"),
                "captures": list(rep.engine.capture_dirs),
                "breaker": rep.breaker.state,
                "dead": rep.dead,
            }
        router_trace = None
        if self._ftel is not None and len(self._ftel.tracer):
            router_trace = "router_trace.json"
            try:
                self._ftel.tracer.export_chrome_trace(
                    os.path.join(path, router_trace),
                    process_name="fleet_router")
            except OSError as e:
                logger.warning("fleet dump: cannot write router trace "
                               "(%s)", e)
                router_trace = None
        dump = {
            "version": FLEET_DUMP_VERSION,
            "reason": reason,
            "time": time.time(),
            "fingerprint": config_fingerprint(),
            "steps": self._steps,
            "health": self.health(),
            "metrics": self.metrics.snapshot(),
            "rollups": self.fleet_registry.rollup_snapshot(),
            "journeys": {str(u): j
                         for u, j in self.request_journeys().items()},
            "anomalies": self.anomaly_summary(),
            "request_metrics": self.request_metrics(),
            "events": self.flight.events(),
            "replicas": replicas,
            "router_trace": router_trace,
        }
        self.flight.dump(os.path.join(path, "fleet.json"), reason,
                         snap=dump)
        return dump

    def _autodump(self, reason: str) -> Optional[str]:
        """One budgeted fleet post-mortem bundle into ``FleetConfig.
        flight_dir`` (no-op unset): failover, quarantine, and fleet-
        shed each leave a bundle, at most ``max_autodumps`` per router
        generation, with collision-safe directory names across
        generations sharing one flight_dir (the PR-9 engine-dump
        discipline)."""
        d = self.cfg.flight_dir
        if not d or self._autodumps >= self.cfg.max_autodumps:
            return None
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            logger.warning("fleet flight_dir %r unusable (%s)", d, e)
            return None
        n = self._autodumps
        while True:
            path = os.path.join(d, f"fleet_{reason}_s{self._steps}_{n}")
            if not os.path.exists(path):
                break
            n += 1
        self._autodumps += 1
        self.flight.note("dump", reason=reason, path=path)
        self.debug_dump(path, reason=reason)
        return path
