"""Fleet observability plane (docs/OBSERVABILITY.md "Fleet
observability").

PR 13 turned one hardened engine into a fleet, but every observability
surface PRs 5/9/10 built — span rings, metrics registries, flight
recorders, anomaly detectors — was strictly per-engine: a request
placed on replica A, surviving A's quarantine, migrating to B and
finishing there left its story scattered across N uncorrelated rings.
This module is the fleet half, built on the SAME contracts rather than
new ones:

* **Request journeys** — :class:`FleetTelemetry` keeps a per-uid
  journey log (placed → (quarantined | migrated | failed-over)* →
  terminal, step-counter timestamps and reasons) plus a router-owned
  :class:`~deepspeed_tpu.telemetry.SpanTracer` whose placement /
  migrate / failover spans carry ``uid`` + ``replica`` args, so the
  merged fleet timeline can flow-connect one request's hops.
* **Fleet metrics aggregation** — :class:`FleetRegistry` scrapes each
  live replica's registry at EXPORT time (pull-gauges stay pull, never
  cached stale) and re-exports every ``serving_*`` series with a
  ``replica=`` label, plus ``serving_fleet_*`` rollups (sum, max for
  peaks/states) and the *reconciled* terminal-status rollup that
  dedups migration/routing double counting.  Dead/quarantined replicas
  export their last scrape with a ``serving_fleet_replica_stale``
  marker instead of silently vanishing.
* **Fleet anomaly catalog** — :func:`default_fleet_detectors` watches
  placement imbalance (load-share skew), affinity hit-rate collapse,
  failover/migration storms, and cross-replica TTFT p95 divergence;
  fires bump ``serving_fleet_anomalies_total{signal=}``, breadcrumb
  the router's flight recorder, and arm a budgeted deep-capture window
  *on the implicated replica* through the engines' existing
  :class:`~deepspeed_tpu.telemetry.ProfilerCapture` seam.
* **Fleet request dedup** — :func:`fleet_request_metrics` merges
  per-replica :class:`~deepspeed_tpu.telemetry.RequestTracker` records
  migration-aware: a migrated uid yields ONE record attributed to its
  finishing replica, with token sums that still equal the sum of the
  per-replica engine counters (the fuzz's reconciliation bar).

Zero-cost-off (the PR-10 bar, counted by test): fleet telemetry off
constructs no monitor, no tracer ring, no journey table, and adds zero
``perf_counter`` reads per router step — the router's only clock stays
its step counter.  Everything here is host-side dict/float work; no
JAX imports (the telemetry/ contract).
"""

from __future__ import annotations

import dataclasses
import os
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..telemetry import (AnomalyConfig, AnomalyMonitor, MetricsRegistry,
                         SpanTracer)
from ..telemetry.anomaly import (EwmaMadDetector,
                                 RollingPercentileDetector,
                                 ThresholdDetector)
from ..telemetry.slo import (BurnRateDetector, SloObjective, SloTracker,
                             default_slo_objectives)
from ..telemetry.metrics import Histogram, _fmt, _prom_label_str, _prom_name
from ..utils.logging import logger

# fleet post-mortem bundle schema (router.debug_dump writes it,
# validate_fleet_dump checks it, the fleet chaos smoke asserts it on
# every auto-dump)
FLEET_DUMP_VERSION = 1
FLEET_DUMP_REQUIRED_KEYS = ("version", "reason", "time", "fingerprint",
                            "steps", "health", "metrics", "rollups",
                            "journeys", "request_metrics", "events",
                            "replicas")

# journey events that end a uid's fleet life — a later placement of the
# same uid starts a FRESH journey (the engine's uid-reuse semantics,
# mirrored; the revived-uid races PR 13 hardened are the reason this is
# explicit)
JOURNEY_TERMINAL = "closed"


class _NoopCtx:
    """Shared do-nothing context manager (telemetry-off placement
    spans): no clock reads, no allocs."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_CTX = _NoopCtx()


@dataclasses.dataclass
class FleetTelemetryConfig:
    """Knobs for the fleet observability plane (constructed only when
    ``FleetConfig.telemetry`` resolves on)."""
    # detector shape knobs shared with the engine catalog; None takes
    # AnomalyConfig defaults
    anomaly: Optional[AnomalyConfig] = None
    # router span-ring capacity (placement/migrate/failover spans +
    # journey instants)
    trace_capacity: int = 1 << 14
    # journey table bound: beyond it the oldest journey is evicted
    # (closed or not — bounded beats complete on a long-lived router)
    max_journeys: int = 4096
    # failover/migration storm: fires when more than ``storm_limit``
    # failover+migration+retry events land within ``storm_window``
    # router steps (a single clean failover is an incident, not a
    # storm)
    storm_window: int = 32
    storm_limit: float = 3.0
    # cross-replica TTFT divergence: fires when the max/min p95 ratio
    # across routable replicas (each with >= ttft_min_samples observed
    # TTFTs) exceeds the ratio
    ttft_divergence_ratio: float = 4.0
    ttft_min_samples: int = 4
    # anomaly-armed deep captures: window length (engine steps) and the
    # fleet-level budget (reset_metrics on the router rearms it)
    capture_steps: int = 4
    max_captures: int = 2
    # where anomaly-armed replica captures land; None falls back to
    # FleetConfig.flight_dir (the post-mortem dir is a sensible home)
    capture_dir: Optional[str] = None
    # fleet-level SLO burn detectors (telemetry/slo.py): the class ->
    # SloObjective map the per-class ``slo_burn_rate_<class>`` signals
    # normalise against.  None takes default_slo_objectives(); a fleet
    # whose replicas run custom objectives should mirror them here.
    # The signals only move when replicas export the serving_slo_*
    # composite counters (InferenceConfig.slo on) — an all-off fleet
    # feeds nothing.
    slo_objectives: Optional[Dict[str, SloObjective]] = None


def default_fleet_detectors(cfg: FleetTelemetryConfig) -> Dict[str, object]:
    """The fleet signal catalog (docs/OBSERVABILITY.md "Fleet anomaly
    catalog").  Every signal is fed from counters and integer loads the
    router already holds — feeding adds no clock reads."""
    a = cfg.anomaly or AnomalyConfig()
    return {
        # busiest replica's share of the fleet's live work — affinity
        # placement trades some imbalance for cache hits, so the
        # detector learns the workload's normal skew and fires on a
        # shift (one replica eating the fleet).  The 0.05 scale floor
        # is 5 share points: share jitter below that is routing noise
        "placement_imbalance": EwmaMadDetector(
            warmup=a.warmup, alpha=a.ewma_alpha, window=a.window,
            z_threshold=a.z_threshold,
            min_scale_frac=a.min_scale_frac, min_scale=0.05,
            direction="high"),
        # per-step affinity hit rate (hit placements / placements)
        # leaving the rolling band low-side: the cache-affinity signal
        # collapsed (an eviction storm somewhere, a workload shift)
        "affinity_hit_rate": RollingPercentileDetector(
            warmup=a.warmup, window=a.window, q=0.95, ratio=2.0,
            direction="low"),
        # failover+migration+retry events within the rolling window —
        # any count above storm_limit is a storm, sustained by
        # construction (the window IS the sustain)
        "failover_migration_storm": ThresholdDetector(
            limit=cfg.storm_limit, warmup=0),
        # max/min cross-replica TTFT p95 ratio: one replica serving
        # visibly worse than its peers (thermal, a poisoned cache, a
        # sick host) while the fleet average still looks fine
        "ttft_divergence": ThresholdDetector(
            limit=cfg.ttft_divergence_ratio, warmup=0),
    }


class FleetTelemetry:
    """The router's observability plane: journey log + span tracer +
    anomaly monitor + capture budget.  Constructed ONLY when
    ``FleetConfig.telemetry`` resolves on — its absence is the
    zero-cost-off guarantee."""

    def __init__(self, cfg: Optional[FleetTelemetryConfig],
                 registry: MetricsRegistry):
        self.cfg = cfg or FleetTelemetryConfig()
        self.tracer = SpanTracer(capacity=self.cfg.trace_capacity,
                                 enabled=True)
        self.monitor = AnomalyMonitor(self.cfg.anomaly, registry,
                                      prefix="serving_fleet")
        self.monitor.watch_all(default_fleet_detectors(self.cfg))
        self._journeys: Dict[int, List[Dict[str, Any]]] = {}
        self._prev: Dict[str, float] = {}     # detector feed scratch
        self._storm: Deque[Tuple[int, int]] = deque()
        # SLO burn-rate scratch: per-(replica, class) last-seen
        # (good, evaluated) composite-counter readings, per-class
        # per-replica bad tallies since the last fire (implication),
        # and the set of lazily-watched burn signals
        self._slo_prev: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._slo_bad: Dict[str, Dict[str, int]] = {}
        self._slo_signals: set = set()
        self._captures_used = 0
        # completed/armed anomaly captures: {signal, replica, dir, step}
        self.captures: List[Dict[str, Any]] = []
        self.last_placed: Optional[str] = None
        self.last_migration_dest: Optional[str] = None
        self._warned_no_capture_dir = False

    # ------------------------------------------------------------------
    # journeys
    # ------------------------------------------------------------------
    def begin_journey(self, uid: int) -> None:
        """Start a fresh journey for a NEW fleet life of ``uid`` (a
        revived uid — fleet-shed then re-admitted — must not inherit
        its dead life's story)."""
        j = self._journeys.get(uid)
        if j is None or (j and j[-1]["event"] == JOURNEY_TERMINAL):
            self._journeys[uid] = []
            while len(self._journeys) > self.cfg.max_journeys:
                self._journeys.pop(next(iter(self._journeys)))

    def journey_event(self, uid: int, event: str, step: int,
                      replica: Optional[str] = None, **extra) -> None:
        """Append one journey event (step-counter timestamp — the
        router's only clock) and mirror it onto the tracer's journey
        track so the merged fleet timeline can flow-connect hops by
        shared ``uid`` args."""
        j = self._journeys.get(uid)
        if j is None:
            j = self._journeys[uid] = []
            while len(self._journeys) > self.cfg.max_journeys:
                self._journeys.pop(next(iter(self._journeys)))
        ev: Dict[str, Any] = {"event": event, "step": int(step)}
        if replica is not None:
            ev["replica"] = replica
        ev.update(extra)
        j.append(ev)
        self.tracer.instant(event, track="journey", uid=int(uid),
                            replica=replica, **extra)

    def journey(self, uid: int) -> Optional[List[Dict[str, Any]]]:
        j = self._journeys.get(uid)
        return None if j is None else list(j)

    # ------------------------------------------------------------------
    # anomaly feeding (called once per router step; ints/floats only —
    # no clock reads, the counted zero-cost bar)
    # ------------------------------------------------------------------
    def span(self, name: str, **args):
        return self.tracer.span(name, track="router", **args)

    def feed_step(self, router) -> None:
        mon, prev, step = self.monitor, self._prev, router._steps
        fired: List[Tuple[object, Optional[str]]] = []
        # placement imbalance: busiest live replica's work share
        loads = [(rep.load(), name)
                 for name, rep in router._reps.items() if not rep.dead]
        total = sum(v for v, _ in loads)
        if len(loads) > 1 and total:
            mx, mx_name = max(loads)
            ev = mon.observe("placement_imbalance", mx / total, step)
            if ev is not None:
                fired.append((ev, mx_name))
        # per-class SLO error-budget burn (replica composite counters)
        self._feed_slo_burn(router, step, fired)
        # affinity hit rate over this step's placements (labeled
        # counter: series_sum folds every policy= series)
        placements = router.metrics.series_sum(
            "serving_fleet_placements_total")
        hits = router._c_place_hits.value()
        dp = placements - prev.get("placements", 0)
        dh = hits - prev.get("hits", 0)
        prev["placements"], prev["hits"] = placements, hits
        if dp > 0:
            ev = mon.observe("affinity_hit_rate", dh / dp, step)
            if ev is not None:
                fired.append((ev, self.last_placed))
        # failover/migration storm: rolling-window event count
        events = int(router._c_failovers.value()) \
            + int(router._c_migrations.value()) \
            + int(router._c_migration_retries.value())
        de = events - int(prev.get("events", 0))
        prev["events"] = events
        if de > 0:
            self._storm.append((step, de))
        while self._storm and step - self._storm[0][0] \
                > self.cfg.storm_window:
            self._storm.popleft()
        ev = mon.observe("failover_migration_storm",
                         float(sum(n for _, n in self._storm)), step)
        if ev is not None:
            fired.append((ev, self.last_migration_dest))
        # cross-replica TTFT p95 divergence
        p95s = []
        for name, rep in router._reps.items():
            if rep.dead:
                continue
            h = rep.engine.metrics.get("serving_ttft_ms")
            if h is not None and h.count() >= self.cfg.ttft_min_samples:
                p95s.append((h.percentile(0.95), name))
        if len(p95s) >= 2:
            hi, hi_name = max(p95s)
            lo, _ = min(p95s)
            ev = mon.observe("ttft_divergence", hi / max(lo, 1e-9), step)
            if ev is not None:
                fired.append((ev, hi_name))
        for ev, name in fired:
            self._on_anomaly(router, ev, name)

    def _feed_slo_burn(self, router, step: int,
                       fired: List[Tuple[object, Optional[str]]]) -> None:
        """Fleet-level error-budget burn: diff each live replica's
        ``serving_slo_*_total`` composite counters (objective=requests,
        bumped by :class:`~..telemetry.slo.SloTracker` at request
        close-out — counter reads only, no clocks) and replay the
        deltas as per-request pass/fail bits through per-class
        request-counted :class:`BurnRateDetector` windows.  A fire
        implicates the replica that contributed the most bad requests
        since the last fire (tie-break by name) so the capture lands
        where the budget is burning."""
        mon, prev = self.monitor, self._slo_prev
        objs = self.cfg.slo_objectives or default_slo_objectives()
        # per-class delta aggregation across live replicas
        goods: Dict[str, int] = {}
        bads: Dict[str, int] = {}
        for name, rep in router._reps.items():
            if rep.dead:
                continue
            m_good = rep.engine.metrics.get("serving_slo_good_total")
            m_eval = rep.engine.metrics.get("serving_slo_evaluated_total")
            if m_good is None or m_eval is None:
                continue  # replica runs with SLO tracking off
            for key, ev_v in m_eval.series():
                labels = dict(key)
                if labels.get("objective") != SloTracker.COMPOSITE:
                    continue
                cls = labels.get("class")
                if cls is None:
                    continue
                good_v = m_good.value(**labels)
                pg, pe = prev.get((name, cls), (0, 0))
                dg = max(int(good_v) - pg, 0)
                de = max(int(ev_v) - pe, 0)
                prev[(name, cls)] = (int(good_v), int(ev_v))
                if de <= 0:
                    continue
                db = max(de - dg, 0)
                goods[cls] = goods.get(cls, 0) + (de - db)
                bads[cls] = bads.get(cls, 0) + db
                if db:
                    tally = self._slo_bad.setdefault(cls, {})
                    tally[name] = tally.get(name, 0) + db
        for cls in sorted(set(goods) | set(bads)):
            sig = f"slo_burn_rate_{cls}"
            if sig not in self._slo_signals:
                self._slo_signals.add(sig)
                mon.watch(sig, BurnRateDetector.for_objective(
                    objs.get(cls) or SloObjective()))
            # goods first so a mixed step's bads land on the freshest
            # window state (order within a step is not observable
            # per-request; bads-last is the deterministic choice) —
            # the detector's bit convention is 1.0 = VIOLATION
            bits = [0.0] * goods.get(cls, 0) + [1.0] * bads.get(cls, 0)
            for bit in bits:
                ev = mon.observe(sig, bit, step)
                if ev is not None:
                    tally = self._slo_bad.pop(cls, {})
                    impl = max(tally.items(),
                               key=lambda kv: (kv[1], kv[0]),
                               default=None)
                    fired.append((ev, impl[0] if impl else None))

    def ops_capture(self, router, reason: str = "ops",
                    replica: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Operator-requested budgeted capture (the gateway's
        ``POST /debug/capture``): same budget + directory rules as an
        anomaly-armed capture, aimed at ``replica`` (default: the
        busiest routable one).  Returns ``{replica, dir}`` or ``None``
        when the budget is spent / nowhere to write / no target."""
        name = replica
        if name is None or name not in router._reps \
                or router._reps[name].dead:
            live = [(rep.load(), n) for n, rep in router._reps.items()
                    if rep.routable()]
            if not live:
                return None
            name = max(live)[1]
        if self._captures_used >= self.cfg.max_captures:
            return None
        d = self.cfg.capture_dir or router.cfg.flight_dir
        if not d:
            return None
        got = router._reps[name].engine.capture(
            steps=self.cfg.capture_steps, reason=reason,
            out_dir=os.path.join(d, "captures", name))
        if got is None:
            return None
        self._captures_used += 1
        self.captures.append({"signal": reason, "replica": name,
                              "dir": got, "step": int(router._steps)})
        return {"replica": name, "dir": got}

    def _on_anomaly(self, router, ev, replica: Optional[str]) -> None:
        """One fired fleet detector: breadcrumb the router's flight
        recorder (the counter was bumped by the monitor) and arm a
        budgeted capture window on the implicated replica through the
        engine's existing ProfilerCapture seam."""
        router.flight.note("fleet_anomaly", replica=replica,
                           **ev.as_dict())
        name = replica
        if name is None or name not in router._reps \
                or router._reps[name].dead:
            # the implicated replica is gone (a storm's source is the
            # DEAD replica): capture where its load landed instead —
            # the busiest routable survivor
            live = [(rep.load(), n) for n, rep in router._reps.items()
                    if rep.routable()]
            if not live:
                return
            name = max(live)[1]
        if self._captures_used >= self.cfg.max_captures:
            return
        d = self.cfg.capture_dir or router.cfg.flight_dir
        if not d:
            if not self._warned_no_capture_dir:
                self._warned_no_capture_dir = True
                logger.warning(
                    "fleet anomaly capture skipped: no capture "
                    "directory (set FleetTelemetryConfig.capture_dir "
                    "or FleetConfig.flight_dir) — detectors still "
                    "fire/count")
            return
        got = router._reps[name].engine.capture(
            steps=self.cfg.capture_steps,
            reason=f"fleet_{ev.signal}",
            out_dir=os.path.join(d, "captures", name))
        if got is not None:
            self._captures_used += 1
            self.captures.append({"signal": ev.signal, "replica": name,
                                  "dir": got, "step": int(ev.step)})

    def summary(self) -> Dict[str, Any]:
        """JSON-able fleet anomaly tally (bench legs / fleet dumps)."""
        return {**self.monitor.summary(),
                "captures": [dict(c) for c in self.captures]}

    def reset(self) -> None:
        """Rearm detectors + capture budget (the router's
        ``reset_metrics``); journeys and spans clear too."""
        self.monitor.reset()
        self._prev.clear()
        self._storm.clear()
        self._slo_prev.clear()
        self._slo_bad.clear()
        self._captures_used = 0
        self.captures.clear()
        self._journeys.clear()
        self.tracer.clear()


# --------------------------------------------------------------------------
# migration-aware fleet request metrics
# --------------------------------------------------------------------------

def _merged_rec(uid: int) -> Dict[str, Any]:
    return {"uid": int(uid), "replica": None, "status": "open",
            "hops": [], "prompt_tokens": 0, "cached_tokens": 0,
            "generated_tokens": 0, "drafted_tokens": 0,
            "accepted_tokens": 0, "preemptions": 0, "retries": 0,
            "ttft_ms": None, "e2e_ms": None,
            "_t0": None, "_t_first": None, "_t_finish": None}


def _fold(cur: Dict[str, Any], name: str, rec) -> None:
    cur["prompt_tokens"] += rec.prompt_tokens
    cur["cached_tokens"] += rec.cached_tokens
    cur["generated_tokens"] += rec.generated_tokens
    cur["drafted_tokens"] += rec.drafted_tokens
    cur["accepted_tokens"] += rec.accepted_tokens
    cur["preemptions"] += rec.preemptions
    cur["retries"] += rec.retries
    if cur["_t0"] is None:
        cur["_t0"] = rec.t_arrival
    if rec.t_first_token is not None and cur["_t_first"] is None:
        cur["_t_first"] = rec.t_first_token
    if rec.t_finish is not None:
        cur["_t_finish"] = rec.t_finish


def _close_merged(cur: Dict[str, Any]) -> Dict[str, Any]:
    if cur["_t_first"] is not None and cur["_t0"] is not None:
        cur["ttft_ms"] = round((cur["_t_first"] - cur["_t0"]) * 1e3, 4)
    if cur["_t_finish"] is not None and cur["_t0"] is not None \
            and cur["status"] not in ("open", "migrating"):
        cur["e2e_ms"] = round((cur["_t_finish"] - cur["_t0"]) * 1e3, 4)
    for k in ("_t0", "_t_first", "_t_finish"):
        del cur[k]
    return cur


def fleet_request_records(router) -> List[Dict[str, Any]]:
    """Merge every replica's lifecycle records into fleet-level request
    records, migration-aware (docs/OBSERVABILITY.md "Fleet
    observability"):

    * a ``migrated`` or ``handed_off`` close on one replica is a HOP —
      it folds into the uid's temporally-next record (the continuation
      the router placed elsewhere), so a migrated or prefill→decode
      handed-off request yields ONE record attributed to its finishing
      replica;
    * an ``open`` record on a DEAD replica is the failover's hop (the
      engine died before closing it; the router re-placed or
      fleet-closed the work);
    * phantom ``shed`` closures — an engine shedding a put the router
      then retried elsewhere (``serving_fleet_replica_shed_retries_
      total``) — are dropped: they were never a fleet terminal;
    * a trailing hop with no continuation takes the FLEET status
      (``migrating`` in the queue, or the router's terminal closure).

    All replicas share one in-process ``perf_counter`` clock, so
    sorting a uid's records by arrival time orders its hops.  Token
    sums over the merged records equal the sum of the per-replica
    engine counters (every hop's tokens were counted where they ran) —
    the invariant the fleet fuzz asserts.
    """
    per_uid: Dict[int, List[Tuple[float, str, Any, bool]]] = {}
    for name, rep in router._reps.items():
        dead = rep.dead
        for rec in rep.engine.requests.records():
            per_uid.setdefault(rec.uid, []).append(
                (rec.t_arrival, name, rec, dead))
    phantom = dict(router._phantoms)
    merged: List[Dict[str, Any]] = []
    for uid, items in sorted(per_uid.items()):
        items.sort(key=lambda e: e[0])
        kept = []
        for t, name, rec, dead in items:
            if rec.status == "shed" and phantom.get((uid, name), 0) > 0:
                phantom[(uid, name)] -= 1        # routing retry, not a
                continue                         # fleet terminal
            kept.append((t, name, rec, dead))
        cur = None
        for t, name, rec, dead in kept:
            hop = rec.status in ("migrated", "handed_off") \
                or (dead and rec.status == "open")
            if cur is None:
                cur = _merged_rec(uid)
            _fold(cur, name, rec)
            cur["hops"].append({"replica": name, "status": rec.status})
            if not hop:
                cur["status"] = rec.status
                cur["replica"] = name
                merged.append(_close_merged(cur))
                cur = None
        if cur is not None:
            # trailing hop: the fleet knows where the story went
            cur["status"] = router._fleet_status_of(uid)
            merged.append(_close_merged(cur))
    return merged


def fleet_request_metrics(router) -> Dict[str, Any]:
    """Fleet-level ``request_metrics()``: migration-deduped records,
    the exact fleet aggregate, and each replica's own aggregate.

    ``aggregate["statuses"]`` is the record-derived fleet truth
    (merged records plus the router's record-gap tally — closures that
    left no engine record, e.g. a fleet-saturation shed); the
    counter-derived twin is the :class:`FleetRegistry`'s reconciled
    ``serving_fleet_requests_terminal_total`` rollup, and the fleet
    fuzz asserts the two agree."""
    records = fleet_request_records(router)
    statuses: Dict[str, int] = {}
    open_n = 0
    sums = {"prompt_tokens": 0, "cached_tokens": 0,
            "generated_tokens": 0, "drafted_tokens": 0,
            "accepted_tokens": 0}
    preemptions = retries = 0
    for r in records:
        if r["status"] in ("open", "migrating"):
            open_n += 1
        else:
            statuses[r["status"]] = statuses.get(r["status"], 0) + 1
        for k in sums:
            sums[k] += r[k]
        preemptions += r["preemptions"]
        retries += r["retries"]
    gaps = dict(router._record_gaps)
    for s, n in gaps.items():
        statuses[s] = statuses.get(s, 0) + n
    return {
        "aggregate": {
            "requests": len(records) + sum(gaps.values()),
            "open": open_n,
            "statuses": statuses,
            **sums,
            "preemptions": preemptions,
            "retries": retries,
            "fleet_shed": int(router._c_shed.value()),
            "fleet_failed": int(router._c_failed.value()),
            "failovers": int(router._c_failovers.value()),
            "migrations": int(router._c_migrations.value()),
        },
        "replicas": {name: rep.engine.request_metrics()["aggregate"]
                     for name, rep in router._reps.items()},
        "requests": records,
    }


def reconciled_terminal_statuses(router) -> Dict[str, int]:
    """Counter-derived fleet terminal statuses, exact (docs/
    OBSERVABILITY.md "Fleet observability"): per-replica
    ``serving_requests_terminal_total`` sums with the migration/routing
    double counting reconciled out —

    * ``migrated`` and ``handed_off`` closures are dropped (internal
      hops, the request lives on);
    * per-replica ``shed`` closures that were fleet routing retries
      (phantoms, counted by ``serving_fleet_replica_shed_retries_
      total``) are subtracted;
    * fleet-level closures with no engine terminal (saturation sheds,
      migration-exhaustion sheds, inexact-record fails, migration-queue
      settles) are added from the router's own ledger.
    """
    tally: Dict[str, int] = {}
    for rep in router._reps.values():
        c = rep.engine.metrics.get("serving_requests_terminal_total")
        if c is None:
            continue
        for k, v in c.series():
            if not k:
                continue
            status = dict(k).get("status")
            if status is None or status in ("migrated", "handed_off"):
                continue
            tally[status] = tally.get(status, 0) + int(v)
    phantoms = int(router._c_phantom.value())
    if phantoms:
        tally["shed"] = tally.get("shed", 0) - phantoms
    for s, n in router._fleet_closures.items():
        tally[s] = tally.get(s, 0) + n
    return {s: n for s, n in tally.items() if n}


# --------------------------------------------------------------------------
# FleetRegistry: one exposition for the whole fleet
# --------------------------------------------------------------------------

def _scrape_registry(reg: MetricsRegistry) -> Dict[str, Dict[str, Any]]:
    """Snapshot one replica registry's ``serving_*`` series for
    re-export.  Pull-based FnGauges evaluate HERE — at scrape time —
    so the exposition is never stale for a live replica, and an absent
    sample (FnGauge None) stays absent."""
    out: Dict[str, Dict[str, Any]] = {}
    for m in reg:
        if not m.name.startswith("serving_"):
            continue
        if isinstance(m, Histogram):
            out[m.name] = {
                "kind": "histogram", "help": m.help,
                "buckets": m.buckets,
                "hist": {k: (list(m._counts[k]), m._sums.get(k, 0.0),
                             m._totals.get(k, 0))
                         for k in m._counts}}
        else:
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "int": bool(getattr(m, "int_valued", False)),
                           "samples": list(m.series())}
    return out


def _with_replica(key, name: str):
    return tuple(sorted(key + (("replica", name),)))


class FleetRegistry:
    """One Prometheus exposition for the fleet: every live replica's
    ``serving_*`` series re-exported under a ``replica=`` label,
    ``serving_fleet_*`` rollups (sum; max for peaks and state codes;
    rates skipped — a summed ratio is a lie), the reconciled terminal
    rollup, a staleness marker per replica, and the router's own fleet
    series — all pulled at export time, nothing cached for a routable
    replica.

    Dead and quarantined replicas keep exporting — their last snapshot
    (a dead engine's registry is frozen host truth; an unreadable one
    serves its cached last scrape), marked
    ``serving_fleet_replica_stale{replica=} 1`` — instead of silently
    vanishing from dashboards mid-incident.

    The registry also accepts fleet-scope registrations (``counter`` /
    ``gauge`` / ``gauge_fn`` / ``histogram`` delegate to an internal
    :class:`MetricsRegistry`); tpulint's metric-name rule checks these
    registration sites like any other registry — and additionally bans
    f-string metric NAMES on fleet receivers: per-replica identity is
    the ``replica=`` label (from the handle), never part of the name.
    """

    def __init__(self, router):
        self._router = router
        self._extra = MetricsRegistry()
        self._last: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._warned_unreadable: set = set()

    # fleet-scope registration (delegates; the exposition includes them)
    def counter(self, name: str, help: str = "", int_valued: bool = False):
        return self._extra.counter(name, help, int_valued)

    def gauge(self, name: str, help: str = ""):
        return self._extra.gauge(name, help)

    def gauge_fn(self, name: str, fn, help: str = ""):
        return self._extra.gauge_fn(name, fn, help)

    def histogram(self, name: str, buckets, help: str = ""):
        return self._extra.histogram(name, buckets, help)

    # ------------------------------------------------------------------
    def collect(self):
        """(per-replica scrape snaps, staleness map).  Every replica
        scrapes LIVE at collect time — in-process, a dead engine's
        registry is frozen host truth, so the live read IS its last
        snapshot (and a quarantined replica's open work is still
        moving its counters; freezing them would break the fleet's
        exact token accounting).  The cache serves only a registry
        that can no longer be read (the remote-replica shape), and the
        ``serving_fleet_replica_stale`` marker flags every
        non-routable (quarantined/dead) replica so dashboards know
        those series no longer describe live traffic-serving."""
        snaps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        stale: Dict[str, bool] = {}
        for name, rep in self._router._reps.items():
            try:
                self._last[name] = _scrape_registry(rep.engine.metrics)
            except Exception as e:  # tpulint: disable=silent-except — scrape fallback: an unreadable replica registry serves its cached last scrape instead of taking the exporter down
                if name not in self._warned_unreadable:
                    self._warned_unreadable.add(name)
                    logger.warning(
                        "fleet registry: replica %s unreadable (%s: "
                        "%s) — exporting its last scrape", name,
                        type(e).__name__, e)
            stale[name] = not rep.breaker.state in ("closed",
                                                    "half_open")
            if name in self._last:
                snaps[name] = self._last[name]
        return snaps, stale

    def _rollup_mode(self, name: str, kind: str) -> Optional[str]:
        """"sum" / "max" / None (skip).  Counters and histograms sum;
        gauges sum except peaks and state codes (max — the worst
        replica is the fleet's number); rates never roll up (recompute
        them from the summed numerators/denominators instead)."""
        if name.endswith("_rate"):
            return None
        if kind == "gauge" and ("peak" in name
                                or name.endswith("_state")):
            return "max"
        return "sum"

    def rollups(self, snaps=None) -> Dict[str, Dict[str, Any]]:
        """``serving_fleet_*`` rollup series.  A rollup whose name
        collides with one of the router's own fleet metrics is skipped
        (the router's series IS the fleet-level truth there); the
        terminal-status rollup is the reconciled one, never the naive
        sum (docs/OBSERVABILITY.md "Fleet observability")."""
        if snaps is None:
            snaps, _ = self.collect()
        router = self._router
        out: Dict[str, Dict[str, Any]] = {}
        names: List[str] = []
        for snap in snaps.values():
            for n in snap:
                if n not in names:
                    names.append(n)
        for name in names:
            rname = "serving_fleet_" + name[len("serving_"):]
            if rname in router.metrics or rname in self._extra:
                continue
            if name == "serving_requests_terminal_total":
                out[rname] = {
                    "kind": "counter",
                    "help": "fleet terminal closures by status, "
                            "reconciled (migration hops and routing-"
                            "retry sheds deduped)",
                    "samples": [((("status", s),), float(v))
                                for s, v in sorted(
                                    reconciled_terminal_statuses(
                                        router).items())]}
                continue
            first = next(snap[name] for snap in snaps.values()
                         if name in snap)
            mode = self._rollup_mode(name, first["kind"])
            if mode is None:
                continue
            if first["kind"] == "histogram":
                agg: Dict[Any, List] = {}
                for snap in snaps.values():
                    ent = snap.get(name)
                    if ent is None:
                        continue
                    for k, (counts, s, t) in ent["hist"].items():
                        got = agg.get(k)
                        if got is None:
                            agg[k] = [list(counts), s, t]
                        else:
                            got[0] = [a + b for a, b
                                      in zip(got[0], counts)]
                            got[1] += s
                            got[2] += t
                out[rname] = {"kind": "histogram",
                              "help": f"fleet rollup of {name}",
                              "buckets": first["buckets"],
                              "hist": {k: tuple(v)
                                       for k, v in agg.items()}}
                continue
            vals: Dict[Any, float] = {}
            for snap in snaps.values():
                ent = snap.get(name)
                if ent is None:
                    continue
                for k, v in ent["samples"]:
                    if mode == "max":
                        vals[k] = max(vals.get(k, v), v)
                    else:
                        vals[k] = vals.get(k, 0.0) + v
            if vals:
                out[rname] = {"kind": first["kind"],
                              "help": f"fleet rollup of {name} "
                                      f"({mode} over replicas)",
                              "samples": sorted(vals.items())}
        return out

    # ------------------------------------------------------------------
    def prometheus_text(self) -> str:
        """The fleet's one Prometheus exposition (text format 0.0.4):
        per-replica re-export + rollups + staleness markers + the
        router's own fleet series."""
        snaps, stale = self.collect()
        lines: List[str] = []
        names: List[str] = []
        for snap in snaps.values():
            for n in snap:
                if n not in names:
                    names.append(n)
        for name in names:
            first = next(snap[name] for snap in snaps.values()
                         if name in snap)
            pname = _prom_name(name)
            if first["help"]:
                lines.append(f"# HELP {pname} {first['help']}")
            lines.append(f"# TYPE {pname} {first['kind']}")
            for rname_, snap in snaps.items():
                ent = snap.get(name)
                if ent is None:
                    continue
                if ent["kind"] == "histogram":
                    self._hist_lines(lines, pname, ent["buckets"],
                                     ent["hist"], rname_)
                else:
                    for k, v in ent["samples"]:
                        lk = _prom_label_str(_with_replica(k, rname_))
                        lines.append(f"{pname}{lk} {_fmt(v)}")
        for rname, ent in self.rollups(snaps).items():
            pname = _prom_name(rname)
            if ent["help"]:
                lines.append(f"# HELP {pname} {ent['help']}")
            lines.append(f"# TYPE {pname} {ent['kind']}")
            if ent["kind"] == "histogram":
                self._hist_lines(lines, pname, ent["buckets"],
                                 ent["hist"], None)
            else:
                for k, v in ent["samples"]:
                    lines.append(
                        f"{pname}{_prom_label_str(tuple(k))} {_fmt(v)}")
        lines.append("# HELP serving_fleet_replica_stale replica "
                     "exporting its last scrape (dead or quarantined) "
                     "rather than live truth")
        lines.append("# TYPE serving_fleet_replica_stale gauge")
        for name in snaps:
            lk = _prom_label_str((("replica", name),))
            lines.append(
                f"serving_fleet_replica_stale{lk} "
                f"{1 if stale[name] else 0}")
        text = "\n".join(lines) + "\n"
        if self._extra._metrics:
            text += self._extra.prometheus_text()
        return text + self._router.metrics.prometheus_text()

    @staticmethod
    def _hist_lines(lines: List[str], pname: str, buckets,
                    hist: Dict[Any, tuple],
                    replica: Optional[str]) -> None:
        for k in sorted(hist):
            counts, hsum, total = hist[k]
            base = _with_replica(tuple(k), replica) \
                if replica is not None else tuple(k)
            cum = 0
            for i, edge in enumerate(buckets):
                cum += counts[i]
                lk = _prom_label_str(
                    tuple(sorted(base + (("le", _fmt(edge)),))))
                lines.append(f"{pname}_bucket{lk} {cum}")
            lk = _prom_label_str(
                tuple(sorted(base + (("le", "+Inf"),))))
            lines.append(f"{pname}_bucket{lk} {cum + counts[-1]}")
            ls = _prom_label_str(base)
            lines.append(f"{pname}_sum{ls} {_fmt(hsum)}")
            lines.append(f"{pname}_count{ls} {total}")

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able fleet view: per-replica scalar snapshots (labels
        flattened), rollup values, staleness."""
        snaps, stale = self.collect()
        reps: Dict[str, Any] = {}
        for name, snap in snaps.items():
            vals: Dict[str, Any] = {}
            for mname, ent in snap.items():
                if ent["kind"] == "histogram":
                    vals[mname] = {
                        _prom_label_str(tuple(k)) or "{}": {
                            "count": t, "sum": round(s, 6)}
                        for k, (c, s, t) in sorted(ent["hist"].items())}
                else:
                    vals[mname] = {
                        _prom_label_str(tuple(k)) or "{}": round(v, 6)
                        for k, v in ent["samples"]}
            reps[name] = vals
        return {"replicas": reps, "rollups": self.rollup_snapshot(snaps),
                "stale": dict(stale)}

    def rollup_snapshot(self, snaps=None) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for rname, ent in self.rollups(snaps).items():
            if ent["kind"] == "histogram":
                out[rname] = {
                    _prom_label_str(tuple(k)) or "{}": {
                        "count": t, "sum": round(s, 6)}
                    for k, (c, s, t) in sorted(ent["hist"].items())}
            else:
                vals = dict(ent["samples"])
                if list(vals) == [()]:
                    out[rname] = round(vals[()], 6)
                else:
                    out[rname] = {
                        _prom_label_str(tuple(k)) or "{}": round(v, 6)
                        for k, v in vals.items()}
        return out


# --------------------------------------------------------------------------
# fleet post-mortem validation
# --------------------------------------------------------------------------

def validate_fleet_dump(dump: Dict[str, Any],
                        base_dir: Optional[str] = None) -> List[str]:
    """Schema check for one fleet post-mortem bundle's ``fleet.json``
    (loaded): returns violations, empty when valid.  With ``base_dir``
    (the bundle directory) each replica's referenced ``flight.json``
    must exist on disk too — the bundle is only a post-mortem if the
    per-replica black boxes actually landed."""
    problems: List[str] = []
    for k in FLEET_DUMP_REQUIRED_KEYS:
        if k not in dump:
            problems.append(f"missing key {k!r}")
    if dump.get("version") != FLEET_DUMP_VERSION:
        problems.append(f"version {dump.get('version')!r} != "
                        f"{FLEET_DUMP_VERSION}")
    fp = dump.get("fingerprint")
    if not (isinstance(fp, dict) and "engine_version" in fp
            and "config_hash" in fp):
        problems.append("fingerprint missing engine_version/config_hash")
    for k in ("metrics", "rollups", "journeys", "replicas"):
        if k in dump and not isinstance(dump[k], dict):
            problems.append(f"{k} is not a dict")
    if not isinstance(dump.get("events"), list):
        problems.append("events is not a list")
    rm = dump.get("request_metrics")
    if not (isinstance(rm, dict) and "aggregate" in rm):
        problems.append("request_metrics missing aggregate")
    reps = dump.get("replicas")
    for name, info in (reps.items() if isinstance(reps, dict) else ()):
        if not isinstance(info, dict) or "flight" not in info:
            problems.append(f"replica {name!r} entry missing flight")
            continue
        if base_dir is not None:
            p = os.path.join(base_dir, info["flight"])
            if not os.path.isfile(p):
                problems.append(
                    f"replica {name!r} flight dump missing: {p}")
    return problems
