"""The signal-driven autoscaling actuator (docs/SERVING.md
"Disaggregated pools & elasticity"; ROADMAP item 1 — the consumer the
fleet anomaly catalog and ``FleetConfig.telemetry="auto"`` were built
for).

The :class:`Autoscaler` closes the loop the observability plane left
open: the PR-14 fleet detectors (placement imbalance, affinity
collapse, failover/migration storms, TTFT divergence) and the pool
depth/load gauges produce scaling *signals*; this actuator turns them
into ``add_replica`` / ``scale_down`` *actions*, sizing the two pools
independently — interactive TTFT is prefill-pool depth, batch TPOT is
decode-pool width.

Design rules, all step-counted and deterministic (the serving-layer
discipline — chaos replays must be machine-independent):

* **hysteresis** — a pressure signal must persist for
  ``hysteresis_steps`` consecutive evaluations before any action; one
  bursty step must not mint a replica.
* **cooldown** — after any action on a pool, that pool holds still for
  ``cooldown_steps`` router steps; the fleet must re-observe the new
  size before acting again (no thrash).
* **anomaly veto** — a fleet anomaly fired this step vetoes
  scale-DOWN everywhere (shrinking a fleet that is visibly struggling
  compounds the struggle) and arms the implicated pool's scale-up
  streak.
* **never below min, never above max** — per-pool bounds; scale-down
  drains the pool's least-loaded replica through the router's
  zero-lost ``scale_down`` path.

Attaching the actuator flips the router's ``telemetry="auto"`` plane
ON (``router.enable_telemetry()``) — the actuator IS the signal
consumer "auto" was waiting for.  Scale-ups build replicas through the
caller's ``replica_factory(pool)``; pair it with
:class:`WeightStreamColdStart` so a new replica's weights restore from
the NVMe weight store spilled once at deploy (fast cold start, and the
resident-weight modes streaming would force off stay available).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

from ..utils.logging import logger

# which pool a fired fleet detector implicates: prompt-side signals
# pressure the prefill pool, decode-side divergence pressures decode;
# a storm is pure veto (scaling during failover churn adds churn)
_SIGNAL_POOL = {
    "placement_imbalance": "prefill",
    "affinity_hit_rate": "prefill",
    "ttft_divergence": "decode",
    "failover_migration_storm": None,
}

# SLO burn signals (``slo_burn_rate_<class>``, telemetry/slo.py) map by
# the burning class: interactive/standard budgets burn on TTFT — queue
# admission pressure, a prefill problem; a batch budget burns on TPOT —
# decode throughput.  Unknown classes lean prefill (admission is the
# commonest bottleneck and a wrong lean is bounded by pool maximums).
_SLO_BURN_POOL = {
    "interactive": "prefill",
    "standard": "prefill",
    "batch": "decode",
}
_SLO_BURN_PREFIX = "slo_burn_rate_"


def _signal_pool(sig: str) -> Optional[str]:
    if sig in _SIGNAL_POOL:
        return _SIGNAL_POOL[sig]
    if sig.startswith(_SLO_BURN_PREFIX):
        return _SLO_BURN_POOL.get(sig[len(_SLO_BURN_PREFIX):], "prefill")
    return None


@dataclasses.dataclass
class AutoscalerConfig:
    """Actuator knobs — all thresholds are integer loads and step
    counts, so decisions replay deterministically."""
    # per-pool size bounds (live replicas serving the pool)
    min_prefill: int = 1
    max_prefill: int = 4
    min_decode: int = 1
    max_decode: int = 4
    # average live+queued requests per pool replica that arm scale-up
    # / scale-down pressure
    up_load: float = 3.0
    down_load: float = 0.5
    # consecutive armed evaluations before acting (hysteresis), and
    # per-pool post-action quiet period (cooldown)
    hysteresis_steps: int = 3
    cooldown_steps: int = 8
    # evaluate every N router steps (1 = every step)
    evaluate_every: int = 1

    def __post_init__(self):
        if self.min_prefill < 1 or self.min_decode < 1:
            raise ValueError("pool minimums must be >= 1")
        if self.max_prefill < self.min_prefill \
                or self.max_decode < self.min_decode:
            raise ValueError("pool maximums must be >= their minimums")
        if self.hysteresis_steps < 1:
            raise ValueError("hysteresis_steps must be >= 1")
        if self.cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")
        if self.evaluate_every < 1:
            raise ValueError("evaluate_every must be >= 1")
        if self.down_load >= self.up_load:
            raise ValueError("down_load must be < up_load (the dead "
                             "band between them is the stability zone)")


class Autoscaler:
    """Per-pool scaling actuator over one :class:`~.router.FleetRouter`
    (module docstring).  ``replica_factory(pool)`` returns a fresh
    engine for a scale-up into ``pool`` ("prefill" / "decode" /
    "mixed"); the router is stepped by its driver as usual — the
    actuator rides ``router.step`` via ``on_router_step`` once
    attached (construction attaches)."""

    def __init__(self, router,
                 replica_factory: Callable[[str], object],
                 cfg: Optional[AutoscalerConfig] = None):
        self.router = router
        self.factory = replica_factory
        self.cfg = cfg or AutoscalerConfig()
        self.decisions: List[Dict] = []
        self._up_streak = {"prefill": 0, "decode": 0}
        self._down_streak = {"prefill": 0, "decode": 0}
        self._cool_until = {"prefill": 0, "decode": 0}
        self._minted = 0
        self._last_anomalies = 0
        # the actuator IS the consumer telemetry="auto" waits for
        router.enable_telemetry()
        router._autoscaler = self

    # ---- bounds ----------------------------------------------------------
    def _bounds(self, pool: str) -> tuple:
        if pool == "prefill":
            return self.cfg.min_prefill, self.cfg.max_prefill
        return self.cfg.min_decode, self.cfg.max_decode

    # ---- the per-step evaluation ----------------------------------------
    def on_router_step(self) -> None:  # tpulint: serving-loop
        """One evaluation: fold this step's anomaly fires and pool
        loads into the streaks, act where hysteresis + cooldown +
        bounds allow.  Called by ``router.step`` after gauges and
        telemetry refresh — integer loads and counter reads only, no
        clocks (the decisions must replay)."""
        router = self.router
        if router._steps % self.cfg.evaluate_every:
            return
        # anomaly deltas since the last evaluation, attributed to pools
        fired_pools = set()
        veto = False
        ftel = router._ftel
        if ftel is not None:
            counts = ftel.monitor.counts
            total = sum(counts.values())
            if total > self._last_anomalies:
                veto = True
                for sig in counts:
                    p = _signal_pool(sig)
                    if p is not None:
                        fired_pools.add(p)
            self._last_anomalies = total
        for pool in ("prefill", "decode"):
            self._evaluate_pool(pool, pool in fired_pools, veto)

    def _evaluate_pool(self, pool: str, anomaly_up: bool,
                       veto: bool) -> None:
        router = self.router
        members = router.pool_members(pool)
        if not members:
            return
        lo, hi = self._bounds(pool)
        load = sum(r.load() for r in members) / len(members)
        if load > self.cfg.up_load or anomaly_up:
            self._up_streak[pool] += 1
            self._down_streak[pool] = 0
        elif load < self.cfg.down_load and not veto:
            self._down_streak[pool] += 1
            self._up_streak[pool] = 0
        else:
            self._up_streak[pool] = 0
            self._down_streak[pool] = 0
        if router._steps < self._cool_until[pool]:
            return
        if self._up_streak[pool] >= self.cfg.hysteresis_steps \
                and len(members) < hi:
            self._scale_up(pool, load)
        elif self._down_streak[pool] >= self.cfg.hysteresis_steps \
                and len(members) > lo:
            self._shrink(pool, members, load)

    # ---- actions ---------------------------------------------------------
    def _decide(self, pool: str, action: str, replica: str,
                load: float) -> None:
        d = {"step": int(self.router._steps), "pool": pool,
             "action": action, "replica": replica,
             "avg_load": round(float(load), 3)}
        self.decisions.append(d)
        self.router.flight.note("scale_decision", **d)
        logger.info("fleet autoscaler: %s %s pool via %s (avg load "
                    "%.2f at step %d)", action, pool, replica, load,
                    self.router._steps)
        self._cool_until[pool] = \
            self.router._steps + self.cfg.cooldown_steps
        self._up_streak[pool] = 0
        self._down_streak[pool] = 0

    def _scale_up(self, pool: str, load: float) -> None:
        self._minted += 1
        name = f"as-{pool}-{self._minted}"
        engine = self.factory(pool)
        self.router.add_replica(name, engine, role=pool)
        self.router._c_scale_ups.inc(pool=pool)
        self._decide(pool, "scale_up", name, load)

    def _shrink(self, pool: str, members, load: float) -> None:
        # drain the least-loaded member (ties broken by name for
        # determinism); its open work re-places through the router's
        # zero-lost scale_down path
        victim = min(members, key=lambda r: (r.load(), r.name))
        self.router.scale_down(victim.name)
        self.router._c_scale_downs.inc(pool=pool)
        self._decide(pool, "scale_down", victim.name, load)

    # ---- reporting -------------------------------------------------------
    def summary(self) -> Dict:
        """JSON-able decision log + streak state (bench/chaos legs)."""
        ups = sum(1 for d in self.decisions
                  if d["action"] == "scale_up")
        downs = sum(1 for d in self.decisions
                    if d["action"] == "scale_down")
        return {"decisions": [dict(d) for d in self.decisions],
                "scale_ups": ups, "scale_downs": downs,
                "up_streak": dict(self._up_streak),
                "down_streak": dict(self._down_streak)}


class WeightStreamColdStart:
    """Scale-up cold start through the NVMe weight-stream store: the
    template engine's stacked block weights are spilled ONCE (deploy
    time), and every minted replica restores them RESIDENT from the
    store's aio read path (``NVMeWeightStore.restore_stacked``)
    instead of re-running checkpoint load — the fleet's weight fabric
    is the cold-start fabric.  Because the new engine never sets
    ``icfg.weight_stream``, none of the modes streaming forces off
    (decode bursts, speculative decode) are forced on it — the test
    bar the satellite names.

    ``build`` is a zero-arg engine constructor (same config the pool
    expects); instances are valid ``replica_factory`` callables for
    :class:`Autoscaler`."""

    def __init__(self, template_engine, build: Callable[[], object],
                 path: str):
        from ..inference.weight_stream import NVMeWeightStore
        if "blocks" not in template_engine.params:
            raise ValueError("template engine has no stacked 'blocks' "
                             "params to spill")
        self.build = build
        self.store = NVMeWeightStore(path,
                                     template_engine.cfg.num_layers)
        self.store.spill({"blocks": template_engine.params["blocks"]})
        self.restores = 0

    def __call__(self, pool: str = "mixed"):
        eng = self.build()
        # bit-identical weights from the store: token parity across a
        # scale-up is the spilled bytes' parity
        eng.params["blocks"] = \
            self.store.restore_stacked()["blocks"]
        self.restores += 1
        return eng
