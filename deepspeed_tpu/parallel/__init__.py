from .sharding import (DEFAULT_RULES, spec_for_axes, add_fsdp_to_spec,
                       tree_specs, infer_logical_axes, named, tree_named)
from .zero import ZeroPolicy, shard_count
from .sequence import (make_attention, make_ulysses_attention,
                       make_ring_attention)
from .pipeline import make_pipelined_loss_fn
from . import moe

__all__ = ["DEFAULT_RULES", "spec_for_axes", "add_fsdp_to_spec", "tree_specs",
           "infer_logical_axes", "named", "tree_named", "ZeroPolicy",
           "shard_count", "make_attention", "make_ulysses_attention",
           "make_ring_attention", "make_pipelined_loss_fn", "moe"]
