from .sharding import (DEFAULT_RULES, spec_for_axes, add_fsdp_to_spec,
                       tree_specs, infer_logical_axes, named, tree_named)
from .zero import ZeroPolicy, shard_count

__all__ = ["DEFAULT_RULES", "spec_for_axes", "add_fsdp_to_spec", "tree_specs",
           "infer_logical_axes", "named", "tree_named", "ZeroPolicy",
           "shard_count"]
