"""Sequence/context parallelism: Ulysses all-to-all + ring attention.

TPU-native re-design of the reference's DeepSpeed-Ulysses
(``deepspeed/sequence/layer.py`` — ``single_all_to_all`` :41,
``DistributedAttention.forward`` :181: scatter heads / gather sequence
before local attention, inverse after) plus **ring attention**, the
context-parallel mechanism the reference lacks (SURVEY §5.7: "ring
attention / blockwise: not present"), which on TPU rides ICI neighbor
links via ``lax.ppermute``.

Both are drop-in ``attention_fn`` implementations for
``deepspeed_tpu.models`` (signature ``(q, k, v, mask=None, scale=None)``),
wrapping the local computation in a nested ``shard_map`` over the ``seq``
mesh axis so they compose with jit/SPMD and TP head sharding.

Constraints (same as the reference, layer.py:52): Ulysses needs
``num_heads % (seq * tensor) == 0`` and ``num_kv_heads % seq == 0``;
ring attention only needs the sequence divisible by the axis size.
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import shard_map
from jax.sharding import PartitionSpec as P

from ..comm.mesh import BATCH_AXES, MeshTopology, SEQ_AXIS, TENSOR_AXIS
from ..models.layers import causal_attention


def make_ulysses_local(base_attention: Callable = causal_attention
                       ) -> Callable:
    """Per-shard Ulysses attention for callers ALREADY inside a shard_map
    over ``seq`` (e.g. the pipeline loss, which runs one outer shard_map
    over pipe x data x seq).  Same a2a dance as ``make_ulysses_attention``
    without the nested shard_map."""

    def attn(q, k, v, mask=None, scale=None):
        a2a = functools.partial(lax.all_to_all, axis_name=SEQ_AXIS,
                                split_axis=2, concat_axis=1, tiled=True)
        q_, k_, v_ = a2a(q), a2a(k), a2a(v)
        if mask is not None:
            mask = lax.all_gather(mask, SEQ_AXIS, axis=1, tiled=True)
        o = base_attention(q_, k_, v_, mask=mask, scale=scale)
        return lax.all_to_all(o, axis_name=SEQ_AXIS, split_axis=1,
                              concat_axis=2, tiled=True)

    return attn


def make_ulysses_attention(topology: MeshTopology,
                           base_attention: Callable = causal_attention
                           ) -> Callable:
    """All-to-all attention: inputs arrive sequence-sharded; a2a trades the
    sequence split for a head split, local attention sees the full sequence
    for its head subset, inverse a2a restores sequence sharding."""
    mesh = topology.mesh
    sp = topology.sp_size
    if sp == 1:
        return base_attention

    def attn(q, k, v, mask=None, scale=None):
        H, Hkv = q.shape[2], k.shape[2]
        tp = topology.tp_size
        if (H % (sp * tp)) or (Hkv % (sp * tp)):
            raise ValueError(
                f"Ulysses needs heads divisible by seq*tensor axes: "
                f"H={H}, Hkv={Hkv}, seq={sp}, tensor={tp}")

        # heads-scatter/seq-gather before local attention, inverse after
        # (reference single_all_to_all layer.py:41)
        inner = make_ulysses_local(base_attention)

        def local(q, k, v, mask):
            return inner(q, k, v, mask=mask, scale=scale)

        qspec = P(BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        mspec = P(BATCH_AXES, SEQ_AXIS) if mask is not None else P()
        return shard_map(
            local, mesh=mesh,
            in_specs=(qspec, qspec, qspec, mspec),
            out_specs=qspec,
            check_vma=False)(q, k, v, mask)

    return attn


# --------------------------------------------------------------------------
# Ring attention (context parallelism over ICI neighbor links)
# --------------------------------------------------------------------------

def _block_attn_update(q, k, v, o, m, l, row0, col0, causal, scale,
                       slopes=None, kv_mask=None):
    """Flash-style streaming-softmax update for one KV block.

    q [B,s,H,D] holds global rows [row0, row0+s); k/v [B,s,Hkv,D] global
    cols [col0, col0+s).  o/m/l are the running output, row-max and
    row-sum (fp32).  ``slopes``: optional ALiBi per-local-head slopes
    [Hkv, rep] — the bias is slope * GLOBAL key position, which the ring
    formulation has by construction (col0).  Returns updated (o, m, l).
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    rep = H // Hkv
    qg = q.reshape(B, S, Hkv, rep, D)
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k) * scale
    logits = logits.astype(jnp.float32)
    cols = col0 + jnp.arange(k.shape[1])
    if slopes is not None:
        logits = logits + (slopes[None, :, :, None, None]
                           * cols[None, None, None, None, :]
                           .astype(jnp.float32))
    if causal:
        rows = row0 + jnp.arange(S)
        keep = rows[:, None] >= cols[None, :]
        logits = jnp.where(keep[None, None, None], logits, -1e30)
    if kv_mask is not None:                     # [B, s] padding mask of
        logits = jnp.where(                     # the block we hold now
            kv_mask[:, None, None, None, :].astype(bool), logits, -1e30)

    blk_max = logits.max(axis=-1)                        # [B,Hkv,rep,q]
    new_m = jnp.maximum(m, blk_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])               # [B,Hkv,rep,q,k]
    new_l = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhrqk,bkhd->bhrqd", p.astype(q.dtype), v)
    new_o = o * correction[..., None] + pv.astype(jnp.float32)
    return new_o, new_m, new_l


def make_ring_attention(topology: MeshTopology, causal: bool = True,
                        alibi_heads: int = 0,
                        attn_scale=None) -> Callable:
    """Blockwise ring attention: Q stays put, KV blocks rotate around the
    ``seq`` axis via ``ppermute`` while a streaming softmax accumulates —
    O(S/sp) memory per device, neighbor-only ICI traffic, arbitrary
    sequence lengths (the >1M-token regime Ulysses alone cannot reach
    because its head split caps sp at num_heads).  ``alibi_heads``: the
    global head count of an ALiBi model — the bias (slope * global key
    position) folds into each block update; heads stay unsplit on the
    seq axis here, but a tensor head split slices the slope series."""
    mesh = topology.mesh
    sp = topology.sp_size
    if sp == 1:
        if alibi_heads:
            from ..models.layers import make_alibi_attention
            return make_alibi_attention()
        return causal_attention
    default_scale = attn_scale

    def attn(q, k, v, mask=None, scale=None):
        scale_ = scale if scale is not None else default_scale
        scale_ = scale_ if scale_ is not None \
            else 1.0 / math.sqrt(q.shape[-1])
        have_mask = mask is not None

        def local(q, k, v, *mk):
            mask = mk[0] if mk else None
            B, s, H, D = q.shape
            Hkv = k.shape[2]
            idx = lax.axis_index(SEQ_AXIS)
            row0 = idx * s

            slopes = None
            if alibi_heads:
                from ..models.layers import alibi_slopes
                sl = alibi_slopes(alibi_heads)
                if H != alibi_heads:   # tensor axis split the heads
                    off = lax.axis_index(TENSOR_AXIS) * H
                    sl = lax.dynamic_slice_in_dim(sl, off, H)
                slopes = sl.reshape(Hkv, H // Hkv)

            o = jnp.zeros((B, Hkv, H // Hkv, s, D), jnp.float32)
            m = jnp.full((B, Hkv, H // Hkv, s), -jnp.inf, jnp.float32)
            l = jnp.zeros((B, Hkv, H // Hkv, s), jnp.float32)
            perm = [(i, (i + 1) % sp) for i in range(sp)]

            def body(i, carry):
                # the padding mask (when present) rotates with its KV
                # block; without one the carry omits it entirely — no
                # dead ppermute on the common unmasked path (have_mask
                # is a trace-time constant)
                o, m, l, k, v = carry[:5]
                km = carry[5] if have_mask else None
                src = (idx - i) % sp          # global block we hold now
                o, m, l = _block_attn_update(
                    q, k, v, o, m, l, row0, src * s, causal, scale_,
                    slopes=slopes, kv_mask=km)
                k = lax.ppermute(k, SEQ_AXIS, perm)
                v = lax.ppermute(v, SEQ_AXIS, perm)
                nxt = (o, m, l, k, v)
                if have_mask:
                    nxt = nxt + (lax.ppermute(km, SEQ_AXIS, perm),)
                return nxt

            init = (o, m, l, k, v) + ((mask,) if have_mask else ())
            o, m, l = lax.fori_loop(0, sp, body, init)[:3]
            out = o / jnp.maximum(l, 1e-30)[..., None]
            # [B,Hkv,rep,s,D] -> [B,s,H,D]
            out = out.transpose(0, 3, 1, 2, 4).reshape(B, s, H, D)
            return out.astype(q.dtype)

        qspec = P(BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)
        in_specs = [qspec, qspec, qspec]
        operands = [q, k, v]
        if have_mask:
            in_specs.append(P(BATCH_AXES, SEQ_AXIS))
            operands.append(mask)
        return shard_map(local, mesh=mesh,
                         in_specs=tuple(in_specs),
                         out_specs=qspec,
                         check_vma=False)(*operands)

    return attn


def make_ulysses_alibi_base(num_heads: int, sp: int, tp: int = 1,
                            attn_scale=None) -> Callable:
    """ALiBi base attention for INSIDE a Ulysses ``shard_map``: after
    the head-scatter a2a each rank owns a contiguous slice of the global
    head set, so the slopes must be the matching slice of the global
    geometric series — offset = tensor_block + seq_sub_block.
    ``attn_scale``: a custom softmax scale (cfg.attn_scale) — rebuilt
    here because this path bypasses the model's resolved wrapper."""
    from ..models import layers as L

    h_tp = num_heads // tp
    h_local = h_tp // sp

    def head_offset():
        off = lax.axis_index(SEQ_AXIS) * h_local
        if tp > 1:
            off = off + lax.axis_index(TENSOR_AXIS) * h_tp
        return off

    base = None
    if attn_scale is not None:
        def base(q, k, v, mask=None, **kw):
            return causal_attention(q, k, v, mask=mask, scale=attn_scale,
                                    **kw)

    return L.make_alibi_attention(base, head_offset=head_offset,
                                  total_heads=num_heads)


def make_attention(topology: MeshTopology, mode: str = "ulysses",
                   base_attention: Callable = causal_attention,
                   alibi_heads: int = 0, alibi_scale=None) -> Callable:
    """(reference config: sequence_parallel.mode).  ``alibi_heads``:
    global head count of an ALiBi model — Ulysses builds the
    head-offset-aware bias inside its shard_map; ring folds
    slope * global-key-position into each block update."""
    if topology.sp_size == 1:
        return base_attention
    if mode == "ulysses":
        if alibi_heads:
            base_attention = make_ulysses_alibi_base(
                alibi_heads, topology.sp_size, topology.tp_size,
                attn_scale=alibi_scale)
        return make_ulysses_attention(topology, base_attention)
    if mode == "ring":
        return make_ring_attention(topology, alibi_heads=alibi_heads,
                                   attn_scale=alibi_scale)
    raise ValueError(f"Unknown sequence-parallel mode {mode!r}")


def sp_cross_entropy(logits, labels, topology: MeshTopology, mask=None):
    """SP-aware LM loss (reference: sequence/cross_entropy.py:11 —
    vocab-parallel loss).  Under SPMD jit the plain fp32 softmax xent is
    already correct for sequence-sharded logits; this alias documents the
    parity point."""
    from ..models.transformer import cross_entropy_loss

    return cross_entropy_loss(logits, labels, mask)
