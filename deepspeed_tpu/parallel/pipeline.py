"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native re-design of the reference pipeline stack
(``runtime/pipe/module.py:86`` PipelineModule layer partitioning,
``schedule.py:189`` TrainSchedule/1F1B instruction generator,
``pipe/engine.py:61`` PipelineEngine instruction interpreter with p2p
send/recv ``pipe/p2p.py:46``).

The reference interprets instruction lists per rank with explicit
send/recv.  Under SPMD there is no per-rank program: the pipeline is a
single ``lax.scan`` over ``T = M + S - 1`` ticks inside a ``shard_map``
over the ``pipe`` axis (GPipe schedule).  Each tick every stage applies
its layer slice and hands its activation to the next stage via
``lax.ppermute`` — the instruction schedule *is* the scan, the p2p layer
*is* ppermute riding ICI neighbor links, and the bubble is the standard
(S-1)/T fraction.

Layer placement: the model's stacked ``blocks`` (leading ``layers`` dim)
are sharded over ``pipe`` — contiguous equal slices, the 'uniform'
partition method of module.py:391.  Embedding/unembedding stay replicated
across stages (the reference's tied-layer broadcast, module.py:77, without
the tie-grad allreduce since SPMD psums automatically).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ..comm.mesh import BATCH_AXES, MeshTopology, PIPE_AXIS
from ..models import layers as L
from ..models.transformer import (TransformerConfig, block_apply,
                                  rolled_lm_targets, _norm)


def make_pipelined_loss_fn(cfg: TransformerConfig, topology: MeshTopology,
                           num_microbatches: int,
                           attention_fn: Callable = L.causal_attention,
                           schedule: str = "gpipe"):
    """Build ``loss_fn(params, batch, rng)`` running the GPipe schedule.

    Requirements: ``num_layers % pipe == 0``; the global micro-batch (the
    engine's per-step batch) divisible by ``num_microbatches``.
    """
    mesh = topology.mesh
    S = topology.pp_size
    M = num_microbatches
    if cfg.num_layers % S:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                         f"pipe stages {S}")
    if cfg.num_experts > 1:
        raise NotImplementedError("pipeline + MoE not yet supported")
    if schedule not in ("gpipe", "1f1b"):
        raise NotImplementedError(f"pipeline schedule {schedule!r}; "
                                  "'gpipe' is implemented ('1f1b' runs as "
                                  "gpipe — same math, more live memory)")

    norm = _norm(cfg)

    dp = topology.dp_world_size

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        B, seq = ids.shape
        if (B // dp) % M:
            raise ValueError(
                f"per-dp-shard batch {B}//{dp} not divisible by "
                f"num_microbatches {M}")
        amask = batch.get("attention_mask")
        labels, tgt_mask = rolled_lm_targets(ids, amask)
        if amask is None:
            amask = jnp.ones_like(ids, jnp.float32)

        if cfg.position == "rope":
            cos, sin = L.rope_freqs(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        else:
            cos = sin = None

        def stage_fwd(blocks_local, x, attn_mask):
            def body(h, lp):
                h, _ = block_apply(cfg, lp, h, cos, sin, mask=attn_mask,
                                   attention_fn=attention_fn)
                return h, None
            body_fn = jax.checkpoint(body) if cfg.remat else body
            x, _ = lax.scan(body_fn, x, blocks_local)
            return x

        def local(blocks, shared, ids, labels, tgt_mask, amask):
            """Runs per pipe shard.  blocks: [L/S, ...] local slice;
            shared (embed/pos/ln_f/head): replicated."""
            stage = lax.axis_index(PIPE_AXIS)
            first, last = stage == 0, stage == S - 1
            dt = shared["embed"]["table"].dtype

            # ids here is the per-(data,fsdp)-shard slice
            mb = ids.shape[0] // M
            ids_mb = ids.reshape(M, mb, seq)
            labels_mb = labels.reshape(M, mb, seq)
            mask_mb = tgt_mask.reshape(M, mb, seq)
            amask_mb = amask.reshape(M, mb, seq)

            T = M + S - 1
            perm = [(i, i + 1) for i in range(S - 1)]

            def tick(carry, t):
                buf, loss_sum, tok_sum = carry
                # stage 0 ingests microbatch t (clamped; masked later)
                t_in = jnp.clip(t, 0, M - 1)
                x0 = L.embed(shared["embed"],
                             lax.dynamic_index_in_dim(
                                 ids_mb, t_in, 0, keepdims=False)).astype(dt)
                if cfg.position == "learned":
                    x0 = x0 + shared["pos_embed"]["table"][:seq].astype(dt)
                x = jnp.where(first, x0, buf)
                # stage s processes microbatch t-s at tick t
                t_here = jnp.clip(t - stage, 0, M - 1)
                m_att = lax.dynamic_index_in_dim(amask_mb, t_here, 0,
                                                 keepdims=False)
                y = stage_fwd(blocks, x, m_att)

                # last stage: unembed + loss for microbatch t-(S-1)
                t_out = jnp.clip(t - (S - 1), 0, M - 1)
                h = norm(shared["ln_f"], y)
                if cfg.tie_embeddings:
                    logits = h @ shared["embed"]["table"].astype(dt).T
                else:
                    logits = h @ shared["lm_head"]["kernel"].astype(dt)
                lbl = lax.dynamic_index_in_dim(labels_mb, t_out, 0,
                                               keepdims=False)
                msk = lax.dynamic_index_in_dim(mask_mb, t_out, 0,
                                               keepdims=False)
                # lse - target_logit form: no fp32 [mb,seq,V] buffer
                # (same rationale as cross_entropy_loss)
                lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
                tgt = jnp.take_along_axis(logits, lbl[..., None],
                                          axis=-1)[..., 0]
                nll = lse - tgt.astype(jnp.float32)
                valid = last & (t >= S - 1)
                contrib = jnp.where(valid, (nll * msk).sum(), 0.0)
                toks = jnp.where(valid, msk.sum(), 0.0)

                # hand activation to the next stage
                buf_next = lax.ppermute(y, PIPE_AXIS, perm) if S > 1 else y
                return (buf_next, loss_sum + contrib, tok_sum + toks), None

            buf0 = jnp.zeros((mb, seq, cfg.d_model), dt)
            (_, loss_sum, tok_sum), _ = lax.scan(
                tick, (buf0, jnp.float32(0.0), jnp.float32(0.0)),
                jnp.arange(T))
            # reduce over the pipe axis (only the last stage contributed)
            # AND the batch axes — each data/fsdp shard saw different
            # samples, and the global loss is sum/sum, not shard 0's mean
            axes = (PIPE_AXIS,) + tuple(BATCH_AXES)
            loss_sum = lax.psum(loss_sum, axes)
            tok_sum = lax.psum(tok_sum, axes)
            return loss_sum / jnp.maximum(tok_sum, 1.0)

        blocks = params["blocks"]
        shared = {k: v for k, v in params.items() if k != "blocks"}

        blocks_specs = jax.tree.map(lambda _: P(PIPE_AXIS), blocks)
        shared_specs = jax.tree.map(lambda _: P(), shared)
        data_spec = P(BATCH_AXES)

        return shard_map(
            local, mesh=mesh,
            in_specs=(blocks_specs, shared_specs, data_spec, data_spec,
                      data_spec, data_spec),
            out_specs=P(),
            check_vma=False)(blocks, shared, ids, labels, tgt_mask, amask)

    return loss_fn
