"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native re-design of the reference pipeline stack
(``runtime/pipe/module.py:86`` PipelineModule layer partitioning,
``schedule.py:189`` TrainSchedule/1F1B instruction generator,
``pipe/engine.py:61`` PipelineEngine instruction interpreter with p2p
send/recv ``pipe/p2p.py:46``).

The reference interprets instruction lists per rank with explicit
send/recv.  Under SPMD there is no per-rank program: a pipeline schedule
is a single ``lax.scan`` over ticks inside one ``shard_map`` over the
``pipe`` axis.  Each tick every stage applies its layer slice and hands
its activation to the next stage via ``lax.ppermute`` — the instruction
schedule *is* the scan and the p2p layer *is* ppermute riding ICI
neighbor links.

Two schedules:

* **gpipe** — forward scan over ``M + S - 1`` ticks, backward by
  autodiff through the scan.  Simple, but reverse-mode saves every
  tick's boundary activation: live activation memory grows with M.
* **1f1b** — the reference TrainSchedule's memory behaviour
  (schedule.py:189: ``num_pipe_buffers = min(S - stage, M)`` :313),
  implemented as an *eager-gradient* custom VJP: the forward runs the
  interleaved fwd/bwd schedule itself (fwd of microbatch m at stage s on
  tick ``m + s``; its backward on tick ``m + 2(S-1) - s + 1``, i.e.
  immediately after the forward on the last stage), stashing only a ring
  of ``min(M, 2S - 1)`` boundary activations per stage and accumulating
  parameter gradients tick by tick.  ``jax.grad`` then merely scales the
  precomputed gradients — activation memory is O(S), independent of M.

Sequence parallelism composes: with ``seq > 1`` the sequence dim is
sharded across the same shard_map and attention runs the per-shard
Ulysses all-to-all (``parallel/sequence.make_ulysses_local``).

Layer placement: the model's stacked ``blocks`` (leading ``layers`` dim)
are sharded over ``pipe`` — contiguous equal slices, the 'uniform'
partition method of module.py:391.  Embedding/unembedding stay replicated
across stages (the reference's tied-layer broadcast, module.py:77; the
tied-weight gradient allreduce is the explicit PIPE psum of the shared
grads below / XLA's psum transpose under gpipe).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..compat import shard_map
from jax.sharding import PartitionSpec as P

from ..comm.mesh import BATCH_AXES, MeshTopology, PIPE_AXIS, SEQ_AXIS
from ..models import layers as L
from ..models.transformer import (TransformerConfig, block_apply,
                                  rolled_lm_targets, _norm)


def make_pipelined_loss_fn(cfg: TransformerConfig, topology: MeshTopology,
                           num_microbatches: int,
                           attention_fn: Callable = L.causal_attention,
                           schedule: str = "gpipe"):
    """Build ``loss_fn(params, batch, rng)`` running a pipeline schedule.

    Requirements: ``num_layers % pipe == 0``; the global micro-batch (the
    engine's per-step batch) divisible by ``num_microbatches``; with
    seq > 1, heads divisible by the seq axis (Ulysses constraint).
    """
    mesh = topology.mesh
    S = topology.pp_size
    M = num_microbatches
    sp = topology.sp_size
    if cfg.num_layers % S:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                         f"pipe stages {S}")
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(gpipe | 1f1b)")
    if cfg.position == "alibi":
        if sp > 1:
            # replace the model's plain ALiBi wrapper: under the
            # pipeline's manual seq axis the bias must slice the GLOBAL
            # slope series at this shard's head offset (the sp>1 branch
            # below then wraps it with the per-shard Ulysses a2a)
            from .sequence import make_ulysses_alibi_base
            attention_fn = make_ulysses_alibi_base(
                cfg.num_heads, sp, attn_scale=cfg.attn_scale)
        elif attention_fn is L.causal_attention:
            # direct callers that never resolved the model's attention:
            # the ALiBi bias (and any custom attn_scale) must not
            # silently vanish under PP — mirror _resolve_attention
            base = L.causal_attention
            if cfg.attn_scale is not None:
                s = cfg.attn_scale

                def base(q, k, v, mask=None, **kw):
                    return L.causal_attention(q, k, v, mask=mask,
                                              scale=s, **kw)
            attention_fn = L.make_alibi_attention(base)

    if sp > 1:
        if cfg.num_heads % sp or cfg.num_kv_heads % sp:
            raise ValueError(
                f"pipeline x seq needs heads divisible by seq axis: "
                f"H={cfg.num_heads}, Hkv={cfg.num_kv_heads}, seq={sp}")
        from .sequence import make_ulysses_local
        attention_fn = make_ulysses_local(attention_fn)

    norm = _norm(cfg)
    dp = topology.dp_world_size
    reduce_axes = (PIPE_AXIS,) + tuple(BATCH_AXES) + \
        ((SEQ_AXIS,) if sp > 1 else ())
    batch_reduce_axes = tuple(BATCH_AXES) + ((SEQ_AXIS,) if sp > 1 else ())
    data_spec = P(BATCH_AXES, SEQ_AXIS) if sp > 1 else P(BATCH_AXES)

    # ---------------------------------------------------------------- util
    def stage_fwd(blocks_local, x, attn_mask, cos, sin):
        """Apply this stage's layer slice.  Returns (x, aux) where aux is
        the mean MoE load-balancing loss over the local layers (0.0 for
        dense models)."""
        def body(h, lp):
            h, metrics = block_apply(cfg, lp, h, cos, sin, mask=attn_mask,
                                     attention_fn=attention_fn)
            aux = metrics.get("moe_aux_loss", jnp.float32(0.0)) \
                if metrics else jnp.float32(0.0)
            return h, aux
        body_fn = jax.checkpoint(body) if cfg.remat else body
        x, aux = lax.scan(body_fn, x, blocks_local)
        return x, jnp.mean(aux)

    def head_nll(shared, y, labels, msk):
        """Unembed + lse - target_logit loss sum (no fp32 [mb,S,V]
        buffer — same rationale as cross_entropy_loss)."""
        dt = shared["embed"]["table"].dtype
        h = norm(shared["ln_f"], y)
        if cfg.tie_embeddings:
            logits = h @ shared["embed"]["table"].astype(dt).T
        else:
            logits = h @ shared["lm_head"]["kernel"].astype(dt)
            if cfg.head_bias:
                logits = logits + shared["lm_head"]["bias"].astype(dt)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = lse - tgt.astype(jnp.float32)
        return (nll * msk).sum()

    def embed_in(shared, ids, pos0, seq_local):
        dt = shared["embed"]["table"].dtype
        x0 = L.embed(shared["embed"], ids).astype(dt)
        if cfg.embed_norm:          # bloom word_embeddings_layernorm
            x0 = norm(shared["ln_embed"], x0)
        if cfg.position == "learned":
            tab = lax.dynamic_slice_in_dim(shared["pos_embed"]["table"],
                                           pos0, seq_local)
            x0 = x0 + tab.astype(dt)
        return x0

    def rope_tables(pos0, seq_local):
        if cfg.position != "rope":
            return None, None
        cos, sin = L.rope_freqs(cfg.rotary_dim, cfg.max_seq_len,
                                cfg.rope_theta)
        return (lax.dynamic_slice_in_dim(cos, pos0, seq_local),
                lax.dynamic_slice_in_dim(sin, pos0, seq_local))

    def pos_offset(seq_local):
        if sp > 1:
            return lax.axis_index(SEQ_AXIS) * seq_local
        return 0

    def stage_ext(blocks_local, shared, x_in, ids, labels, msk, amask,
                  cos, sin, pos0, seq_local):
        """One stage's whole per-microbatch compute: (embed |
        passthrough) -> layer slice -> (loss head on the last stage).
        Differentiable in (blocks_local, shared, x_in)."""
        stage = lax.axis_index(PIPE_AXIS)
        first, last = stage == 0, stage == S - 1
        x0 = embed_in(shared, ids, pos0, seq_local)
        x = jnp.where(first, x0, x_in)
        y, aux = stage_fwd(blocks_local, x, amask, cos, sin)
        contrib = jnp.where(last, head_nll(shared, y, labels, msk), 0.0)
        return y, contrib, aux

    # ------------------------------------------------------------- shared
    def split_params(params):
        blocks = params["blocks"]
        shared = {k: v for k, v in params.items() if k != "blocks"}
        return blocks, shared

    def batch_views(ids, labels, tgt_mask, amask):
        B, seq_local = ids.shape
        mb = B // M
        return (ids.reshape(M, mb, seq_local),
                labels.reshape(M, mb, seq_local),
                tgt_mask.reshape(M, mb, seq_local),
                amask.reshape(M, mb, seq_local), mb, seq_local)

    def mb_slice(arrs, m):
        return tuple(lax.dynamic_index_in_dim(a, m, 0, keepdims=False)
                     for a in arrs)

    perm_down = [(i, i + 1) for i in range(S - 1)]
    perm_up = [(i + 1, i) for i in range(S - 1)]

    # ===================================================== gpipe schedule
    def gpipe_loss(params, batch, rng):
        ids = batch["input_ids"]
        B, seq = ids.shape
        if (B // dp) % M:
            raise ValueError(
                f"per-dp-shard batch {B}//{dp} not divisible by "
                f"num_microbatches {M}")
        amask = batch.get("attention_mask")
        labels, tgt_mask = rolled_lm_targets(ids, amask)
        if amask is None:
            amask = jnp.ones_like(ids, jnp.float32)

        def local(blocks, shared, ids, labels, tgt_mask, amask):
            stage = lax.axis_index(PIPE_AXIS)
            last = stage == S - 1
            dt = shared["embed"]["table"].dtype
            views = batch_views(ids, labels, tgt_mask, amask)
            ids_mb, labels_mb, mask_mb, amask_mb, mb, seq_local = views
            pos0 = pos_offset(seq_local)
            cos, sin = rope_tables(pos0, seq_local)

            T = M + S - 1

            def tick(carry, t):
                buf, loss_sum, tok_sum, aux_sum, aux_n = carry
                t_here = jnp.clip(t - stage, 0, M - 1)
                i, lbl, msk, am = mb_slice(
                    (ids_mb, labels_mb, mask_mb, amask_mb), t_here)
                y, contrib, aux = stage_ext(blocks, shared, buf, i, lbl,
                                            msk, am, cos, sin, pos0,
                                            seq_local)
                # the last stage processes microbatch t-(S-1) at tick t
                valid = last & (t >= S - 1)
                contrib = jnp.where(valid, contrib, 0.0)
                toks = jnp.where(valid, msk.sum(), 0.0)
                # every stage contributes its layers' MoE aux loss for
                # the microbatch it actually processed this tick
                a_valid = (t >= stage) & (t - stage < M)
                aux_sum = aux_sum + jnp.where(a_valid, aux, 0.0)
                aux_n = aux_n + a_valid.astype(jnp.float32)
                buf_next = lax.ppermute(y, PIPE_AXIS, perm_down) \
                    if S > 1 else y
                return (buf_next, loss_sum + contrib, tok_sum + toks,
                        aux_sum, aux_n), None

            buf0 = jnp.zeros((mb, seq_local, cfg.d_model), dt)
            (_, loss_sum, tok_sum, aux_sum, aux_n), _ = lax.scan(
                tick, (buf0, jnp.float32(0.0), jnp.float32(0.0),
                       jnp.float32(0.0), jnp.float32(0.0)),
                jnp.arange(T))
            loss_sum = lax.psum(loss_sum, reduce_axes)
            tok_sum = lax.psum(tok_sum, reduce_axes)
            loss = loss_sum / jnp.maximum(tok_sum, 1.0)
            if cfg.num_experts > 1:
                # mean over (stages x microbatches x data shards) of the
                # per-stage layer-mean aux loss (reference: l_aux summed
                # into the LM loss, sharded_moe.py)
                aux_sum = lax.psum(aux_sum, reduce_axes)
                aux_n = lax.psum(aux_n, reduce_axes)
                loss = loss + cfg.aux_loss_coef * (
                    aux_sum / jnp.maximum(aux_n, 1.0))
            return loss

        blocks, shared = split_params(params)
        blocks_specs = jax.tree.map(lambda _: P(PIPE_AXIS), blocks)
        shared_specs = jax.tree.map(lambda _: P(), shared)
        return shard_map(
            local, mesh=mesh,
            in_specs=(blocks_specs, shared_specs, data_spec, data_spec,
                      data_spec, data_spec),
            out_specs=P(),
            check_vma=False)(blocks, shared, ids, labels, tgt_mask, amask)

    if schedule == "gpipe":
        return gpipe_loss

    # ====================================================== 1f1b schedule
    # fwd of mb m at stage s on tick m+s; bwd on tick m + 2(S-1) - s + 1.
    # Ring of R = min(M, 2S-1) stashed boundary activations per stage.
    R = min(M, 2 * S - 1)
    T2 = M + 2 * S - 1

    def sched_local(blocks, shared, ids, labels, tgt_mask, amask):
        """Runs the full interleaved schedule; returns per-shard
        (loss_sum, tok_sum, grad_blocks, grad_shared), all psum'd."""
        stage = lax.axis_index(PIPE_AXIS)
        last = stage == S - 1
        dt = shared["embed"]["table"].dtype
        views = batch_views(ids, labels, tgt_mask, amask)
        ids_mb, labels_mb, mask_mb, amask_mb, mb, seq_local = views
        pos0 = pos_offset(seq_local)
        cos, sin = rope_tables(pos0, seq_local)

        def run_ext(x_in, m):
            i, lbl, msk, am = mb_slice(
                (ids_mb, labels_mb, mask_mb, amask_mb), m)
            return lambda b, sh, x: stage_ext(
                b, sh, x, i, lbl, msk, am, cos, sin, pos0, seq_local)

        # tokens and aux-slot counts are needed BEFORE the schedule so
        # the eager VJP can seed ALREADY-NORMALIZED cotangents — a single
        # cotangent chain then carries both the LM and the MoE aux terms
        # masks are REPLICATED across pipe — count them once per batch
        # (and seq) shard only
        tok_global = lax.psum(tgt_mask.sum().astype(jnp.float32),
                              batch_reduce_axes)
        inv_tok = 1.0 / jnp.maximum(tok_global, 1.0)
        # every (stage, microbatch, batch shard) contributes one aux value
        n_aux = float(M * S * dp * sp)
        aux_seed = (cfg.aux_loss_coef / n_aux) \
            if cfg.num_experts > 1 else 0.0

        def tick(carry, t):
            buf_f, buf_b, stash, gb, gsh, loss_sum, aux_acc = carry

            # ---- backward slot (reads stash BEFORE this tick's fwd write)
            m_b = t - 2 * (S - 1) + stage - 1
            b_active = (m_b >= 0) & (m_b < M)
            m_b_c = jnp.clip(m_b, 0, M - 1)
            x_st = lax.dynamic_index_in_dim(stash, m_b_c % R, 0,
                                            keepdims=False)
            fn = run_ext(x_st, m_b_c)
            _, pull = jax.vjp(fn, blocks, shared, x_st)
            seed_y = jnp.where(b_active, buf_b, jnp.zeros_like(buf_b))
            seed_c = jnp.where(b_active & last, inv_tok, 0.0)
            seed_a = jnp.where(b_active, jnp.float32(aux_seed), 0.0)
            gb_m, gsh_m, x_bar = pull((seed_y.astype(dt), seed_c, seed_a))
            act = b_active.astype(jnp.float32)
            gb = jax.tree.map(lambda a, g: a + act * g.astype(jnp.float32),
                              gb, gb_m)
            gsh = jax.tree.map(lambda a, g: a + act * g.astype(jnp.float32),
                               gsh, gsh_m)
            x_bar = jnp.where(b_active, x_bar, jnp.zeros_like(x_bar))

            # ---- forward slot
            m_f = t - stage
            f_active = (m_f >= 0) & (m_f < M)
            m_f_c = jnp.clip(m_f, 0, M - 1)
            fn_f = run_ext(buf_f, m_f_c)
            y, contrib, aux = fn_f(blocks, shared, buf_f)
            valid = last & f_active
            loss_sum = loss_sum + jnp.where(valid, contrib, 0.0)
            aux_acc = aux_acc + jnp.where(f_active, aux, 0.0)
            stash = stash.at[m_f_c % R].set(
                jnp.where(f_active, buf_f, stash[m_f_c % R]))

            # ---- hand off: activation down, cotangent up.  NOTE: these
            # and the slots' collectives are mutually independent; on the
            # virtual CPU mesh this requires the sequential thunk
            # scheduler (--xla_cpu_enable_concurrency_optimized_scheduler
            # =false, see tests/conftest.py) or the in-process rendezvous
            # can deadlock.  Real TPUs are unaffected.
            buf_f_next = lax.ppermute(y, PIPE_AXIS, perm_down) \
                if S > 1 else y
            buf_b_next = lax.ppermute(x_bar, PIPE_AXIS, perm_up) \
                if S > 1 else jnp.zeros_like(x_bar)
            return (buf_f_next, buf_b_next, stash, gb, gsh,
                    loss_sum, aux_acc), None

        zeros_f32 = lambda tree: jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), tree)
        buf0 = jnp.zeros((mb, seq_local, cfg.d_model), dt)
        stash0 = jnp.zeros((R, mb, seq_local, cfg.d_model), dt)
        carry0 = (buf0, jnp.zeros_like(buf0), stash0,
                  zeros_f32(blocks), zeros_f32(shared),
                  jnp.float32(0.0), jnp.float32(0.0))
        (_, _, _, gb, gsh, loss_sum, aux_acc), _ = lax.scan(
            tick, carry0, jnp.arange(T2))

        # blocks grads: each stage owns its slice — reduce over data axes
        # only; shared grads: reduce over everything incl. pipe (the tied
        # embed/head gradient allreduce of module.py:77)
        loss = lax.psum(loss_sum, reduce_axes) * inv_tok
        if cfg.num_experts > 1:
            loss = loss + cfg.aux_loss_coef * \
                lax.psum(aux_acc, reduce_axes) / n_aux
        gb = jax.tree.map(lambda g: lax.psum(g, batch_reduce_axes), gb)
        gsh = jax.tree.map(lambda g: lax.psum(g, reduce_axes), gsh)
        return loss, gb, gsh

    def run_sched(params, batch):
        ids = batch["input_ids"]
        B, seq = ids.shape
        if (B // dp) % M:
            raise ValueError(
                f"per-dp-shard batch {B}//{dp} not divisible by "
                f"num_microbatches {M}")
        amask = batch.get("attention_mask")
        labels, tgt_mask = rolled_lm_targets(ids, amask)
        if amask is None:
            amask = jnp.ones_like(ids, jnp.float32)
        blocks, shared = split_params(params)
        blocks_specs = jax.tree.map(lambda _: P(PIPE_AXIS), blocks)
        shared_specs = jax.tree.map(lambda _: P(), shared)
        loss, gb, gsh = shard_map(
            sched_local, mesh=mesh,
            in_specs=(blocks_specs, shared_specs, data_spec, data_spec,
                      data_spec, data_spec),
            out_specs=(P(), blocks_specs, shared_specs),
            check_vma=False)(blocks, shared, ids, labels, tgt_mask, amask)
        grads = dict(gsh)
        grads["blocks"] = gb
        # cotangents were seeded pre-normalized (1/tokens for the LM
        # term, coef/n_aux for MoE) — grads are d(loss)/dp directly
        return loss, grads

    @jax.custom_vjp
    def loss_1f1b(params, batch):
        loss, _ = run_sched(params, batch)
        return loss

    def loss_1f1b_fwd(params, batch):
        loss, grads = run_sched(params, batch)
        aval = lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        return loss, (grads, jax.tree.map(aval, params),
                      jax.tree.map(aval, batch))

    def loss_1f1b_bwd(res, g):
        grads, pavals, bavals = res
        pbar = jax.tree.map(lambda gr, a: (g * gr).astype(a.dtype),
                            grads, pavals)
        # batch cotangents are never consumed (grad is taken w.r.t.
        # params only): float0 for integer leaves, zeros for float ones
        bbar = jax.tree.map(
            lambda a: np.zeros(a.shape, jax.dtypes.float0)
            if jnp.issubdtype(a.dtype, jnp.integer)
            or jnp.issubdtype(a.dtype, jnp.bool_)
            else jnp.zeros(a.shape, a.dtype), bavals)
        return pbar, bbar

    loss_1f1b.defvjp(loss_1f1b_fwd, loss_1f1b_bwd)

    def loss_fn(params, batch, rng):
        return loss_1f1b(params, batch)

    # forward-only evaluation path: loss_1f1b's primal runs the FULL
    # interleaved schedule (per-tick vjp pullbacks + param-grad
    # accumulation) even when nobody wants gradients; eval_batch uses
    # the gpipe forward instead (same loss, ~half the FLOPs, O(1)
    # activation memory)
    loss_fn.eval_fn = gpipe_loss
    return loss_fn
