"""Mixture-of-Experts: top-k gating + expert-parallel dispatch.

TPU-native re-design of the reference MoE stack
(``deepspeed/moe/layer.py:17`` MoE, ``moe/sharded_moe.py`` — ``TopKGate``
:374, top-1/2/k gating with capacity/jitter/RSample :183-449, ``MOELayer``
einsum dispatch → all_to_all → local experts → all_to_all → combine :533,
``_AllToAll`` autograd :96, ``Experts`` moe/experts.py:13).

Here the dispatch is the GShard dense-einsum formulation: build
``dispatch [T,E,C]`` / ``combine [T,E,C]`` masks from the gate top-k with
per-expert capacity, then

    expert_in  = einsum('tec,td->ecd', dispatch, x)     # XLA: all_to_all
    expert_out = ff_e(expert_in)                        # E sharded on mesh
    y          = einsum('tec,ecd->td', combine, expert_out)

With expert weights sharded over the ``expert`` mesh axis and tokens over
the batch axes, the SPMD partitioner inserts exactly the reference's
all_to_all pair.  Capacity keeps every shape static (XLA requirement —
and the reference drops tokens the same way, sharded_moe.py).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class GateOutput(NamedTuple):
    dispatch: jnp.ndarray   # [T, E, C] float (0/1)
    combine: jnp.ndarray    # [T, E, C] float (gate weights)
    aux_loss: jnp.ndarray   # scalar load-balancing loss
    dropped: jnp.ndarray    # scalar fraction of tokens dropped


def top_k_gating(logits: jnp.ndarray, top_k: int, capacity: int,
                 rng: Optional[jax.Array] = None,
                 noise_policy: Optional[str] = None,
                 norm_topk: bool = True) -> GateOutput:
    """logits: [T, E].  (reference: top1gating/top2gating/topkgating
    sharded_moe.py:183,290,449)."""
    T, E = logits.shape
    if noise_policy == "RSample" and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) / E

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [T, E]

    # iterative top-k: mask out previous choices
    dispatch_parts = []
    combine_parts = []
    remaining = gates
    sel_masks = []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                      # [T]
        sel = jax.nn.one_hot(idx, E, dtype=jnp.float32)           # [T, E]
        sel_masks.append(sel)
        remaining = remaining * (1.0 - sel)

    # aux loss from the top-1 assignment (Switch/GShard style,
    # reference sharded_moe.py l_aux)
    me = gates.mean(axis=0)                                       # [E]
    ce = sel_masks[0].mean(axis=0)                                # [E]
    aux_loss = (me * ce).sum() * E

    # capacity assignment: position of each token within its expert,
    # counting across all k choices in priority order
    prev_counts = jnp.zeros((E,), jnp.float32)
    kept_any = jnp.zeros((T,), jnp.float32)
    for sel in sel_masks:
        pos = jnp.cumsum(sel, axis=0) - 1.0 + prev_counts[None, :]  # [T, E]
        keep = sel * (pos < capacity)
        pos_idx = (pos * keep).astype(jnp.int32)
        disp = keep[:, :, None] * jax.nn.one_hot(
            pos_idx, capacity, dtype=jnp.float32)
        gate_val = (gates * keep).sum(axis=-1, keepdims=True)     # [T, 1]
        dispatch_parts.append(disp)
        combine_parts.append(disp * gate_val[:, :, None])
        prev_counts = prev_counts + sel.sum(axis=0)
        kept_any = jnp.maximum(kept_any, keep.sum(axis=-1))

    dispatch = sum(dispatch_parts)
    combine = sum(combine_parts)
    if top_k > 1 and norm_topk:
        # renormalize kept gate weights to sum 1 per token (reference: top2
        # normalization sharded_moe.py:290; top-1 keeps the raw probability
        # as in Switch / reference top1gating; qwen2-moe's
        # norm_topk_prob=False keeps the raw softmax probabilities)
        denom = combine.sum(axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    dropped = 1.0 - kept_any.mean()
    return GateOutput(dispatch=dispatch, combine=combine,
                      aux_loss=aux_loss, dropped=dropped)


class SparseGateOutput(NamedTuple):
    """Index-form gating (the megablox-style dispatch): one (expert,
    slot, weight) triple per (token, choice) instead of [T, E, C]
    one-hot masks."""
    ids: jnp.ndarray        # [T, K] i32 expert per choice
    pos: jnp.ndarray        # [T, K] i32 slot within the expert (== C when
                            #            dropped — scatter mode="drop")
    vals: jnp.ndarray       # [T, K] f32 gate weights (0 when dropped)
    aux_loss: jnp.ndarray
    dropped: jnp.ndarray


def top_k_gating_sparse(logits: jnp.ndarray, top_k: int, capacity: int,
                        rng: Optional[jax.Array] = None,
                        noise_policy: Optional[str] = None,
                        norm_topk: bool = True) -> SparseGateOutput:
    """Same selection/capacity/renormalization math as
    :func:`top_k_gating`, returning indices instead of one-hot masks —
    dispatch/combine become gather/scatter (O(T·K·d)) instead of
    mask einsums (O(T·E·C·d)), the dense-mask cost the reference pays in
    sharded_moe.py:533 and solves with the cutlass moe_gemm
    (inference/v2/kernels/cutlass_ops) — here the index form IS the
    XLA-friendly kernel."""
    T, E = logits.shape
    if noise_policy == "RSample" and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) / E

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [T, E]

    remaining = gates
    sel_masks = []
    ids = []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                      # [T]
        sel = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        sel_masks.append(sel)
        ids.append(idx.astype(jnp.int32))
        remaining = remaining * (1.0 - sel)

    me = gates.mean(axis=0)
    ce = sel_masks[0].mean(axis=0)
    aux_loss = (me * ce).sum() * E

    prev_counts = jnp.zeros((E,), jnp.float32)
    kept_any = jnp.zeros((T,), jnp.float32)
    pos_list, val_list = [], []
    for k, sel in enumerate(sel_masks):
        pos = jnp.cumsum(sel, axis=0) - 1.0 + prev_counts[None, :]
        keep = sel * (pos < capacity)
        pos_t = (pos * sel).sum(axis=-1)                          # [T]
        kept_t = keep.sum(axis=-1)                                # [T]
        gate_val = (gates * keep).sum(axis=-1)                    # [T]
        # dropped choices point at slot C — scatters with mode="drop"
        # discard them, gathers never see them (vals = 0)
        pos_list.append(jnp.where(kept_t > 0, pos_t,
                                  float(capacity)).astype(jnp.int32))
        val_list.append(gate_val)
        prev_counts = prev_counts + sel.sum(axis=0)
        kept_any = jnp.maximum(kept_any, kept_t)

    vals = jnp.stack(val_list, axis=1)                            # [T, K]
    if top_k > 1 and norm_topk:
        vals = vals / jnp.maximum(vals.sum(axis=1, keepdims=True), 1e-9)
    return SparseGateOutput(
        ids=jnp.stack(ids, axis=1), pos=jnp.stack(pos_list, axis=1),
        vals=vals, aux_loss=aux_loss, dropped=1.0 - kept_any.mean())


def capacity_for(tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float, min_capacity: int = 4) -> int:
    """(reference: _capacity sharded_moe.py)."""
    cap = int(math.ceil(tokens * top_k * capacity_factor / num_experts))
    return max(cap, min_capacity)


# --------------------------------------------------------------------------
# Expert FFN params (stacked on a leading expert dim)
# --------------------------------------------------------------------------

def experts_init(key, num_experts: int, d_model: int, d_ff: int,
                 gated: bool = False, out_scale: float = None):
    """Params [E, ...] with logical axes led by 'expert'
    (reference: Experts moe/experts.py:13 — a python list of FFNs; here one
    stacked tensor so a single grouped matmul serves all local experts)."""
    out_scale = out_scale or 1.0 / math.sqrt(d_ff)
    k1, k2, k3 = jax.random.split(key, 3)
    p = {"wi": jax.random.normal(k1, (num_experts, d_model, d_ff))
         / math.sqrt(d_model),
         "wo": jax.random.normal(k2, (num_experts, d_ff, d_model)) * out_scale}
    a = {"wi": ("expert", "embed", "mlp"), "wo": ("expert", "mlp", "embed")}
    if gated:
        p["wg"] = jax.random.normal(k3, (num_experts, d_model, d_ff)) \
            / math.sqrt(d_model)
        a["wg"] = ("expert", "embed", "mlp")
    return p, a


def experts_apply(p, x, activation, gated: bool = False):
    """x: [E, C, d_model] -> [E, C, d_model]; one grouped matmul per
    projection (megablox-style grouped GEMM is the Pallas upgrade path,
    reference cutlass moe_gemm)."""
    dt = x.dtype
    u = jnp.einsum("ecd,edf->ecf", x, p["wi"].astype(dt))
    if gated:
        u = activation(jnp.einsum("ecd,edf->ecf", x, p["wg"].astype(dt))) * u
    else:
        u = activation(u)
    return jnp.einsum("ecf,efd->ecd", u, p["wo"].astype(dt))


def gate_init(key, d_model: int, num_experts: int):
    return ({"kernel": jax.random.normal(key, (d_model, num_experts)) * 0.01},
            {"kernel": ("embed", None)})


def _ragged_moe(expert_p, x, logits, *, top_k: int, activation, gated: bool,
                norm_topk: bool = True,
                noise_policy: Optional[str], rng: Optional[jax.Array],
                dt) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """DROPLESS grouped-GEMM MoE (``dispatch_mode="ragged"``): tokens
    sort by assigned expert and each projection is ONE
    ``jax.lax.ragged_dot`` over per-expert row groups — the megablox
    formulation, and the TPU answer to the reference's cutlass grouped
    GEMMs (inference/v2/kernels/cutlass_ops/mixed_gemm + moe_gemm): no
    capacity padding, no dropped tokens, MXU-shaped contiguous groups.

    Expert weights must be locally addressable (replicated or
    fsdp-memory-sharded); expert-parallel meshes keep the
    scatter/einsum dispatch whose all-to-all GSPMD understands."""
    B, S, dm = x.shape
    T = B * S
    E = logits.shape[-1]
    lf = logits.reshape(T, E)
    if noise_policy == "RSample" and rng is not None:
        lf = lf + jax.random.normal(rng, lf.shape) / E
    gates = jax.nn.softmax(lf.astype(jnp.float32), axis=-1)       # [T, E]

    remaining = gates
    ids, vals = [], []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                      # [T]
        ids.append(idx)
        vals.append(jnp.take_along_axis(gates, idx[:, None],
                                        axis=1)[:, 0])
        remaining = remaining * (1.0 - jax.nn.one_hot(idx, E,
                                                      dtype=jnp.float32))
    ids = jnp.stack(ids, axis=1)                                  # [T, K]
    vals = jnp.stack(vals, axis=1)                                # [T, K]
    if top_k > 1 and norm_topk:
        # renormalize to sum 1 per token — same convention as
        # top_k_gating (reference top2 normalization sharded_moe.py:290)
        vals = vals / jnp.maximum(vals.sum(axis=1, keepdims=True), 1e-9)
    me = gates.mean(axis=0)
    ce = jax.nn.one_hot(ids[:, 0], E, dtype=jnp.float32).mean(axis=0)
    aux_loss = (me * ce).sum() * E

    flat_ids = ids.reshape(-1)                                    # [T*K]
    order = jnp.argsort(flat_ids, stable=True)
    tok = order // top_k                                          # [T*K]
    xs = x.reshape(T, dm)[tok].astype(dt)
    group_sizes = jnp.zeros((E,), jnp.int32).at[flat_ids].add(1)

    u = jax.lax.ragged_dot(xs, expert_p["wi"].astype(dt), group_sizes)
    if gated:
        g = jax.lax.ragged_dot(xs, expert_p["wg"].astype(dt), group_sizes)
        u = activation(g) * u
    else:
        u = activation(u)
    out = jax.lax.ragged_dot(u, expert_p["wo"].astype(dt), group_sizes)

    w = vals.reshape(-1)[order].astype(dt)
    y = jnp.zeros((T, dm), dt).at[tok].add(out * w[:, None])
    return y.reshape(B, S, dm), {
        "moe_aux_loss": aux_loss,
        "moe_dropped": jnp.float32(0.0)}


def moe_ffn(gate_p, expert_p, x, *, top_k: int, capacity_factor: float,
            min_capacity: int = 4, activation=jax.nn.gelu,
            gated: bool = False, rng: Optional[jax.Array] = None,
            noise_policy: Optional[str] = None,
            dispatch_mode: str = "scatter",
            norm_topk: bool = True
            ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Full MoE FFN over x [B, S, d_model] (reference: MOELayer.forward
    sharded_moe.py:533).  Returns (y, metrics) with metrics carrying the
    aux load-balancing loss.

    Tokens are gated **per group** (one group per sequence, the GShard
    grouping) so dispatch state is linear in total tokens (Cg is the
    per-group capacity).

    ``dispatch_mode="scatter"`` (default) is the megablox-style index
    form: dispatch is a scatter of token ids into [E, Cg] slots and a
    gather, combine a K-way weighted gather — O(T·K·d) data movement.
    ``"einsum"`` is the GShard dense-mask formulation (one-hot
    [Tg, E, Cg] masks contracted against activations — O(T·E·Cg·d), the
    cost the reference's cutlass moe_gemm kernels exist to avoid); kept
    as the executable specification the scatter path is tested against.
    ``"ragged"`` is the DROPLESS megablox-style grouped GEMM
    (``jax.lax.ragged_dot`` over expert-sorted tokens — no capacity, no
    drops; see :func:`_ragged_moe`).

    Measured (mixtral-ish shapes, E8 d1024 ff3584 T16k): equal step time
    on a v5e, but the scatter form compiles to 2.4x less temp memory
    (420 vs 1007 MB on the CPU-mesh compile) — hence the default.
    """
    B, S, dm = x.shape
    E = expert_p["wi"].shape[0]
    cap = capacity_for(S, E, top_k, capacity_factor, min_capacity)
    if noise_policy == "Jitter" and rng is not None:
        # jitter gets its own stream: reusing ``rng`` here would
        # correlate the input jitter with the gating noise drawn below
        jitter_rng, rng = jax.random.split(rng)
        xg = x * jax.random.uniform(jitter_rng, x.shape,
                                    minval=0.98, maxval=1.02)
    else:
        xg = x
    logits = jnp.einsum("gtd,de->gte", xg, gate_p["kernel"].astype(x.dtype))
    dt = x.dtype
    if dispatch_mode == "ragged":
        return _ragged_moe(expert_p, x, logits, top_k=top_k,
                           activation=activation, gated=gated,
                           noise_policy=noise_policy, rng=rng, dt=dt,
                           norm_topk=norm_topk)
    rngs = jax.random.split(rng, B) if rng is not None else None

    gate_fn = functools.partial(
        top_k_gating_sparse if dispatch_mode == "scatter" else top_k_gating,
        top_k=top_k, capacity=cap, noise_policy=noise_policy,
        norm_topk=norm_topk)
    if rngs is None:
        gate = jax.vmap(lambda l: gate_fn(l, rng=None))(logits)
    else:
        gate = jax.vmap(lambda l, r: gate_fn(l, rng=r))(logits, rngs)

    if dispatch_mode == "scatter":
        def dispatch_group(ids, pos, x_g):
            # token index per (expert, slot); empty slots point at token
            # 0 with zero validity
            tok = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[:, None], ids.shape)
            slot_tok = jnp.zeros((E, cap), jnp.int32).at[
                ids, pos].set(tok, mode="drop")
            valid = jnp.zeros((E, cap), dt).at[
                ids, pos].set(jnp.ones_like(tok, dt), mode="drop")
            return x_g[slot_tok] * valid[..., None]

        expert_in = jax.vmap(dispatch_group, in_axes=(0, 0, 0),
                             out_axes=1)(gate.ids, gate.pos, x)
        expert_in = expert_in.reshape(E, B * cap, dm)
        expert_out = experts_apply(expert_p, expert_in, activation, gated)
        expert_out = expert_out.reshape(E, B, cap, dm)

        def combine_group(ids, pos, vals, eo_g):
            # eo_g: [E, Cg, d]; K-way weighted gather per token
            safe_pos = jnp.minimum(pos, cap - 1)
            picked = eo_g[ids, safe_pos]                  # [Tg, K, d]
            return (picked * vals[..., None].astype(dt)).sum(axis=1)

        y = jax.vmap(combine_group, in_axes=(0, 0, 0, 1))(
            gate.ids, gate.pos, gate.vals, expert_out)
    else:
        # [G,Tg,E,Cg] x [G,Tg,d] -> [E, G*Cg, d]; SPMD: the all_to_all
        expert_in = jnp.einsum("gtec,gtd->egcd", gate.dispatch.astype(dt), x)
        expert_in = expert_in.reshape(E, B * cap, dm)
        expert_out = experts_apply(expert_p, expert_in, activation, gated)
        expert_out = expert_out.reshape(E, B, cap, dm)
        y = jnp.einsum("gtec,egcd->gtd", gate.combine.astype(dt), expert_out)
    return y, {"moe_aux_loss": gate.aux_loss.mean(),
               "moe_dropped": gate.dropped.mean()}
