"""Logical-axis sharding rules.

TPU-native replacement for the reference's parameter-partitioning machinery
(``runtime/zero/partition_parameters.py``, ``module_inject/auto_tp.py:30``
``ReplaceWithTensorSlicing``, and the v2 declarative sharding helpers
``inference/v2/model_implementations/sharding/``).  Instead of slicing
tensors imperatively, every parameter carries a tuple of *logical axis
names* (``('embed', 'mlp')`` …), and a table of rules maps logical axes to
mesh axes.  ``jax.jit`` + XLA SPMD then insert all gathers/reduce-scatters.

This is the idiomatic TPU formulation (T5X/MaxText-style); combined with the
ZeRO stage policy in :mod:`deepspeed_tpu.parallel.zero` it reproduces the
reference's DP/TP/ZeRO behaviors declaratively.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comm.mesh import (AXIS_ORDER, DATA_AXIS, EXPERT_AXIS, FSDP_AXIS,
                         MeshTopology, PIPE_AXIS, SEQ_AXIS, TENSOR_AXIS)

# A logical axis annotation: tuple of names, one per tensor dim (None = never shard)
LogicalAxes = Tuple[Optional[str], ...]

# Default logical->mesh rules (tensor parallelism).  Multiple candidates are
# tried in order; first mesh axis with size>1 that still divides wins.
DEFAULT_RULES: Dict[str, Sequence[str]] = {
    # activations / batch-like
    "batch": (DATA_AXIS, FSDP_AXIS),
    "seq": (SEQ_AXIS,),
    # stacked layer dim: pipeline stages own contiguous layer slices
    "layers": (PIPE_AXIS,),
    # parameter axes
    "vocab": (TENSOR_AXIS,),
    "embed": (),                      # residual stream: replicated under TP
    "mlp": (TENSOR_AXIS,),            # MLP hidden (column-parallel in, row-parallel out)
    "heads": (TENSOR_AXIS,),          # attention heads (Megatron-style head split)
    "kv_heads": (TENSOR_AXIS,),
    "head_dim": (),
    "expert": (EXPERT_AXIS,),         # MoE expert dimension
    "norm": (),
    "conv_in": (), "conv_out": (TENSOR_AXIS,), "conv_k": (),
}


def spec_for_axes(axes: LogicalAxes, rules: Optional[Dict[str, Sequence[str]]],
                  topology: MeshTopology, shape: Optional[Tuple[int, ...]] = None) -> P:
    """Map one parameter's logical axes to a PartitionSpec under `rules`.

    A mesh axis is only assigned once per spec and only if it has size > 1
    (size-1 axes would be no-ops but pollute the spec) and, when `shape` is
    given, only if it divides the dim size.
    """
    rules = {**DEFAULT_RULES, **(rules or {})}
    used = set()
    entries = []
    for i, name in enumerate(axes):
        assigned = None
        for mesh_axis in rules.get(name, ()) if name else ():
            size = topology.axis_sizes.get(mesh_axis, 1)
            if mesh_axis in used or size <= 1:
                continue
            if shape is not None and shape[i] % size != 0:
                continue
            assigned = mesh_axis
            used.add(mesh_axis)
            break
        entries.append(assigned)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def add_fsdp_to_spec(spec: P, shape: Tuple[int, ...], topology: MeshTopology,
                     min_size: int = 0, axis: str = FSDP_AXIS) -> P:
    """Layer ZeRO/FSDP sharding on top of a TP spec: shard the largest
    still-unsharded dim that the fsdp axis size divides (reference analog:
    flat 1-D partitioning in stage_1_and_2.py:646 / stage3 — but on TPU we
    shard a real tensor dim so XLA can gather lazily per use)."""
    n = topology.axis_sizes.get(axis, 1)
    if n <= 1 or int(np.prod(shape)) < max(min_size, 1):
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    # candidate dims: not already sharded; divisible by n after existing shards
    best, best_size = None, 0
    for i, dim in enumerate(shape):
        cur = entries[i]
        cur_axes = (cur,) if isinstance(cur, str) else tuple(cur or ())
        if axis in cur_axes:
            return spec
        denom = 1
        for a in cur_axes:
            denom *= topology.axis_sizes.get(a, 1)
        local = dim // denom
        if local % n == 0 and local > best_size:
            best, best_size = i, local
    if best is None:
        return spec
    cur = entries[best]
    if cur is None:
        entries[best] = axis
    elif isinstance(cur, str):
        entries[best] = (cur, axis)
    else:
        entries[best] = tuple(cur) + (axis,)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def tree_specs(axes_tree: Any, topology: MeshTopology,
               rules: Optional[Dict[str, Sequence[str]]] = None,
               shapes: Any = None) -> Any:
    """Map a pytree of LogicalAxes (+ optional matching shapes tree) to specs."""
    if shapes is None:
        return jax.tree.map(
            lambda ax: spec_for_axes(ax, rules, topology),
            axes_tree, is_leaf=lambda x: isinstance(x, tuple) and
            all(e is None or isinstance(e, str) for e in x))
    return jax.tree.map(
        lambda ax, sh: spec_for_axes(ax, rules, topology, tuple(sh)),
        axes_tree, shapes,
        is_leaf=lambda x: isinstance(x, tuple) and
        all(e is None or isinstance(e, str) for e in x))


def named(topology: MeshTopology, spec: P) -> NamedSharding:
    return NamedSharding(topology.mesh, spec)


def tree_named(topology: MeshTopology, spec_tree: Any) -> Any:
    return jax.tree.map(lambda s: NamedSharding(topology.mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def infer_logical_axes(params: Any) -> Any:
    """Fallback when a model provides no logical axes: mark every dim None
    (replicated under TP; fsdp layering still applies by shape)."""
    return jax.tree.map(lambda p: tuple([None] * np.ndim(p)), params)
