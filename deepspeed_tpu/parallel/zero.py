"""ZeRO stages as declarative sharding policy.

TPU-native re-design of the reference's ZeRO optimizers
(``runtime/zero/stage_1_and_2.py:96`` — flat-buffer partitioning, grad-hook
IPG bucketing, ``stage3.py:109`` — hook-driven param gather/release).  Under
XLA SPMD none of that machinery exists: each ZeRO stage is simply a choice of
PartitionSpecs for (params, grads, optimizer state) over the ``fsdp`` mesh
axis, and the partitioner inserts exactly the collectives the reference
hand-codes:

* stage 0 — everything replicated; grads psum over data+fsdp.
* stage 1 — master/opt state sharded over fsdp; compute params replicated.
            XLA emits grad all-reduce + sharded update + param all-gather —
            the same comm pattern as stage_1_and_2.py step (:1823).
* stage 2 — grads also sharded over fsdp: XLA emits reduce-scatter instead
            of all-reduce at the GAS boundary (reduce_ipg_grads :1364).
* stage 3 — compute params sharded too: XLA inserts per-use all-gathers in
            forward/backward, freeing full params between uses (the
            fetch/release of partitioned_param_coordinator.py:262 becomes
            compiler-scheduled, overlapped with compute automatically).

ZeRO++-style variants:
* hpZ (secondary partition, ``zero_hpz_partition_size``) — params shard over
  an *intra-slice* subaxis so the backward all-gather never crosses DCN.
* qwZ/qgZ (quantized collectives) — see deepspeed_tpu/ops/quant.py; applied
  inside manual shard_map collectives when enabled.

Small parameters stay replicated below ``param_persistence_threshold``
(reference: stage3 persistence threshold).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..comm.mesh import FSDP_AXIS, MeshTopology
from ..config.config import ZeroConfig
from . import sharding as shd


@dataclass
class ZeroPolicy:
    """Resolved sharding policy for one training run."""

    stage: int
    topology: MeshTopology
    rules: Optional[Dict[str, Sequence[str]]] = None
    param_persistence_threshold: int = 10_000
    # ZeRO-Offload shards masters over the *full* DP world (data x fsdp),
    # like the reference partitions optimizer state across all DP ranks
    # (stage_1_and_2.py:646): minimises host DRAM per rank and keeps every
    # leaf partitioned, which XLA host-memory placement requires.
    offload: bool = False
    # hpZ (ZeRO++ secondary partition, zero_hpz_partition_size): compute
    # params shard over the small intra-slice fsdp axis (cheap ICI
    # gathers) while master/opt/grads shard over the full data x fsdp
    # world — the engine shrinks the fsdp axis to the hpz size and folds
    # the rest into data (reference: ds_secondary_tensor, groups.py:529).
    hpz: bool = False

    @classmethod
    def from_config(cls, zcfg: ZeroConfig, topology: MeshTopology,
                    rules: Optional[Dict[str, Sequence[str]]] = None) -> "ZeroPolicy":
        return cls(stage=zcfg.stage, topology=topology, rules=rules,
                   param_persistence_threshold=zcfg.param_persistence_threshold,
                   # cpu: host-DRAM minimization; nvme: per-rank swap
                   # fragments (each process stores/updates only its own
                   # data x fsdp shard — stage3.py:614 per-rank swap)
                   offload=zcfg.offload_optimizer.device in ("cpu", "nvme"),
                   hpz=zcfg.zero_hpz_partition_size > 1)

    # ---- spec builders ---------------------------------------------------
    def _tp_spec(self, axes, shape) -> P:
        return shd.spec_for_axes(axes, self.rules, self.topology, shape)

    def param_spec(self, axes, shape) -> P:
        """Compute-parameter sharding (what forward/backward sees)."""
        spec = self._tp_spec(axes, shape)
        if self.stage >= 3:
            spec = shd.add_fsdp_to_spec(spec, shape, self.topology,
                                        min_size=self.param_persistence_threshold)
        return spec

    def master_spec(self, axes, shape) -> P:
        """fp32 master params + optimizer moments: sharded from stage 1 on."""
        spec = self._tp_spec(axes, shape)
        if self.stage >= 1:
            spec = shd.add_fsdp_to_spec(spec, shape, self.topology, min_size=0)
        if self.offload or self.hpz:
            spec = shd.add_fsdp_to_spec(spec, shape, self.topology, min_size=0,
                                        axis=shd.DATA_AXIS)
        return spec

    def grad_spec(self, axes, shape) -> P:
        """Gradient sharding at the reduction boundary: stage >=2 shards
        (reduce-scatter); below that grads follow the compute params."""
        if self.stage >= 2:
            return self.master_spec(axes, shape)
        return self.param_spec(axes, shape)

    # ---- tree level ------------------------------------------------------
    def tree_param_specs(self, axes_tree, params) -> Any:
        return _tree_zip_specs(self.param_spec, axes_tree, params)

    def tree_master_specs(self, axes_tree, params) -> Any:
        return _tree_zip_specs(self.master_spec, axes_tree, params)

    def tree_grad_specs(self, axes_tree, params) -> Any:
        return _tree_zip_specs(self.grad_spec, axes_tree, params)

    def tree_named(self, spec_tree) -> Any:
        return jax.tree.map(
            lambda s: NamedSharding(self.topology.mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, P))


def _is_axes(x) -> bool:
    return isinstance(x, tuple) and all(e is None or isinstance(e, str) for e in x)


def _tree_zip_specs(fn, axes_tree, params):
    return jax.tree.map(
        lambda ax, p: fn(ax, tuple(np.shape(p))),
        axes_tree, params, is_leaf=lambda x: _is_axes(x))


def shard_count(topology: MeshTopology) -> int:
    return topology.axis_sizes.get(FSDP_AXIS, 1)
