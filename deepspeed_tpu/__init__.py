"""deepspeed_tpu — a TPU-native distributed training & inference framework.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the
DeepSpeed reference (see SURVEY.md): config-driven engine, ZeRO-style
sharded training over a named device mesh, pipeline/tensor/sequence/expert
parallelism, mixed precision, offload, checkpointing, and ragged-batch
inference.
"""

__version__ = "0.1.0"

from .config import Config, load_config                     # noqa: F401
from .comm import MeshTopology, init_distributed            # noqa: F401
from .platform import get_platform                          # noqa: F401


def initialize(*args, **kwargs):
    """Build a training engine (reference: deepspeed.initialize,
    deepspeed/__init__.py:69).  Lazy import keeps base import light."""
    from .runtime.engine import initialize as _init

    return _init(*args, **kwargs)


def HybridEngine(*args, **kwargs):
    """Train + fast-generate on shared weights for RLHF (reference:
    deepspeed.runtime.hybrid_engine.DeepSpeedHybridEngine)."""
    from .runtime.hybrid_engine import HybridEngine as _HE

    return _HE(*args, **kwargs)
