"""Launcher / elasticity / OptimizedLinear / compression tests
(reference analogs: tests/unit/launcher/, tests/unit/elasticity/,
tests/unit/linear/, tests/unit/compression/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds


class TestLauncher:
    def test_parse_hostfile(self):
        from deepspeed_tpu.launcher import parse_hostfile

        hosts = parse_hostfile("""
        # comment
        worker-0 slots=4
        worker-1 slots=4
        worker-2
        """)
        assert list(hosts) == ["worker-0", "worker-1", "worker-2"]
        assert hosts["worker-0"] == 4 and hosts["worker-2"] == 1

    def test_include_exclude(self):
        from deepspeed_tpu.launcher import (parse_hostfile,
                                            parse_inclusion_exclusion)

        hosts = parse_hostfile("\n".join(
            f"worker-{i} slots=4" for i in range(4)))
        inc = parse_inclusion_exclusion(hosts, include="worker-[0-1]")
        assert list(inc) == ["worker-0", "worker-1"]
        exc = parse_inclusion_exclusion(hosts, exclude="worker-3")
        assert list(exc) == ["worker-0", "worker-1", "worker-2"]
        slot = parse_inclusion_exclusion(hosts, include="worker-0:0,1")
        assert slot == {"worker-0": 2}
        with pytest.raises(ValueError):
            parse_inclusion_exclusion(hosts, include="a", exclude="b")

    def test_runner_commands(self, tmp_path):
        from deepspeed_tpu.launcher.runner import (SSHRunner, build_parser,
                                                   parse_hostfile)

        args = build_parser().parse_args(
            ["--master_port", "12345", "train.py", "--lr", "0.1"])
        hosts = parse_hostfile("h0 slots=1\nh1 slots=1")
        r = SSHRunner(args, hosts)
        cmds = r.launch_cmds()
        assert len(cmds) == 2
        host, cmd = cmds[1]
        joined = " ".join(cmd)
        assert cmd[0] == "ssh" and host == "h1"
        assert "DSPD_PROCESS_ID=1" in joined
        assert "DSPD_NUM_PROCESSES=2" in joined
        assert "h0:12345" in joined          # coordinator = first host
        assert "train.py --lr 0.1" in joined

    def test_local_launch_executes(self, tmp_path):
        import subprocess, sys

        script = tmp_path / "job.py"
        script.write_text("print('JOB_RAN', flush=True)")
        out = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
             str(script)], capture_output=True, text=True, timeout=120,
            cwd="/root/repo")
        assert "JOB_RAN" in out.stdout, out.stderr

    def test_elastic_restart_resumes_from_checkpoint(self, tmp_path):
        """--elastic_training: the agent relaunches a crashed worker
        group; the script resumes from its 'latest' checkpoint and step
        continuity holds (reference: elastic_agent.py:32 restart loop)."""
        import subprocess, sys

        ckpt = tmp_path / "latest"
        log = tmp_path / "steps.log"
        script = tmp_path / "train.py"
        script.write_text(f"""
import os, sys
ckpt, log = {str(ckpt)!r}, {str(log)!r}
start = int(open(ckpt).read()) if os.path.exists(ckpt) else 0
for step in range(start + 1, 7):
    with open(log, "a") as f:
        f.write(f"{{step}}\\n")
    with open(ckpt, "w") as f:
        f.write(str(step))
    if step == 3 and os.environ.get("_CRASHED") is None and \\
            not os.path.exists(ckpt + ".crashed"):
        open(ckpt + ".crashed", "w").write("1")
        sys.exit(17)                      # simulated node failure
print("DONE", flush=True)
""")
        out = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
             "--elastic_training", "--max_elastic_restarts", "3",
             str(script)], capture_output=True, text=True, timeout=180,
            cwd="/root/repo")
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "DONE" in out.stdout
        steps = [int(x) for x in log.read_text().split()]
        # crash after step 3, resume AT step 4 — no gap, no redo
        assert steps == [1, 2, 3, 4, 5, 6], steps

    def test_elastic_budget_exhausted(self, tmp_path):
        import subprocess, sys

        script = tmp_path / "always_fail.py"
        script.write_text("import sys; sys.exit(9)")
        out = subprocess.run(
            [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
             "--elastic_training", "--max_elastic_restarts", "2",
             str(script)], capture_output=True, text=True, timeout=180,
            cwd="/root/repo")
        assert out.returncode == 9


class TestElasticity:
    def test_compute_elastic_config(self):
        from deepspeed_tpu.elasticity import compute_elastic_config

        cfg = {"elasticity": {
            "enabled": True, "max_train_batch_size": 100,
            "micro_batch_sizes": [2, 4], "min_devices": 1,
            "max_devices": 8, "version": 0.2}}
        batch, valid = compute_elastic_config(cfg)
        assert batch <= 100
        # every valid device count divides the batch with some micro batch
        for n in valid:
            assert any(batch % (mb * n) == 0 for mb in (2, 4))
        b2, v2, micro = compute_elastic_config(cfg, world_size=valid[0])
        assert b2 == batch and micro in (2, 4)

    def test_incompatible_world_size(self):
        from deepspeed_tpu.elasticity import (ElasticityError,
                                              compute_elastic_config)

        cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 8,
                              "micro_batch_sizes": [8],
                              "min_devices": 1, "max_devices": 1}}
        with pytest.raises(ElasticityError):
            compute_elastic_config(cfg, world_size=7)

    def test_fingerprint_immutability(self):
        from deepspeed_tpu.elasticity import (ElasticityError,
                                              elasticity_fingerprint,
                                              ensure_immutable)

        c1 = {"elasticity": {"enabled": True, "max_train_batch_size": 64}}
        fp = elasticity_fingerprint(c1)
        ensure_immutable(c1, fp)
        c2 = {"elasticity": {"enabled": True, "max_train_batch_size": 32}}
        with pytest.raises(ElasticityError):
            ensure_immutable(c2, fp)


class TestOptimizedLinear:
    def test_lora_quantized_forward(self):
        from deepspeed_tpu.linear import (LoRAConfig, QuantizationConfig,
                                          apply_optimized_linear,
                                          init_optimized_linear)

        lora = LoRAConfig(lora_r=8, lora_alpha=16)
        p = init_optimized_linear(jax.random.PRNGKey(0), 32, 64, lora=lora,
                                  quant=QuantizationConfig(q_bits=8))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
        y = apply_optimized_linear(p, x, lora=lora)
        assert y.shape == (4, 64)
        # lora_b starts at zero => output equals quantized base matmul
        from deepspeed_tpu.ops.quant import dequantize
        np.testing.assert_allclose(y, x @ dequantize(p["base"]), atol=1e-5)

    def test_trainable_filter_freezes_base(self):
        from deepspeed_tpu.linear import (LoRAConfig, init_optimized_linear,
                                          trainable_filter)

        p = init_optimized_linear(jax.random.PRNGKey(0), 16, 16,
                                  lora=LoRAConfig(lora_r=4))
        f = trainable_filter(p)
        assert f["lora_a"] and f["lora_b"] and not f["base"]

    def test_fp8_quantize_roundtrip(self):
        from deepspeed_tpu.ops.quant import dequantize, fp_quantize

        x = jax.random.normal(jax.random.PRNGKey(0), (256,)) * 3
        qt = fp_quantize(x, fmt="fp8_e4m3", num_groups=4)
        assert qt.data.dtype == jnp.float8_e4m3fn
        y = dequantize(qt)
        np.testing.assert_allclose(y, x, rtol=0.1, atol=0.05)

    def test_merge_lora(self):
        from deepspeed_tpu.linear import (LoRAConfig, init_optimized_linear,
                                          merge_lora)

        lora = LoRAConfig(lora_r=4, lora_alpha=4)
        p = init_optimized_linear(jax.random.PRNGKey(0), 16, 16, lora=lora)
        p["lora_b"] = jnp.ones_like(p["lora_b"])
        w = merge_lora(p, lora)
        want = p["base"] + (p["lora_a"] @ p["lora_b"])
        np.testing.assert_allclose(w, want, atol=1e-6)


class TestCompression:
    def test_sparse_pruning_ratio(self):
        from deepspeed_tpu.compression import sparse_pruning

        w = jax.random.normal(jax.random.PRNGKey(0), (64, 64))
        pruned = sparse_pruning(w, 0.5)
        assert float((pruned == 0).mean()) == pytest.approx(0.5, abs=0.02)

    def test_row_and_head_pruning(self):
        from deepspeed_tpu.compression import head_pruning, row_pruning

        w = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
        rp = row_pruning(w, 0.25)
        zero_rows = int((np.abs(np.asarray(rp)).sum(1) == 0).sum())
        assert zero_rows == 4
        hp = head_pruning(w, num_heads=4, ratio=0.5)
        blocks = np.asarray(hp).reshape(4, 4, 8)
        assert int((np.abs(blocks).sum((1, 2)) == 0).sum()) == 2

    def test_scheduler_from_reference_config(self):
        from deepspeed_tpu.compression import CompressionScheduler

        cc = {"weight_quantization": {
                  "shared_parameters": {"enabled": True,
                                        "schedule_offset": 5},
                  "different_groups": {"wq1": {
                      "params": {"start_bits": 8, "target_bits": 8,
                                 "quantization_groups": 4},
                      "modules": ["w.*"]}}},
              "sparse_pruning": {
                  "shared_parameters": {"enabled": True,
                                        "schedule_offset": 0},
                  "different_groups": {"sp1": {
                      "params": {"ratio": 0.5}, "modules": ["w2"]}}}}
        sched = CompressionScheduler.from_config(cc)
        params = {"w1": jax.random.normal(jax.random.PRNGKey(0), (32, 32)),
                  "w2": jax.random.normal(jax.random.PRNGKey(1), (32, 32)),
                  "bias": jnp.ones((32,))}
        early = sched.apply(params, step=0)       # only pruning active
        assert float((np.asarray(early["w2"]) == 0).mean()) >= 0.45
        np.testing.assert_array_equal(early["w1"], params["w1"])
        late = sched.apply(params, step=10)       # + quantization
        assert not np.array_equal(np.asarray(late["w1"]),
                                  np.asarray(params["w1"]))

    def test_redundancy_clean(self):
        from deepspeed_tpu.compression import redundancy_clean

        cfg = {"compression_training": {"sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 100},
            "different_groups": {"sp1": {"params": {"ratio": 0.9},
                                         "modules": ["*"]}}}}}
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (64,))}
        out = redundancy_clean(params, cfg)
        assert float((np.asarray(out["w"]) == 0).mean()) >= 0.85


class TestLayerReductionDistillation:
    """(reference: compression/compress.py:119 layer_reduction +
    student_initialization :192)."""

    def _models(self):
        from deepspeed_tpu.models import build_model
        t = build_model("gpt2", vocab_size=128, num_layers=8, d_model=32,
                        num_heads=4, max_seq_len=16, seed=0)
        s = build_model("gpt2", vocab_size=128, num_layers=4, d_model=32,
                        num_heads=4, max_seq_len=16, seed=1)
        return t, s

    def test_student_init_gathers_teacher_layers(self):
        import numpy as np
        from deepspeed_tpu.compression.compress import student_initialization
        t, s = self._models()
        cfg = {"compression_training": {"layer_reduction": {
            "enabled": True, "keep_number_layer": 4,
            "teacher_layer": [1, 3, 5, 7]}}}
        p = student_initialization(s.params, t.params, cfg)
        np.testing.assert_array_equal(
            np.asarray(p["blocks"]["attn"]["wq"][2]),
            np.asarray(t.params["blocks"]["attn"]["wq"][5]))
        np.testing.assert_array_equal(
            np.asarray(p["embed"]["table"]),
            np.asarray(t.params["embed"]["table"]))

    @pytest.mark.nightly
    def test_student_trains_and_distills(self):
        import numpy as np
        import jax.numpy as jnp
        import deepspeed_tpu as ds
        from deepspeed_tpu.compression.compress import (kd_loss,
                                                        student_initialization)
        t, s = self._models()
        cfg = {"compression_training": {"layer_reduction": {
            "enabled": True, "keep_number_layer": 4}}}
        sp = student_initialization(s.params, t.params, cfg)
        ids = np.random.RandomState(0).randint(0, 128, (8, 16))

        def loss_fn(params, batch, rng):
            sl = s.apply(params, batch["input_ids"], dtype=jnp.float32)
            tl = t.apply(t.params, batch["input_ids"], dtype=jnp.float32)
            return kd_loss(sl, tl, temperature=2.0)

        eng = ds.initialize(loss_fn=loss_fn, params=sp, config={
            "train_micro_batch_size_per_device": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
            "mesh": {"data": 8}, "steps_per_print": 1000})
        losses = [float(eng.train_batch({"input_ids": ids})["loss"])
                  for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_bad_config_raises(self):
        import pytest
        from deepspeed_tpu.compression.compress import student_initialization
        t, s = self._models()
        with pytest.raises(ValueError, match="enabled"):
            student_initialization(s.params, t.params, {})
        with pytest.raises(ValueError, match="out of range"):
            student_initialization(s.params, t.params, {
                "compression_training": {"layer_reduction": {
                    "enabled": True, "keep_number_layer": 4,
                    "teacher_layer": [0, 1, 2, 99]}}})


class TestElasticityV02:
    """v0.2 planning (reference: _get_compatible_gpus_v02) — node
    granularity with model-parallel awareness."""

    def _cfg(self, **kw):
        e = {"enabled": True, "version": 0.2,
             "micro_batch_sizes": [2, 4], "max_train_batch_size": 512,
             "min_devices": 8, "max_devices": 64,
             "devices_per_node": 8, **kw}
        return {"elasticity": e}

    def test_node_granularity(self):
        from deepspeed_tpu.elasticity.elasticity import \
            compute_elastic_config
        batch, valid = compute_elastic_config(self._cfg())
        # every valid count is a whole number of 8-device nodes
        assert valid and all(v % 8 == 0 for v in valid)
        assert batch <= 512

    def test_model_parallel_scaling(self):
        from deepspeed_tpu.elasticity.elasticity import \
            compute_elastic_config
        b_mp4, v_mp4, micro = compute_elastic_config(
            self._cfg(model_parallel_size=4), world_size=16)
        # mp=4 on 8-dev nodes => 2 data replicas per node
        assert b_mp4 <= 512 and b_mp4 % 2 == 0
        assert 16 in v_mp4 and all(v % 8 == 0 for v in v_mp4)
        dp_world = 16 // 4
        assert (b_mp4 // dp_world) % micro == 0

    def test_mp_must_divide_node(self):
        import pytest
        from deepspeed_tpu.elasticity.elasticity import (
            ElasticityError, compute_elastic_config)
        with pytest.raises(ElasticityError, match="divide"):
            compute_elastic_config(self._cfg(model_parallel_size=3))

    def test_incompatible_world_rejected(self):
        import pytest
        from deepspeed_tpu.elasticity.elasticity import (
            ElasticityError, compute_elastic_config)
        with pytest.raises(ElasticityError, match="incompatible"):
            compute_elastic_config(self._cfg(), world_size=12)  # 1.5 nodes


class TestCommBench:
    def test_sweep_all_ops(self, devices):
        """ds_bench analog: every collective sweeps and reports busbw."""
        from deepspeed_tpu.comm.bench import OPS, sweep
        recs = sweep(list(OPS), min_pow=12, max_pow=13, trials=2,
                     warmups=1, print_table=False)
        assert len(recs) == len(OPS) * 2
        for r in recs:
            assert r["devices"] == 8
            assert r["busbw_gbps"] > 0
            assert r["latency_us"] > 0

    def test_cli_json(self, devices, capsys):
        import json as js
        from deepspeed_tpu.comm.bench import main
        main(["--ops", "all_reduce", "--minsize", "12", "--maxsize",
              "12", "--trials", "2", "--json"])
        lines = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
        rec = js.loads(lines[0])
        assert rec["op"] == "all_reduce" and rec["busbw_gbps"] > 0
