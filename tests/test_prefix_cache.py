"""Automatic prefix caching for the paged KV cache (shared-prompt block
reuse with copy-on-write).

The correctness bar is STRICT parity: with greedy or seeded sampling,
``prefix_cache="on"`` must be token-for-token identical to ``"off"``
across mixed chunked traffic, stop tokens, pipeline depths 1 and 2, and
under eviction pressure (pool sized so cached blocks are reclaimed
mid-run) — plus allocator accounting
``referenced + cached_free + free == total`` after every phase, and the
hit-rate counters in ``engine.timings`` / ``query()`` asserted so the
metric cannot silently rot."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                     KVCacheConfig, SamplingParams,
                                     StateManager)
from deepspeed_tpu.inference.ragged.allocator import BlockedAllocator
from deepspeed_tpu.models import build_model


@pytest.fixture(scope="module")
def model():
    return build_model("llama-tiny", vocab_size=128, num_layers=2,
                       d_model=64, num_heads=4, num_kv_heads=2, d_ff=128,
                       max_seq_len=128)


def mk(m, **over):
    """fp32 engine (exact-parity convention of test_inference.py) with
    a block size small enough that 20-30-token prompts span blocks."""
    kw = dict(token_budget=32, max_seqs=4, kv_block_size=8,
              num_kv_blocks=32, kv_dtype=jnp.float32,
              param_dtype=jnp.float32, prefix_cache="on")
    kw.update(over)
    return InferenceEngine(m, InferenceConfig(**kw))


def check_allocator(eng):
    al = eng.state.allocator
    al.assert_invariants()
    held = [b for s in eng.state.seqs.values() for b in s.blocks]
    assert al.free_blocks + len(set(held)) == al.total_blocks


GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)


class TestRefcountedAllocator:
    def test_alias_and_release_cycle(self):
        a = BlockedAllocator(8)
        blocks = a.allocate(3)
        a.ref(blocks[0])                      # alias: refcount 2
        assert a.refcount(blocks[0]) == 2
        a.free(blocks)                        # drops one ref each
        assert a.refcount(blocks[0]) == 1     # still aliased
        assert a.free_blocks == 7
        a.free([blocks[0]])
        assert a.free_blocks == 8
        a.assert_invariants()

    def test_cached_free_lru_eviction_order(self):
        evicted = []
        a = BlockedAllocator(4, on_evict=evicted.append)
        blocks = a.allocate(4)
        for b in blocks:
            a.mark_cached(b)
        a.free([blocks[2]])                   # oldest on the LRU list
        a.free([blocks[0]])
        a.free([blocks[1]])
        assert a.cached_free_blocks == 3 and a.free_blocks == 3
        got = a.allocate(2)                   # evicts oldest-released
        assert evicted == [blocks[2], blocks[0]]
        assert got == [blocks[2], blocks[0]]
        a.assert_invariants()

    def test_revive_from_cached_free(self):
        a = BlockedAllocator(4)
        [b] = a.allocate(1)
        a.mark_cached(b)
        a.free([b])
        assert a.cached_free_blocks == 1
        a.ref(b)                              # match revives it
        assert a.refcount(b) == 1 and a.cached_free_blocks == 0
        a.free([b])
        a.assert_invariants()

    def test_free_list_preferred_over_cached(self):
        a = BlockedAllocator(4)
        [b] = a.allocate(1)
        a.mark_cached(b)
        a.free([b])
        got = a.allocate(3)
        assert b not in got                   # reuse-before-overwrite
        assert a.is_cached(b)
        a.assert_invariants()

    def test_double_free_and_bad_ref(self):
        a = BlockedAllocator(4)
        [b] = a.allocate(1)
        a.free([b])
        with pytest.raises(ValueError, match="Double free"):
            a.free([b])
        with pytest.raises(ValueError, match="Cannot ref"):
            a.ref(b)

    def test_duplicate_in_one_free_call_rejected_atomically(self):
        """More frees than references WITHIN one call must raise the
        documented ValueError and mutate nothing (not partially retire
        the block then KeyError)."""
        a = BlockedAllocator(4)
        [b] = a.allocate(1)
        with pytest.raises(ValueError, match="Double free"):
            a.free([b, b])
        assert a.refcount(b) == 1              # untouched
        a.ref(b)
        a.free([b, b])                         # two refs: now legal
        assert a.free_blocks == 4
        a.assert_invariants()


class TestStateManagerMatching:
    def cfg(self):
        return KVCacheConfig(num_layers=2, num_kv_heads=2, head_dim=16,
                             block_size=4, num_blocks=16)

    def test_release_then_identical_prompt_matches(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 11))           # 10 tokens, 2 full blocks
        sm.build_batch([(0, list(prompt))], token_budget=16)
        first_blocks = list(sm.seqs[0].blocks[:2])
        sm.release(0)
        assert sm.allocator.cached_free_blocks == 2   # full blocks cached
        n = sm.match_prefix(1, list(prompt))
        assert n == 8                          # block-aligned prefix
        assert sm.seqs[1].blocks == first_blocks      # same physical ids
        assert sm.seqs[1].seen_tokens == 8
        assert sm.seqs[1].cached_tokens == 8
        sm.allocator.assert_invariants()

    def test_live_block_sharing_refcounts(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 11))
        sm.build_batch([(0, list(prompt))], token_budget=16)
        n = sm.match_prefix(1, list(prompt))
        assert n == 8
        shared = sm.seqs[1].blocks
        assert shared == sm.seqs[0].blocks[:2]
        assert all(sm.allocator.refcount(b) == 2 for b in shared)
        sm.release(0)
        assert all(sm.allocator.refcount(b) == 1 for b in shared)
        sm.release(1)
        sm.allocator.assert_invariants()
        assert sm.allocator.free_blocks == sm.allocator.total_blocks

    def test_full_cover_match_queues_cow(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 9))            # exactly 2 blocks
        sm.build_batch([(0, list(prompt))], token_budget=16)
        orig = list(sm.seqs[0].blocks)
        sm.release(0)
        n = sm.match_prefix(1, list(prompt))
        assert n == 7                          # one token left to prefill
        seq = sm.seqs[1]
        assert seq.blocks[0] == orig[0]
        assert seq.blocks[1] != orig[1]        # private COW copy
        assert sm.cow_pending == [(1, orig[1], seq.blocks[1])]
        assert sm.take_cow_copies() == [(orig[1], seq.blocks[1])]
        assert sm.cow_pending == []
        sm.allocator.assert_invariants()

    def test_release_drops_pending_cow(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 9))
        sm.build_batch([(0, list(prompt))], token_budget=16)
        sm.release(0)
        sm.match_prefix(1, list(prompt))
        assert sm.cow_pending
        sm.release(1)                          # dst freed with its owner
        assert sm.cow_pending == []
        sm.allocator.assert_invariants()
        assert sm.allocator.free_blocks == sm.allocator.total_blocks

    def test_eviction_drops_index_entries_leaf_first(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 11))
        sm.build_batch([(0, list(prompt))], token_budget=16)
        sm.release(0)
        assert sm.allocator.cached_free_blocks == 2
        # exhaust the plain free list so allocation evicts ONE cached
        # block; release retired the chain LEAF first, so eviction takes
        # the leaf and the surviving root block is still matchable
        sm.build_batch([(1, list(range(60, 119)))], token_budget=64)
        assert sm.allocator.cached_free_blocks == 1
        assert sm.match_prefix(2, list(prompt)) == 4   # root survived
        sm.allocator.assert_invariants()

    def test_evicting_whole_chain_empties_index(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 11))
        sm.build_batch([(0, list(prompt))], token_budget=16)
        sm.release(0)
        # allocate everything: both cached blocks evicted (the index
        # now only holds the NEW sequence's live full blocks)
        sm.build_batch([(1, list(range(60, 123)))], token_budget=64)
        assert sm.allocator.cached_free_blocks == 0
        assert set(sm._hash_index.values()) <= set(sm.seqs[1].blocks)
        assert sm.match_prefix(2, list(prompt)) == 0
        sm.allocator.assert_invariants()

    def test_feedback_token_breaks_chain(self):
        from deepspeed_tpu.inference.ragged.state import FEEDBACK_TOKEN
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        sm.build_batch([(0, [1, 2, 3])], token_budget=16)
        assert not sm.seqs[0].chain_broken
        sm.build_batch([(0, [FEEDBACK_TOKEN])], token_budget=16)
        assert sm.seqs[0].chain_broken
        # deferred token values never enter the hash chain
        assert sm.seqs[0].chain == [1, 2, 3]

    def test_max_pool_take_caps_revivals(self):
        sm = StateManager(self.cfg(), max_seqs=2, prefix_cache=True)
        prompt = list(range(1, 14))           # 3 full blocks
        sm.build_batch([(0, list(prompt))], token_budget=16)
        sm.release(0)
        assert sm.allocator.cached_free_blocks == 3
        n = sm.match_prefix(1, list(prompt), max_pool_take=2)
        assert n == 8                          # capped at 2 revivals
        sm.allocator.assert_invariants()


class TestPrefixCacheParity:
    """Token-for-token parity of prefix_cache on vs off (fp32/greedy is
    exact; fp32/seeded is exact because sampling keys fold
    (uid, position), not step index)."""

    def _shared_traffic(self, seed=0):
        r = np.random.RandomState(seed)
        shared = list(r.randint(1, 128, 24))          # 3 full blocks
        mk_tail = lambda n: list(r.randint(1, 128, n))  # noqa: E731
        return shared, mk_tail

    def _run(self, eng, waves, sp, rng=None):
        out = []
        for wave in waves:
            out.append(eng.generate({u: list(p) for u, p in wave.items()},
                                    sp, rng=rng))
        return out

    def test_greedy_parity_mixed_chunked_traffic(self, model):
        """Sequential waves of prompts sharing a 24-token prefix, budget
        16 so every prompt spans several SplitFuse chunks; the second
        and later waves hit the cache."""
        shared, tail = self._shared_traffic()
        waves = [{0: shared + tail(6)},
                 {1: shared + tail(3), 2: shared + tail(5)},
                 {3: shared + tail(4)}]
        ref = self._run(mk(model, prefix_cache="off", token_budget=16),
                        waves, GREEDY)
        eng = mk(model, token_budget=16)
        got = self._run(eng, waves, GREEDY)
        assert got == ref
        assert eng.timings["cached_tokens"] > 0
        assert eng.timings["prefix_hits"] >= 3
        check_allocator(eng)

    def test_live_sharing_within_one_wave(self, model):
        """Two identical prompts in ONE generate call with a tight
        budget: the later-admitted sequence aliases the earlier one's
        LIVE blocks (registered the step they filled)."""
        shared, tail = self._shared_traffic(1)
        prompt = shared + tail(4)
        waves = [{0: prompt, 1: list(prompt)}]
        ref = self._run(mk(model, prefix_cache="off", token_budget=16),
                        waves, GREEDY)
        eng = mk(model, token_budget=16)
        got = self._run(eng, waves, GREEDY)
        assert got == ref
        assert got[0][0] == got[0][1]          # identical prompts agree
        assert eng.timings["cached_tokens"] > 0
        check_allocator(eng)

    def test_stop_token_parity(self, model):
        shared, tail = self._shared_traffic(2)
        prompt = shared + tail(5)
        base = mk(model, prefix_cache="off").generate(
            {0: list(prompt)}, GREEDY)[0]
        sp = SamplingParams(temperature=0.0, max_new_tokens=50,
                            stop_token=base[2])
        waves = [{0: list(prompt)}, {1: list(prompt)}]
        ref = self._run(mk(model, prefix_cache="off"), waves, sp)
        eng = mk(model)
        got = self._run(eng, waves, sp)
        assert got == ref
        assert got[1][1][-1] == base[2]
        assert eng.timings["cached_tokens"] > 0

    @pytest.mark.parametrize("depth", [1, 2])
    def test_pipeline_depth_parity(self, model, depth):
        shared, tail = self._shared_traffic(3)
        waves = [{0: shared + tail(6)}, {1: shared + tail(2)}]
        ref = self._run(mk(model, prefix_cache="off", pipeline_depth=depth,
                           token_budget=16), waves, GREEDY)
        eng = mk(model, pipeline_depth=depth, token_budget=16)
        got = self._run(eng, waves, GREEDY)
        assert got == ref
        assert eng.timings["cached_tokens"] > 0
        check_allocator(eng)

    def test_eviction_pressure_parity(self, model):
        """Pool of 12 blocks x 8 = 96 tokens with 30-token requests:
        cached blocks MUST be reclaimed mid-run; outputs stay identical
        and accounting stays exact."""
        r = np.random.RandomState(4)
        pA = list(r.randint(1, 128, 24))
        pB = list(r.randint(1, 128, 24))
        waves = [{0: pA + [5, 7]}, {1: pB + [9]}, {2: pA + [3, 1]},
                 {3: pB + [2]}, {4: pA + [8, 8]}]
        kw = dict(num_kv_blocks=12, token_budget=16, max_seqs=2)
        ref = self._run(mk(model, prefix_cache="off", **kw), waves, GREEDY)
        eng = mk(model, **kw)
        got = self._run(eng, waves, GREEDY)
        assert got == ref
        al = eng.state.allocator
        al.assert_invariants()
        assert al.free_blocks == al.total_blocks   # all flushed
        # the tight pool forced evictions, yet some hits still landed
        assert eng.timings["prefix_hits"] > 0

    def test_seeded_sampling_parity(self, model):
        """Seeded sampling on vs off: sampling keys are a pure function
        of (base key, uid, position), so collapsing prefill steps via
        the cache cannot change any sampled token."""
        shared, tail = self._shared_traffic(5)
        waves = [{0: shared + tail(6)}, {1: shared + tail(4)}]
        spr = SamplingParams(temperature=1.0, top_k=8, max_new_tokens=6)
        key = jax.random.PRNGKey(11)
        ref = self._run(mk(model, prefix_cache="off", token_budget=16),
                        waves, spr, rng=key)
        eng = mk(model, token_budget=16)
        got = self._run(eng, waves, spr, rng=key)
        assert got == ref
        assert eng.timings["cached_tokens"] > 0

    def test_full_cover_cow_parity(self, model):
        """Prompt length exactly a block multiple and fully cached: the
        last block is aliased as a copy-on-write private copy, one token
        is re-scheduled, and output parity still holds."""
        shared, _ = self._shared_traffic(6)
        waves = [{0: list(shared)}, {1: list(shared)}, {2: list(shared)}]
        ref = self._run(mk(model, prefix_cache="off"), waves, GREEDY)
        eng = mk(model)
        got = self._run(eng, waves, GREEDY)
        assert got == ref
        # 24-token prompt, full-cover match = 23 tokens served per hit
        assert eng.timings["cached_tokens"] == 2 * (len(shared) - 1)
        check_allocator(eng)

    def test_miss_path_costs_nothing(self, model):
        """Disjoint prompts: hit-rate 0, identical outputs, and the
        engine never dispatches a COW copy (the only device work the
        cache can add)."""
        r = np.random.RandomState(7)
        waves = [{0: list(r.randint(1, 128, 20))},
                 {1: list(r.randint(1, 128, 20))}]
        ref = self._run(mk(model, prefix_cache="off"), waves, GREEDY)
        eng = mk(model)
        got = self._run(eng, waves, GREEDY)
        assert got == ref
        assert eng.timings["cached_tokens"] == 0
        assert eng.timings["prefix_hits"] == 0
        assert eng._cow_fn is None             # COW program never built
        assert eng.timings["prompt_tokens"] == 40

    def test_query_and_counters_during_decode(self, model):
        """query() exposes per-sequence cached_tokens while the request
        is live; engine.timings tracks the cumulative hit counters."""
        shared, tail = self._shared_traffic(8)
        prompt = shared + tail(4)
        eng = mk(model)
        eng.generate({0: list(prompt)}, GREEDY)
        assert eng.query(0)["cached_tokens"] == 0      # flushed
        eng.put(1, list(prompt))
        while not eng.state.seqs.get(1):
            eng.step(sampling=GREEDY)
        q = eng.query(1)
        assert q["cached_tokens"] == 24                # 3 aliased blocks
        assert q["seen_tokens"] >= 24
        tm = eng.timings
        assert tm["cached_tokens"] == 24
        assert tm["prefix_hits"] == 1
        assert tm["prompt_tokens"] == 2 * len(prompt)
        eng.flush(1)
        check_allocator(eng)

    def test_prefix_cache_off_is_inert(self, model):
        eng = mk(model, prefix_cache="off")
        shared, tail = self._shared_traffic(9)
        eng.generate({0: shared + tail(2)}, GREEDY)
        eng.generate({1: shared + tail(2)}, GREEDY)
        assert eng.timings["cached_tokens"] == 0
        assert eng.state._hash_index == {}
        al = eng.state.allocator
        assert al.cached_free_blocks == 0
        assert al.free_blocks == al.total_blocks

    def test_bad_config_value_raises(self, model):
        with pytest.raises(ValueError, match="prefix_cache"):
            mk(model, prefix_cache="maybe")
