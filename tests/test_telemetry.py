"""Telemetry subsystem tests (docs/OBSERVABILITY.md): span-tracer units
(nesting, ring wraparound, disabled-mode cost), Chrome-trace schema
validation of an exported file, metrics registry + Prometheus
text-exposition round-trip, monitor fan-out, and request-lifecycle
accounting parity — the sum of per-request prompt/cached/generated
token counts must reconcile EXACTLY with the engine counters across
mixed chunked traffic, prefix cache on/off, pipeline depth 1/2, and
decode bursts (both sides are bumped at the same statements; a drift
means an accounting site was added on one side only)."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                     SamplingParams)
from deepspeed_tpu.models import build_model
from deepspeed_tpu.telemetry import (CounterDictView, MetricsRegistry,
                                     RequestTracker, SpanTracer,
                                     parse_prometheus_text)


def tiny_model(**over):
    kw = dict(vocab_size=128, num_layers=2, d_model=64, num_heads=4,
              num_kv_heads=2, d_ff=128, max_seq_len=128)
    kw.update(over)
    return build_model("llama-tiny", **kw)


def make_engine(m, **over):
    kw = dict(token_budget=32, max_seqs=4, kv_block_size=16,
              num_kv_blocks=64, kv_dtype=jnp.float32,
              param_dtype=jnp.float32)
    kw.update(over)
    return InferenceEngine(m, InferenceConfig(**kw))


@pytest.fixture(scope="module")
def model():
    return tiny_model()


# --------------------------------------------------------------------------
# span tracer units
# --------------------------------------------------------------------------

class TestSpanTracer:
    def test_disabled_is_shared_noop(self):
        tr = SpanTracer(capacity=8, enabled=False)
        s1 = tr.span("a")
        s2 = tr.span("b", track="t", k=1)
        assert s1 is s2                      # one shared no-op object
        with s1:
            pass
        tr.record("x", 0.0, 1.0)
        tr.instant("y")
        assert len(tr) == 0 and tr.events() == []

    def test_span_nesting_depth(self):
        tr = SpanTracer(capacity=16, enabled=True)
        with tr.span("outer", track="t"):
            with tr.span("inner", track="t"):
                pass
        evs = tr.events()
        # inner exits (and records) first
        assert [e["name"] for e in evs] == ["inner", "outer"]
        assert evs[0]["depth"] == 1 and evs[1]["depth"] == 0
        # containment: outer started before inner and ended after
        assert evs[1]["ts_ns"] <= evs[0]["ts_ns"]
        assert (evs[1]["ts_ns"] + evs[1]["dur_ns"]
                >= evs[0]["ts_ns"] + evs[0]["dur_ns"])

    def test_ring_wraparound(self):
        tr = SpanTracer(capacity=4, enabled=True)
        for i in range(10):
            tr.instant(f"e{i}")
        assert len(tr) == 4
        assert tr.dropped == 6
        # oldest-first, wraparound-corrected: the last 4 recorded
        assert [e["name"] for e in tr.events()] == ["e6", "e7", "e8", "e9"]
        tr.clear()
        assert len(tr) == 0 and tr.dropped == 0

    def test_record_explicit_endpoints_and_args(self):
        tr = SpanTracer(capacity=8, enabled=True)
        tr.record("step", 1.5, 1.75, track="loop", sid=3)
        (ev,) = tr.events()
        assert ev["track"] == "loop"
        assert ev["ts_ns"] == int(1.5e9)
        assert ev["dur_ns"] == int(0.25e9)
        assert ev["args"] == {"sid": 3}

    def test_enable_disable_and_capacity_validation(self):
        tr = SpanTracer(capacity=4)
        assert not tr.enabled
        tr.enable()
        tr.instant("x")
        tr.disable()
        tr.instant("y")
        assert [e["name"] for e in tr.events()] == ["x"]
        with pytest.raises(ValueError, match="capacity"):
            SpanTracer(capacity=0)

    def test_disabled_overhead_smoke(self):
        """Disabled-mode cost: 50k no-op span entries must be ~free (no
        clock reads, no allocation) — generous bound for CI noise."""
        tr = SpanTracer(capacity=8, enabled=False)
        t0 = time.perf_counter()
        for _ in range(50_000):
            with tr.span("hot"):
                pass
        dt = time.perf_counter() - t0
        assert len(tr) == 0
        assert dt < 2.0, f"disabled tracer cost {dt:.3f}s for 50k spans"


class TestChromeTrace:
    def _tracer(self):
        tr = SpanTracer(capacity=64, enabled=True)
        tr.record("schedule", 0.001, 0.002, track="schedule", sid=1)
        tr.record("dispatch", 0.002, 0.004, track="dispatch", sid=1)
        tr.record("wait", 0.004, 0.005, track="wait", sid=1)
        tr.instant("evict", track="schedule")
        return tr

    def test_chrome_trace_schema(self, tmp_path):
        path = str(tmp_path / "trace.json")
        assert self._tracer().export_chrome_trace(path) == path
        doc = json.load(open(path))
        assert doc["displayTimeUnit"] == "ms"
        assert doc["otherData"]["dropped_spans"] == 0
        evs = doc["traceEvents"]
        assert isinstance(evs, list)
        meta = [e for e in evs if e["ph"] == "M"]
        names = {e["args"]["name"] for e in meta
                 if e["name"] == "thread_name"}
        assert names == {"schedule", "dispatch", "wait"}
        assert any(e["name"] == "process_name" for e in meta)
        # one tid per track, stable sort indices
        sort_meta = [e for e in meta if e["name"] == "thread_sort_index"]
        assert len(sort_meta) == 3
        for e in evs:
            if e["ph"] == "X":
                assert isinstance(e["ts"], float)
                assert isinstance(e["dur"], float) and e["dur"] >= 0
                assert isinstance(e["tid"], int) and e["pid"] == 1
            elif e["ph"] == "i":
                assert e["s"] == "t" and "dur" not in e
        # durations in microseconds
        disp = next(e for e in evs if e.get("name") == "dispatch"
                    and e["ph"] == "X")
        assert abs(disp["dur"] - 2000.0) < 1e-6

    def test_jsonl_export(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        self._tracer().export_jsonl(path)
        lines = [json.loads(ln) for ln in open(path)]
        assert len(lines) == 4
        assert lines[0]["name"] == "schedule"
        assert lines[-1]["instant"] is True


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_gauge_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("toks", int_valued=True)
        c.inc(3)
        c.inc()
        assert c.value() == 4
        assert reg.counter("toks") is c          # get-or-create identity
        g = reg.gauge("depth")
        g.set(2.5)
        g.inc(0.5)
        assert g.value() == 3.0
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("toks")

    def test_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("req")
        c.inc(2, phase="prefill")
        c.inc(1, phase="decode")
        assert c.value(phase="prefill") == 2
        assert c.value(phase="decode") == 1
        assert len(list(c.series())) == 2

    def test_histogram_math(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", (1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 5.0, 50.0, 500.0):
            h.observe(v)
        assert h.count() == 5
        assert h.sum() == 560.5
        assert h.mean() == pytest.approx(112.1)
        bc = h.bucket_counts()
        assert bc == {"1": 1, "10": 3, "100": 4, "+Inf": 5}
        # quantiles: monotone in q, overflow clamps to the last edge
        assert h.percentile(0.2) <= h.percentile(0.5) \
            <= h.percentile(0.9) <= h.percentile(1.0) == 100.0
        with pytest.raises(ValueError, match="sorted"):
            reg.histogram("bad", (3.0, 1.0))

    def test_snapshot_is_jsonable(self):
        reg = MetricsRegistry()
        reg.counter("steps", int_valued=True).inc(7)
        reg.counter("labeled").inc(1, k="v")
        reg.histogram("h", (1.0, 2.0)).observe(1.5)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["steps"] == 7
        assert snap["h"]["count"] == 1
        assert snap["labeled"] == {'{k="v"}': 1}

    def test_prometheus_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("serving_steps_total", "steps", int_valued=True).inc(5)
        reg.gauge("queue_depth").set(3)
        reg.counter("hits").inc(2, cache="prefix")
        h = reg.histogram("ttft_ms", (10.0, 100.0), "ttft")
        h.observe(7.0)
        h.observe(70.0)
        h.observe(700.0)
        text = reg.prometheus_text()
        assert "# TYPE serving_steps_total counter" in text
        assert "# HELP serving_steps_total steps" in text
        parsed = parse_prometheus_text(text)
        assert parsed["serving_steps_total"]["type"] == "counter"
        assert parsed["serving_steps_total"]["samples"][
            ("serving_steps_total", ())] == 5.0
        assert parsed["hits"]["samples"][
            ("hits", (("cache", "prefix"),))] == 2.0
        hs = parsed["ttft_ms"]["samples"]
        assert hs[("ttft_ms_count", ())] == 3.0
        assert hs[("ttft_ms_sum", ())] == 777.0
        assert hs[("ttft_ms_bucket", (("le", "10"),))] == 1.0
        assert hs[("ttft_ms_bucket", (("le", "100"),))] == 2.0
        assert hs[("ttft_ms_bucket", (("le", "+Inf"),))] == 3.0

    def test_write_jsonl(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        path = str(tmp_path / "metrics.jsonl")
        reg.write_jsonl(path, step=1)
        reg.counter("c").inc()
        reg.write_jsonl(path, step=2)
        lines = [json.loads(ln) for ln in open(path)]
        assert [ln["step"] for ln in lines] == [1, 2]
        assert [ln["metrics"]["c"] for ln in lines] == [1, 2]
        assert all("time" in ln for ln in lines)

    def test_monitor_fanout(self):
        """Registry values ride the monitor/ writer event shape
        ((name, value, step) triples — monitor/monitor.py)."""
        class StubMonitor:
            events = []

            def write_events(self, evs):
                self.events.extend(evs)

        reg = MetricsRegistry()
        reg.counter("steps").inc(4)
        reg.histogram("lat_ms", (1.0, 10.0)).observe(2.0)
        mon = StubMonitor()
        reg.publish(mon, step=9)
        d = {name: (value, step) for name, value, step in mon.events}
        assert d["steps"] == (4.0, 9)
        assert d["lat_ms_count"] == (1.0, 9)
        assert d["lat_ms_sum"] == (2.0, 9)
        assert "lat_ms_p50" in d
        reg.publish(None, step=10)               # no-op without a monitor

    def test_reset_keeps_registrations(self):
        reg = MetricsRegistry()
        c = reg.counter("c")
        h = reg.histogram("h", (1.0,))
        c.inc(3)
        h.observe(0.5)
        reg.reset()
        assert reg.counter("c") is c and c.value() == 0
        assert h.count() == 0 and "h" in reg

    def test_counter_dict_view(self):
        reg = MetricsRegistry()
        cs = {"a_ms": reg.counter("a_ms_total"),
              "n": reg.counter("n_total", int_valued=True)}
        tm = CounterDictView(cs)
        tm["a_ms"] += 1.5
        tm["n"] += 2
        assert tm["a_ms"] == 1.5
        assert tm["n"] == 2 and isinstance(tm["n"], int)
        assert sorted(tm) == ["a_ms", "n"]
        assert len(tm) == 2
        assert dict(tm) == {"a_ms": 1.5, "n": 2}
        tm["n"] = 0                              # reset-style assignment
        assert reg.counter("n_total").value() == 0
        with pytest.raises(TypeError):
            del tm["n"]
        with pytest.raises(KeyError):
            tm["unknown"]
        tm["a_ms"] += 1.0
        tm.reset()
        assert tm["a_ms"] == 0.0


# --------------------------------------------------------------------------
# request lifecycle units
# --------------------------------------------------------------------------

class TestRequestTracker:
    def test_lifecycle_math(self):
        reg = MetricsRegistry()
        t = RequestTracker(reg)
        t.on_arrival(7, now=100.0)
        t.on_admitted(7, prompt_tokens=10, cached_tokens=4, now=100.5)
        t.on_prefill_start(7, 100.6)
        t.on_tokens(7, 1, 101.0)
        t.on_tokens(7, 1, 101.2)
        t.on_tokens(7, 1, 101.4)
        t.on_finish(7, now=101.5)
        (rec,) = t.records()
        assert rec.queue_wait_ms == pytest.approx(500.0)
        assert rec.ttft_ms == pytest.approx(1000.0)
        assert rec.tpot_ms == pytest.approx(200.0)   # (101.4-101.0)/2
        assert rec.e2e_ms == pytest.approx(1500.0)
        assert (rec.prompt_tokens, rec.cached_tokens,
                rec.generated_tokens) == (10, 4, 3)
        d = rec.as_dict()
        assert d["finished"] is True and d["uid"] == 7
        agg = t.aggregate()
        assert agg["requests"] == 1 and agg["finished"] == 1
        assert agg["ttft_ms"]["count"] == 1
        assert agg["tpot_ms"]["count"] == 1
        assert agg["queue_wait_ms"]["count"] == 1

    def test_single_token_request_has_no_tpot(self):
        t = RequestTracker(MetricsRegistry())
        t.on_arrival(1, now=0.0)
        t.on_admitted(1, 3, 0, now=0.1)
        t.on_tokens(1, 1, 0.2)
        t.on_finish(1, now=0.3)
        (rec,) = t.records()
        assert rec.tpot_ms is None               # no decode tail
        assert t.aggregate()["tpot_ms"]["count"] == 0

    def test_burst_emission_anchors_decode_tail(self):
        """An n>1 burst lands all tokens at one readback instant; the
        decode tail anchors at the burst's dispatch time so TPOT
        doesn't collapse to zero, while TTFT stays at readback (the
        host can't see the tokens earlier)."""
        t = RequestTracker(MetricsRegistry())
        t.on_arrival(1, now=0.0)
        t.on_admitted(1, 2, 0, now=0.1)
        t.on_tokens(1, 4, 1.0, t_dispatch=0.2)   # one 4-token burst
        t.on_finish(1, now=1.1)
        (rec,) = t.records()
        assert rec.ttft_ms == pytest.approx(1000.0)
        assert rec.tpot_ms == pytest.approx((1.0 - 0.2) * 1e3 / 3)
        # stepwise records are unaffected: tail anchor == first token
        t.on_arrival(2, now=0.0)
        t.on_tokens(2, 1, 1.0)
        t.on_tokens(2, 1, 1.5)
        t.on_finish(2, now=1.6)
        rec2 = t.records()[-1]
        assert rec2.tpot_ms == pytest.approx(500.0)

    def test_continuation_arrival_is_noop(self):
        t = RequestTracker(MetricsRegistry())
        r1 = t.on_arrival(1, now=0.0)
        r2 = t.on_arrival(1, now=5.0)
        assert r1 is r2 and r1.t_arrival == 0.0
        assert t.aggregate()["requests"] == 1

    def test_finished_ring_is_bounded(self):
        t = RequestTracker(MetricsRegistry(), max_finished=2)
        for uid in range(4):
            t.on_arrival(uid, now=float(uid))
            t.on_finish(uid, now=float(uid) + 1)
        assert [r.uid for r in t.records()] == [2, 3]
        assert t.aggregate()["finished"] == 4    # counter keeps the total


# --------------------------------------------------------------------------
# engine integration: accounting parity + trace export + back-compat
# --------------------------------------------------------------------------

def _assert_parity(eng):
    """Sum of per-request token counts == engine counters, exactly."""
    recs = eng.request_metrics()["requests"]
    tm = eng.timings
    assert sum(r["prompt_tokens"] for r in recs) == tm["prompt_tokens"]
    assert sum(r["cached_tokens"] for r in recs) == tm["cached_tokens"]
    assert sum(r["generated_tokens"] for r in recs) \
        == tm["generated_tokens"]


class TestEngineTelemetry:
    MIXED = {0: list(range(1, 51)), 1: [3, 1, 4], 2: list(range(60, 80))}

    @pytest.mark.parametrize("depth", [1, 2])
    def test_parity_mixed_chunked_traffic(self, model, depth):
        """Prompts straddling the token budget (chunked prefill + decode
        mixed steps) at both pipeline depths."""
        eng = make_engine(model, pipeline_depth=depth, token_budget=16)
        sp = SamplingParams(max_new_tokens=6)
        out = eng.generate({u: list(p) for u, p in self.MIXED.items()}, sp)
        _assert_parity(eng)
        tm = eng.timings
        assert tm["prompt_tokens"] == sum(len(p) for p in
                                          self.MIXED.values())
        assert tm["generated_tokens"] >= sum(len(v) for v in out.values())
        agg = eng.request_metrics()["aggregate"]
        assert agg["requests"] == agg["finished"] == len(self.MIXED)
        assert agg["open"] == 0
        # every finished record carries the full latency story
        for r in eng.request_metrics()["requests"]:
            assert r["finished"]
            assert r["queue_wait_ms"] is not None \
                and r["queue_wait_ms"] >= 0
            assert r["ttft_ms"] is not None and r["ttft_ms"] >= 0
            assert r["tpot_ms"] is not None and r["tpot_ms"] >= 0
            assert r["e2e_ms"] >= r["ttft_ms"]
            assert r["generated_tokens"] == len(out[r["uid"]])

    @pytest.mark.parametrize("mode", ["off", "on"])
    def test_parity_prefix_cache(self, model, mode):
        """Shared-prefix traffic arriving sequentially: the cache-on
        engine serves prompt tokens from the cache; per-request
        cached_tokens reconcile with the hit counters either way."""
        shared = list(range(1, 33))              # two full 16-tok blocks
        prompts = {u: shared + [100 + u, 101 + u, 102 + u]
                   for u in range(3)}
        eng = make_engine(model, prefix_cache=mode)
        sp = SamplingParams(max_new_tokens=2)
        for u, p in prompts.items():             # sequential: later
            eng.generate({u: list(p)}, sp)       # requests can hit
        _assert_parity(eng)
        tm = eng.timings
        if mode == "on":
            assert tm["cached_tokens"] > 0 and tm["prefix_hits"] >= 2
        else:
            assert tm["cached_tokens"] == 0 == tm["prefix_hits"]
        assert eng.request_metrics()["aggregate"]["finished"] == 3

    def test_parity_decode_burst(self, model):
        """The burst path (device-side multi-token decode) bumps the
        same counters as the stepwise collect."""
        eng = make_engine(model, decode_burst=4)
        sp = SamplingParams(max_new_tokens=8)
        out = eng.generate({0: [5, 17, 99], 1: [7, 7, 1, 2]}, sp)
        assert all(len(v) == 8 for v in out.values())
        _assert_parity(eng)
        assert eng.timings["generated_tokens"] \
            >= sum(len(v) for v in out.values())

    def test_trace_export_has_serving_span_types(self, model, tmp_path):
        """A pipelined generate() with tracing on exports a valid Chrome
        trace carrying >= 4 distinct serving-loop span types, one track
        each (the acceptance-criteria artifact)."""
        eng = make_engine(model, pipeline_depth=2, trace=True)
        eng.generate({0: list(range(1, 40)), 1: [9, 8, 7]},
                     SamplingParams(max_new_tokens=5))
        path = str(tmp_path / "serving_trace.json")
        eng.tracer.export_chrome_trace(path)
        doc = json.load(open(path))
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        names = {e["name"] for e in spans}
        assert {"schedule", "stage", "dispatch", "wait",
                "readback"} <= names
        tracks = {e["args"]["name"] for e in doc["traceEvents"]
                  if e.get("ph") == "M" and e["name"] == "thread_name"}
        assert len(tracks) >= 4
        # spans carry their dispatch sequence id for cross-track joins
        assert any("sid" in e.get("args", {}) for e in spans)

    def test_trace_disabled_by_default(self, model):
        eng = make_engine(model)
        eng.generate({0: [1, 2, 3]}, SamplingParams(max_new_tokens=3))
        assert not eng.tracer.enabled and len(eng.tracer) == 0

    def test_timings_backcompat_and_resets(self, model):
        """engine.timings stays a dict-shaped accumulator (bench.py and
        older tests read/reset it) while the same numbers live in the
        registry."""
        eng = make_engine(model)
        eng.generate({0: [1, 2, 3, 4]}, SamplingParams(max_new_tokens=4))
        tm = eng.timings
        assert set(tm) == {"schedule_ms", "stage_ms", "device_ms",
                           "wait_ms", "readback_ms", "compile_ms",
                           "steps", "compiles", "compile_retraces",
                           "prompt_tokens", "cached_tokens",
                           "prefix_hits", "generated_tokens",
                           "spec_drafted_tokens", "spec_accepted_tokens",
                           "spec_rejected_tokens", "spec_windows",
                           "step_retries", "requests_failed",
                           "kv_tier_demotions", "kv_tier_spills",
                           "kv_tier_drops", "kv_tier_revives_ram",
                           "kv_tier_revives_nvme",
                           "kv_tier_revives_remote",
                           "kv_tier_restage_overlap_hits",
                           "kv_tier_verify_failures",
                           "kv_tier_demoted_bytes",
                           "kv_tier_spilled_bytes",
                           "kv_tier_remote_blocks"}
        assert tm["steps"] > 0 and isinstance(tm["steps"], int)
        assert dict(tm)["steps"] == tm["steps"]
        # the registry sees the same number
        assert eng.metrics.get("serving_steps_total").value() \
            == tm["steps"]
        eng.reset_timings()
        assert tm["steps"] == 0 and tm["schedule_ms"] == 0.0
        # reset_timings does NOT clear request records ...
        assert eng.request_metrics()["aggregate"]["finished"] == 1
        # ... reset_metrics clears everything
        eng.generate({1: [1, 2]}, SamplingParams(max_new_tokens=2))
        eng.reset_metrics()
        assert eng.timings["steps"] == 0
        assert eng.request_metrics()["requests"] == []
        assert len(eng.tracer) == 0
        assert eng.request_metrics()["aggregate"]["ttft_ms"]["count"] == 0

    def test_prometheus_and_snapshot_from_engine(self, model):
        eng = make_engine(model)
        eng.generate({0: [1, 2, 3, 4, 5]}, SamplingParams(max_new_tokens=4))
        snap = json.loads(json.dumps(eng.metrics_snapshot()))
        assert snap["serving_steps_total"] == eng.timings["steps"]
        assert snap["serving_ttft_ms"]["count"] == 1
        parsed = parse_prometheus_text(eng.metrics.prometheus_text())
        assert parsed["serving_steps_total"]["samples"][
            ("serving_steps_total", ())] == float(eng.timings["steps"])
        assert parsed["serving_ttft_ms"]["samples"][
            ("serving_ttft_ms_count", ())] == 1.0

    def test_engine_monitor_fanout(self, model):
        class StubMonitor:
            def __init__(self):
                self.events = []

            def write_events(self, evs):
                self.events.extend(evs)

        eng = make_engine(model)
        eng.generate({0: [1, 2, 3]}, SamplingParams(max_new_tokens=3))
        mon = StubMonitor()
        eng.publish_metrics(mon, step=1)
        names = {n for n, _, _ in mon.events}
        assert "serving_steps_total" in names
        assert "serving_ttft_ms_count" in names


# --------------------------------------------------------------------------
# training-engine telemetry
# --------------------------------------------------------------------------

class TestTrainingTelemetry:
    def _engine(self, monitor=None, **telemetry):
        import deepspeed_tpu as ds

        m = build_model("gpt2", max_seq_len=32, num_layers=2, d_model=32,
                        num_heads=2, vocab_size=64)
        return ds.initialize(model=m, config={
            "train_micro_batch_size_per_device": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": -1},
            "steps_per_print": 1,
            "telemetry": telemetry,
        }, monitor=monitor), m

    def _batch(self, eng):
        from deepspeed_tpu.runtime.dataloader import (DataLoader,
                                                      synthetic_lm_data)

        data = synthetic_lm_data(64, eng.train_batch_size * 4, 32)
        return next(iter(DataLoader(data, eng.train_batch_size)))

    def test_step_phases_and_trace(self):
        eng, _ = self._engine(trace=True)
        for _ in range(2):
            eng.train_batch(self._batch(eng))
        snap = eng.metrics_snapshot()
        assert snap["training_steps_total"] == 2
        assert snap["training_step_host_ms"]["count"] == 2
        for k in ("training_pre_step_ms_total", "training_stage_ms_total",
                  "training_dispatch_ms_total"):
            assert snap[k] >= 0.0
        names = {e["name"] for e in eng.tracer.events()}
        assert {"pre_step", "stage", "dispatch", "fetch"} <= names

    def test_registry_rides_monitor_pipeline(self):
        class StubMonitor:
            enabled = True

            def __init__(self):
                self.events = []

            def write_events(self, evs):
                self.events.extend(evs)

            def write_scalars(self, step, scalars):
                self.write_events([(k, float(v), step)
                                   for k, v in scalars.items()])

        mon = StubMonitor()
        eng, _ = self._engine(monitor=mon)
        eng.train_batch(self._batch(eng))
        names = {n for n, _, _ in mon.events}
        # loss scalars AND registry metrics through ONE writer
        assert "Train/loss" in names
        assert "training_steps_total" in names
        assert "training_step_host_ms_count" in names
