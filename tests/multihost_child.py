"""Two-process multi-host worker (driven by tests/test_multihost.py).

Covers the multi-host-critical paths no single-process test can reach:
``engine.shard_batch``'s ``make_array_from_process_local_data`` assembly
and the checkpoint engine's replica-deduped multi-host writes + resume
(reference analog: tests/unit/common.py:117 N-process NCCL-loopback
harness; here two jax.distributed CPU processes over Gloo).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=2 "
    "--xla_cpu_enable_concurrency_optimized_scheduler=false")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    workdir = sys.argv[3]
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert jax.device_count() == 4

    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model

    def make_engine():
        m = build_model("gpt2", vocab_size=128, num_layers=2, d_model=32,
                        num_heads=4, max_seq_len=16, seed=7)
        return ds.initialize(model=m, config={
            "train_micro_batch_size_per_device": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "mesh": {"data": 4},
            "steps_per_print": 1000})

    eng = make_engine()
    assert eng.train_batch_size == 4

    def local_batch(seed):
        # every process holds only ITS devices' rows (the multi-host
        # contract of shard_batch)
        full = np.random.RandomState(seed).randint(0, 128, (4, 16))
        return {"input_ids": full[pid * 2:(pid + 1) * 2]}

    losses = []
    for i in range(2):
        losses.append(float(eng.train_batch(local_batch(i))["loss"]))
    print(f"RANK{pid} LOSSES {losses[0]:.6f} {losses[1]:.6f}", flush=True)

    ckpt_dir = os.path.join(workdir, "ckpt")
    eng.save_checkpoint(ckpt_dir, tag="step2")

    # resume into a FRESH engine and take one more step; the original
    # engine takes the same step — trajectories must coincide
    eng2 = make_engine()
    eng2.load_checkpoint(ckpt_dir, tag="step2")
    a = float(eng2.train_batch(local_batch(2))["loss"])
    b = float(eng.train_batch(local_batch(2))["loss"])
    print(f"RANK{pid} RESUME {a:.6f} CONT {b:.6f}", flush=True)
    assert abs(a - b) < 1e-5, (a, b)

    # ---- ZeRO-Infinity: per-process NVMe master fragments --------------
    # (reference: per-rank swap files, runtime/zero/stage3.py:614)
    def make_nvme_engine(swap):
        m = build_model("gpt2", vocab_size=128, num_layers=2, d_model=32,
                        num_heads=4, max_seq_len=16, seed=7)
        return ds.initialize(model=m, config={
            "train_micro_batch_size_per_device": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 2,
                "offload_optimizer": {
                    "device": "nvme",
                    "nvme_path": os.path.join(workdir, swap, f"p{pid}"),
                    "buffer_size": 4096}},
            "mesh": {"data": 4},
            "steps_per_print": 1000})

    neng = make_nvme_engine("swap_a")
    assert neng._nvme is not None and neng._nvme._multi
    # the masters really are per-rank FRAGMENTS: at least one sharded
    # leaf's local fragment covers strictly less than the full extent
    def frag_elems(i):
        return sum(
            int(np.prod(neng._nvme._frag_shape(i, k)))
            for k in range(len(neng._nvme._frags[i])))
    metas = neng._nvme._leaf_meta
    assert any(frag_elems(i) < int(np.prod(metas[i][0]))
               for i in range(len(metas))), \
        "no leaf is fragment-sharded; per-rank swap is not happening"
    nlosses = [float(neng.train_batch(local_batch(10 + i))["loss"])
               for i in range(2)]
    print(f"RANK{pid} NVME_LOSSES {nlosses[0]:.6f} {nlosses[1]:.6f}",
          flush=True)
    nckpt = os.path.join(workdir, "nvme_ckpt")
    neng.save_checkpoint(nckpt, tag="step2")

    neng2 = make_nvme_engine("swap_b")
    neng2.load_checkpoint(nckpt, tag="step2")
    na = float(neng2.train_batch(local_batch(12))["loss"])
    nb = float(neng.train_batch(local_batch(12))["loss"])
    print(f"RANK{pid} NVME_RESUME {na:.6f} CONT {nb:.6f}", flush=True)
    assert abs(na - nb) < 1e-5, (na, nb)

    # the NVMe run must match a plain stage-2 run (the masters on disk
    # are the same math, just swapped per rank)
    peng = make_engine()
    plosses = [float(peng.train_batch(local_batch(10 + i))["loss"])
               for i in range(2)]
    assert all(abs(x - y) < 5e-4 for x, y in zip(nlosses, plosses)), (
        nlosses, plosses)

    # ---- multi-host per-layer param STREAMING --------------------------
    # (offload_param=nvme: every process streams only its fragments of
    # each layer; the 70B ZeRO-Infinity north-star config end-to-end)
    def make_stream_engine(swap, stage3=True):
        m = build_model("gpt2", vocab_size=128, num_layers=2, d_model=32,
                        num_heads=4, max_seq_len=16, seed=7)
        # threshold 0: the toy leaves are all under the default
        # param_persistence_threshold and would stay replicated
        zo = {"stage": 3, "param_persistence_threshold": 0}
        if stage3 == "stream":
            zo = {"stage": 3, "param_persistence_threshold": 0,
                  "offload_optimizer": {
                      "device": "nvme",
                      "nvme_path": os.path.join(workdir, swap, f"p{pid}"),
                      "buffer_size": 4096},
                  "offload_param": {
                      "device": "nvme",
                      "nvme_path": os.path.join(workdir, swap,
                                                f"p{pid}")}}
        # fsdp=4 spans BOTH processes, so each process's devices address
        # only half of every fsdp-sharded leaf — true per-rank fragments
        return ds.initialize(model=m, config={
            "train_micro_batch_size_per_device": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": zo,
            "mesh": {"fsdp": 4},
            "steps_per_print": 1000})

    seng = make_stream_engine("sswap_a", stage3="stream")
    assert seng._stream is not None and seng._stream._multi
    # layer fragments are strictly per-rank for at least one sharded leaf
    from deepspeed_tpu.runtime.zero_infinity import fragment_shape
    tpl_flat = [s.shape for s in jax.tree.leaves(seng._stream._layer_tpl)]
    assert any(
        sum(int(np.prod(fragment_shape(shp, idx)))
            for idx in seng._stream._lfrags[j]) < int(np.prod(shp))
        for j, shp in enumerate(tpl_flat)), \
        "no layer leaf is fragment-sharded under param streaming"
    slosses = [float(np.asarray(seng.train_batch(
        local_batch(20 + i))["loss"])) for i in range(2)]
    print(f"RANK{pid} STREAM_LOSSES {slosses[0]:.6f} {slosses[1]:.6f}",
          flush=True)
    # parity vs a plain multi-host stage-3 run on the same batches
    pe3 = make_stream_engine("unused", stage3=True)
    p3 = [float(np.asarray(pe3.train_batch(
        local_batch(20 + i))["loss"])) for i in range(2)]
    assert all(abs(x - y) < 5e-4 for x, y in zip(slosses, p3)), (
        slosses, p3)

    # checkpoint save -> fresh streamed engine -> resume parity
    sckpt = os.path.join(workdir, "stream_ckpt")
    seng.save_checkpoint(sckpt, tag="step2")
    seng2 = make_stream_engine("sswap_b", stage3="stream")
    seng2.load_checkpoint(sckpt, tag="step2")
    sa = float(np.asarray(seng2.train_batch(local_batch(22))["loss"]))
    sb = float(np.asarray(seng.train_batch(local_batch(22))["loss"]))
    print(f"RANK{pid} STREAM_RESUME {sa:.6f} CONT {sb:.6f}", flush=True)
    assert abs(sa - sb) < 1e-5, (sa, sb)
    print(f"RANK{pid} OK", flush=True)


if __name__ == "__main__":
    main()
