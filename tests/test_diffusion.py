"""Diffusers model family: UNet2DCondition + AutoencoderKL over the
spatial op suite (reference: model_implementations/diffusers/unet.py:8,
vae.py:8; containers module_inject/containers/unet.py:13, vae.py:10).
NHWC (channels-last) conv path throughout — the TPU-native layout."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.diffusion import (AutoencoderKL, UNet2DCondition,
                                            UNetConfig, VAEConfig)


def tiny_unet(**over):
    kw = dict(block_out_channels=(32, 64, 64), layers_per_block=1,
              cross_attention_dim=48, attention_head_dim=4, num_groups=8)
    kw.update(over)
    return UNet2DCondition(UNetConfig(**kw))


class TestUNet:
    def test_sd_shaped_smoke(self):
        """SD-1.x structure: 4 stages, 2 res layers/block, cross-attn
        transformers at the three shallower stages, /8 downsampling —
        channels reduced for the test box."""
        unet = tiny_unet(block_out_channels=(32, 64, 128, 128),
                         layers_per_block=2)
        lat = jnp.asarray(np.random.RandomState(0).randn(1, 32, 32, 4),
                          jnp.float32)
        ctx = jnp.asarray(np.random.RandomState(1).randn(1, 7, 48),
                          jnp.float32)
        eps = unet(lat, jnp.asarray([10]), ctx)
        assert eps.shape == lat.shape
        assert np.isfinite(np.asarray(eps)).all()

    def test_context_conditions_output(self):
        unet = tiny_unet()
        lat = jnp.asarray(np.random.RandomState(0).randn(1, 16, 16, 4),
                          jnp.float32)
        r = np.random.RandomState(1)
        c1 = jnp.asarray(r.randn(1, 5, 48), jnp.float32)
        c2 = jnp.asarray(r.randn(1, 5, 48), jnp.float32)
        t = jnp.asarray([50])
        e1 = unet(lat, t, c1)
        e2 = unet(lat, t, c2)
        assert float(jnp.abs(e1 - e2).max()) > 1e-6   # cross-attn is live

    def test_timestep_conditions_output(self):
        unet = tiny_unet()
        lat = jnp.asarray(np.random.RandomState(0).randn(1, 16, 16, 4),
                          jnp.float32)
        ctx = jnp.asarray(np.random.RandomState(1).randn(1, 5, 48),
                          jnp.float32)
        e1 = unet(lat, jnp.asarray([1]), ctx)
        e2 = unet(lat, jnp.asarray([900]), ctx)
        assert float(jnp.abs(e1 - e2).max()) > 1e-6

    def test_cfg_denoise_loop(self):
        """Classifier-free-guidance denoise loop — the serving usage:
        batched cond+uncond forward, guidance mix, iterative update."""
        unet = tiny_unet()
        r = np.random.RandomState(3)
        lat = jnp.asarray(r.randn(1, 16, 16, 4), jnp.float32)
        cond = jnp.asarray(r.randn(1, 5, 48), jnp.float32)
        uncond = jnp.zeros_like(cond)
        ctx2 = jnp.concatenate([uncond, cond])
        for t in (800, 500, 200):
            lat2 = jnp.concatenate([lat, lat])
            e_un, e_c = jnp.split(
                unet(lat2, jnp.full((2,), t), ctx2), 2)
            eps = e_un + 7.5 * (e_c - e_un)
            lat = lat - 0.1 * eps                 # toy scheduler step
        assert np.isfinite(np.asarray(lat)).all()


class TestVAE:
    def test_encode_decode_shapes(self):
        vae = AutoencoderKL(VAEConfig(block_out_channels=(16, 32, 32),
                                      layers_per_block=1, num_groups=8))
        img = jnp.asarray(np.random.RandomState(0).randn(1, 32, 32, 3),
                          jnp.float32)
        z = vae.encode(img)
        assert z.shape == (1, 8, 8, 4)            # /4 for 3 stages
        rec = vae.decode(z)
        assert rec.shape == img.shape

    def test_sampled_posterior(self):
        vae = AutoencoderKL(VAEConfig(block_out_channels=(16, 32),
                                      layers_per_block=1, num_groups=8))
        img = jnp.asarray(np.random.RandomState(1).randn(1, 16, 16, 3),
                          jnp.float32)
        z1 = vae.encode(img, rng=jax.random.PRNGKey(0))
        z2 = vae.encode(img, rng=jax.random.PRNGKey(1))
        zm = vae.encode(img)
        assert float(jnp.abs(z1 - z2).max()) > 0     # stochastic
        assert float(jnp.abs(z1 - zm).max()) > 0


class TestPipelineCompose:
    def test_vae_unet_latent_pipeline(self):
        """VAE.encode -> UNet denoise -> VAE.decode — the txt2img data
        path end-to-end at tiny scale."""
        vae = AutoencoderKL(VAEConfig(block_out_channels=(16, 32, 32),
                                      layers_per_block=1, num_groups=8))
        unet = tiny_unet()
        img = jnp.asarray(np.random.RandomState(0).randn(1, 32, 32, 3),
                          jnp.float32)
        ctx = jnp.asarray(np.random.RandomState(1).randn(1, 5, 48),
                          jnp.float32)
        z = vae.encode(img)
        eps = unet(z, jnp.asarray([100]), ctx)
        out = vae.decode(z - 0.1 * eps)
        assert out.shape == img.shape
