"""Pallas paged-attention kernel vs the XLA gather formulation
(reference analog: inference/v2/kernels/ragged_ops blocked_flash tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.model import (_paged_attention,
                                           _paged_attention_pallas)
from deepspeed_tpu.inference.ragged.state import RaggedBatch


def _mixed_batch(T=16, max_seqs=4, nblocks=12, bs=8, Hkv=2, D=16, seed=0):
    """Three live sequences at different positions + budget padding."""
    r = np.random.RandomState(seed)
    # seq 0: decode at pos 19 (3 blocks); seq 1: prefill chunk pos 4..11
    # (2 blocks); seq 2: decode at pos 0 (1 block)
    tables = np.full((max_seqs, nblocks), -1, np.int32)
    tables[0, :3] = [5, 2, 9]
    tables[1, :2] = [1, 7]
    tables[2, :1] = [4]
    tok_pos = [(0, 19)] + [(1, p) for p in range(4, 12)] + [(2, 0)]
    T_used = len(tok_pos)
    positions = np.zeros(T, np.int32)
    seq_slot = np.zeros(T, np.int32)
    valid = np.zeros(T, bool)
    for i, (s, p) in enumerate(tok_pos):
        seq_slot[i], positions[i], valid[i] = s, p, True
    kv = jnp.asarray(r.randn(nblocks + 1, bs, 2, Hkv, D), jnp.float32)
    batch = RaggedBatch(
        token_ids=jnp.zeros(T, jnp.int32),
        positions=jnp.asarray(positions),
        seq_slot=jnp.asarray(seq_slot),
        token_valid=jnp.asarray(valid),
        block_tables=jnp.asarray(tables),
        context_lens=jnp.zeros(max_seqs, jnp.int32),
        logits_idx=jnp.full(max_seqs, -1, jnp.int32),
        n_tokens=T_used, n_seqs=3)
    return kv, batch, bs


class TestPagedAttentionKernel:
    @pytest.mark.parametrize("H", [4, 2])
    def test_matches_xla_gather(self, H):
        kv, batch, bs = _mixed_batch()
        Hkv, D = kv.shape[3], kv.shape[4]
        q = jnp.asarray(np.random.RandomState(1).randn(
            batch.token_ids.shape[0], H, D), jnp.float32)
        scale = 1.0 / np.sqrt(D)
        ref = _paged_attention(kv, q, batch, bs, 4, scale)
        out = _paged_attention_pallas(kv, q, batch, bs, 4, scale)
        valid = np.asarray(batch.token_valid)
        np.testing.assert_allclose(np.asarray(out)[valid],
                                   np.asarray(ref)[valid],
                                   atol=1e-5, rtol=1e-5)

    def test_under_jit_with_bf16(self):
        kv, batch, bs = _mixed_batch()
        kv = kv.astype(jnp.bfloat16)
        D = kv.shape[4]
        q = jnp.asarray(np.random.RandomState(2).randn(
            batch.token_ids.shape[0], 4, D), jnp.bfloat16)
        scale = 1.0 / np.sqrt(D)
        f_ref = jax.jit(lambda kv, q: _paged_attention(kv, q, batch, bs, 4,
                                                       scale))
        f_pal = jax.jit(lambda kv, q: _paged_attention_pallas(
            kv, q, batch, bs, 4, scale))
        valid = np.asarray(batch.token_valid)
        np.testing.assert_allclose(
            np.asarray(f_pal(kv, q)).astype(np.float32)[valid],
            np.asarray(f_ref(kv, q)).astype(np.float32)[valid],
            atol=2e-2, rtol=2e-2)

    def test_engine_forced_pallas_decode_parity(self):
        """Full serving stack with attn_impl=pallas matches the dense
        forward (the greedy-parity bar from test_inference.py)."""
        import deepspeed_tpu  # noqa: F401  (registers presets)
        from tests.test_inference import make_fp32_engine, tiny_model
        from deepspeed_tpu.models import apply

        m = tiny_model()
        eng = make_fp32_engine(m, attn_impl="pallas")
        prompt = list(np.random.RandomState(3).randint(1, 128, 12))
        out = eng.generate({7: prompt}, SamplingParams_greedy())[7]
        # dense reference: greedy continuation with full attention
        ids = list(prompt)
        for _ in range(len(out)):
            logits = apply(m.config, m.params,
                           jnp.asarray([ids], jnp.int32))
            ids.append(int(jnp.argmax(logits[0, -1])))
        assert out == ids[len(prompt):]

    def test_engine_auto_probe_selects_and_serves(self):
        import deepspeed_tpu  # noqa: F401
        from tests.test_inference import make_fp32_engine, tiny_model

        m = tiny_model()
        eng = make_fp32_engine(m, attn_impl="auto")
        prompt = [3, 5, 7, 11]
        out = eng.generate({1: prompt}, SamplingParams_greedy())
        assert len(out[1]) > 0


class TestAliasedBlockTables:
    """Prefix-cache aliasing at the attention level: two sequences'
    block tables referencing the SAME physical block must read identical
    KV from it — attention is a pure gather by block id, so aliasing is
    invisible to the kernel.  Checked against a de-aliased reference
    where the shared content is duplicated into a private block."""

    def _aliased_batch(self, bs=8, Hkv=2, D=16, nblocks=12):
        r = np.random.RandomState(5)
        kv = np.asarray(r.randn(nblocks + 1, bs, 2, Hkv, D), np.float32)
        # both sequences share physical block 4 for positions 0..7, then
        # diverge; the de-aliased reference gives seq 1 a private copy
        # (block 9) with identical content
        kv[9] = kv[4]
        tables = np.full((4, nblocks), -1, np.int32)
        tables[0, :2] = [4, 2]
        tables[1, :2] = [4, 7]
        dealiased = tables.copy()
        dealiased[1, 0] = 9
        # one decode token per sequence, deep enough to read the shared
        # block AND the private tail
        tok_pos = [(0, 12), (1, 14)]
        T = 4
        positions = np.zeros(T, np.int32)
        seq_slot = np.zeros(T, np.int32)
        valid = np.zeros(T, bool)
        for i, (s, p) in enumerate(tok_pos):
            seq_slot[i], positions[i], valid[i] = s, p, True

        def batch(tab):
            return RaggedBatch(
                token_ids=jnp.zeros(T, jnp.int32),
                positions=jnp.asarray(positions),
                seq_slot=jnp.asarray(seq_slot),
                token_valid=jnp.asarray(valid),
                block_tables=jnp.asarray(tab),
                context_lens=jnp.zeros(4, jnp.int32),
                logits_idx=jnp.full(4, -1, jnp.int32),
                n_tokens=2, n_seqs=2)
        return jnp.asarray(kv), batch(tables), batch(dealiased), bs, valid

    @pytest.mark.parametrize("impl", [_paged_attention,
                                      _paged_attention_pallas])
    def test_shared_block_reads_identical_kv(self, impl):
        kv, aliased, dealiased, bs, valid = self._aliased_batch()
        D = kv.shape[4]
        q = jnp.asarray(np.random.RandomState(6).randn(
            aliased.token_ids.shape[0], 4, D), jnp.float32)
        scale = 1.0 / np.sqrt(D)
        out_alias = impl(kv, q, aliased, bs, 4, scale)
        out_ref = impl(kv, q, dealiased, bs, 4, scale)
        np.testing.assert_allclose(np.asarray(out_alias)[valid],
                                   np.asarray(out_ref)[valid],
                                   atol=1e-6, rtol=1e-6)


def SamplingParams_greedy():
    from deepspeed_tpu.inference import SamplingParams
    return SamplingParams(temperature=0.0, max_new_tokens=6)
