"""Known-good twin of bad_donation_lifetime (no findings)."""
import jax
import jax.numpy as jnp


def step(params, kv, batch):
    return kv + batch, kv * 2


def step2(params, kv):
    return kv + 1, kv * 2


class Engine:
    def __init__(self):
        self.kv = jnp.zeros((4, 4))
        self._step = jax.jit(step, donate_argnums=(1,))

    def run(self, params, batch):
        out, self.kv = self._step(params, self.kv, batch)
        return out + self.kv               # rebound: fresh buffer


class Pipelined:
    def _build(self):
        def pstep(params, kv):
            return kv * 2, kv + 1
        return jax.jit(pstep, donate_argnums=(1,))

    def serve(self, params):
        fn = self._build()
        kv = jnp.zeros((2, 2))
        a, kv = fn(params, kv)             # rebound in the call
        return a + kv


class Cache:
    def peek(self, kv):
        return float(jnp.sum(kv))          # reads, stores nothing


def run_with_peek(params, batch):
    step_fn = jax.jit(step, donate_argnums=(1,))
    cache = Cache()
    kv = jnp.zeros((4, 4))
    cache.peek(kv)
    out, kv = step_fn(params, kv, batch)
    return out, kv


def consume(params, kv):
    fn = jax.jit(step2, donate_argnums=(1,))
    out, _ = fn(params, kv)
    return out


def call_no_reuse(params):
    kv = jnp.zeros((4, 4))
    out = consume(params, kv)
    return out * 2


def distinct_positions(params):
    fn = jax.jit(step2, donate_argnums=(1,))
    kv = jnp.zeros((4, 4))
    out, _ = fn(params, kv)
    return out
