"""Known-good twin of bad_silent_except (no silent-except findings)."""
import logging

logger = logging.getLogger(__name__)


def probe(fn, x):
    try:
        return fn(x), True
    except Exception as e:
        logger.warning("probe failed (%s); falling back", e)
        return None, False


def load(path):
    try:
        with open(path) as f:
            return f.read()
    except OSError:                     # narrow handler: fine silent
        return ""


def wrapped(fn):
    try:
        return fn()
    except Exception as e:
        raise RuntimeError("fn failed") from e


def intentional(fn):
    try:
        return fn()
    except Exception:  # tpulint: disable=silent-except
        return None
