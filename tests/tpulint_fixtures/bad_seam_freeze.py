"""Deliberately-bad fixture: seam-freeze.

Two paths reach the engine without the executor seam, and neither is
visible to the per-file async-blocking rule (which only inspects
syntactic ``async def`` bodies): a sync helper *called from* a
coroutine (loop domain), and a spawned thread target (thread domain).
"""
import threading


class Relay:
    def __init__(self, engine):
        self.engine = engine

    async def drive(self):
        self._tick()

    def _tick(self):
        self.engine.step({})             # BAD: loop domain, no seam

    def watch(self):
        t = threading.Thread(target=self._probe, daemon=True)
        t.start()

    def _probe(self):
        self.engine.query(0)             # BAD: thread domain, no seam
