"""Known-good twin of bad_raise_escape: every device-ish raise
reachable from a serving loop is caught between the raise and the loop
and routed through the failure classifier seam.
"""


class DispatchTimeoutError(RuntimeError):
    pass


class InjectedFault(RuntimeError):
    pass


class Engine:
    def __init__(self, failures):
        self.failures = failures

    def step(self, fn):  # tpulint: serving-loop
        try:
            return self._dispatch(fn)
        except DispatchTimeoutError as e:
            return self.failures.classify_failure(e)

    def _dispatch(self, fn):
        if fn is None:
            raise DispatchTimeoutError("device stalled")
        return fn()

    def decode_burst(self, fn):  # tpulint: serving-loop
        try:
            return self._inject(fn)
        except Exception as e:
            return self.failures.classify_failure(e)

    def _inject(self, fn):
        # caught INSIDE the callee: never reaches the serving loop
        try:
            if fn is None:
                raise InjectedFault("chaos tier fault")
        except InjectedFault as e:
            return self.failures.classify_failure(e)
        return fn()

    def flush(self, fn):  # tpulint: serving-loop
        try:
            return self.failures.run(fn)
        except DispatchTimeoutError as e:
            return self.failures.classify_failure(e)
