"""Known-good twin of bad_counter_pairing: every function that bumps
one side of a declared pair bumps the other side in the same region.
"""


class _Counter:
    def inc(self, **labels):
        return None


class Metrics:
    # tpulint: pair=_c_finished/_c_terminal
    # tpulint: pair=drafted/accepted
    def __init__(self):
        self._c_finished = _Counter()
        self._c_terminal = _Counter()
        self.tm = {"drafted": 0, "accepted": 0}

    def note_finish(self, status):
        self._c_finished.inc()
        self._c_terminal.inc(status=status)

    def note_draft(self, n, hits):
        self.tm["drafted"] += n
        self.tm["accepted"] += hits

    def unrelated(self):
        # bumping something outside any declared pair is fine
        self.tm["steps"] = self.tm.get("steps", 0) + 1
