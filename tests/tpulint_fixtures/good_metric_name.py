"""Known-good fixture for the metric-name rule: grammar-conforming
names, a prefix-carrying dynamic name, a fully dynamic name (skipped as
unverifiable), and a non-registry receiver (out of scope)."""


def setup_metrics(registry, reg, sink, compute_name):
    registry.counter("serving_steps_total")
    reg.gauge("training_mfu")
    registry.histogram("serving_ttft_ms", (1.0, 2.0))
    registry.gauge_fn("serving_kv_blocks_free", lambda: 0)
    for k in ("schedule", "stage"):
        registry.counter(f"serving_{k}_ms_total")
    # fully dynamic: the rule cannot verify it and stays quiet
    registry.counter(compute_name())
    # not a metrics registry: naming is that object's own business
    sink.counter("WhateverCase")
    return registry
