"""Known-bad: silent bf16/f32 promotion in traced code (tpulint:
dtype-flow) — the _mm residual-stream bug class."""
import jax
import jax.numpy as jnp


@jax.jit
def promote_local(x):
    w = jnp.zeros((4, 4), dtype=jnp.float32)
    h = x.astype(jnp.bfloat16)
    return h @ w                           # BAD: bf16 @ f32 -> silent f32


def helper(h, w):
    return h * w                           # BAD: mixes caller's bf16 and f32


@jax.jit
def promote_through_call(x):
    h = x.astype(jnp.bfloat16)
    w = jnp.ones((4,), dtype=jnp.float32)
    return helper(h, w)
