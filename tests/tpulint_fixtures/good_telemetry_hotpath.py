"""Known-good twin of bad_telemetry_hotpath (no findings)."""
import time

import jax

tracer = object()
metrics = object()


class Engine:
    def step(self):  # tpulint: serving-loop
        t0 = time.perf_counter()            # monotonic: the right clock
        self._run()
        return time.perf_counter() - t0

    def snapshot(self):
        # unmarked method: wall-clock timestamps on record/export paths
        # (JSONL snapshot stamps etc.) are legitimate
        return {"time": time.time()}

    def _run(self):
        return 0


def host_loop(x):
    # telemetry AROUND the dispatch, on the host side, is the pattern
    with tracer.span("step"):
        y = jitted(x)
    metrics.inc("steps", 1)
    return y


@jax.jit
def jitted(x):
    return x * 2
