"""Known-bad: profiler session control on the serving loop
(tpulint: profiler-capture)."""
import jax
from jax.profiler import start_trace, stop_trace


class Engine:
    def step(self):  # tpulint: serving-loop
        jax.profiler.start_trace("/tmp/t")    # BAD: unbounded session
        out = self._run()
        jax.profiler.stop_trace()             # BAD: bypasses the seam
        return out

    def _collect(self):  # tpulint: serving-loop
        start_trace("/tmp/t")                 # BAD: direct-import form
        with jax.profiler.trace("/tmp/t"):    # BAD: session ctx manager
            out = self._run()
        stop_trace()                          # BAD: direct-import form
        return out

    def _run(self):
        return 0
