"""Known-good twin of bad_lock_order_cycle: both paths acquire the two
locks in the same global order, so the acquisition graph is acyclic."""
import threading


class Ledger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0
        self.b = 0

    def credit(self, n):
        with self._alock:
            with self._block:
                self.a += n
                self.b += n

    def debit(self, n):
        with self._alock:
            with self._block:
                self.b -= n
                self.a -= n
