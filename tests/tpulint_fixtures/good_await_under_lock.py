"""Known-good twin of bad_await_under_lock: the awaited section runs
under an ``asyncio.Lock`` (``async with`` suspends cleanly), and the
shared counter's sync-lock region contains no await — main-thread
readers share the same sync lock."""
import asyncio
import threading


class Budget:
    def __init__(self):
        self._alock = asyncio.Lock()
        self._sync = threading.Lock()
        self.spent = 0

    async def charge(self, amount):
        async with self._alock:
            await asyncio.sleep(0)
            with self._sync:
                self.spent += amount

    def snapshot(self):
        with self._sync:
            return self.spent
