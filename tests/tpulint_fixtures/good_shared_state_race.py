"""Known-good twin of bad_shared_state_race: the same worker-thread
shape, with every cross-domain access behind a recognized discipline —
a ``queue.Queue`` hand-off, a shared ``threading.Lock``, and a
single-writer constant flag."""
import queue
import threading


class TokenFeed:
    def __init__(self):
        self.pending = queue.Queue()     # hand-off: thread-safe by type
        self.total = 0
        self.stopped = False             # single-writer constant flag
        self._lock = threading.Lock()
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def _drain(self):
        while not self.stopped:
            item = self.pending.get()
            with self._lock:
                self.total += len(item)

    def submit(self, item):
        self.pending.put(item)

    def stats(self):
        with self._lock:
            return self.total

    def stop(self):
        self.stopped = True
