"""Known-good twin of bad_async_blocking (no findings): every blocking
call routes through the executor/to_thread seam, awaits are awaited,
and sync helpers keep their direct engine calls (they run ON the
engine thread)."""
import asyncio
from functools import partial


async def drive(engine, executor):
    loop = asyncio.get_running_loop()
    # the executor pattern: the engine call is an ARGUMENT, not a call
    out = await loop.run_in_executor(executor, engine.step)
    return out


async def finish(backend, executor):
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        executor, partial(backend.drain, 1000.0))


async def admit(backend, uid, tokens):
    # to_thread hands the thunk off the loop; the lambda's body is the
    # deferred sync context, not this coroutine's
    return await asyncio.to_thread(lambda: backend.put(uid, tokens))


async def throttle():
    await asyncio.sleep(0.5)


async def pump(queue, watcher):
    item = await queue.get()
    queue.put_nowait(item)          # non-blocking queue op
    watcher.cancel()                # asyncio.Task.cancel: not an engine
    return item


def drain_backlog(engine):
    # sync helper: runs on the engine thread, direct calls are its job
    return engine.drain(500.0)
