"""Known-bad: speculative draft-window key derivation that reuses a
consumed key (tpulint: rng-discipline).  The verify step must key every
window column with ``fold_in(fold_in(rng, uid), position)`` — one fresh
fold per sampled position (sampler.window_keys); re-consuming one row
key across columns replays the same randomness at every draft position.
"""
import jax
import jax.numpy as jnp


def window_row_key_reused(rng, uid, logits):
    """logits [W, V]: every column sampled with the SAME row key."""
    row_key = jax.random.fold_in(rng, uid)
    out = []
    for w in range(logits.shape[0]):
        out.append(jax.random.categorical(row_key, logits[w]))  # BAD: loop-invariant key
    return jnp.stack(out)


def window_base_key_double_consume(rng, logits0, logits1):
    """Bonus column sampled off the already-consumed base key."""
    first = jax.random.categorical(rng, logits0)
    bonus = jax.random.categorical(rng, logits1)   # BAD: rng already consumed
    return first + bonus
