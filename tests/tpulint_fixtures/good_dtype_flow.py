"""Known-good twin of bad_dtype_flow (no dtype-flow findings)."""
import jax
import jax.numpy as jnp


@jax.jit
def explicit_cast(x):
    w = jnp.zeros((4, 4), dtype=jnp.float32)
    h = x.astype(jnp.bfloat16)
    wide = h.astype(jnp.float32) @ w       # widened deliberately
    narrow = h @ w.astype(jnp.bfloat16)    # narrowed deliberately
    acc = (h @ w.astype(jnp.bfloat16)).astype(jnp.float32)
    return wide, narrow, acc


def helper(h, w):
    return h * w.astype(h.dtype)           # runtime-matched, not static


@jax.jit
def matched_through_call(x):
    h = x.astype(jnp.bfloat16)
    w = jnp.ones((4,), dtype=jnp.float32)
    return helper(h, w)
