"""Known-bad: collective axis names absent from the mesh
(tpulint: axis-name — valid vocabulary comes from comm/mesh.py)."""
import jax
from jax import lax


def grad_sync(g):
    return lax.psum(g, "model")             # BAD: no "model" mesh axis


def gather(x):
    return lax.all_gather(x, axis_name="tp", axis=0, tiled=True)  # BAD


def rank():
    return lax.axis_index("stage")          # BAD: "stage" not a mesh axis


def mixed(v):
    return lax.pmean(v, ("data", "shard"))  # BAD: "shard" invalid
