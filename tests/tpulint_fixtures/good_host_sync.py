"""Known-good twin of bad_host_sync (no host-sync findings)."""
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@jax.jit
def decorated(x):
    return jnp.sum(x)                   # stays on device


@partial(jax.jit, static_argnames=("n",))
def partial_decorated(x, n):
    k = int(np.prod(x.shape))           # shape arithmetic is static
    return x * k + n


def host_side(x):
    # NOT jit-traced: syncing here is the caller's business
    return float(np.asarray(x).sum())


def fetch(i):
    return np.asarray(i) + 1            # host callback body: host is fine


def streamed(x):
    y = jax.pure_callback(fetch, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return y * 2


streamed_jit = jax.jit(streamed)
