"""Known-good twin of bad_terminal_exhaustive: every removal from a
live set reaches a close-out root (directly, via the call graph, or as
a transfer into another live set), every close-out literal is a
declared terminal status, and every declared status is emitted.
"""

TERMINAL_STATUSES = ("finished", "cancelled", "shed")


class Tracker:
    def __init__(self):
        # tpulint: live-set — uid -> prompt tokens
        self.open = {}
        # tpulint: live-set — uid -> tokens parked for migration
        self.parked = {}

    def put(self, uid, tokens):
        self.open[uid] = tokens

    def _close(self, uid, status):       # tpulint: close-out
        self.open.pop(uid, None)
        return status

    def on_finish(self, uid):
        self._close(uid, "finished")

    def cancel(self, uid):
        self._close(uid, "cancelled")

    def reap(self, stale):
        # removal is fine here: this function reaches a close-out root
        for uid in stale:
            self._close(uid, "shed")

    def park(self, uid):
        # transfer, not a leak: the uid moves to another live set
        self.parked[uid] = self.open.pop(uid)

    def unpark(self, uid):
        self.open[uid] = self.parked.pop(uid)
