"""Known-good twin of bad_static_args (no static-args findings)."""
import jax
from functools import partial


@partial(jax.jit, static_argnames=("block_size",))
def kernel(x, block_size):
    return x * block_size


@partial(jax.jit, static_argnames=("mode",))
def configured(x, mode="fast"):         # hashable static default
    return x


def scale(x, factor=2):
    return x * factor


scaled = jax.jit(scale, static_argnums=(1,))
