"""Known-bad fixture for the metric-name rule: registry metric names
off the ^(serving|training)_[a-z0-9_]+$ grammar, and a duplicate
registration site forking a series."""


def setup_metrics(registry):
    registry.counter("request_count")               # BAD: no family prefix
    registry.gauge("serving_QueueDepth")            # BAD: uppercase
    registry.histogram("servng_ttft_ms", (1.0,))    # BAD: typo'd prefix
    registry.gauge_fn("serving-mfu", lambda: 0.0)   # BAD: dash not underscore
    for k in ("schedule", "stage"):
        registry.counter(f"srv_{k}_ms_total")       # BAD: dynamic head off-grammar
    a = registry.counter("serving_tokens_total")
    b = registry.counter("serving_tokens_total")    # BAD: second site forks the series
    return a, b
