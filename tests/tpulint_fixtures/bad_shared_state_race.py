"""Deliberately-bad fixture: shared-state-race.

A worker thread drains a plain list and bumps a counter that the main
(serving) thread also mutates/reads — no lock, no queue, no flag
discipline.  Exactly two attrs conflict: ``pending`` and ``total``.
"""
import threading


class TokenFeed:
    def __init__(self):
        self.pending = []
        self.total = 0
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def _drain(self):
        while self.pending:
            item = self.pending.pop()    # BAD: list mutated from thread
            self.total += len(item)      # BAD: counter written from thread

    def submit(self, item):
        self.pending.append(item)        # ... and appended from main

    def stats(self):
        return self.total                # ... and read from main
