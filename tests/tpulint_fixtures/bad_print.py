"""Known-bad: stray stdout/debugger use in library code (tpulint: print)."""
import pdb                              # BAD: debugger import


def train_step(x):
    print("step", x)                    # BAD: print in library code
    if x < 0:
        pdb.set_trace()                 # BAD: debugger call
    breakpoint()                        # BAD: debugger call
    return x
