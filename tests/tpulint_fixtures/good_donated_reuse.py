"""Known-good twin of bad_donated_reuse (no donated-reuse findings)."""
import jax
import jax.numpy as jnp


def step(params, kv, batch):
    return kv + batch, kv * 2


def serve(params, batch):
    step_fn = jax.jit(step, donate_argnums=(1,))
    kv = jnp.zeros((4, 4))
    logits, kv = step_fn(params, kv, batch)     # rebound: fresh buffer
    return logits + kv


class Engine:
    def __init__(self):
        self.kv = jnp.zeros((4, 4))

    def run(self, params, batch):
        fn = jax.jit(step, donate_argnums=(1,))
        out, self.kv = fn(params, self.kv, batch)   # rebound in the call
        return out * self.kv.sum()
