"""Known-good twin of bad_axis_name (no axis-name findings)."""
import jax
from jax import lax
from jax.sharding import Mesh

LOCAL_AXIS = "rows"                         # file-local axis constant


def grad_sync(g):
    return lax.psum(g, "data")              # declared in comm/mesh.py


def gather(x):
    return lax.all_gather(x, axis_name="tensor", axis=0, tiled=True)


def toy(devices):
    mesh = Mesh(devices, ("rows", "cols"))  # file-local mesh vocabulary
    del mesh
    return lax.axis_index("cols")


def local(v):
    return lax.pmean(v, ("fsdp", LOCAL_AXIS))   # variables aren't checked
