"""Deliberately-bad fixture: await-under-lock.

The coroutine suspends while still holding a *synchronous*
``threading.Lock`` — every thread and every other task that needs the
lock now waits on a parked coroutine.
"""
import asyncio
import threading


class Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self.spent = 0

    async def charge(self, amount):
        with self._lock:
            await asyncio.sleep(0)       # BAD: suspends with the lock held
            self.spent += amount
