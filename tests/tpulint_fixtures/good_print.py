"""Known-good twin of bad_print (no print findings)."""
import logging

logger = logging.getLogger(__name__)


def train_step(x):
    logger.info("step %s", x)
    return x


def report(lines):
    # explicit CLI output, pragma'd as intentional
    print("\n".join(lines))  # tpulint: disable=print
