"""Known-bad: device-ish exceptions escaping serving loops (tpulint:
raise-escape).

Three escape shapes: a raise two calls deep with no handler between,
a direct raise in the loop body, and the watchdog dispatch seam
(``.failures.run``) called bare — a virtual DispatchTimeoutError
source even though no raise is visible here.
"""


class DispatchTimeoutError(RuntimeError):
    pass


class InjectedFault(RuntimeError):
    pass


class Engine:
    def __init__(self, failures):
        self.failures = failures

    def step(self, fn):  # tpulint: serving-loop  # BAD: _dispatch raises through
        return self._dispatch(fn)

    def _dispatch(self, fn):
        if fn is None:
            raise DispatchTimeoutError("device stalled")
        return fn()

    def decode_burst(self, fn):  # tpulint: serving-loop  # BAD: direct raise
        if fn is None:
            raise InjectedFault("chaos tier fault")
        return fn()

    def flush(self, fn):  # tpulint: serving-loop  # BAD: bare dispatch seam
        return self.failures.run(fn)
