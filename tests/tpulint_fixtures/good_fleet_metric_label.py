"""Known-good twin for the fleet re-export label-hygiene check: ONE
literal series name per metric, the replica carried as a label VALUE
from the handle — and non-fleet registries keep their existing
f-string-with-constant-head allowance."""


def reexport(fleet_registry, registry, handle):
    c = fleet_registry.counter("serving_fleet_tokens_labeled_total")
    c.inc(5, replica=handle.name)
    g = fleet_registry.gauge("serving_fleet_replica_lag")
    g.set(0, replica=handle.name)
    # an ordinary (non-fleet) registry may still build names from a
    # constant serving_/training_ head
    for k in ("schedule", "stage"):
        registry.counter(f"serving_{k}_ms_total")
    return c, g
