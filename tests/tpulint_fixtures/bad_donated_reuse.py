"""Known-bad: buffer reuse after donation (tpulint: donated-reuse)."""
import jax
import jax.numpy as jnp


def step(params, kv, batch):
    return kv + batch, params


def serve(params, batch):
    step_fn = jax.jit(step, donate_argnums=(1,))
    kv = jnp.zeros((4, 4))
    logits, _ = step_fn(params, kv, batch)
    return logits + kv                  # BAD: kv was donated above


class Engine:
    def __init__(self):
        self.kv = jnp.zeros((4, 4))

    def run(self, params, batch):
        fn = jax.jit(step, donate_argnums=(1,))
        out, _ = fn(params, self.kv, batch)
        return out * self.kv.sum()      # BAD: self.kv donated, not rebound
