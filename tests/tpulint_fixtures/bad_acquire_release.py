"""Known-bad: acquire/release pairing violations (tpulint:
acquire-release).

Every acquisition below leaks on some path: a ledger entry removed
without releasing what it owns, allocator results dropped or bound and
forgotten, a bare fd, a worker thread nobody joins, a profiler capture
armed but never finished, and a revive op that is never resolved.
"""
import threading


class StateTable:
    def __init__(self, allocator):
        self.allocator = allocator
        # tpulint: ledger=allocator — every live descriptor owns blocks
        self.seqs = {}

    def admit(self, uid, seq):
        self.seqs[uid] = seq

    def evict(self, uid):
        return self.seqs.pop(uid)        # BAD: entry's blocks never given back

    def grow(self):
        self.allocator.allocate(4)       # BAD: result dropped, blocks unreleasable

    def reserve(self):
        blocks = self.allocator.allocate(4)  # BAD: bound but never used again
        return None

    def revive(self, tier, uid):
        tier.begin_revive(uid)           # BAD: revive op dropped, never resolved


class TraceDump:
    def dump(self, data):
        f = open("/tmp/trace.bin", "wb")  # BAD: fd neither closed nor stored
        f.write(data)


class Watchdog:
    def start(self):
        self._t = threading.Thread(target=self._loop)  # BAD: no daemon, no join
        self._t.start()

    def _loop(self):
        return None


class CaptureOwner:
    def __init__(self, cap):
        self._cap = cap

    def begin(self):
        self._cap.arm(steps=3)           # BAD: armed capture never finished
