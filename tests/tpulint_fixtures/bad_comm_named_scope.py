"""Known-bad: comm helpers whose collectives carry no jax.named_scope
label (tpulint: comm-named-scope — tracemerge's device tracks, and the
T3 overlap measurement bar, are built from these labels)."""
import jax
from jax import lax


def tile_reduce(p):
    return lax.psum(p, "data")              # BAD: unlabeled all-reduce


def ring_hop(x, perm):
    return lax.ppermute(x, "data", perm)    # BAD: unlabeled ring hop


def grad_scatter(g):
    return lax.psum_scatter(                # BAD: unlabeled reduce-scatter
        g, "data", scatter_dimension=0, tiled=True)


def gather_logits(x):
    # labeling only the GEMM does not cover a comm helper defined
    # elsewhere — the gather below runs with no label in ITS chain
    with jax.named_scope("unembed_gemm"):
        y = x * 2.0
    return _unlabeled_gather(y)


def _unlabeled_gather(v):
    return lax.all_gather(v, "tensor", axis=0, tiled=True)  # BAD
