"""Known-bad: terminal-status discipline violations (tpulint:
terminal-exhaustive).

``reap`` drops a uid from the declared live set without any close-out;
``shed`` closes with a literal that is not in TERMINAL_STATUSES; and
the declared ``"zombie"`` status is never emitted by anything — a dead
contract surface.
"""

TERMINAL_STATUSES = (
    "finished",
    "cancelled",
    "zombie",                            # BAD: declared but never emitted
)


class Tracker:
    def __init__(self):
        # tpulint: live-set — uid -> prompt tokens
        self.open = {}

    def put(self, uid, tokens):
        self.open[uid] = tokens

    def on_finish(self, uid, status):
        self.open.pop(uid, None)
        return status

    def close(self, uid):
        self.on_finish(uid, "finished")

    def cancel(self, uid):
        self.on_finish(uid, "cancelled")

    def reap(self, stale):
        for uid in stale:
            self.open.pop(uid, None)     # BAD: uid vanishes, no terminal status

    def shed(self, uid):
        self.on_finish(uid, "evicted")   # BAD: 'evicted' not in TERMINAL_STATUSES
