"""Known-bad: broken jit static args (tpulint: static-args)."""
import jax
from functools import partial


@partial(jax.jit, static_argnames=("block_sz",))
def kernel(x, block_size):              # BAD: "block_sz" is not a param
    return x * block_size


@partial(jax.jit, static_argnames=("opts",))
def configured(x, opts={"mode": "fast"}):   # BAD: unhashable static default
    return x


def scale(x, factor=2):
    return x * factor


scaled = jax.jit(scale, static_argnums=(5,))    # BAD: out of range
