"""Known-good twin of bad_serving_sync (no serving-sync findings)."""
import numpy as np


class Engine:
    def step(self):  # tpulint: serving-loop
        st = self._dispatch()
        toks = self._fetch_tokens(st)
        n = int(np.prod(toks.shape))        # shape arithmetic is static
        return toks, n

    def _fetch_tokens(self, st):  # tpulint: serving-loop
        # the single sanctioned emit point
        return np.asarray(st)  # tpulint: disable=serving-sync

    def unmarked_helper(self, x):
        # not part of the serving loop: syncing is the caller's business
        return float(np.asarray(x).sum())

    def _dispatch(self):
        return np.zeros(4)
