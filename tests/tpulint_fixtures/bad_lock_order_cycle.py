"""Deliberately-bad fixture: lock-order-cycle.

``credit`` takes ``_alock`` then ``_block``; ``debit`` takes them
reversed — two threads interleaving the two paths deadlock.
"""
import threading


class Ledger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0
        self.b = 0

    def credit(self, n):
        with self._alock:
            with self._block:
                self.a += n
                self.b += n

    def debit(self, n):
        with self._block:
            with self._alock:            # BAD: reversed acquisition order
                self.b -= n
                self.a -= n
