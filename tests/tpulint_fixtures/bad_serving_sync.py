"""Known-bad: blocking device->host readbacks inside serving-loop
methods (tpulint: serving-sync)."""
import numpy as np


class Engine:
    def step(self):  # tpulint: serving-loop
        toks = self._run()
        fetched = np.asarray(toks)          # BAD: per-step readback
        score = float(toks[0])              # BAD: float() on array value
        one = toks.item()                   # BAD: .item() blocks
        return fetched, score, one

    def emit(
            self, st):  # tpulint: serving-loop
        # marker on a multi-line def header still marks the method
        return np.array(st.toks)            # BAD: ad-hoc materialization

    def _run(self):
        return [0]
