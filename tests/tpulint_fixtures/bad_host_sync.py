"""Known-bad: host syncs inside jit-traced code (tpulint: host-sync)."""
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@jax.jit
def decorated(x):
    return x.sum().item()               # BAD: .item() inside jit


@partial(jax.jit, static_argnames=("n",))
def partial_decorated(x, n):
    s = float(jnp.sum(x))               # BAD: float() on traced value
    return x * s + n


def wrapped(x):
    return np.asarray(x) * 2            # BAD: traced value -> host numpy


def helper(x):
    return int(jnp.argmax(x))           # BAD: called from a jit root


def root(x):
    return helper(x)


wrapped_jit = jax.jit(wrapped)
root_jit = jax.jit(root)
