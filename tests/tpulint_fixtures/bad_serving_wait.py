"""Known-bad: unbounded blocking waits inside serving-loop methods
(tpulint: serving-wait)."""
import time


class Engine:
    def _collect(self, st):  # tpulint: serving-loop
        while not st.ready:                 # BAD: polling loop, no bound
            time.sleep(0.001)
        return st.result

    def _drain(self, q):  # tpulint: serving-loop
        item = q.get()                      # BAD: no-timeout queue get
        return item

    def _sync(self, ev, worker):  # tpulint: serving-loop
        ev.wait()                           # BAD: no-timeout event wait
        worker.join()                       # BAD: no-timeout join
        return True

    def _spin(self, peer):  # tpulint: serving-loop
        while peer.pending():               # BAD: poll forever on a peer
            if peer.dead():
                continue
            time.sleep(0.01)
