"""Known-good twin of bad_retrace_hazard (no findings)."""
import functools

import jax
import jax.numpy as jnp


def fn(x):
    return x * 2


def jit_hoisted(x):
    f = jax.jit(fn)                        # one wrapper, reused
    outs = []
    for _ in range(3):
        outs.append(f(x))
    return outs


def jit_cache_fill(xs):
    cache = {}
    for n in (1, 2, 4):
        cache[n] = jax.jit(fn)             # keyed executable cache
    return [cache[n](x) for n, x in zip((1, 2, 4), xs)]


@functools.partial(jax.jit, static_argnames=("n",))
def padded(x, n):
    return jnp.pad(x, (0, n - x.shape[0]))


def constant_static(xs):
    outs = []
    for x in xs:
        outs.append(padded(x, n=8))        # static arg never changes
    return outs


@functools.partial(jax.jit, static_argnames=("cfg",))
def configured(x, cfg=None):
    return x


def hashable_static(x):
    return configured(x, cfg=(1, 2))


step = jax.jit(fn)


def stable_shapes(n):
    outs = []
    for _ in range(1, n):
        outs.append(step(jnp.zeros((4, 4))))
    return outs
