"""Known-bad: wall-clock reads and telemetry calls on the hot paths
(tpulint: telemetry-hotpath)."""
import time

import jax

tracer = object()
metrics = object()


class Engine:
    def step(self):  # tpulint: serving-loop
        t0 = time.time()                    # BAD: non-monotonic wall clock
        self._run()
        return time.time() - t0             # BAD: same, on the hot path

    def _run(self):
        return 0


@jax.jit
def traced_step(x):
    with tracer.span("fwd"):                # BAD: telemetry inside jit
        y = x * 2
    tracer.record("fwd", 0.0, 1.0)          # BAD: baked into the trace
    return y


def helper(x):
    metrics.inc("tokens", 1)                # BAD: jit-reachable via below
    return x


helper_jit = jax.jit(helper)
