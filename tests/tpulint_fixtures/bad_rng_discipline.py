"""Known-bad: PRNG key misuse (tpulint: rng-discipline)."""
import jax
import jax.numpy as jnp


def double_consume(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(key, (4,))       # BAD: key already consumed
    return a + b


def use_after_split(key):
    k1, k2 = jax.random.split(key)
    noise = jax.random.normal(key, (2,))   # BAD: parent key is dead
    return k1, k2, noise


def loop_invariant(key):
    out = []
    for _ in range(4):
        out.append(jax.random.uniform(key, (2,)))   # BAD: same draw each turn
    return jnp.stack(out)


def draw(k):
    return jax.random.normal(k, (2,))


def helper_double(key):
    x = draw(key)
    y = draw(key)                          # BAD: draw() consumed key already
    return x + y
