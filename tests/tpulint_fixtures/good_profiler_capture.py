"""Known-good twin of bad_profiler_capture (no findings)."""
import jax


class Engine:
    def step(self):  # tpulint: serving-loop
        # the gated capture-window seam (telemetry/profiler.py): the
        # manager owns the jax.profiler session, the budget, and the
        # clock anchor — the loop only hits step boundaries
        cap = self._cap
        if cap is not None and cap.armed:
            cap.begin(step=0)
        out = self._run()
        if cap is not None and cap.active:
            cap.end_step(step=1)
        return out

    def _run(self):
        return 0


def offline_profile_tool():
    # unmarked host tooling (bench scripts, one-shot profilers) may
    # drive the profiler directly — only the serving loop is gated
    jax.profiler.start_trace("/tmp/t")
    jax.profiler.stop_trace()
