"""Known-good twin of bad_rng_discipline (no rng-discipline findings)."""
import jax
import jax.numpy as jnp


def split_chain(key):
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (4,))
    key, sub = jax.random.split(key)
    b = jax.random.normal(sub, (4,))
    return a + b


def fold_per_iteration(key):
    out = []
    for i in range(4):
        k = jax.random.fold_in(key, i)
        out.append(jax.random.uniform(k, (2,)))
    return jnp.stack(out)


def loop_over_split_keys(key):
    out = []
    for k in jax.random.split(key, 4):
        out.append(jax.random.normal(k, (2,)))
    return jnp.stack(out)


def exclusive_branches(key, flag):
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))


def draw(k):
    return jax.random.normal(k, (2,))


def helper_fresh_keys(key):
    sub1, sub2 = jax.random.split(key)
    return draw(sub1) + draw(sub2)
