"""Known-bad: recompilation hazards (tpulint: retrace-hazard)."""
import functools

import jax
import jax.numpy as jnp


def fn(x):
    return x * 2


def jit_in_loop(x):
    outs = []
    for _ in range(3):
        f = jax.jit(fn)                    # BAD: fresh wrapper every turn
        outs.append(f(x))
    return outs


@functools.partial(jax.jit, static_argnames=("n",))
def padded(x, n):
    return jnp.pad(x, (0, n - x.shape[0]))


def varying_static(x):
    outs = []
    for n in range(1, 5):
        outs.append(padded(x, n=n))        # BAD: static arg varies per turn
    return outs


@functools.partial(jax.jit, static_argnames=("cfg",))
def configured(x, cfg=None):
    return x


def unhashable_static(x):
    return configured(x, cfg={"a": 1})     # BAD: dict can never hash


step = jax.jit(fn)


def varying_shapes(n):
    outs = []
    for i in range(1, n):
        outs.append(step(jnp.zeros((i, 4))))   # BAD: new shape per turn
    return outs
