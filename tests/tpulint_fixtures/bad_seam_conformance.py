"""Known-bad: engine-seam contract violations (tpulint:
seam-conformance).

``InferenceEngine`` below is the in-file reference (full verb set).
``QuotaFront`` is engine-shaped (6/8 verbs) but dropped ``drain`` and
``snapshot``; ``DriftFront`` has every verb but drifted two signatures;
``ThinFront`` (2 verbs, NOT engine-shaped) is caught only because it
flows into the ``Gateway(...)`` backend position.
"""


class InferenceEngine:
    """The reference seam: the verb set every backend must speak."""

    def put(self, uid, tokens):
        return uid

    def step(self, sampling=None):
        return {}

    def flush(self):
        return None

    def cancel(self, uid):
        return uid

    def query(self, uid):
        return None

    def drain(self, deadline_ms=None):
        return {}

    def snapshot(self):
        return {}

    def health_state(self):
        return "healthy"


class QuotaFront:                        # BAD: engine-shaped, missing drain + snapshot
    def put(self, uid, tokens):
        return uid

    def step(self, sampling=None):
        return {}

    def flush(self):
        return None

    def cancel(self, uid):
        return uid

    def query(self, uid):
        return None

    def health_state(self):
        return "healthy"


class DriftFront:
    def put(self, uid, tokens, priority):  # BAD: extra required arg vs reference
        return uid

    def step(self, sampling=None):
        return {}

    def flush(self):
        return None

    def cancel(self):                    # BAD: cannot accept the uid seam callers pass
        return None

    def query(self, uid):
        return None

    def drain(self, deadline_ms=None):
        return {}

    def snapshot(self):
        return {}

    def health_state(self):
        return "healthy"


class ThinFront:
    """Two verbs only — below the engine-shaped threshold, so only the
    position-flow check can see it."""

    def put(self, uid, tokens):
        return uid

    def query(self, uid):
        return None


def build_front():
    return Gateway(ThinFront())          # BAD: 2/8-verb class in the backend seat  # noqa: F821
