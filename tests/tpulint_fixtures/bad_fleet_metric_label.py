"""Known-bad fixture for the metric-name rule's fleet re-export label
hygiene: per-replica identity interpolated into the metric NAME on a
FleetRegistry receiver — the replica belongs in the ``replica=`` label
(from the handle), never the name, or the re-export forks one series
per replica that dashboards and rollups can never join back up."""


def reexport(fleet_registry, freg, replica):
    fleet_registry.counter(f"serving_tokens_{replica}_total")  # BAD: replica in the NAME
    fleet_registry.gauge(f"serving_{replica}_kv_blocks_free")  # BAD: even with the family prefix first
    freg.histogram(f"serving_ttft_{replica}_ms", (1.0,))       # BAD: same via the freg spelling
    return fleet_registry
