"""Known-good twin of bad_seam_conformance: every class flowing into a
seam position (or simply engine-shaped) speaks the full verb set with
reference-compatible arities — extra OPTIONAL parameters and varargs
are fine, only required-arity drift is a violation.
"""


class InferenceEngine:
    def put(self, uid, tokens):
        return uid

    def step(self, sampling=None):
        return {}

    def flush(self):
        return None

    def cancel(self, uid):
        return uid

    def query(self, uid):
        return None

    def drain(self, deadline_ms=None):
        return {}

    def snapshot(self):
        return {}

    def health_state(self):
        return "healthy"


class ConformingFront:
    """Full verb set; optional extras do not drift the seam."""

    def put(self, uid, tokens, priority=0):
        return uid

    def step(self, sampling=None, rng=None):
        return {}

    def flush(self):
        return None

    def cancel(self, uid):
        return uid

    def query(self, uid):
        return None

    def drain(self, deadline_ms=None):
        return {}

    def snapshot(self):
        return {}

    def health_state(self):
        return "healthy"


class VarargFront:
    """A forwarding proxy: *args absorbs whatever the seam passes."""

    def put(self, *args, **kwargs):
        return None

    def step(self, *args, **kwargs):
        return {}

    def flush(self, *args, **kwargs):
        return None

    def cancel(self, *args, **kwargs):
        return None

    def query(self, *args, **kwargs):
        return None

    def drain(self, *args, **kwargs):
        return {}

    def snapshot(self, *args, **kwargs):
        return {}

    def health_state(self, *args, **kwargs):
        return "healthy"


def make_engine():
    return ConformingFront()


def build_front():
    return Gateway(ConformingFront())    # full verb set in the backend seat  # noqa: F821


def build_fleet(serve):
    # factory seam: the zero-state constructor returns a conforming class
    return serve(engine_factory=make_engine)
