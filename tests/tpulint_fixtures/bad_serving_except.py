"""Known-bad: broad excepts in serving-loop methods that bypass the
failure classifier (tpulint: serving-except).  Each handler logs (so
silent-except stays quiet — this fixture isolates its own rule) but
invents a local failure policy instead of routing through the ONE
classifier seam."""
import logging

logger = logging.getLogger(__name__)


class Engine:
    def _dispatch(self, fn):  # tpulint: serving-loop
        try:
            return fn()
        except Exception as e:                       # BAD: ad-hoc policy
            logger.warning("step failed: %s", e)
            return None

    def _collect(self, st):  # tpulint: serving-loop
        try:
            return st.result()
        except:                                      # BAD: bare except  # noqa: E722
            logger.warning("collect failed; dropping step")
            return {}

    def decode_burst(self, fn):  # tpulint: serving-loop
        try:
            return fn()
        except BaseException as e:                   # BAD: swallows all
            logger.error("burst failed: %s", e)
            self._retry = True
            return {}

    def _step(self, fn):  # tpulint: serving-loop
        try:
            return fn()
        except Exception as e:                       # BAD: near-miss name
            # counting/logging a "failure" is not ROUTING it — only the
            # exact classifier seam (or a .failures receiver) passes
            logger.warning("step failed: %s", e)
            self.metrics.count_failures(e)
            return None
