"""Known-good twin of bad_serving_wait (no serving-wait findings)."""
import time


class Engine:
    def _collect(self, st):  # tpulint: serving-loop
        # bounded poll: a monotonic deadline in the loop condition
        deadline = time.perf_counter() + 5.0
        while not st.ready and time.perf_counter() < deadline:
            time.sleep(0.001)
        if not st.ready:
            raise TimeoutError("step did not complete in 5s")
        return st.result

    def _drain(self, q):  # tpulint: serving-loop
        # a timeout kwarg bounds the blocking get
        return q.get(timeout=0.5)

    def _sync(self, ev, worker):  # tpulint: serving-loop
        # positional timeout on Event.wait; join with timeout kwarg
        ok = ev.wait(1.0)
        worker.join(timeout=1.0)
        return ok

    def _spin(self, peer):  # tpulint: serving-loop
        # a step budget guarding a raise bounds the poll
        attempts = 0
        while peer.pending():
            attempts += 1
            if attempts > 100:
                raise RuntimeError("peer wedged")
            time.sleep(0.01)

    def unmarked_helper(self, ev):
        # not part of the serving loop: blocking is the caller's business
        ev.wait()
        while not ev.is_set():
            time.sleep(0.1)
