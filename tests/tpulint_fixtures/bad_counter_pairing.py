"""Known-bad: one-sided bumps of declared counter pairs (tpulint:
counter-pairing).

The pair declarations say these counters move together — that is what
keeps sum(per-request) == engine-counter invariants true.  Both
functions below bump exactly one side.
"""


class _Counter:
    def inc(self, **labels):
        return None


class Metrics:
    # tpulint: pair=_c_finished/_c_terminal
    # tpulint: pair=drafted/accepted
    def __init__(self):
        self._c_finished = _Counter()
        self._c_terminal = _Counter()
        self.tm = {"drafted": 0, "accepted": 0}

    def note_finish(self):
        self._c_finished.inc()           # BAD: pair _c_terminal never bumps here

    def note_draft(self, n):
        self.tm["drafted"] += n          # BAD: pair 'accepted' never bumps here
