"""Known-good twin of bad_comm_named_scope (no findings): every
collective stage carries a jax.named_scope label, directly or through
its enclosing helper."""
import jax
from jax import lax


def tile_reduce(p):
    with jax.named_scope("t3_comm_t0_ar"):
        return lax.psum(p, "data")


def ring_hop(x, perm):
    with jax.named_scope("ring_ag_hop0"):
        return lax.ppermute(x, "data", perm)


def grad_scatter(g):
    with jax.named_scope("t3_rs_t0"):
        return lax.psum_scatter(g, "data", scatter_dimension=0,
                                tiled=True)


def ring_chain(x, perm):
    # a label on the enclosing helper covers its hops: the chain
    # renders as one named track with per-hop ops under it
    with jax.named_scope("ring_reduce"):
        acc = x
        for _ in range(3):
            acc = acc + lax.ppermute(acc, "data", perm)
        return acc


def rank():
    # axis queries move no data; no label required
    return lax.axis_index("data")
