"""Known-good twin of bad_rng_draft_window (no findings): the
draft-window key derivation the speculative verify step actually uses —
per-(uid, position) ``fold_in`` chains, one fresh key per sampled
window column (mirrors sampler.window_keys / model.pipelined_ragged_step).
"""
import jax
import jax.numpy as jnp


def window_keys(rng, uids, positions):
    """[S, W] keys: fold_in(fold_in(rng, uid), position) per column."""
    def one_row(u, ps):
        row_key = jax.random.fold_in(rng, u)
        return jax.vmap(lambda p: jax.random.fold_in(row_key, p))(ps)
    return jax.vmap(one_row)(uids, positions)


def sample_window(rng, uids, positions, logits):
    """logits [S, W, V] -> tokens [S, W], each column its own key."""
    S, W, V = logits.shape
    keys = window_keys(rng, uids, positions)
    flat = jax.vmap(jax.random.categorical)(
        keys.reshape((S * W,) + keys.shape[2:]), logits.reshape(S * W, V))
    return flat.reshape(S, W)


def fold_per_column(rng, uid, logits):
    """Python-loop variant: fold_in of the loop index is the fix."""
    row_key = jax.random.fold_in(rng, uid)
    out = []
    for w in range(logits.shape[0]):
        k = jax.random.fold_in(row_key, w)
        out.append(jax.random.categorical(k, logits[w]))
    return jnp.stack(out)
