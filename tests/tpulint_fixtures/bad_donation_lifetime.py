"""Known-bad: donated-buffer lifetimes across call boundaries
(tpulint: donation-lifetime)."""
import jax
import jax.numpy as jnp


def step(params, kv, batch):
    return kv + batch, kv * 2


def step2(params, kv):
    return kv + 1, kv * 2


class Engine:
    """Donating binding stored on ``self`` in one method, misused in
    another — invisible to per-file, per-scope analysis."""

    def __init__(self):
        self.kv = jnp.zeros((4, 4))
        self._step = jax.jit(step, donate_argnums=(1,))

    def run(self, params, batch):
        out, _ = self._step(params, self.kv, batch)
        return out + self.kv               # BAD: self.kv was donated


class Pipelined:
    """Donating binding produced by a builder method."""

    def _build(self):
        def pstep(params, kv):
            return kv * 2, kv + 1
        return jax.jit(pstep, donate_argnums=(1,))

    def serve(self, params):
        fn = self._build()
        kv = jnp.zeros((2, 2))
        a, _ = fn(params, kv)
        return a + kv                      # BAD: kv donated via builder fn


class Cache:
    def __init__(self):
        self.saved = None

    def stash(self, kv):
        self.saved = kv


def run_with_stash(params, batch):
    step_fn = jax.jit(step, donate_argnums=(1,))
    cache = Cache()
    kv = jnp.zeros((4, 4))
    cache.stash(kv)
    out, _ = step_fn(params, kv, batch)    # BAD: cache.saved aliases kv
    return out, cache


def consume(params, kv):
    fn = jax.jit(step2, donate_argnums=(1,))
    out, _ = fn(params, kv)
    return out


def call_then_reuse(params):
    kv = jnp.zeros((4, 4))
    out = consume(params, kv)
    return out + kv                        # BAD: consume() donated kv


def alias_positions(params):
    fn = jax.jit(step2, donate_argnums=(1,))
    kv = jnp.zeros((4, 4))
    out, _ = fn(kv, kv)                    # BAD: donated AND read in one call
    return out
