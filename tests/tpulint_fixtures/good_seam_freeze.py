"""Known-good twin of bad_seam_freeze: every engine touch routes
through ONE executor seam (`_call` forwards its callable to
``run_in_executor``), so the executor-domain thunk is the engine's
only home — the frozen PR-15 gateway contract."""
import asyncio
import functools


class Relay:
    def __init__(self, engine, executor):
        self.engine = engine
        self._exec = executor

    async def _call(self, fn, *args):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._exec, functools.partial(fn, *args))

    async def drive(self):
        await self._call(self.engine.step, {})
        await self._call(self._pump)

    def _pump(self):
        self.engine.flush()              # executor domain: sanctioned
