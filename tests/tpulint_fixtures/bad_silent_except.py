"""Known-bad: swallowed exceptions (tpulint: silent-except)."""


def probe(fn, x):
    try:
        return fn(x), True
    except Exception:                   # BAD: silent fallback
        return None, False


def load(path):
    try:
        with open(path) as f:
            return f.read()
    except:                             # BAD: bare except  # noqa: E722
        return ""


def best_effort(cleanup):
    try:
        cleanup()
    except BaseException:               # BAD: swallows even SystemExit
        pass
