"""Known-good twin of bad_acquire_release: every acquisition is
released in-function, parked on a ledger/attribute (ownership
transfer), handed to the caller, or covered by the declared release
receiver.
"""
import threading


class StateTable:
    def __init__(self, allocator):
        self.allocator = allocator
        # tpulint: ledger=allocator — every live descriptor owns blocks
        self.seqs = {}

    def admit(self, uid, seq):
        self.seqs[uid] = seq

    def evict(self, uid):
        seq = self.seqs.pop(uid)
        self.allocator.free(seq.blocks)
        return seq

    def grow(self):
        blocks = self.allocator.allocate(4)
        self.allocator.free(blocks)

    def reserve(self):
        # ownership transfer: the blocks land on the ledger attribute
        self.spare = self.allocator.allocate(4)

    def lease(self):
        # handed to the caller — the caller owns the release
        return self.allocator.allocate(4)

    def revive(self, tier, uid):
        op = tier.begin_revive(uid)
        op.resolve()


class TraceDump:
    def __init__(self):
        self._sink = None

    def dump(self, data):
        with open("/tmp/trace.bin", "wb") as f:
            f.write(data)

    def attach(self):
        # stored on an attribute: close() owns the descriptor now
        self._sink = open("/tmp/trace.bin", "ab")

    def close(self):
        if self._sink is not None:
            self._sink.close()
            self._sink = None


class Watchdog:
    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        return None


class Poller:
    def start(self):
        self._t = threading.Thread(target=self._loop)
        self._t.start()

    def _loop(self):
        return None

    def stop(self):
        self._t.join()


class CaptureOwner:
    def __init__(self, cap):
        self._cap = cap

    def begin(self):
        self._cap.arm(steps=3)

    def end(self):
        self._cap.finish_now()
