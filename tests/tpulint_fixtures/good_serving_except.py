"""Known-good twin of bad_serving_except (no serving-except findings):
broad excepts on the serving loop route through the failure classifier,
re-raise, or catch narrowly."""
import logging

logger = logging.getLogger(__name__)


class Engine:
    def _dispatch(self, fn, uids):  # tpulint: serving-loop
        try:
            return fn()
        except Exception as e:
            # the sanctioned shape: the classifier seam decides
            self._handle_step_failure(e, uids, "dispatch")
            return None

    def _collect(self, st):  # tpulint: serving-loop
        try:
            return st.result()
        except Exception as e:
            verdict = classify_failure(e)
            if verdict is None:
                raise
            return {}

    def decode_burst(self, fn):  # tpulint: serving-loop
        try:
            return fn()
        except Exception:
            raise                  # a bare re-raise defers the decision

    def _step(self, fn, uids):  # tpulint: serving-loop
        try:
            return fn()
        except Exception as e:
            # a call on the FailurePolicy receiver also routes
            return self.failures.recover(e, uids)

    def _probe(self, fn):  # tpulint: serving-loop
        try:
            return fn()
        except ValueError as e:    # narrow catches pick their own policy
            logger.warning("probe rejected: %s", e)
            return None

    def _handle_step_failure(self, e, uids, phase):
        logger.warning("%s failed: %s", phase, e)


def classify_failure(e):
    return None
