"""Known-bad: synchronous blocking work directly inside ``async def``
(tpulint: async-blocking — one blocked coroutine stalls the whole
event loop: every open SSE stream, every health probe, every metrics
scrape behind one engine step)."""
import asyncio
import time


async def drive(engine):
    out = engine.step()                      # BAD: engine step on the loop
    return out


async def finish(backend):
    backend.drain(1000.0)                    # BAD: drain blocks for seconds


async def admit(backend, uid, tokens):
    verdict = backend.put(uid, tokens)       # BAD: engine put on the loop
    return verdict


async def throttle():
    time.sleep(0.5)                          # BAD: blocking sleep
    asyncio.sleep(0.5)                       # BAD: un-awaited -> no-op


async def proxy(sock):
    data = sock.recv(4096)                   # BAD: blocking socket read
    sock.sendall(data)                       # BAD: blocking socket write
    return data


async def probe(router):
    return router.health()                   # BAD: fleet probe on the loop


async def outer(backend):
    # a NESTED coroutine is its own scope: its blocking call is
    # reported exactly once, attributed to `inner`
    async def inner():
        return backend.step()                # BAD: inner coroutine blocks
    return await inner()
