"""Flash-attention kernel numerics vs the XLA reference (interpret mode on
CPU; reference analog: tests/unit/ops/transformer — per-kernel numeric
comparison against a python reference, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import compat as _compat
from deepspeed_tpu.models.layers import causal_attention
from deepspeed_tpu.ops import flash_attention


def qkv(B=2, S=256, H=4, Hkv=4, D=64, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, S, H, D), dtype),
            jax.random.normal(ks[1], (B, S, Hkv, D), dtype),
            jax.random.normal(ks[2], (B, S, Hkv, D), dtype))


class TestForward:
    def test_matches_xla(self):
        q, k, v = qkv()
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v)),
            np.asarray(causal_attention(q, k, v)), atol=2e-5)

    def test_gqa(self):
        q, k, v = qkv(Hkv=2)
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v)),
            np.asarray(causal_attention(q, k, v)), atol=2e-5)

    def test_multiple_kv_blocks(self):
        q, k, v = qkv(S=512)
        got = flash_attention(q, k, v, block_q=128, block_k=128)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(causal_attention(q, k, v)),
            atol=2e-5)

    def test_custom_scale(self):
        q, k, v = qkv()
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v, scale=0.5)),
            np.asarray(causal_attention(q, k, v, scale=0.5)), atol=2e-5)

    def test_mask_falls_back(self):
        q, k, v = qkv()
        mask = jnp.ones((2, 256))
        out = flash_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(causal_attention(q, k, v)),
            atol=1e-5)

    def test_ragged_seq_falls_back(self):
        q, k, v = qkv(S=100)     # 100 not divisible by any block
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(causal_attention(q, k, v)),
            atol=1e-5)


class TestBackward:
    @pytest.mark.parametrize("Hkv", [4, 2])
    def test_grads_match(self, Hkv):
        q, k, v = qkv(Hkv=Hkv)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        g = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(causal_attention), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, err_msg=f"d{name}")

    @pytest.mark.skipif(
        not _compat._MODERN,
        reason="seed-locked losses[-1]<losses[0] short-run assert flips "
        "under legacy XLA float scheduling (0.002 loss delta)")
    def test_grad_through_jit_and_scan_layers(self):
        """flash inside the transformer stack (remat 'flash' policy)."""
        import deepspeed_tpu as ds
        from deepspeed_tpu.models import build_model

        m = build_model("gpt2", vocab_size=128, num_layers=2, d_model=64,
                        num_heads=4, max_seq_len=128, attention_impl="flash",
                        remat=True, remat_policy="flash")
        eng = ds.initialize(model=m, config={
            "train_micro_batch_size_per_device": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "mesh": {"data": -1}, "steps_per_print": 1000})
        r = np.random.RandomState(0)
        losses = []
        for i in range(5):
            ids = r.randint(0, 128, (eng.train_batch_size, 128))
            losses.append(float(eng.train_batch({"input_ids": ids})["loss"]))
        assert losses[-1] < losses[0]


class TestLongContextStreaming:
    """KV streams through the grid: no VMEM cap, so the kernel must stay
    numerically exact at sequence lengths where the old whole-KV-resident
    variant fell back to XLA (VERDICT r2 item 5)."""

    @pytest.mark.nightly
    @pytest.mark.parametrize("S", [4096, 8192])
    def test_long_context_numerics(self, S):
        r = np.random.RandomState(0)
        B, H, Hkv, D = 1, 2, 1, 64
        q = jnp.asarray(r.randn(B, S, H, D), jnp.float32) * 0.3
        k = jnp.asarray(r.randn(B, S, Hkv, D), jnp.float32) * 0.3
        v = jnp.asarray(r.randn(B, S, Hkv, D), jnp.float32) * 0.3
        o = flash_attention(q, k, v)
        ref = causal_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.nightly
    def test_long_context_grads(self):
        S = 4096
        r = np.random.RandomState(1)
        B, H, Hkv, D = 1, 2, 2, 64
        q = jnp.asarray(r.randn(B, S, H, D), jnp.float32) * 0.3
        k = jnp.asarray(r.randn(B, S, Hkv, D), jnp.float32) * 0.3
        v = jnp.asarray(r.randn(B, S, Hkv, D), jnp.float32) * 0.3

        def loss(fn):
            return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

        g = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(causal_attention), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3)


def test_cross_length_falls_back():
    """Sk != Sq (diffusers cross-attention) must take the XLA fallback —
    the kernels assume one shared S (caught by round-3 verify)."""
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(2, 256, 4, 64), jnp.float32) * 0.2
    k = jnp.asarray(r.randn(2, 24, 4, 64), jnp.float32) * 0.2
    v = jnp.asarray(r.randn(2, 24, 4, 64), jnp.float32) * 0.2
    o = flash_attention(q, k, v, causal=False)
    ref = causal_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)
