"""Overload-policy tests (docs/SERVING.md "Surviving overload"):
admission verdicts + backpressure shed policies, chunked-prefill
interleaving, preemption-by-eviction, deadline enforcement, client
cancels, the terminal-lifecycle-close-out-on-every-exit-path guarantee
(request_metrics() can never leak an open record), and query()'s
explicit status field.

Most tests are host-only (scheduler + allocator, no device step) and
run in milliseconds; the preempt/resume parity tests dispatch real
steps on the CPU backend.
"""

import time

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                     SamplingParams)
from deepspeed_tpu.inference.overload import (AdmissionVerdict,
                                              OverloadConfig,
                                              admission_decision,
                                              effective_priority,
                                              select_victim)
from deepspeed_tpu.models import build_model
from deepspeed_tpu.telemetry import TERMINAL_STATUSES


@pytest.fixture(scope="module")
def model():
    return build_model("llama-tiny", vocab_size=128, num_layers=2,
                       d_model=64, num_heads=4, num_kv_heads=2, d_ff=128,
                       max_seq_len=256)


def mk(model, overload=None, **kw):
    cfg = dict(token_budget=16, max_seqs=3, kv_block_size=8,
               num_kv_blocks=6, max_seq_len=48)
    cfg.update(kw)
    return InferenceEngine(model, InferenceConfig(overload=overload, **cfg))


def sched_round(eng):
    """One host-side scheduler round, materialized (the fuzz-test
    idiom: _schedule reserves, build_batch allocates for real)."""
    sched = eng._schedule()
    if sched:
        eng.state.build_batch(sched, eng.icfg.token_budget,
                              stager=eng._stager)
    return sched


def check_allocator(eng):
    al = eng.state.allocator
    al.assert_invariants()
    return al


# --------------------------------------------------------------------------
# pure policy units (inference/overload.py)
# --------------------------------------------------------------------------

class TestPolicyUnits:
    def test_effective_priority_aging(self):
        # waiting aging_ms promotes one whole tier
        assert effective_priority(2, t_arrival=0.0, now=1.0,
                                  aging_ms=1000.0) == pytest.approx(1.0)
        # aging disabled: raw priority
        assert effective_priority(2, 0.0, 99.0, None) == 2.0
        assert effective_priority(2, 0.0, 99.0, 0) == 2.0

    def test_admission_decision_bounds(self):
        cfg = OverloadConfig(max_queued_requests=2)
        q = [(1, 0.0, 4), (2, 0.0, 4)]
        assert admission_decision(cfg, 0, 4, [], 0.0) == ("admit", ())
        assert admission_decision(cfg, 0, 4, q, 0.0) == ("shed", ())
        cfg = OverloadConfig(max_queued_tokens=10)
        assert admission_decision(cfg, 0, 3, q, 0.0) == ("shed", ())
        assert admission_decision(cfg, 0, 2, q, 0.0) == ("admit", ())

    def test_admission_decision_policies(self):
        q = [(1, 2.0, 4), (2, 5.0, 4)]
        cfg = OverloadConfig(max_queued_requests=2,
                             shed_policy="evict-lowest")
        # newcomer outranks the worst queued entry -> evict it
        assert admission_decision(cfg, 0, 4, q, 0.0) == ("evict", (2,))
        # tie (or worse) sheds the newcomer, never churns the backlog
        assert admission_decision(cfg, 5, 4, q, 0.0) == ("shed", ())
        cfg = OverloadConfig(max_queued_requests=2, shed_policy="degrade")
        assert admission_decision(cfg, 0, 4, q, 0.0) == ("degrade", ())

    def test_evict_lowest_holds_token_bound(self):
        """One eviction is not always enough: the token bound must
        actually hold after the evictions, or the 'bounded' queue
        drifts upward without limit."""
        cfg = OverloadConfig(max_queued_tokens=20,
                             shed_policy="evict-lowest")
        q = [(1, 5.0, 6), (2, 5.0, 6), (3, 5.0, 6)]
        # queue holds 18; a 14-token newcomer needs TWO 6-token
        # evictions (12+14 > 20, 6+14 <= 20)
        action, victims = admission_decision(cfg, 0, 14, q, 0.0)
        assert action == "evict" and len(victims) == 2
        assert set(victims) <= {1, 2, 3}
        # one eviction suffices for an 8-token newcomer
        action, victims = admission_decision(cfg, 0, 8, q, 0.0)
        assert action == "evict" and len(victims) == 1
        # even shedding every worse entry cannot fit a 24-token one
        assert admission_decision(cfg, 0, 24, q, 0.0) == ("shed", ())

    def test_select_victim(self):
        cands = [(10, 1.0, 2), (11, 2.0, 3), (12, 2.0, 5)]
        # worst tier wins; ties break toward the most KV blocks
        assert select_victim(cands, better_than=0.0) == 12
        # only STRICTLY worse qualifies
        assert select_victim(cands, better_than=2.0) is None
        assert select_victim([], 0.0) is None

    def test_config_validation(self):
        with pytest.raises(ValueError):
            OverloadConfig(shed_policy="nope")
        with pytest.raises(ValueError):
            OverloadConfig(prefill_chunk=0)
        with pytest.raises(ValueError):
            OverloadConfig(max_preemptions_per_step=-1)


# --------------------------------------------------------------------------
# put() verdicts + backpressure
# --------------------------------------------------------------------------

class TestAdmission:
    def test_default_put_is_legacy(self, model):
        eng = mk(model)
        v = eng.put(0, [1, 2, 3])
        assert isinstance(v, AdmissionVerdict) and bool(v)
        assert v.status == "queued"
        assert eng.put(0, [4]).status == "continued"
        # unbounded default: a pile of requests all admit
        assert all(eng.put(u, [1] * 30) for u in range(1, 20))

    def test_reject_policy(self, model):
        eng = mk(model, OverloadConfig(max_queued_requests=2))
        assert eng.put(0, [1] * 4)
        assert eng.put(1, [1] * 4)
        v = eng.put(2, [1] * 4)
        assert not v and v.status == "shed"
        assert eng.query(2)["status"] == "shed"
        agg = eng.request_metrics()["aggregate"]
        assert agg["statuses"].get("shed") == 1
        assert agg["open"] == 2
        # continuations are never shed, even over the bound
        assert eng.put(0, [9]).status == "continued"

    def test_token_bound(self, model):
        eng = mk(model, OverloadConfig(max_queued_tokens=10))
        assert eng.put(0, [1] * 8)
        assert not eng.put(1, [1] * 8)
        assert eng.put(2, [1] * 2)      # still fits

    def test_evict_lowest(self, model):
        eng = mk(model, OverloadConfig(max_queued_requests=2,
                                       shed_policy="evict-lowest"))
        eng.put(0, [1] * 4, priority=0)
        eng.put(1, [1] * 4, priority=5)
        v = eng.put(2, [1] * 4, priority=1)
        assert v and v.status == "queued" and v.evicted_uids == (1,)
        assert eng.query(1)["status"] == "shed"
        assert 1 not in eng._pending
        # equal priority: the newcomer sheds instead
        v = eng.put(3, [1] * 4, priority=1)
        assert not v and v.status == "shed"

    def test_degrade(self, model):
        eng = mk(model, OverloadConfig(max_queued_requests=1,
                                       shed_policy="degrade"))
        eng.put(0, [1] * 4)
        v = eng.put(1, [1] * 4, priority=3)
        assert v and v.status == "degraded"
        assert eng._meta[1].degraded
        assert eng._meta[1].priority == eng.ocfg.degrade_priority

    def test_shed_never_opens_kv(self, model):
        eng = mk(model, OverloadConfig(max_queued_requests=1))
        eng.put(0, [1] * 4)
        eng.put(1, [1] * 4)
        sched_round(eng)
        assert 1 not in eng.state.seqs
        rec = {r["uid"]: r for r in eng.request_metrics()["requests"]}
        assert rec[1]["status"] == "shed"
        assert rec[1]["prompt_tokens"] == 0
        check_allocator(eng)


# --------------------------------------------------------------------------
# chunked prefill
# --------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_prompt_interleaving(self, model):
        eng = mk(model, OverloadConfig(prefill_chunk=4), num_kv_blocks=12,
                 max_seq_len=96)
        eng.put(0, list(range(1, 21)))
        eng.put(1, list(range(1, 21)))
        sched = sched_round(eng)
        # both prompts share the step, neither takes more than a chunk
        assert {u for u, _ in sched} == {0, 1}
        assert all(len(t) <= 4 for _, t in sched)

    def test_decode_never_queues_behind_prefill(self, model):
        eng = mk(model, OverloadConfig(prefill_chunk=8), num_kv_blocks=12,
                 max_seq_len=96, token_budget=8)
        eng.put(0, [1, 2, 3])
        sched_round(eng)
        eng.put(0, [7])                    # decode continuation
        eng.put(1, list(range(1, 41)))     # monster prompt arrives
        for _ in range(4):
            sched = sched_round(eng)
            if not eng._pending.get(1):
                break
            # the decode token rides EVERY step the prompt is chunking
            assert sched[0][0] == 0 and len(sched[0][1]) == 1
            eng.put(0, [7])

    def test_no_cap_reproduces_legacy(self, model):
        eng = mk(model, num_kv_blocks=12, max_seq_len=96)
        eng.put(0, list(range(1, 41)))
        sched = sched_round(eng)
        assert sum(len(t) for _, t in sched) == eng.icfg.token_budget


# --------------------------------------------------------------------------
# preemption-by-eviction
# --------------------------------------------------------------------------

class TestPreemption:
    def test_starved_high_tier_preempts(self, model):
        # pool exactly fits the low-tier victim: the newcomer starves
        # (prompts are DISJOINT — a shared prefix would admit through
        # the cache without needing blocks, correctly avoiding the
        # preemption this test wants to force)
        eng = mk(model, OverloadConfig(preemption=True), num_kv_blocks=4)
        eng.put(0, list(range(1, 33)), priority=5)   # low tier, 4 blocks
        while eng._pending.get(0):
            sched_round(eng)
        assert len(eng.state.seqs[0].blocks) == 4
        eng.put(1, list(range(40, 64)), priority=0)  # disjoint, free 0
        sched = sched_round(eng)
        assert 0 not in eng.state.seqs          # victim evicted
        assert any(u == 1 for u, _ in sched)    # newcomer admitted
        # the victim re-queued its full host-known stream
        assert eng._pending[0] == list(range(1, 33))
        assert eng.query(0)["status"] == "queued"
        rec = {r["uid"]: r for r in eng.request_metrics()["requests"]}
        assert rec[0]["status"] == "open" and rec[0]["preemptions"] == 1
        assert eng.request_metrics()["aggregate"]["preemptions"] == 1
        check_allocator(eng)

    def test_single_tier_is_inert(self, model):
        """All requests at one priority: preemption can never trigger
        (raw-tier comparison is strict), reproducing legacy behavior."""
        eng = mk(model, OverloadConfig(preemption=True), num_kv_blocks=4)
        eng.put(0, list(range(1, 33)))
        while eng._pending.get(0):
            sched_round(eng)
        eng.put(1, list(range(40, 64)))
        sched_round(eng)
        assert 0 in eng.state.seqs              # untouched
        assert 1 not in eng.state.seqs          # newcomer just waits
        assert eng.request_metrics()["aggregate"]["preemptions"] == 0

    def test_preemption_respects_cap_and_inflight(self, model):
        eng = mk(model, OverloadConfig(preemption=True,
                                       max_preemptions_per_step=1),
                 num_kv_blocks=4)
        eng.put(0, list(range(1, 33)), priority=5)
        while eng._pending.get(0):
            sched_round(eng)
        # a sequence with an uncollected in-flight step is untouchable
        eng._inflight_sched[0] = 1
        eng.put(1, list(range(40, 64)), priority=0)
        sched_round(eng)
        assert 0 in eng.state.seqs
        eng._inflight_sched.pop(0)
        sched_round(eng)
        assert 0 not in eng.state.seqs

    def test_victim_stale_pending_not_readmitted_same_round(self, model):
        """A victim preempted MID-ROUND while its own pending entry is
        still ahead in the iteration: the stale entry (mid-stream
        tokens) must be skipped, not admitted as a fresh prompt at
        position 0 — the requeued full stream waits for the next
        round."""
        eng = mk(model, OverloadConfig(preemption=True), num_kv_blocks=4)
        eng.put(0, list(range(1, 41)), priority=5)   # 40-token prompt
        sched_round(eng)                             # prefill 16
        sched_round(eng)                             # prefill 16 (32 in)
        assert eng.state.seqs[0].seen_tokens == 32
        assert eng._pending[0] == list(range(33, 41))  # 8 left, free 0
        eng.put(1, list(range(60, 68)), priority=0)  # disjoint, starves
        sched = sched_round(eng)
        # uid 1 preempted uid 0 and got the step to itself
        assert {u for u, _ in sched} == {1}
        assert 0 not in eng.state.seqs
        # the victim's pending is the FULL requeued stream, untouched by
        # its stale (pre-preemption) iteration entry
        assert eng._pending[0] == list(range(1, 41))
        # and its mid-stream tokens were not double-counted as a prompt
        assert int(eng.timings["prompt_tokens"]) == 40 + 8
        # once the preemptor releases the pool, the requeue re-prefills
        # from position 0 normally (via the cached chain where it
        # survived uid 1's eviction pressure)
        eng.flush(1)
        sched = sched_round(eng)
        assert any(u == 0 for u, _ in sched)
        check_allocator(eng)

    def test_broken_chain_never_victim(self, model):
        eng = mk(model, OverloadConfig(preemption=True), num_kv_blocks=4)
        eng.put(0, list(range(1, 33)), priority=5)
        while eng._pending.get(0):
            sched_round(eng)
        eng.state.seqs[0].chain_broken = True   # burst-written KV
        eng.put(1, list(range(40, 64)), priority=0)
        sched_round(eng)
        assert 0 in eng.state.seqs


# --------------------------------------------------------------------------
# deadlines, cancels, and the close-out-on-every-exit-path guarantee
# --------------------------------------------------------------------------

class TestTerminalCloseout:
    def test_deadline_queued(self, model):
        eng = mk(model)
        eng.put(0, [1] * 4, deadline_ms=0.01)
        time.sleep(0.002)
        assert sched_round(eng) == []
        assert eng.query(0)["status"] == "deadline_exceeded"
        assert 0 not in eng._pending and 0 not in eng._meta
        assert eng._drain_reaped() == {0}
        assert not eng.requests.open

    def test_deadline_running(self, model):
        eng = mk(model)
        eng.put(0, [1] * 4, deadline_ms=5.0)
        sched_round(eng)
        assert 0 in eng.state.seqs
        time.sleep(0.01)
        sched_round(eng)
        assert 0 not in eng.state.seqs
        assert eng.query(0)["status"] == "deadline_exceeded"
        al = check_allocator(eng)
        assert al.referenced_blocks == 0

    def test_cancel_queued_and_running(self, model):
        eng = mk(model)
        eng.put(0, [1] * 4)
        eng.cancel(0)
        assert eng.query(0)["status"] == "cancelled"
        eng.put(1, [1] * 4)
        sched_round(eng)
        eng.cancel(1)
        assert 1 not in eng.state.seqs
        assert eng.query(1)["status"] == "cancelled"
        assert eng._drain_reaped() == {0, 1}
        assert not eng.requests.open
        check_allocator(eng)
        eng.cancel(42)                      # unknown uid: no-op

    def test_direct_release_closes_record(self, model):
        """Satellite fix: a mid-flight StateManager.release used to
        leak the open record forever."""
        eng = mk(model)
        eng.put(0, [1] * 4)
        sched_round(eng)
        eng.state.release(0)
        assert eng.query(0)["status"] == "released"
        assert not eng.requests.open

    def test_ctx_exhausted_closes_record(self, model):
        """Satellite fix: context-exhausted requests never closed out in
        RequestTracker under the direct step() API."""
        eng = mk(model, num_kv_blocks=8, max_seq_len=32)
        eng.put(0, [1] * 30)
        while eng._pending.get(0):
            sched_round(eng)
        eng.put(0, [1, 2, 3])               # beyond max context
        # the first rounds still fit tokens into the last block; the
        # round that finds ctx_remaining == 0 marks exhaustion
        for _ in range(4):
            if 0 in eng._ctx_exhausted:
                break
            sched_round(eng)
        assert 0 in eng._ctx_exhausted
        eng._close_ctx_exhausted()
        assert 0 not in eng.state.seqs
        assert eng.query(0)["status"] == "context_exhausted"
        assert not eng.requests.open
        check_allocator(eng)

    def test_flush_is_finished_and_idempotent(self, model):
        eng = mk(model)
        eng.put(0, [1] * 4)
        sched_round(eng)
        eng.flush(0)
        assert eng.query(0)["status"] == "finished"
        eng.flush(0)                        # second close: no-op
        agg = eng.request_metrics()["aggregate"]
        assert agg["finished"] == 1
        assert agg["statuses"] == {"finished": 1}

    def test_statuses_are_documented(self, model):
        eng = mk(model)
        for s in ("finished", "shed", "deadline_exceeded",
                  "context_exhausted", "cancelled", "released"):
            assert s in TERMINAL_STATUSES


# --------------------------------------------------------------------------
# query() status field
# --------------------------------------------------------------------------

class TestQueryStatus:
    def test_full_ladder(self, model):
        eng = mk(model, OverloadConfig(max_queued_requests=1))
        assert eng.query(99)["status"] == "unknown"
        eng.put(0, [1] * 4)
        assert eng.query(0)["status"] == "queued"
        sched_round(eng)
        assert eng.query(0)["status"] == "running"
        eng.flush(0)
        assert eng.query(0)["status"] == "finished"
        eng.put(1, [1] * 4)
        assert not eng.put(2, [1] * 4)
        assert eng.query(2)["status"] == "shed"

    def test_generated_survives_preemption(self, model):
        eng = mk(model, OverloadConfig(preemption=True), num_kv_blocks=4)
        eng.put(0, list(range(1, 33)), priority=5)
        while eng._pending.get(0):
            sched_round(eng)
        eng.state.seqs[0].tokens.extend([7, 8])   # as _collect would
        eng.put(1, list(range(40, 64)), priority=0)
        sched_round(eng)                          # preempts uid 0
        assert eng.query(0)["generated"] == [7, 8]


# --------------------------------------------------------------------------
# end-to-end: real steps through the overloaded engine
# --------------------------------------------------------------------------

def drive(eng, prompts, max_new, rng=None, preempt=None, priorities=None):
    """Minimal direct-API serving loop (what a front-end runs):
    ``preempt=(victim_uid, after_n_steps)`` force-evicts mid-run."""
    for uid, p in prompts.items():
        eng.put(uid, p, priority=(priorities or {}).get(uid, 0))
    done = {u: [] for u in prompts}
    active = set(prompts)
    n = 0
    while active:
        outs = eng.step(rng=rng)
        active -= eng._drain_reaped()
        for uid, tok in outs.items():
            if uid not in active:
                continue
            done[uid].append(tok)
            if len(done[uid]) >= max_new:
                active.discard(uid)
                eng.flush(uid)
            else:
                eng.put(uid, [tok])
        n += 1
        if preempt is not None and n == preempt[1] \
                and preempt[0] in eng.state.seqs:
            eng._preempt(preempt[0])
        assert n < 500, "drive() did not terminate"
    return done


class TestPreemptResumeParity:
    """Evict-and-re-prefill must be invisible in the output stream:
    (uid, position)-folded sampling keys + the host-known chain requeue
    make a preempted-then-resumed request token-identical to an
    undisturbed run."""

    def test_greedy_parity(self, model):
        r = np.random.RandomState(3)
        prompts = {0: list(r.randint(1, 128, 12)),
                   1: list(r.randint(1, 128, 9))}
        kw = dict(num_kv_blocks=16, max_seq_len=96, token_budget=16)
        ref = drive(mk(model, prefix_cache="on", **kw), dict(prompts), 6)
        eng = mk(model, prefix_cache="on", **kw)
        got = drive(eng, dict(prompts), 6, preempt=(1, 3))
        assert got == ref
        assert eng.request_metrics()["aggregate"]["preemptions"] == 1
        check_allocator(eng)

    def test_seeded_parity_cache_off(self, model):
        """Token-identical even when the re-prefill is a full recompute
        (prefix cache off) and sampling is stochastic."""
        r = np.random.RandomState(5)
        prompts = {0: list(r.randint(1, 128, 10)),
                   1: list(r.randint(1, 128, 14))}
        spr = dict(rng=jax.random.PRNGKey(17))
        kw = dict(num_kv_blocks=16, max_seq_len=96, token_budget=16,
                  prefix_cache="off")
        ref = drive(mk(model, **kw), dict(prompts), 5, **spr)
        got = drive(mk(model, **kw), dict(prompts), 5, preempt=(0, 4),
                    **spr)
        assert got == ref

    def test_policy_preemption_end_to_end(self, model):
        """The scheduler's own preemption (not a forced _preempt): a
        high-tier arrival under pool starvation evicts the low-tier
        victim, both still complete, token accounting stays exact."""
        r = np.random.RandomState(9)
        eng = mk(model, OverloadConfig(preemption=True),
                 num_kv_blocks=6, max_seq_len=48, token_budget=16)
        p0 = list(r.randint(1, 128, 30))
        eng.put(0, p0, priority=5)
        done = {0: [], 1: []}
        fed = False
        for _ in range(60):
            outs = eng.step()
            for uid, tok in outs.items():
                done[uid].append(tok)
                if len(done[uid]) < 4:
                    eng.put(uid, [tok])
                else:
                    eng.flush(uid)
            seq0 = eng.state.seqs.get(0)
            if not fed and seq0 is not None \
                    and seq0.seen_tokens >= len(p0):
                eng.put(1, list(r.randint(1, 128, 20)), priority=0)
                fed = True
            if all(len(v) >= 4 for v in done.values()):
                break
        assert all(len(v) >= 4 for v in done.values())
        assert eng.request_metrics()["aggregate"]["preemptions"] >= 1
        rec = {x["uid"]: x for x in eng.request_metrics()["requests"]}
        tm = eng.timings
        assert sum(x["prompt_tokens"] for x in rec.values()) \
            == int(tm["prompt_tokens"])
        assert sum(x["generated_tokens"] for x in rec.values()) \
            == int(tm["generated_tokens"])
        check_allocator(eng)

    def test_generate_with_bounded_queue(self, model):
        """generate() under a shedding config: shed prompts return empty
        rows, admitted ones complete, nothing hangs."""
        eng = mk(model, OverloadConfig(max_queued_requests=2),
                 num_kv_blocks=16, max_seq_len=96)
        r = np.random.RandomState(11)
        prompts = {u: list(r.randint(1, 128, 6)) for u in range(4)}
        out = eng.generate(prompts, SamplingParams(max_new_tokens=3))
        assert set(out) == set(prompts)
        shed = [u for u in prompts if eng.query(u)["status"] == "shed"]
        assert len(shed) == 2 and all(out[u] == [] for u in shed)
        assert all(len(out[u]) == 3 for u in prompts if u not in shed)
