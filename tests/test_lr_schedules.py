"""LR schedule semantics (reference: runtime/lr_schedules.py test analogs in
tests/unit/runtime/test_lr_schedulers.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime import lr_schedules as lrs


def ev(sched, step):
    return float(sched(jnp.asarray(step, jnp.float32)))


class TestWarmupLR:
    def test_linear_warmup(self):
        s = lrs.warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1.0,
                          warmup_num_steps=100, warmup_type="linear")
        assert ev(s, 0) == pytest.approx(0.0)
        assert ev(s, 50) == pytest.approx(0.5)
        assert ev(s, 100) == pytest.approx(1.0)
        assert ev(s, 1000) == pytest.approx(1.0)

    def test_log_warmup_reaches_peak(self):
        s = lrs.warmup_lr(warmup_max_lr=0.1, warmup_num_steps=100,
                          warmup_type="log")
        assert ev(s, 100) == pytest.approx(0.1, rel=1e-5)
        assert 0 < ev(s, 10) < 0.1


class TestWarmupDecayLR:
    def test_decays_to_zero(self):
        s = lrs.warmup_decay_lr(total_num_steps=1000, warmup_max_lr=0.1,
                                warmup_num_steps=100, warmup_type="linear")
        assert ev(s, 100) == pytest.approx(0.1, rel=1e-5)
        assert ev(s, 550) == pytest.approx(0.05, rel=1e-3)
        assert ev(s, 1000) == pytest.approx(0.0, abs=1e-7)
        assert ev(s, 2000) == pytest.approx(0.0, abs=1e-7)


class TestWarmupCosineLR:
    def test_shape(self):
        s = lrs.warmup_cosine_lr(total_num_steps=1000, warmup_num_steps=100,
                                 cos_min_ratio=0.1, lr=1.0)
        assert ev(s, 100) == pytest.approx(1.0, rel=1e-4)
        mid = ev(s, 550)
        assert 0.1 < mid < 1.0
        assert ev(s, 1000) == pytest.approx(0.1, rel=1e-3)


class TestOneCycle:
    def test_triangle(self):
        s = lrs.one_cycle(cycle_min_lr=0.01, cycle_max_lr=0.1,
                          cycle_first_step_size=100)
        assert ev(s, 0) == pytest.approx(0.01)
        assert ev(s, 100) == pytest.approx(0.1)
        assert ev(s, 200) == pytest.approx(0.01, rel=1e-4)

    def test_decay_phase(self):
        s = lrs.one_cycle(cycle_min_lr=0.01, cycle_max_lr=0.1,
                          cycle_first_step_size=100, decay_step_size=100,
                          decay_lr_rate=1.0)
        assert ev(s, 300) < 0.01


class TestLRRangeTest:
    def test_continuous(self):
        s = lrs.lr_range_test(lr_range_test_min_lr=1e-3,
                              lr_range_test_step_size=100,
                              lr_range_test_step_rate=1.0)
        assert ev(s, 0) == pytest.approx(1e-3)
        assert ev(s, 100) == pytest.approx(2e-3)

    def test_staircase(self):
        s = lrs.lr_range_test(lr_range_test_min_lr=1e-3,
                              lr_range_test_step_size=100,
                              lr_range_test_staircase=True)
        assert ev(s, 99) == pytest.approx(1e-3)
        assert ev(s, 100) == pytest.approx(2e-3)
        assert ev(s, 199) == pytest.approx(2e-3)


class TestRegistry:
    def test_build(self):
        s = lrs.build_schedule("WarmupLR", {"warmup_max_lr": 0.5})
        assert callable(s)

    def test_unknown(self):
        with pytest.raises(ValueError):
            lrs.build_schedule("Bogus")

    def test_all_jittable(self):
        import jax
        for name, factory in lrs.SCHEDULES.items():
            if name == "Constant":
                s = factory(1e-3)
            elif name in ("WarmupDecayLR", "WarmupCosineLR"):
                s = factory(total_num_steps=100)
            elif name == "OneCycle":
                s = factory(cycle_min_lr=0.0, cycle_max_lr=0.1)
            else:
                s = factory()
            # one compile per schedule under test; the loop is the
            # parametrization, not a hot path
            out = jax.jit(s)(jnp.asarray(3.0))  # tpulint: disable=retrace-hazard
            assert np.isfinite(float(out))
