"""Sparse gradient reduction (reference analogs: runtime/sparse_tensor.py
+ engine.py sparse_allreduce_bucket; tests/unit sparse grad tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.compat import shard_map
from deepspeed_tpu.runtime.sparse_grads import (default_capacity,
                                                is_sparse_leaf, sparse_psum)


class TestSparsePsum:
    def _run(self, per_shard, capacity):
        """8 shards, each with a row-sparse [V, d] grad."""
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("dp",))
        V, d = 32, 4
        g = jnp.stack(per_shard)                          # [8, V, d]

        def local(g):
            return sparse_psum(g[0], "dp", capacity)[None]

        out = jax.jit(shard_map(
            local, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False))(g)
        return np.asarray(out[0])

    def test_matches_dense_psum_when_capacity_suffices(self):
        r = np.random.RandomState(0)
        V, d = 32, 4
        shards = []
        for s in range(8):
            g = np.zeros((V, d), np.float32)
            rows = r.choice(V, 5, replace=False)
            g[rows] = r.randn(5, d)
            shards.append(jnp.asarray(g))
        got = self._run(shards, capacity=5)
        want = np.sum([np.asarray(s) for s in shards], axis=0)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_overfull_drops_lowest_mass_rows(self):
        V, d = 32, 4
        g = np.zeros((V, d), np.float32)
        g[0] = 100.0                 # heavy row survives
        g[1] = 0.001                 # light row dropped at capacity 1
        shards = [jnp.asarray(g)] * 8
        got = self._run(shards, capacity=1)
        np.testing.assert_allclose(got[0], np.full(d, 800.0), atol=1e-4)
        np.testing.assert_allclose(got[1], np.zeros(d), atol=1e-6)

    def test_leaf_predicate_and_capacity(self):
        assert is_sparse_leaf(("vocab", "embed"))
        assert not is_sparse_leaf(("embed", "vocab"))
        assert not is_sparse_leaf(None)
        assert default_capacity(batch_tokens=4096, vocab=50257) == 4096
        assert default_capacity(batch_tokens=10 ** 9, vocab=50257) == 50257


class TestEngineSparseGradients:
    def test_training_matches_dense(self):
        """sparse_gradients=True reproduces dense training numerics on an
        UNTIED-embedding LM (the lookup grad touches <= tokens-per-shard
        rows, so the capacity is lossless; tied heads would be dense)."""
        from deepspeed_tpu.models import build_model

        m = build_model("llama-tiny", vocab_size=512, num_layers=2,
                        d_model=32, num_heads=4, num_kv_heads=2, d_ff=64,
                        max_seq_len=16, seed=0)
        ids = np.random.RandomState(0).randint(0, 512, (16, 16))
        losses = {}
        for sparse in (False, True):
            eng = ds.initialize(model=m, config={
                "train_micro_batch_size_per_device": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "sparse_gradients": sparse,
                "mesh": {"data": 8}, "steps_per_print": 1000})
            ls = [float(eng.train_batch({"input_ids": ids})["loss"])
                  for _ in range(4)]
            losses[sparse] = ls
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=2e-4, atol=2e-4)
        assert losses[True][-1] < losses[True][0]

    def test_tied_embeddings_rejected_unless_opted_out(self):
        """Tied models get dense vocab grads: sparse_gradients is a hard
        ConfigError by default, and degrades loudly only under
        allow_feature_degradation."""
        from deepspeed_tpu.config.config import ConfigError
        from deepspeed_tpu.models import build_model

        m = build_model("gpt2", vocab_size=256, num_layers=2, d_model=32,
                        num_heads=4, max_seq_len=16)
        base = {
            "train_micro_batch_size_per_device": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "sparse_gradients": True,
            "mesh": {"data": 8}, "steps_per_print": 1000}
        with pytest.raises(ConfigError, match="ties embeddings"):
            ds.initialize(model=m, config=dict(base))
        eng = ds.initialize(model=m, config=dict(
            base, allow_feature_degradation=True))
        assert eng._sparse_axes == ()

    def test_head_bias_leaf_not_sparse(self):
        # a 1-D vocab leaf (lm_head bias) receives DENSE gradients
        assert not is_sparse_leaf(("vocab",))

    @pytest.mark.nightly
    def test_matches_dense_under_stage2_fsdp(self):
        """Stage-2 + fsdp reduce-scatters the table grad first; the
        capacity must cover rows merged from every scattered peer."""
        from deepspeed_tpu.models import build_model

        m = build_model("llama-tiny", vocab_size=512, num_layers=2,
                        d_model=32, num_heads=4, num_kv_heads=2, d_ff=64,
                        max_seq_len=16, seed=0)
        ids = np.random.RandomState(0).randint(0, 512, (16, 16))
        losses = {}
        for sparse in (False, True):
            eng = ds.initialize(model=m, config={
                "train_micro_batch_size_per_device": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "sparse_gradients": sparse,
                "zero_optimization": {"stage": 2},
                "mesh": {"data": 2, "fsdp": 4}, "steps_per_print": 1000})
            losses[sparse] = [
                float(eng.train_batch({"input_ids": ids})["loss"])
                for _ in range(4)]
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=2e-4, atol=2e-4)
