"""Engine train-step correctness (reference analogs:
tests/unit/runtime/zero/test_zero.py — correctness vs unsharded baseline
across stages; tests/unit/runtime/half_precision — fp16/bf16 paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from tests.simple_model import make_batch, make_mlp


def base_config(**over):
    cfg = {
        "train_micro_batch_size_per_device": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2,
                                                  "weight_decay": 0.0}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data": -1},
        "gradient_clipping": 0.0,
        "steps_per_print": 1000,
    }
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k].update(v)
        else:
            cfg[k] = v
    return cfg


def run_steps(cfg, n=5, params=None, axes=None, seed=0):
    p, ax, loss_fn = make_mlp(seed=seed)
    eng = ds.initialize(loss_fn=loss_fn, params=params or p,
                        param_axes=axes or ax, config=cfg)
    losses = []
    gas = eng.gas
    for i in range(n):
        batch = make_batch(eng.train_batch_size, seed=i)
        m = eng.train_batch(batch)
        losses.append(float(m["loss"]))
    return eng, losses


class TestZeroStageEquivalence:
    """All ZeRO stages must produce the same optimization trajectory —
    sharding is a layout choice, not a numerics choice."""

    def test_stages_match(self):
        ref = None
        for stage in (0, 1, 2, 3):
            cfg = base_config(zero_optimization={"stage": stage},
                              mesh={"data": 2, "fsdp": 4})
            _, losses = run_steps(cfg, n=5)
            if ref is None:
                ref = losses
            else:
                np.testing.assert_allclose(losses, ref, rtol=1e-5,
                                           err_msg=f"stage {stage} diverged")

    def test_dp_vs_fsdp_layout(self):
        _, a = run_steps(base_config(mesh={"data": 8, "fsdp": 1},
                                     zero_optimization={"stage": 0}))
        _, b = run_steps(base_config(mesh={"data": 1, "fsdp": 8},
                                     zero_optimization={"stage": 3}))
        np.testing.assert_allclose(a, b, rtol=1e-5)


class TestMiCS:
    """mics_shard_size bounds the shard group (reference:
    runtime/zero/mics.py:64): fsdp shrinks to the group size, the rest
    folds into data replicas; numerics must match plain ZeRO."""

    def test_mics_matches_full_sharding(self):
        _, ref = run_steps(base_config(mesh={"data": 1, "fsdp": 8},
                                       zero_optimization={"stage": 3}))
        _, mics = run_steps(base_config(
            mesh={"data": 1, "fsdp": 8},
            zero_optimization={"stage": 3, "mics_shard_size": 2}))
        np.testing.assert_allclose(mics, ref, rtol=1e-5)

    def test_mics_remaps_mesh_and_master_specs(self):
        from jax.sharding import PartitionSpec  # noqa: F401

        eng, _ = run_steps(base_config(
            mesh={"data": 1, "fsdp": 8},
            zero_optimization={"stage": 3, "mics_shard_size": 2}), n=1)
        assert eng.topology.axis_sizes["fsdp"] == 2
        assert eng.topology.axis_sizes["data"] == 4
        # masters shard within the group only: specs mention fsdp, never
        # data (replicated across groups — the MiCS memory/comm trade)
        leaves = jax.tree.leaves(
            eng.master_shardings,
            is_leaf=lambda x: hasattr(x, "spec"))
        flat_axes = set()
        for sh in leaves:
            for entry in sh.spec:
                if isinstance(entry, str):
                    flat_axes.add(entry)
                elif entry is not None:
                    flat_axes.update(entry)
        assert "fsdp" in flat_axes and "data" not in flat_axes

    def test_mics_conflicts_rejected(self):
        from deepspeed_tpu.config.config import ConfigError

        with pytest.raises(ConfigError, match="only one"):
            run_steps(base_config(
                mesh={"data": 1, "fsdp": 8},
                zero_optimization={"stage": 3, "mics_shard_size": 2,
                                   "zero_hpz_partition_size": 2}), n=1)
        with pytest.raises(ConfigError, match="divide"):
            run_steps(base_config(
                mesh={"data": 1, "fsdp": 8},
                zero_optimization={"stage": 3, "mics_shard_size": 3}), n=1)
        with pytest.raises(ConfigError, match="explicit mesh.fsdp"):
            run_steps(base_config(
                mesh={"data": 2, "fsdp": -1},
                zero_optimization={"stage": 3, "mics_shard_size": 2}), n=1)


class TestGradAccumulation:
    def test_gas_equivalence(self):
        """gas=4 with micro=1 must match gas=1 with micro=4 (same global
        batch; reference: GAS boundary engine.py:1960)."""
        cfg_a = base_config(train_micro_batch_size_per_device=4,
                            gradient_accumulation_steps=1)
        cfg_b = base_config(train_micro_batch_size_per_device=1,
                            gradient_accumulation_steps=4)
        _, a = run_steps(cfg_a, n=4)
        _, b = run_steps(cfg_b, n=4)
        np.testing.assert_allclose(a, b, rtol=1e-4)


class TestPrecision:
    def test_bf16_trains(self):
        cfg = base_config(bf16={"enabled": True},
                          zero_optimization={"stage": 2},
                          mesh={"data": 1, "fsdp": 8})
        _, losses = run_steps(cfg, n=10)
        assert losses[-1] < losses[0]

    def test_fp16_loss_scale_skips_overflow(self):
        p, ax, _ = make_mlp()

        calls = {"n": 0}

        def loss_fn(params, batch, rng):
            x, y = batch["x"], batch["y"]
            h = jnp.tanh(x @ params["w1"] + params["b1"])
            out = h @ params["w2"] + params["b2"]
            return jnp.mean((out.astype(jnp.float32) - y) ** 2)

        cfg = base_config(fp16={"enabled": True, "initial_scale_power": 32,
                                "loss_scale_window": 2, "hysteresis": 1})
        eng = ds.initialize(loss_fn=loss_fn, params=p, param_axes=ax,
                            config=cfg)
        scale0 = float(eng.state.loss_scale.scale)
        m = eng.train_batch(make_batch(eng.train_batch_size))
        # 2^32 scale overflows fp16 grads -> step skipped, scale halved
        assert int(m["overflow"]) == 1
        assert int(eng.state.skipped) == 1
        assert float(eng.state.loss_scale.scale) == scale0 / 2
        assert int(eng.state.step) == 0
        # keep stepping until scale is trainable; then loss decreases
        for i in range(40):
            m = eng.train_batch(make_batch(eng.train_batch_size, seed=i))
            if not int(m["overflow"]):
                break
        assert int(eng.state.step) >= 1

    def test_fp16_scale_growth(self):
        cfg = base_config(fp16={"enabled": True, "initial_scale_power": 8,
                                "loss_scale_window": 2})
        p, ax, loss_fn = make_mlp()
        eng = ds.initialize(loss_fn=loss_fn, params=p, param_axes=ax, config=cfg)
        s0 = float(eng.state.loss_scale.scale)
        for i in range(4):
            eng.train_batch(make_batch(eng.train_batch_size, seed=i))
        assert float(eng.state.loss_scale.scale) > s0


class TestGradClipping:
    def test_clip_reduces_norm(self):
        cfg = base_config(gradient_clipping=1e-4)
        _, losses_clipped = run_steps(cfg, n=3)
        _, losses_free = run_steps(base_config(), n=3)
        # clipped training moves slower
        assert losses_clipped[-1] > losses_free[-1]


class TestBatchResolution:
    def test_inconsistent_raises(self):
        from deepspeed_tpu.config import ConfigError
        cfg = base_config(train_batch_size=100,
                          train_micro_batch_size_per_device=4,
                          gradient_accumulation_steps=1)
        p, ax, loss_fn = make_mlp()
        with pytest.raises(ConfigError):
            ds.initialize(loss_fn=loss_fn, params=p, config=cfg)

    def test_triangulation(self):
        cfg = base_config(train_batch_size=64,
                          train_micro_batch_size_per_device=None,
                          gradient_accumulation_steps=2)
        del cfg["train_micro_batch_size_per_device"]
        p, ax, loss_fn = make_mlp()
        eng = ds.initialize(loss_fn=loss_fn, params=p, config=cfg)
        assert eng.micro_batch_size == 4   # 64 / (2 * 8)


class TestEvalAndParams:
    def test_eval_batch(self):
        cfg = base_config()
        eng, _ = run_steps(cfg, n=2)
        loss = eng.eval_batch(make_batch(32))
        assert np.isfinite(loss)

    def test_compute_params_dtype(self):
        cfg = base_config(bf16={"enabled": True})
        p, ax, loss_fn = make_mlp()
        eng = ds.initialize(loss_fn=loss_fn, params=p, param_axes=ax, config=cfg)
        cp = eng.compute_params
        assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(cp))


class TestShardingLayouts:
    def test_master_sharded_stage1(self):
        cfg = base_config(zero_optimization={"stage": 1},
                          mesh={"data": 1, "fsdp": 8})
        p, ax, loss_fn = make_mlp()
        eng = ds.initialize(loss_fn=loss_fn, params=p, param_axes=ax, config=cfg)
        w1 = eng.state.master["w1"]   # (16, 64): fsdp=8 divides 64
        assert not w1.is_fully_replicated
        m = eng.state.opt_state.m["w1"]
        assert not m.is_fully_replicated

    def test_tp_sharding_applied(self):
        cfg = base_config(mesh={"data": 2, "tensor": 4})
        p, ax, loss_fn = make_mlp()
        eng = ds.initialize(loss_fn=loss_fn, params=p, param_axes=ax, config=cfg)
        spec = eng.param_specs["w1"]
        assert "tensor" in str(spec)
