"""Indexed dataset + offline DataAnalyzer (reference analogs:
data_sampling/indexed_dataset.py, data_analyzer.py,
tests/unit/runtime/data_pipeline)."""

import os

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_analyzer import (DataAnalyzer,
                                                 difficulty_buckets,
                                                 samples_up_to_difficulty)
from deepspeed_tpu.runtime.indexed_dataset import (MMapIndexedDataset,
                                                   MMapIndexedDatasetBuilder)


def build_corpus(prefix, n=20, seed=0, dtype=np.int32):
    r = np.random.RandomState(seed)
    b = MMapIndexedDatasetBuilder(prefix, dtype=dtype)
    samples = [r.randint(0, 100, r.randint(3, 12)).astype(dtype)
               for _ in range(n)]
    for s in samples:
        b.add_item(s)
    b.finalize()
    return samples


class TestIndexedDataset:
    def test_roundtrip(self, tmp_path):
        prefix = str(tmp_path / "corpus")
        samples = build_corpus(prefix)
        ds = MMapIndexedDataset(prefix)
        assert len(ds) == len(samples)
        for i, s in enumerate(samples):
            np.testing.assert_array_equal(ds[i], s)
        assert ds.total_tokens == sum(len(s) for s in samples)

    def test_negative_and_slice(self, tmp_path):
        prefix = str(tmp_path / "c")
        samples = build_corpus(prefix)
        ds = MMapIndexedDataset(prefix)
        np.testing.assert_array_equal(ds[-1], samples[-1])
        got = ds[2:5]
        for g, s in zip(got, samples[2:5]):
            np.testing.assert_array_equal(g, s)

    def test_batch_pads_and_truncates(self, tmp_path):
        prefix = str(tmp_path / "c")
        b = MMapIndexedDatasetBuilder(prefix)
        b.add_item(np.array([1, 2, 3], np.int32))
        b.add_item(np.arange(10, 30, dtype=np.int32))
        b.finalize()
        ds = MMapIndexedDataset(prefix)
        out = ds.batch([0, 1], seq_len=8, pad_id=-1)
        np.testing.assert_array_equal(out[0], [1, 2, 3, -1, -1, -1, -1, -1])
        np.testing.assert_array_equal(out[1], np.arange(10, 18))

    def test_merge_file(self, tmp_path):
        a = str(tmp_path / "a")
        c = str(tmp_path / "b")
        sa = build_corpus(a, n=5, seed=1)
        sb = build_corpus(c, n=7, seed=2)
        m = MMapIndexedDatasetBuilder(str(tmp_path / "m"))
        for s in sa:
            m.add_item(s)
        m.merge_file(c)
        m.finalize()
        ds = MMapIndexedDataset(str(tmp_path / "m"))
        assert len(ds) == 12
        np.testing.assert_array_equal(ds[5], sb[0])

    def test_bad_magic_raises(self, tmp_path):
        prefix = str(tmp_path / "x")
        build_corpus(prefix)
        with open(prefix + ".idx", "r+b") as f:
            f.write(b"GARBAGE!")
        with pytest.raises(ValueError, match="magic"):
            MMapIndexedDataset(prefix)


class TestDataAnalyzer:
    def test_map_reduce_single_worker(self, tmp_path):
        prefix = str(tmp_path / "c")
        samples = build_corpus(prefix, n=30)
        ds = MMapIndexedDataset(prefix)
        out = str(tmp_path / "analysis")
        DataAnalyzer(ds, {"length": len,
                          "mean_tok": lambda s: float(np.mean(s))},
                     save_path=out).run()
        lens = np.load(os.path.join(out, "length", "sample_to_metric.npy"))
        np.testing.assert_array_equal(lens,
                                      [len(s) for s in samples])
        order = np.load(os.path.join(out, "length",
                                     "metric_sorted_samples.npy"))
        sorted_lens = lens[order]
        assert (np.diff(sorted_lens) >= 0).all()

    def test_multi_worker_matches_single(self, tmp_path):
        prefix = str(tmp_path / "c")
        build_corpus(prefix, n=23)
        ds = MMapIndexedDataset(prefix)
        single = str(tmp_path / "s")
        DataAnalyzer(ds, {"length": len}, save_path=single).run()
        multi = str(tmp_path / "m")
        for w in range(3):
            DataAnalyzer(ds, {"length": len}, save_path=multi,
                         num_workers=3, worker_id=w).run_map()
        DataAnalyzer(ds, {"length": len}, save_path=multi,
                     num_workers=3).run_reduce()
        np.testing.assert_array_equal(
            np.load(os.path.join(single, "length", "sample_to_metric.npy")),
            np.load(os.path.join(multi, "length", "sample_to_metric.npy")))

    def test_curriculum_consumption(self, tmp_path):
        prefix = str(tmp_path / "c")
        samples = build_corpus(prefix, n=40)
        ds = MMapIndexedDataset(prefix)
        out = str(tmp_path / "a")
        DataAnalyzer(ds, {"length": len}, save_path=out).run()
        easy = samples_up_to_difficulty(out, "length", max_value=6)
        assert all(len(samples[i]) <= 6 for i in easy)
        assert len(easy) == sum(len(s) <= 6 for s in samples)
        buckets = difficulty_buckets(out, "length", 4)
        assert sum(len(b) for b in buckets) == 40
        assert max(len(samples[i]) for i in buckets[0]) <= \
            min(len(samples[i]) for i in buckets[-1])


def test_more_workers_than_samples(tmp_path):
    """Late workers get empty shards instead of crashing."""
    prefix = str(tmp_path / "c")
    build_corpus(prefix, n=5)
    ds = MMapIndexedDataset(prefix)
    out = str(tmp_path / "a")
    for w in range(4):
        DataAnalyzer(ds, {"length": len}, save_path=out,
                     num_workers=4, worker_id=w).run_map()
    DataAnalyzer(ds, {"length": len}, save_path=out,
                 num_workers=4).run_reduce()
    vals = np.load(os.path.join(out, "length", "sample_to_metric.npy"))
    assert len(vals) == 5 and np.isfinite(vals).all()
