"""Fleet observability plane (deepspeed_tpu/serving/fleet_telemetry.py
— docs/OBSERVABILITY.md "Fleet observability"): request journeys under
the nasty PR-13 races (revived uids, migrate-home round trips, journey
vs engine status-ladder agreement), the FleetRegistry one-exposition
view (replica= labels, rollups, staleness, reconciled terminal rollup),
migration-deduped fleet request metrics, fleet post-mortem bundles, the
fleet anomaly catalog, and the PR-10-style zero-cost-off bar (telemetry
off constructs no monitor and adds ZERO perf_counter reads per router
step — counted).

End-to-end chaos coverage (kill + quarantine + migrate with auto-dumps,
anomaly-armed captures, and the merged --fleet timeline) lives in
tools/loadgen.fleet_chaos_smoke, asserted tier-1 via
tests/test_loadgen.py."""

import json
import time

import jax
import pytest

from deepspeed_tpu.inference import (FailureConfig, InferenceConfig,
                                     InferenceEngine, OverloadConfig,
                                     SamplingParams)
from deepspeed_tpu.models import build_model
from deepspeed_tpu.serving import (FleetConfig, FleetRouter,
                                   FleetTelemetryConfig,
                                   default_fleet_detectors,
                                   reconciled_terminal_statuses,
                                   validate_fleet_dump)
from deepspeed_tpu.serving.fleet_telemetry import FleetTelemetry
from deepspeed_tpu.telemetry import (AnomalyMonitor, MetricsRegistry,
                                     parse_prometheus_text)


@pytest.fixture(scope="module")
def model():
    return build_model("llama-tiny", vocab_size=128, num_layers=2,
                       d_model=64, num_heads=4, num_kv_heads=2, d_ff=128,
                       max_seq_len=256)


def make_engine(model, **kw):
    icfg = dict(token_budget=32, max_seqs=4, kv_block_size=8,
                num_kv_blocks=32, max_seq_len=96, prefix_cache="on",
                failure=FailureConfig(dispatch_timeout_ms=None))
    icfg.update(kw)
    return InferenceEngine(model, InferenceConfig(**icfg))


def make_router(model, n=2, tcfg=None, **cfg_kw):
    cfg_kw.setdefault("telemetry", "on")
    return FleetRouter({f"r{i}": make_engine(model) for i in range(n)},
                       FleetConfig(telemetry_cfg=tcfg, **cfg_kw))


def drive(router, prompts, n_tok=4, sampling=None, rng=None,
          on_step=None, max_steps=300):
    sampling = sampling or SamplingParams(max_new_tokens=1 << 30)
    done = {u: [] for u in prompts}
    for u, p in prompts.items():
        router.put(u, list(p))
    active = set(prompts)
    n = 0
    while active:
        n += 1
        assert n < max_steps, f"fleet drive wedged with {active}"
        if on_step is not None:
            on_step(router, n)
        outs = router.step(rng=rng, sampling=sampling)
        active -= router.drain_reaped()
        for u, t in outs.items():
            if u not in active:
                continue
            done[u].append(t)
            if len(done[u]) >= n_tok:
                active.discard(u)
                router.flush(u)
            else:
                router.put(u, [t])
    return done


def events(journey):
    return [e["event"] for e in journey]


# --------------------------------------------------------------------------
# journeys
# --------------------------------------------------------------------------

class TestJourneys:
    def test_placed_and_closed_with_step_stamps(self, model):
        router = make_router(model, n=2)
        drive(router, {0: [1, 2, 3, 4]})
        j = router.request_journey(0)
        assert events(j) == ["placed", "closed"]
        assert j[0]["replica"] in ("r0", "r1")
        assert j[0]["via"] == "arrival" and "score" in j[0]
        assert j[-1]["status"] == "finished"
        # step-counter timestamps, monotone — the router's only clock
        assert all(isinstance(e["step"], int) for e in j)
        assert j[0]["step"] <= j[-1]["step"]
        # query() folds the journey in
        q = router.query(0)
        assert q["status"] == "finished" and q["journey"] == j

    def test_revived_uid_gets_a_fresh_journey(self, model):
        """The PR-13 revival race, journey-side: a uid fleet-shed (here
        evicted by backpressure) and later re-admitted must START OVER
        — inheriting the dead life's closed journey would make the new
        life look already-terminal."""
        bound = OverloadConfig(max_queued_requests=2,
                               shed_policy="evict-lowest")
        router = FleetRouter(
            {"r0": InferenceEngine(model, InferenceConfig(
                token_budget=32, max_seqs=4, kv_block_size=8,
                num_kv_blocks=32, max_seq_len=96, overload=bound))},
            FleetConfig(telemetry="on"))
        router.put(5, [1, 2, 3], priority=5)
        router.put(7, [4, 5, 6], priority=5)
        v = router.put(6, [7, 8, 9], priority=0)
        assert v.admitted and v.evicted_uids
        eu = v.evicted_uids[0]
        j_dead = router.request_journey(eu)
        assert events(j_dead)[-1] == "closed"
        assert j_dead[-1]["status"] == "shed"
        v2 = router.put(eu, [1, 2, 3], priority=0)   # revived
        assert v2.admitted
        j_new = router.request_journey(eu)
        assert events(j_new) == ["placed"], \
            "revived uid inherited its dead life's journey"
        router.step()
        assert eu not in router.drain_reaped()

    def test_fleet_shed_closes_journey_and_revives_fresh(self, model):
        router = FleetRouter(
            {"r0": make_engine(model)},
            FleetConfig(telemetry="on", probe_interval_steps=1000))
        b = router.replica("r0").breaker
        b.record_failure(1)
        b.record_failure(2)          # nothing routable
        v = router.put(0, [1, 2, 3])
        assert not v.admitted
        j = router.request_journey(0)
        assert events(j) == ["closed"]
        assert j[0]["status"] == "shed" \
            and "no routable" in j[0]["reason"]

    def test_migrate_round_trip_journey(self, model):
        """The migrate-home round trip: a request migrated OFF its
        replica whose destination then dies comes back — the journey
        shows placed(r0) -> migrated -> placed(r1) -> failed_over ->
        placed(r0), and the stream stays token-identical to an
        undisturbed run."""
        router = make_router(model, n=2)
        ref = drive(FleetRouter({"solo": make_engine(model)}),
                    {0: [1, 2, 3, 4, 5]}, n_tok=6)

        def ops(rt, n):
            if n == 2:
                owner = rt._owner[0]
                assert rt.migrate([0], owner) == 1
            if n == 3:
                owner = rt._owner[0]
                rt.replica(owner).engine.failures.inject("fatal")

        done = drive(make_router(model, n=2), {0: [1, 2, 3, 4, 5]},
                     n_tok=6, on_step=ops)
        assert done == ref
        # rebuild the journey story on a fresh router for determinism
        router = make_router(model, n=2)
        done = drive(router, {0: [1, 2, 3, 4, 5]}, n_tok=6, on_step=ops)
        assert done == ref
        j = router.request_journey(0)
        ev = events(j)
        placed = [e["replica"] for e in j if e["event"] == "placed"]
        assert len(placed) == 3
        assert placed[0] == placed[2] != placed[1], \
            f"not a round trip: {placed}"
        assert "migrated" in ev and "failed_over" in ev
        assert ev.index("migrated") < ev.index("failed_over")
        assert j[-1]["event"] == "closed" \
            and j[-1]["status"] == "finished"

    def test_home_on_exhaustion_journey(self, model):
        """The exhaustion-home branch: a migration record whose
        exclusion set leaves nowhere to go retries, exhausts, and goes
        HOME instead of shedding — the journey records the retries and
        the via='home' placement."""
        router = FleetRouter(
            {"r0": make_engine(model), "r1": make_engine(model)},
            FleetConfig(telemetry="on", max_migration_retries=1,
                        migration_backoff_steps=1,
                        probe_interval_steps=1000))
        router.put(0, [1, 2, 3, 4])
        outs = router.step()
        router.put(0, [outs[0]])
        # r1 leaves the routable set; then a record sourced at r0
        # enters the queue (the failover shape, driven directly — the
        # public migrate() refuses extraction with no destination)
        b = router.replica("r1").breaker
        b.record_failure(1)
        b.record_failure(2)
        part = router.replica("r0").engine.migrate_out([0])
        router._owner.pop(0)
        router.replica("r0").engine._drain_reaped()
        assert router._enqueue_migration(part["requests"][0],
                                         source="r0") == 1
        for _ in range(6):
            router.step()
        assert router.query(0)["status"] in ("queued", "running")
        assert router._owner[0] == "r0"          # came home
        j = router.request_journey(0)
        assert "migration_retry" in events(j)
        assert j[-1]["event"] == "placed" and j[-1]["via"] == "home" \
            and j[-1]["replica"] == "r0"
        router.flush(0)

    @pytest.mark.parametrize("mode,cache", [("greedy", "on"),
                                            ("greedy", "off"),
                                            ("seeded", "on"),
                                            ("seeded", "off")])
    def test_journey_agrees_with_engine_status_ladder(self, model,
                                                      mode, cache):
        """router.query()'s journey info must agree with the engine-
        side status ladder at EVERY step: a live status means an open
        journey whose last hop is a placement-shaped event, a terminal
        status means a closed journey with the same status."""
        sp = SamplingParams(max_new_tokens=1 << 30) if mode == "greedy" \
            else SamplingParams(temperature=0.8, top_k=40,
                                max_new_tokens=1 << 30)
        rng = None if mode == "greedy" else jax.random.PRNGKey(7)
        router = FleetRouter(
            {f"r{i}": make_engine(model, prefix_cache=cache)
             for i in range(2)},
            FleetConfig(telemetry="on"))
        prompts = {u: [20 + u, 21, 22, 23] for u in range(3)}

        def check(rt, n):
            if n == 2:
                owner = rt._owner.get(0)
                if owner is not None:
                    rt.migrate([0], owner)
            for u in prompts:
                q = rt.query(u)
                j = q.get("journey")
                if q["status"] in ("queued", "running", "migrating"):
                    assert j and j[-1]["event"] != "closed", (u, q)
                elif q["status"] in ("finished", "cancelled", "shed",
                                     "failed"):
                    assert j and j[-1]["event"] == "closed" \
                        and j[-1]["status"] == q["status"], (u, q)

        drive(router, prompts, n_tok=4, sampling=sp, rng=rng,
              on_step=check)
        for u in prompts:
            q = router.query(u)
            assert q["status"] == "finished"
            assert q["journey"][-1]["status"] == "finished"

    def test_quarantine_rides_owned_journeys(self, model):
        router = FleetRouter(
            {"r0": make_engine(model)},
            FleetConfig(telemetry="on", failure_threshold=2,
                        probe_interval_steps=3))
        router.put(0, [1, 2, 3, 4])
        outs = router.step()
        router.put(0, [outs[0]])     # keep it decoding through the
        router.replica("r0").engine.failures.inject("transient", n=2)
        for _ in range(8):           # quarantine window
            outs = router.step()
            if 0 in outs:
                router.put(0, [outs[0]])
        assert "quarantined" in events(router.request_journey(0))
        router.flush(0)

    def test_journeys_off_when_telemetry_off(self, model):
        router = FleetRouter({"r0": make_engine(model)}, FleetConfig())
        assert router._ftel is None
        router.put(0, [1, 2, 3])
        assert router.request_journey(0) is None
        assert "journey" not in router.query(0)
        assert router.anomaly_summary() is None
        router.flush(0)

    def test_journey_table_bounded(self, model):
        router = FleetRouter(
            {"r0": make_engine(model)},
            FleetConfig(telemetry="on",
                        telemetry_cfg=FleetTelemetryConfig(
                            max_journeys=4)))
        for u in range(8):
            router.put(u, [1, 2, 3])
            router.flush(u)
        assert len(router.request_journeys()) <= 4
        assert router.request_journey(7) is not None   # newest kept


# --------------------------------------------------------------------------
# migration-deduped fleet request metrics
# --------------------------------------------------------------------------

class TestFleetRequestMetrics:
    def test_migrated_uid_yields_one_record(self, model):
        router = make_router(model, n=2)

        def ops(rt, n):
            if n == 2:
                owner = rt._owner[0]
                rt.migrate([0], owner)

        drive(router, {0: [1, 2, 3, 4, 5], 1: [9, 8, 7]}, n_tok=4,
              on_step=ops)
        rm = router.request_metrics()
        recs = [r for r in rm["requests"] if r["uid"] == 0]
        assert len(recs) == 1, "migrated uid forked into two records"
        rec = recs[0]
        assert rec["status"] == "finished"
        assert len(rec["hops"]) == 2
        assert rec["hops"][0]["status"] == "migrated"
        assert rec["replica"] == rec["hops"][-1]["replica"]
        # attribution: the finishing replica
        fin_eng = router.replica(rec["replica"]).engine
        assert fin_eng.query(0)["status"] == "finished"
        # sums stay exact fleet-wide (the reconciliation bar)
        for key in ("prompt_tokens", "generated_tokens"):
            ctr = sum(int(router.replica(n).engine.timings[key])
                      for n in router.replica_names)
            assert rm["aggregate"][key] == ctr

    def test_routing_retry_sheds_are_phantoms(self, model):
        """A put shed by one replica and admitted by the next leaves an
        engine-side shed record on the first — a PHANTOM the deduped
        view drops and the reconciled rollup subtracts (the PR-13
        known-but-unfixed double counting, fixed)."""
        bound = OverloadConfig(max_queued_requests=0,
                               shed_policy="reject")
        full = InferenceEngine(model, InferenceConfig(
            token_budget=32, max_seqs=4, kv_block_size=8,
            num_kv_blocks=32, max_seq_len=96, overload=bound))
        router = FleetRouter(
            {"r0": full, "r1": make_engine(model)},
            FleetConfig(telemetry="on", placement="least_loaded"))
        # r0 is least-loaded-first (name tiebreak) and sheds instantly;
        # r1 admits — fleet truth: ONE life, zero sheds
        v = router.put(0, [1, 2, 3, 4])
        assert v.admitted and v.replica == "r1"
        assert int(router.metrics.get(
            "serving_fleet_replica_shed_retries_total").value()) == 1
        drive_done = {0: []}
        for _ in range(8):
            outs = router.step()
            if 0 in outs:
                drive_done[0].append(outs[0])
                if len(drive_done[0]) >= 2:
                    router.flush(0)
                    break
                router.put(0, [outs[0]])
        rm = router.request_metrics()
        assert rm["aggregate"]["statuses"] == {"finished": 1}
        assert [r["status"] for r in rm["requests"]] == ["finished"]
        assert reconciled_terminal_statuses(router) == {"finished": 1}
        # the engine-side truth still shows the shed (raw, per replica)
        assert rm["replicas"]["r0"]["statuses"].get("shed") == 1

    def test_queue_settle_after_prior_migration_counts_once(self, model):
        """Review regression: a request that already MIGRATED once (a
        'migrated' hop survives on its first replica) and later parks
        in the migration queue (scale-down with no routable
        destination) must count exactly ONCE when the client flushes
        it — the surviving hop record makes it visible to the merged
        view, so no record-gap entry may be added on top."""
        from tools.loadgen import check_fleet_invariants

        router = FleetRouter(
            {"r0": make_engine(model), "r1": make_engine(model)},
            FleetConfig(telemetry="on", probe_interval_steps=1000))
        router.put(0, [1, 2, 3, 4])
        outs = router.step()
        router.put(0, [outs[0]])
        src = router._owner[0]
        assert router.migrate([0], src) == 1     # hop record on src
        dst = router._owner[0]
        # quarantine the original source so the scale-down record has
        # nowhere to go and parks in the queue
        b = router.replica(src).breaker
        b.record_failure(1)
        b.record_failure(2)
        router.scale_down(dst, deadline_ms=0.0)
        assert router.query(0)["status"] == "migrating"
        router.flush(0)                           # settles in the queue
        assert router.query(0)["status"] == "finished"
        rm = router.request_metrics()
        assert rm["aggregate"]["statuses"] == {"finished": 1}
        assert reconciled_terminal_statuses(router) == {"finished": 1}
        check_fleet_invariants(router)

    def test_fleet_saturation_shed_counts_once(self, model):
        bound = OverloadConfig(max_queued_requests=0,
                               shed_policy="reject")

        def bounded():
            return InferenceEngine(model, InferenceConfig(
                token_budget=32, max_seqs=4, kv_block_size=8,
                num_kv_blocks=32, max_seq_len=96, overload=bound))

        router = FleetRouter({"r0": bounded(), "r1": bounded()},
                             FleetConfig(telemetry="on"))
        v = router.put(0, [1, 2, 3])
        assert not v.admitted
        # two engine shed records + one fleet shed == ONE fleet terminal
        rm = router.request_metrics()
        assert rm["aggregate"]["statuses"] == {"shed": 1}
        assert reconciled_terminal_statuses(router) == {"shed": 1}
        assert rm["aggregate"]["fleet_shed"] == 1


# --------------------------------------------------------------------------
# FleetRegistry: one exposition
# --------------------------------------------------------------------------

class TestFleetRegistry:
    def test_replica_labels_and_rollups(self, model):
        router = make_router(model, n=2)
        drive(router, {0: [1, 2, 3, 4], 1: [5, 6, 7]})
        text = router.fleet_registry.prometheus_text()
        parsed = parse_prometheus_text(text)
        # every replica's series under replica= labels
        steps = parsed["serving_steps_total"]["samples"]
        assert {dict(k[1])["replica"] for k in steps} == {"r0", "r1"}
        # rollup == sum over replicas == engine counter truth
        gen = parsed["serving_fleet_generated_tokens_total"]["samples"]
        ctr = sum(int(router.replica(n).engine.timings
                      ["generated_tokens"])
                  for n in router.replica_names)
        assert int(sum(gen.values())) == ctr
        # pull gauges stay pull: scraped at export, never cached —
        # the pool gauge reads live allocator truth (all blocks free
        # after the drive)
        free = parsed["serving_kv_blocks_free"]["samples"]
        total = parsed["serving_kv_blocks_total"]["samples"]
        assert sum(free.values()) == sum(total.values())
        # rates never roll up (a summed ratio is a lie)
        assert "serving_fleet_prefix_hit_rate" not in parsed
        # histograms re-export per replica AND roll up
        assert "serving_ttft_ms" in parsed
        assert "serving_fleet_ttft_ms" in parsed
        # the router's own fleet series ride the same exposition
        assert "serving_fleet_placements_total" in parsed
        # and the exposition round-trips through the shared parser
        assert parsed  # parse_prometheus_text raised on no line

    def test_reconciled_terminal_rollup(self, model):
        router = make_router(model, n=2)

        def ops(rt, n):
            if n == 2:
                owner = rt._owner[0]
                rt.migrate([0], owner)

        drive(router, {0: [1, 2, 3, 4, 5]}, n_tok=4, on_step=ops)
        parsed = parse_prometheus_text(
            router.fleet_registry.prometheus_text())
        rec = parsed["serving_fleet_requests_terminal_total"]["samples"]
        by_status = {dict(k[1])["status"]: int(v)
                     for k, v in rec.items()}
        # the naive per-replica sum would count the migrated hop too
        assert by_status == {"finished": 1}
        raw = parsed["serving_requests_terminal_total"]["samples"]
        raw_statuses = {dict(k[1])["status"] for k in raw}
        assert "migrated" in raw_statuses   # raw truth still exported

    def test_dead_replica_exports_with_staleness_marker(self, model):
        router = make_router(model, n=2)
        drive(router, {0: [1, 2, 3, 4]})
        victim = next(iter(router.replica_names))
        router.replica(victim).engine._health = "dead"
        router._failover(victim)
        parsed = parse_prometheus_text(
            router.fleet_registry.prometheus_text())
        stale = {dict(k[1])["replica"]: v for k, v in
                 parsed["serving_fleet_replica_stale"]["samples"].items()}
        assert stale[victim] == 1.0
        assert all(v == 0.0 for n, v in stale.items() if n != victim)
        # the dead replica's series did NOT vanish
        steps = parsed["serving_steps_total"]["samples"]
        assert victim in {dict(k[1])["replica"] for k in steps}

    def test_fleet_scope_registration_delegates(self, model):
        router = make_router(model, n=1)
        fleet_registry = router.fleet_registry
        c = fleet_registry.counter("serving_fleet_custom_total",
                                   "fleet-scope test counter",
                                   int_valued=True)
        c.inc(3, replica="r0")
        parsed = parse_prometheus_text(
            fleet_registry.prometheus_text())
        samples = parsed["serving_fleet_custom_total"]["samples"]
        assert {dict(k[1])["replica"]: v
                for k, v in samples.items()} == {"r0": 3.0}

    def test_snapshot_json_able(self, model):
        router = make_router(model, n=2)
        drive(router, {0: [1, 2, 3, 4]})
        snap = router.fleet_registry.snapshot()
        json.dumps(snap)
        assert set(snap["replicas"]) == {"r0", "r1"}
        assert "serving_fleet_generated_tokens_total" in snap["rollups"]
        assert snap["stale"] == {"r0": False, "r1": False}


# --------------------------------------------------------------------------
# fleet post-mortem bundle
# --------------------------------------------------------------------------

class TestFleetDump:
    def test_debug_dump_bundle_validates(self, model, tmp_path):
        router = make_router(model, n=2)
        drive(router, {0: [1, 2, 3, 4]})
        bdir = tmp_path / "bundle"
        dump = router.debug_dump(str(bdir), reason="test")
        assert validate_fleet_dump(dump, base_dir=str(bdir)) == []
        on_disk = json.loads((bdir / "fleet.json").read_text())
        assert validate_fleet_dump(on_disk, base_dir=str(bdir)) == []
        assert set(on_disk["replicas"]) == {"r0", "r1"}
        assert on_disk["journeys"], "journeys missing from the bundle"
        assert (bdir / "router_trace.json").exists()
        assert (bdir / "replicas" / "r0" / "flight.json").exists()
        # the bundle's request metrics are the deduped fleet view
        assert on_disk["request_metrics"]["aggregate"]["statuses"] \
            == {"finished": 1}

    def test_validator_catches_breakage(self, model, tmp_path):
        router = make_router(model, n=1)
        dump = router.debug_dump(str(tmp_path / "b"), reason="test")
        bad = dict(dump)
        bad.pop("journeys")
        bad["version"] = 99
        problems = validate_fleet_dump(bad)
        assert any("journeys" in p for p in problems)
        assert any("version" in p for p in problems)
        missing = dict(dump)
        missing["replicas"] = {"r0": {"flight": "nope/flight.json"}}
        assert any("flight dump missing" in p for p in
                   validate_fleet_dump(missing, base_dir=str(tmp_path)))

    def test_autodump_budget_and_collision_safety(self, model,
                                                  tmp_path):
        d = str(tmp_path / "flight")
        router = FleetRouter(
            {"r0": make_engine(model)},
            FleetConfig(telemetry="on", flight_dir=d, max_autodumps=2,
                        probe_interval_steps=1000))
        b = router.replica("r0").breaker
        b.record_failure(1)
        b.record_failure(2)          # nothing routable: every put sheds
        import os
        for u in range(4):
            router.put(u, [1, 2, 3])
        bundles = [p for p in os.listdir(d)
                   if p.startswith("fleet_fleet_shed")]
        assert len(bundles) == 2     # budgeted
        # a second router generation sharing the dir must not overwrite
        router2 = FleetRouter(
            {"r0": make_engine(model)},
            FleetConfig(telemetry="on", flight_dir=d, max_autodumps=2,
                        probe_interval_steps=1000))
        b2 = router2.replica("r0").breaker
        b2.record_failure(1)
        b2.record_failure(2)
        router2.put(0, [1, 2, 3])
        now = [p for p in os.listdir(d)
               if p.startswith("fleet_fleet_shed")]
        assert len(now) == 3, "generation collision destroyed a bundle"


# --------------------------------------------------------------------------
# fleet anomaly catalog
# --------------------------------------------------------------------------

class TestFleetAnomalies:
    def _monitor(self, cfg=None):
        reg = MetricsRegistry()
        cfg = cfg or FleetTelemetryConfig()
        mon = AnomalyMonitor(cfg.anomaly, reg, prefix="serving_fleet")
        mon.watch_all(default_fleet_detectors(cfg))
        return mon, reg

    def test_catalog_signals(self):
        mon, _ = self._monitor()
        assert set(mon.signals) == {
            "placement_imbalance", "affinity_hit_rate",
            "failover_migration_storm", "ttft_divergence"}

    def test_storm_detector_fires_on_burst_not_single(self):
        mon, reg = self._monitor(FleetTelemetryConfig(storm_limit=3.0))
        # a single clean failover (1-2 windowed events) is an incident,
        # not a storm
        assert mon.observe("failover_migration_storm", 2.0, 1) is None
        ev = mon.observe("failover_migration_storm", 6.0, 2)
        assert ev is not None and ev.signal == "failover_migration_storm"
        c = reg.get("serving_fleet_anomalies_total")
        assert c.value(signal="failover_migration_storm") == 1

    def test_ttft_divergence_threshold(self):
        mon, _ = self._monitor(
            FleetTelemetryConfig(ttft_divergence_ratio=4.0))
        assert mon.observe("ttft_divergence", 2.0, 1) is None
        assert mon.observe("ttft_divergence", 9.0, 2) is not None

    def test_kill_fires_storm_and_arms_capture(self, model, tmp_path):
        """The end-to-end wiring on real engines: a mid-traffic kill
        (failover + migrations in one step) fires the storm signal,
        bumps serving_fleet_anomalies_total, breadcrumbs the flight
        ring, and arms a budgeted capture on the implicated replica."""
        router = FleetRouter(
            {f"r{i}": make_engine(model) for i in range(3)},
            FleetConfig(telemetry="on", flight_dir=str(tmp_path),
                        telemetry_cfg=FleetTelemetryConfig(
                            storm_limit=1.0, capture_steps=2)))

        def ops(rt, n):
            if n == 3:
                name = max((rt.replica(n2).load(), n2)
                           for n2 in rt.replica_names
                           if not rt.replica(n2).dead)[1]
                rt.replica(name).engine.failures.inject("fatal")

        drive(router, {u: [30 + u, 31, 32, 33] for u in range(5)},
              n_tok=6, on_step=ops)
        for n in router.replica_names:
            if not router.replica(n).dead:
                router.replica(n).engine.finish_capture()
        asum = router.anomaly_summary()
        assert asum["by_signal"].get("failover_migration_storm", 0) >= 1
        c = router.metrics.get("serving_fleet_anomalies_total")
        assert c.value(signal="failover_migration_storm") >= 1
        assert any(e.get("kind") == "fleet_anomaly"
                   and e.get("signal") == "failover_migration_storm"
                   for e in router.flight.events())
        assert asum["captures"], "no capture armed for the anomaly"
        cap = asum["captures"][0]
        assert not router.replica(cap["replica"]).dead
        assert cap["dir"] in \
            router.replica(cap["replica"]).engine.capture_dirs

    def test_capture_budget_bounds_armed_windows(self, model,
                                                 tmp_path):
        router = FleetRouter(
            {"r0": make_engine(model), "r1": make_engine(model)},
            FleetConfig(telemetry="on", flight_dir=str(tmp_path),
                        telemetry_cfg=FleetTelemetryConfig(
                            max_captures=0, storm_limit=0.0)))
        router.put(0, [1, 2, 3, 4])
        router.step()
        owner = router._owner[0]
        router.migrate([0], owner)   # storm_limit=0: any event fires
        router.step()
        asum = router.anomaly_summary()
        assert asum["total"] >= 1
        assert asum["captures"] == []   # budget 0: fired, not armed


# --------------------------------------------------------------------------
# the zero-cost-off bar (counted, PR-10 style)
# --------------------------------------------------------------------------

class _StubState:
    def __init__(self):
        self.seqs = {}
        self._hash_index = {}

    def prefix_digests(self):
        return frozenset()


class _StubICfg:
    kv_block_size = 8


class _StubEngine:
    """The minimal engine surface the router's hot path touches — no
    clocks anywhere, so any perf_counter read counted during a router
    step is the ROUTER's own."""

    max_blocks_per_seq = 4

    def __init__(self):
        from deepspeed_tpu.inference.overload import AdmissionVerdict
        self._verdict = AdmissionVerdict(True, "queued")
        self.icfg = _StubICfg()
        self.state = _StubState()
        self._pending = {}
        self._meta = {}
        self._draining = False
        self._health = "healthy"
        self.metrics = MetricsRegistry()
        self.timings = {"step_retries": 0, "steps": 0}

    def put(self, uid, tokens, priority=0, deadline_ms=None,
            slo_class=None):
        self._pending[uid] = list(tokens)
        return self._verdict

    def step(self, rng=None, sampling=None):
        self.timings = dict(self.timings, steps=self.timings["steps"] + 1)
        return {}

    def _drain_reaped(self):
        return set()

    def health_state(self):
        return "healthy"


class TestZeroCostOff:
    def _drive(self, router, steps=8):
        for u in range(3):
            router.put(u, [1, 2, 3])
        for _ in range(steps):
            router.step()

    def test_off_constructs_no_monitor_and_no_tracer(self, monkeypatch):
        def boom(*a, **k):
            raise AssertionError("constructed with telemetry off")

        monkeypatch.setattr(FleetTelemetry, "__init__", boom)
        router = FleetRouter({"r0": _StubEngine(), "r1": _StubEngine()})
        assert router.cfg.telemetry == "auto"    # auto resolves OFF
        assert router._ftel is None
        monkeypatch.setattr(AnomalyMonitor, "observe", boom)
        self._drive(router)                      # no detector touched

    def test_off_adds_zero_perf_counter_reads_per_step(self,
                                                       monkeypatch):
        """THE counted bar: with stub replicas (no clocks of their
        own), a router step with fleet telemetry off performs ZERO
        perf_counter/perf_counter_ns reads — the router's only clock
        stays its step counter.  Telemetry ON reads clocks (the span
        ring), proving the counter instrumentation sees them."""
        reads = [0]
        real_pc, real_ns = time.perf_counter, time.perf_counter_ns

        def pc():
            reads[0] += 1
            return real_pc()

        def ns():
            reads[0] += 1
            return real_ns()

        router_off = FleetRouter({"r0": _StubEngine(),
                                  "r1": _StubEngine()},
                                 FleetConfig(telemetry="off"))
        router_on = FleetRouter({"r0": _StubEngine(),
                                 "r1": _StubEngine()},
                                FleetConfig(telemetry="on"))
        monkeypatch.setattr(time, "perf_counter", pc)
        monkeypatch.setattr(time, "perf_counter_ns", ns)
        self._drive(router_off)
        assert reads[0] == 0, \
            f"telemetry off added {reads[0]} clock reads"
        self._drive(router_on)
        assert reads[0] > 0, \
            "the counter instrumentation saw no reads even with " \
            "telemetry on — the bar test is vacuous"
