"""ZeRO-Inference: quantized-weight serving + KV offload
(reference analogs: inference/quantization tests, ZeRO-Inference
README.md:35 — 'serve models 20x bigger via weight quantization +
KV-cache offload')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference import (InferenceConfig, InferenceEngine,
                                     SamplingParams)
from deepspeed_tpu.models import apply, build_model
from tests.test_inference import make_engine, tiny_model

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


class TestQuantizeModelParams:
    def test_split_and_roundtrip(self):
        from deepspeed_tpu.inference.quantization import (
            layer_weight, quantize_model_params)
        m = tiny_model()
        dense, quant = quantize_model_params(m.params, bits=8)
        # weights moved out of the dense tree; norms stay dense
        assert "wq" not in dense["blocks"]["attn"]
        assert "scale" in dense["blocks"]["ln1"]
        qt = quant["blocks"]["attn"]["wq"]
        assert qt.data.dtype == jnp.int8
        for i in range(m.config.num_layers):
            w = layer_weight(qt, i, jnp.float32)
            ref = np.asarray(m.params["blocks"]["attn"]["wq"][i])
            err = np.abs(np.asarray(w) - ref).max()
            assert err < np.abs(ref).max() * 0.02, err

    def test_int4_packs_half_bytes(self):
        from deepspeed_tpu.inference.quantization import (
            quantize_model_params)
        m = tiny_model()
        _, q8 = quantize_model_params(m.params, bits=8)
        _, q4 = quantize_model_params(m.params, bits=4)
        assert (q4["blocks"]["attn"]["wq"].data.size ==
                q8["blocks"]["attn"]["wq"].data.size // 2)


class TestQuantizedServing:
    @pytest.mark.parametrize("wq", ["int8", "int4"])
    def test_greedy_close_to_fp(self, wq):
        """Quantized serving tracks the fp path (int8 should match
        greedy tokens on a tiny model; int4 must at least run and
        produce logits close to fp)."""
        m = tiny_model()
        eng_fp = make_engine(m, kv_dtype=jnp.float32,
                             param_dtype=jnp.float32)
        eng_q = make_engine(m, kv_dtype=jnp.float32,
                            param_dtype=jnp.float32, weight_quant=wq)
        prompt = list(np.random.RandomState(0).randint(1, 128, 10))
        out_fp = eng_fp.generate({1: prompt}, GREEDY)[1]
        out_q = eng_q.generate({1: prompt}, GREEDY)[1]
        assert len(out_q) == len(out_fp)
        if wq == "int8":
            assert out_q == out_fp

    def test_mixed_gemm_serving_matches_dequant(self):
        """mixed_gemm='on' routes all six projection matmuls through the
        VMEM-dequant kernel (interpret off-TPU) and must reproduce the
        fused-dequant greedy decode exactly on a tiny model."""
        m = tiny_model()
        eng_d = make_engine(m, kv_dtype=jnp.float32,
                            param_dtype=jnp.float32, weight_quant="int8",
                            mixed_gemm="off")
        eng_m = make_engine(m, kv_dtype=jnp.float32,
                            param_dtype=jnp.float32, weight_quant="int8",
                            mixed_gemm="on")
        assert eng_m._quant_is_rowwise()
        prompt = list(np.random.RandomState(1).randint(1, 128, 12))
        out_d = eng_d.generate({1: prompt}, GREEDY)[1]
        out_m = eng_m.generate({1: prompt}, GREEDY)[1]
        assert eng_m._mixed_gemm_active
        assert out_m == out_d

    def test_mixed_gemm_rejected_for_grouped_layouts(self):
        """Grouped/minifloat trees are not layouts the kernel family
        consumes: forcing mixed_gemm='on' must raise (same contract as
        the streamed path), while 'auto' quietly keeps the kernel off.
        (int4 is now the packed row-wise layout and IS eligible — fp6
        stays the ineligible exemplar.)"""
        m = tiny_model()
        with pytest.raises(ValueError, match="mixed_gemm"):
            make_engine(m, kv_dtype=jnp.float32,
                        param_dtype=jnp.float32, weight_quant="fp6",
                        mixed_gemm="on")
        eng = make_engine(m, kv_dtype=jnp.float32,
                          param_dtype=jnp.float32, weight_quant="fp6",
                          mixed_gemm="auto")
        prompt = list(np.random.RandomState(2).randint(1, 128, 8))
        out = eng.generate({1: prompt}, GREEDY)[1]
        assert len(out) == GREEDY.max_new_tokens
        assert not eng._mixed_gemm_active

    def test_quantized_embeddings_serving_runs(self):
        m = tiny_model()
        eng = make_engine(m, weight_quant="int8",
                          quantize_embeddings=True)
        out = eng.generate({0: [3, 1, 4, 1, 5]}, GREEDY)[0]
        assert len(out) == 8

    def test_resident_weight_bytes_shrink(self):
        m = tiny_model(d_model=128, d_ff=512)
        def nbytes(tree):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree)
                       if hasattr(x, "dtype"))
        eng_fp = make_engine(m)
        eng_q = make_engine(m, weight_quant="int4")
        dense_fp = nbytes(eng_fp.params)
        resident_q = nbytes(eng_q.params) + nbytes(eng_q._quant)
        assert resident_q < 0.55 * dense_fp, (resident_q, dense_fp)


class TestKVOffload:
    def test_kv_offload_best_effort(self):
        """Serving works with kv_offload requested; on backends with an
        addressable host space the cache reports pinned_host."""
        m = tiny_model()
        eng = make_engine(m, weight_quant="int8", kv_offload=True)
        out = eng.generate({0: [7, 3, 9]}, GREEDY)[0]
        assert len(out) == 8
        if eng._kv_on_host:
            kind = getattr(eng.state.kv.sharding, "memory_kind", None)
            assert kind in ("pinned_host", "unpinned_host")


class TestMinifloatServing:
    def test_fp6_serving_runs_and_tracks_fp(self):
        """fp6 weights (reference FP6 of csrc/fp_quantizer) serve with
        bounded drift from the fp path."""
        m = tiny_model()
        eng_fp = make_engine(m, kv_dtype=jnp.float32,
                             param_dtype=jnp.float32)
        eng_q = make_engine(m, kv_dtype=jnp.float32,
                            param_dtype=jnp.float32, weight_quant="fp6")
        prompt = list(np.random.RandomState(4).randint(1, 128, 8))
        out_fp = eng_fp.generate({1: prompt}, GREEDY)[1]
        out_q = eng_q.generate({1: prompt}, GREEDY)[1]
        assert len(out_q) == len(out_fp)

    def test_fp12_matches_greedy(self):
        m = tiny_model()
        eng_fp = make_engine(m, kv_dtype=jnp.float32,
                             param_dtype=jnp.float32)
        eng_q = make_engine(m, kv_dtype=jnp.float32,
                            param_dtype=jnp.float32, weight_quant="fp12")
        prompt = list(np.random.RandomState(5).randint(1, 128, 8))
        assert eng_q.generate({1: prompt}, GREEDY)[1] == \
            eng_fp.generate({1: prompt}, GREEDY)[1]


class TestWeightStream:
    """Per-layer NVMe weight streaming (reference:
    partitioned_param_swapper.py:290 / the ZeRO-Inference NVMe leg)."""

    def _gen(self, eng, prompts):
        from deepspeed_tpu.inference import SamplingParams
        return eng.generate({u: list(p) for u, p in prompts.items()},
                            SamplingParams(temperature=0.0,
                                           max_new_tokens=6))

    def test_streamed_matches_resident(self, tmp_path):
        from deepspeed_tpu.inference import InferenceConfig, InferenceEngine
        from deepspeed_tpu.models import build_model

        m = build_model("llama-tiny", vocab_size=128, num_layers=3,
                        d_model=32, num_heads=4, num_kv_heads=2, d_ff=64,
                        max_seq_len=64)
        kw = dict(token_budget=16, max_seqs=2, kv_block_size=8,
                  num_kv_blocks=32, attn_impl="xla",
                  param_dtype=jnp.float32, kv_dtype=jnp.float32)
        prompts = {0: [5, 17, 99, 3], 1: [8, 9]}
        ref = self._gen(InferenceEngine(m, InferenceConfig(**kw)), prompts)
        eng = InferenceEngine(m, InferenceConfig(
            weight_stream=str(tmp_path / "w"), **kw))
        # block weights left HBM: the resident tree has no 'blocks'
        assert "blocks" not in eng.params
        import os
        assert any(f.startswith("layer") for f in
                   os.listdir(tmp_path / "w"))
        assert ref == self._gen(eng, prompts)

    def test_streamed_quantized_matches_resident_quantized(self, tmp_path):
        """int8 payloads are what streams — the fetch is quantized-sized,
        dequantization happens on device after the callback."""
        from deepspeed_tpu.inference import InferenceConfig, InferenceEngine
        from deepspeed_tpu.models import build_model

        m = build_model("llama-tiny", vocab_size=128, num_layers=3,
                        d_model=32, num_heads=4, num_kv_heads=2, d_ff=64,
                        max_seq_len=64)
        # pin the GEMM path: the probe may legitimately pick mixed for
        # one engine and dequant for the other (their cost profiles
        # differ), and the two paths differ in bf16 rounding — the
        # variable under test is the streaming machinery, nothing else
        kw = dict(token_budget=16, max_seqs=2, kv_block_size=8,
                  num_kv_blocks=32, attn_impl="xla", weight_quant="int8",
                  mixed_gemm="off",
                  param_dtype=jnp.float32, kv_dtype=jnp.float32)
        prompts = {0: [5, 17, 99, 3], 1: [8, 9]}
        ref = self._gen(InferenceEngine(m, InferenceConfig(**kw)), prompts)
        eng = InferenceEngine(m, InferenceConfig(
            weight_stream=str(tmp_path / "wq"), **kw))
        assert eng._quant["blocks"] == {}       # payloads live on NVMe
        assert ref == self._gen(eng, prompts)

    def test_streamed_mixed_gemm_matches(self, tmp_path):
        """mixed_gemm='on' + weight_stream: streamed row-wise int8
        payloads stay quantized all the way into the VMEM-dequant kernel
        and reproduce the streamed-dequant greedy decode."""
        from deepspeed_tpu.inference import InferenceConfig, InferenceEngine
        from deepspeed_tpu.models import build_model

        def mk():
            return build_model("llama-tiny", vocab_size=128, num_layers=3,
                               d_model=32, num_heads=4, num_kv_heads=2,
                               d_ff=64, max_seq_len=64)
        # bf16 serving dtype: the mixed kernel's MXU feed is bf16 by
        # construction, so the dequant reference must run the same
        # precision for exact greedy parity (at f32 the reference keeps
        # unrounded weights the kernel never sees — on real TPUs too)
        kw = dict(token_budget=16, max_seqs=2, kv_block_size=8,
                  num_kv_blocks=32, attn_impl="xla", weight_quant="int8",
                  param_dtype=jnp.bfloat16, kv_dtype=jnp.float32)
        prompts = {0: [5, 17, 99, 3], 1: [8, 9]}
        ref = self._gen(InferenceEngine(mk(), InferenceConfig(
            weight_stream=str(tmp_path / "wd"), mixed_gemm="off", **kw)),
            prompts)
        eng = InferenceEngine(mk(), InferenceConfig(
            weight_stream=str(tmp_path / "wm"), mixed_gemm="on", **kw))
        assert eng._stream.mixed_gemm_eligible
        out = self._gen(eng, prompts)
        assert eng._mixed_gemm_active
        assert out == ref


class TestStreamedMoEServing:
    def test_streamed_moe_matches_resident(self, tmp_path):
        """NVMe weight streaming with an MoE model: the streamed layer
        sweep rebuilds the gate/experts/shared groups and moe_ffn
        consumes them dense — tokens match the resident engine exactly
        (fp and int8)."""
        m = build_model("mixtral-tiny", vocab_size=128, num_layers=2,
                        d_model=64, num_heads=4, num_kv_heads=2, d_ff=128,
                        num_experts=4, capacity_factor=4.0,
                        eval_capacity_factor=4.0)
        base = dict(token_budget=32, max_seqs=4, kv_block_size=16,
                    num_kv_blocks=64, param_dtype=jnp.float32,
                    kv_dtype=jnp.float32)
        gr = SamplingParams(temperature=0.0, max_new_tokens=5)
        for name, kw in (("fp", {}), ("int8", {"weight_quant": "int8"})):
            ref = InferenceEngine(m, InferenceConfig(**base, **kw)
                                  ).generate({0: [1, 2, 3]}, gr)[0]
            out = InferenceEngine(
                m, InferenceConfig(**base, **kw,
                                   weight_stream=str(tmp_path / name))
                ).generate({0: [1, 2, 3]}, gr)[0]
            assert out == ref, name


class TestSharedExpertQuantServing:
    """qwen2-moe regression: the dense 'shared' expert group is consumed
    by plain matmuls (models/transformer._shared_expert), so the
    mixed-GEMM path must dequantize it like 'experts' — previously
    mixed_gemm='on' crashed at trace time handing _shared_expert a
    QuantizedTensor, and 'auto' silently disabled the kernel when the
    probe swallowed that crash."""

    def _model(self):
        return build_model(
            "qwen2-moe-tiny", vocab_size=128, num_layers=2, d_model=64,
            num_heads=4, num_kv_heads=2, d_ff=96, moe_shared_ff=128,
            max_seq_len=256, capacity_factor=4.0, eval_capacity_factor=4.0)

    def _kw(self):
        return dict(token_budget=32, max_seqs=4, kv_block_size=16,
                    num_kv_blocks=64, param_dtype=jnp.float32,
                    kv_dtype=jnp.float32, weight_quant="int8")

    def test_shared_group_still_mixed_eligible(self):
        eng = InferenceEngine(self._model(), InferenceConfig(**self._kw()))
        assert "shared" in eng._quant["blocks"]      # it IS quantized...
        assert eng._quant_is_rowwise()               # ...but doesn't veto

    def test_mixed_on_traces_and_matches_dequant(self):
        gr = SamplingParams(temperature=0.0, max_new_tokens=5)
        prompt = {0: [1, 2, 3, 4]}
        ref = InferenceEngine(
            self._model(), InferenceConfig(mixed_gemm="off", **self._kw())
        ).generate(prompt, gr)[0]
        eng = InferenceEngine(
            self._model(), InferenceConfig(mixed_gemm="on", **self._kw()))
        out = eng.generate(prompt, gr)[0]
        assert eng._mixed_gemm_active
        assert out == ref

    def test_streamed_mixed_on(self, tmp_path):
        gr = SamplingParams(temperature=0.0, max_new_tokens=5)
        prompt = {0: [1, 2, 3, 4]}
        ref = InferenceEngine(
            self._model(), InferenceConfig(mixed_gemm="off", **self._kw())
        ).generate(prompt, gr)[0]
        eng = InferenceEngine(self._model(), InferenceConfig(
            mixed_gemm="on", weight_stream=str(tmp_path / "w"),
            **self._kw()))
        assert eng._stream.mixed_gemm_eligible
        assert eng.generate(prompt, gr)[0] == ref
