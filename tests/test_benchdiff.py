"""tools/benchdiff regression sentinel — the tier-1 gate that turns the
BENCH_r* trajectory from an eyeballed log into a guarded one: same
config fingerprint => hard per-leg thresholds (nonzero exit on
regression), changed fingerprint => report-only.  Pure host JSON work,
no JAX."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.benchdiff import (compare, diff_files, main,  # noqa: E402
                             metric_direction, smoke)


def test_smoke_is_the_acceptance_check():
    out = smoke()
    assert out["ok"] and len(out["checks"]) == 9
    assert "anomaly_delta_reports_not_gates" in out["checks"]
    assert "slo_delta_reports_not_gates" in out["checks"]


def test_anomaly_deltas_report_only():
    """``<leg>_anomalies`` totals (PR 10) are listed as deltas but never
    gate — detector fires are rig-noise sensitive."""
    base = {"engine_version": "1.0", "config_hash": "aaaa",
            "value": 100.0,
            "pipe2_anomalies": {"total": 0, "by_signal": {}}}
    noisy = dict(base, pipe2_anomalies={"total": 12,
                                        "by_signal": {"ttft_ms": 12}})
    v = compare(base, noisy)
    assert v["ok"]
    assert v["anomaly_deltas"] == [
        {"metric": "pipe2_anomalies", "old": 0, "new": 12}]
    # a leg whose anomaly subtree is None (anomaly off) stays silent
    off = dict(base, pipe2_anomalies=None)
    assert compare(off, off)["anomaly_deltas"] == []


def test_fleet_anomaly_deltas_report_only():
    """``fleet_*_anomalies`` subtrees (PR 14: {"fleet": ...,
    "replicas": {name: ...}}) report fleet-total and per-replica
    deltas under ``fleet_anomaly_deltas`` but can never fail a run —
    even under a matching fingerprint."""
    base = {"engine_version": "1.0", "config_hash": "aaaa",
            "value": 100.0,
            "fleet_serving_anomalies": {
                "fleet": {"total": 0, "by_signal": {}},
                "replicas": {"r0": {"total": 0}}}}
    stormy = dict(base, fleet_serving_anomalies={
        "fleet": {"total": 9, "by_signal": {"failover_migration_storm": 9}},
        "replicas": {"r0": {"total": 9}}})
    v = compare(base, stormy)
    assert v["ok"], "fleet anomaly deltas must never gate"
    assert v["fleet_anomaly_deltas"] == [
        {"metric": "fleet_serving_anomalies.fleet", "old": 0, "new": 9},
        {"metric": "fleet_serving_anomalies.replicas.r0",
         "old": 0, "new": 9}]
    # not double-counted into the flat anomaly deltas
    assert v["anomaly_deltas"] == []
    assert compare(base, base)["fleet_anomaly_deltas"] == []


def test_metric_direction_classification():
    assert metric_direction("pipe2_decode_tok_s") == 1
    assert metric_direction("value") == 1
    assert metric_direction("shared_prefix_speedup") == 1
    assert metric_direction("goodput_qps_sla4") == 1
    assert metric_direction("mfu") == 1
    assert metric_direction("serving_ttft_p50_ms") == -1
    assert metric_direction("llama8b_int8_decode_ms_per_tok_ema") == -1
    assert metric_direction("platform") is None
    assert metric_direction("steps") is None
    assert metric_direction("config_hash") is None


def test_fleet_leg_metrics_are_gated():
    """The fleet_serving_bench leg's headline metrics (PR 13) land
    top-level under names the EXISTING direction rules gate: goodput /
    hit-rate up-is-better, TTFT ms down-is-better — a fleet goodput or
    affinity regression fails a same-fingerprint benchdiff run."""
    assert metric_direction("fleet_goodput_tok_s") == 1
    assert metric_direction("fleet_single_goodput_tok_s") == 1
    assert metric_direction("fleet_affinity_hit_rate") == 1
    assert metric_direction("fleet_round_robin_hit_rate") == 1
    assert metric_direction("fleet_ttft_p95_prekill_ms") == -1
    assert metric_direction("fleet_ttft_p95_postkill_ms") == -1
    # and a regression actually trips the gate
    base = {"engine_version": "1", "config_hash": "aaaa",
            "value": 100.0, "fleet_goodput_tok_s": 500.0,
            "fleet_affinity_hit_rate": 0.7}
    worse = dict(base, fleet_goodput_tok_s=300.0)
    v = compare(base, worse)
    assert not v["ok"]
    assert any(r["metric"] == "fleet_goodput_tok_s"
               for r in v["regressions"])


def test_http_leg_metrics_are_gated():
    """The http_serving_bench leg (PR 15, the network gateway): its
    headline metrics land top-level under names the EXISTING direction
    rules gate — goodput up-is-better for both columns, TTFT ms
    down-is-better, and the wire-overhead ratio (client-wall TTFT p95
    over in-process engine-record p95) is gated down-is-better via its
    ``ttft`` stem, so a gateway that gets relatively slower fails a
    same-fingerprint compare even when both legs improved."""
    assert metric_direction("http_goodput_tok_s") == 1
    assert metric_direction("inproc_goodput_tok_s") == 1
    assert metric_direction("http_ttft_p95_ms") == -1
    assert metric_direction("inproc_ttft_p95_ms") == -1
    assert metric_direction("http_ttft_overhead_ratio") == -1
    # and an overhead regression actually trips the gate
    base = {"engine_version": "1", "config_hash": "aaaa",
            "value": 100.0, "http_goodput_tok_s": 50.0,
            "http_ttft_overhead_ratio": 1.1}
    worse = dict(base, http_ttft_overhead_ratio=1.6)
    v = compare(base, worse)
    assert not v["ok"]
    assert any(r["metric"] == "http_ttft_overhead_ratio"
               for r in v["regressions"])


def test_tiered_kv_leg_metrics_are_gated():
    """The tiered_kv_serving_bench leg (docs/KV_TIERING.md): its
    headline metrics land top-level under names the EXISTING direction
    rules gate — hit rate up-is-better, the TTFT columns down-is-better
    including ``tiered_kv_ttft_vs_allhbm`` (the 1.25x acceptance bar:
    tiered p95 over the all-HBM ceiling, gated via its ``ttft`` stem),
    and the fleet remote-restage speedup up-is-better — so a tier that
    drifts away from the all-HBM curve or loses to re-prefill fails a
    same-fingerprint compare."""
    assert metric_direction("tiered_kv_hit_rate") == 1
    assert metric_direction("tiered_kv_ttft_p95_ms") == -1
    assert metric_direction("tiered_kv_baseline_ttft_p95_ms") == -1
    assert metric_direction("tiered_kv_allhbm_ttft_p95_ms") == -1
    assert metric_direction("tiered_kv_ttft_vs_allhbm") == -1
    assert metric_direction("tiered_kv_remote_restage_speedup") == 1
    # and drifting off the all-HBM curve actually trips the gate
    base = {"engine_version": "1", "config_hash": "aaaa",
            "value": 100.0, "tiered_kv_hit_rate": 0.6,
            "tiered_kv_ttft_vs_allhbm": 1.2,
            "tiered_kv_remote_restage_speedup": 1.1}
    worse = dict(base, tiered_kv_ttft_vs_allhbm=1.7)
    v = compare(base, worse)
    assert not v["ok"]
    assert any(r["metric"] == "tiered_kv_ttft_vs_allhbm"
               for r in v["regressions"])


def test_disagg_autoscale_leg_metrics_are_gated():
    """The disagg_serving_bench / autoscale_serving_bench legs
    (docs/SERVING.md "Disaggregated pools & elasticity"): their
    headline metrics land top-level under names the EXISTING direction
    rules gate — ``disagg_interactive_speedup`` (colocated TTFT p95
    rounds over disaggregated: the >1.0 acceptance bar) up-is-better
    via its ``speedup`` stem, both TTFT ms columns down-is-better,
    goodput up-is-better — so a PR that erodes the disaggregation win
    fails a same-fingerprint compare."""
    assert metric_direction("disagg_interactive_speedup") == 1
    assert metric_direction("disagg_ttft_p95_interactive_ms") == -1
    assert metric_direction(
        "disagg_colocated_ttft_p95_interactive_ms") == -1
    assert metric_direction("disagg_goodput_tok_s") == 1
    assert metric_direction("disagg_colocated_goodput_tok_s") == 1
    # a speedup erosion actually trips the gate...
    base = {"engine_version": "1", "config_hash": "aaaa",
            "value": 100.0, "disagg_interactive_speedup": 2.0,
            "disagg_ttft_p95_interactive_ms": 40.0}
    worse = dict(base, disagg_interactive_speedup=1.0)
    v = compare(base, worse)
    assert not v["ok"]
    assert any(r["metric"] == "disagg_interactive_speedup"
               for r in v["regressions"])
    # ...and so does the leg disappearing from the capture entirely
    gone = {k: v2 for k, v2 in base.items()
            if not k.startswith("disagg_")}
    v = compare(base, gone)
    assert not v["ok"]
    assert set(v["only_old"]) == {"disagg_interactive_speedup",
                                  "disagg_ttft_p95_interactive_ms"}


def test_matching_fingerprint_enforces_and_exits_nonzero(tmp_path):
    old = {"engine_version": "1", "config_hash": "aaaa",
           "value": 100.0, "serving_decode_tok_s": 700.0}
    new = dict(old, serving_decode_tok_s=400.0)
    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(json.dumps(old))
    pn.write_text(json.dumps(new))
    assert main([str(po), str(pn), "--json"]) == 1
    v = diff_files(str(po), str(pn))
    assert v["enforced"] and not v["ok"]
    assert v["regressions"][0]["metric"] == "serving_decode_tok_s"
    # same capture against itself is green
    assert main([str(po), str(po)]) == 0


def test_mismatched_fingerprint_is_report_only(tmp_path):
    old = {"engine_version": "1", "config_hash": "aaaa",
           "value": 100.0, "serving_decode_tok_s": 700.0}
    new = {"engine_version": "2", "config_hash": "bbbb",
           "value": 100.0, "serving_decode_tok_s": 400.0}
    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(json.dumps(old))
    pn.write_text(json.dumps(new))
    assert main([str(po), str(pn)]) == 0          # reported, not gated
    v = diff_files(str(po), str(pn))
    assert not v["enforced"] and v["ok"] and v["regressions"]


def test_missing_fingerprint_never_enforces():
    # pre-PR-8 captures (BENCH_r01..r05) carry no config_hash: nothing
    # to anchor comparability, so the gate must not fire
    v = compare({"value": 100.0}, {"value": 10.0})
    assert not v["enforced"] and v["ok"] and v["regressions"]


def test_diagnostic_subtrees_and_directionless_keys_skipped():
    old = {"config_hash": "x", "engine_version": "1", "value": 10.0,
           "steps": 100, "platform": "cpu",
           "serving_request_metrics": {"ttft_ms": {"p50": 5.0}}}
    new = dict(old, steps=1, platform="tpu",
               serving_request_metrics={"ttft_ms": {"p50": 500.0}})
    assert compare(old, new)["ok"]


def test_latency_direction_and_threshold_boundary():
    base = {"config_hash": "x", "engine_version": "1",
            "serving_ttft_p50_ms": 100.0}
    assert compare(base, dict(base, serving_ttft_p50_ms=114.0))["ok"]
    assert not compare(base, dict(base, serving_ttft_p50_ms=120.0))["ok"]
    # looser threshold clears it
    assert compare(base, dict(base, serving_ttft_p50_ms=120.0),
                   threshold=0.3)["ok"]


def test_dropped_leg_is_a_regression():
    base = {"config_hash": "x", "engine_version": "1",
            "value": 10.0, "spec_decode_speedup": 1.5}
    v = compare(base, {"config_hash": "x", "engine_version": "1",
                       "value": 10.0})
    assert not v["ok"] and v["only_old"] == ["spec_decode_speedup"]


def test_cli_smoke_leg():
    """The wired tier-1 leg: ``python -m tools.benchdiff --smoke``."""
    r = subprocess.run([sys.executable, "-m", "tools.benchdiff",
                        "--smoke"], cwd=REPO, capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["ok"]


def test_real_capture_parses_if_present():
    """benchdiff must at least parse the repo's own BENCH trajectory
    (old captures have no fingerprint -> report-only)."""
    captures = sorted(REPO.glob("BENCH_r*.json"))
    if len(captures) < 2:
        pytest.skip("fewer than two BENCH captures in the repo")
    v = diff_files(str(captures[-2]), str(captures[-1]))
    assert isinstance(v["regressions"], list)
