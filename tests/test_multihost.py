"""Two-process CPU multi-host test (VERDICT r2 item 10).

Spawns two ``jax.distributed`` CPU processes (Gloo collectives, 2
virtual devices each), trains two steps, round-trips a checkpoint, and
asserts resumed-vs-continued step parity.  The child lives in
``tests/multihost_child.py``.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.nightly


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_train_and_checkpoint(tmp_path):
    child = os.path.join(os.path.dirname(__file__), "multihost_child.py")
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, child, str(pid), port, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo") for pid in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    assert all(p.returncode == 0 for p in procs), "\n---\n".join(outs)
    assert "RANK0 OK" in outs[0] and "RANK1 OK" in outs[1]
    # the psum'd loss is identical on both hosts
    l0 = [ln for ln in outs[0].splitlines() if "LOSSES" in ln][0].split()
    l1 = [ln for ln in outs[1].splitlines() if "LOSSES" in ln][0].split()
    assert l0[2:] == l1[2:], (l0, l1)
