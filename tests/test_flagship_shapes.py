"""Flagship-scale shape smoke tests: prove the 8B/70B configs lay out
cleanly under stage-3 + offload + TP sharding WITHOUT allocating them
(jax.eval_shape + NamedSharding.shard_shape divisibility).

These catch the divisibility/layout bugs a real 70B run would hit
(BASELINE.json north star: Llama-3-70B ZeRO-3 + offload on v5p-128;
FastGen Llama-3-8B on v5e-8)."""

import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_VALIDATE = r'''
import sys; sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from deepspeed_tpu.comm.mesh import MeshTopology
from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.config.config import ZeroConfig
from deepspeed_tpu.models import build_config
from deepspeed_tpu.models.transformer import init_params
from deepspeed_tpu.parallel.zero import ZeroPolicy
from jax.sharding import NamedSharding

cfg = build_config({preset!r})
_cap = {{}}
def _abstract_init():
    p, a = init_params(cfg, jax.random.PRNGKey(0))
    _cap["axes"] = a              # axes are static python; capture at trace
    return p
shapes = jax.eval_shape(_abstract_init)
axes = _cap["axes"]
topo = MeshTopology.build(MeshConfig(**{mesh!r}))
zcfg = ZeroConfig(stage=3)
zcfg.offload_optimizer.device = {offload!r}
pol = ZeroPolicy.from_config(zcfg, topo)

n_params = 0
for name, spec_tree in (("param", pol.tree_param_specs(axes, shapes)),
                        ("master", pol.tree_master_specs(axes, shapes)),
                        ("grad", pol.tree_grad_specs(axes, shapes))):
    flat_s = jax.tree.leaves(spec_tree,
                             is_leaf=lambda x: hasattr(x, "index"))
    flat_p = jax.tree.leaves(shapes)
    assert len(flat_s) == len(flat_p)
    for spec, leaf in zip(flat_s, flat_p):
        sh = NamedSharding(topo.mesh, spec)
        # raises if any dim is not divisible by its mesh axes
        local = sh.shard_shape(tuple(leaf.shape))
        if name == "param":
            n_params += int(np.prod(leaf.shape))
print("OK", {preset!r}, "params:", n_params)
'''


def _run(preset, mesh, n_devices, offload="none"):
    code = _VALIDATE.format(repo=REPO, preset=preset, mesh=mesh,
                            offload=offload)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices} "
        "--xla_cpu_enable_concurrency_optimized_scheduler=false")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout, out.stdout


class TestFlagshipShapes:
    def test_llama3_70b_v5p128_stage3_offload_tp(self):
        """The BASELINE north-star config: 70B, ZeRO-3 + CPU offload,
        dp4 x fsdp16 x tp2 over 128 chips."""
        _run("llama3-70b", dict(data=4, fsdp=16, tensor=2), 128,
             offload="cpu")

    def test_llama3_8b_v5e8_stage3(self):
        _run("llama3-8b", dict(data=1, fsdp=4, tensor=2), 8)

    def test_mixtral_8x7b_expert_parallel(self):
        _run("mixtral-8x7b", dict(data=2, fsdp=8, expert=8), 128)

    def test_gpt2_xl_tp4(self):
        _run("gpt2-xl", dict(data=2, tensor=4), 8)
