"""Checkpoint fragment-store tests (reference analogs:
tests/unit/checkpoint/test_zero_optimizer.py — save/load across stages,
test_universal_checkpoint.py — resume at different parallelism degree via
DistributedFixture, SURVEY §4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.checkpoint import consolidate, load_tree, save_tree
from tests.simple_model import make_batch, make_mlp


def cfg_for(stage, mesh, **over):
    c = {
        "train_micro_batch_size_per_device": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "mesh": mesh,
        "steps_per_print": 1000,
    }
    c.update(over)
    return c


def make_engine(stage=2, mesh=None, seed=0):
    p, ax, loss_fn = make_mlp(seed=seed)
    return ds.initialize(loss_fn=loss_fn, params=p, param_axes=ax,
                         config=cfg_for(stage, mesh or {"data": 2, "fsdp": 4}))


class TestTreeRoundtrip:
    def test_sharded_roundtrip(self, tmp_path, fsdp8):
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(fsdp8.mesh, P("fsdp"))
        x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
        tree = {"a": x, "b": jnp.float32(3.5)}
        save_tree(tree, str(tmp_path / "t"))
        loaded, meta = load_tree(tree, {"a": sh, "b": NamedSharding(
            fsdp8.mesh, P())}, str(tmp_path / "t"))
        np.testing.assert_array_equal(np.asarray(loaded["a"]), np.asarray(x))
        assert float(loaded["b"]) == 3.5

    def test_reshard_on_load(self, tmp_path, fsdp8, mesh8):
        """Save sharded over fsdp=8, load sharded over data2/fsdp2/tensor2 —
        the universal-checkpoint property, no offline conversion."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        src = NamedSharding(fsdp8.mesh, P("fsdp", None))
        x = jax.device_put(jnp.arange(256.0).reshape(16, 16), src)
        save_tree({"w": x}, str(tmp_path / "t"))
        dst = NamedSharding(mesh8.mesh, P(("data", "fsdp"), "tensor"))
        loaded, _ = load_tree({"w": x}, {"w": dst}, str(tmp_path / "t"))
        np.testing.assert_array_equal(np.asarray(loaded["w"]), np.asarray(x))
        assert loaded["w"].sharding == dst


class TestEngineCheckpoint:
    def test_save_load_resume(self, tmp_path):
        eng = make_engine(stage=2)
        for i in range(3):
            eng.train_batch(make_batch(eng.train_batch_size, seed=i))
        eng.save_checkpoint(str(tmp_path), tag="t3",
                            client_state={"note": "hi"})
        saved_step = eng.global_steps
        loss_before = float(eng.train_batch(make_batch(32, seed=99))["loss"])

        eng2 = make_engine(stage=2)
        _, client = eng2.load_checkpoint(str(tmp_path), tag="t3")
        assert client["note"] == "hi"
        assert eng2.global_steps == saved_step
        # identical state -> identical next-step loss
        # (rerun same batch on fresh engine from checkpoint)
        loss_after = float(eng2.train_batch(make_batch(32, seed=99))["loss"])
        assert loss_after == pytest.approx(loss_before, rel=1e-6)

    def test_latest_pointer(self, tmp_path):
        eng = make_engine()
        eng.train_batch(make_batch(eng.train_batch_size))
        eng.save_checkpoint(str(tmp_path))
        assert os.path.exists(tmp_path / "latest")
        eng2 = make_engine()
        eng2.load_checkpoint(str(tmp_path))       # resolves via latest
        assert eng2.global_steps == 1

    def test_elastic_resize(self, tmp_path):
        """Train at fsdp=4/data=2 + ZeRO-2, resume at fsdp=8 + ZeRO-3 —
        the reference needs universal-checkpoint conversion for this
        (checkpoint/ds_to_universal.py); here it is the default."""
        eng = make_engine(stage=2, mesh={"data": 2, "fsdp": 4})
        for i in range(3):
            eng.train_batch(make_batch(eng.train_batch_size, seed=i))
        eng.save_checkpoint(str(tmp_path), tag="resize")
        before = consolidate(str(tmp_path / "resize"))

        eng2 = make_engine(stage=3, mesh={"data": 1, "fsdp": 8})
        eng2.load_checkpoint(str(tmp_path), tag="resize")
        assert eng2.global_steps == 3
        # trajectories continue identically (same math regardless of layout)
        a = float(eng.train_batch(make_batch(32, seed=50))["loss"])
        b = float(eng2.train_batch(make_batch(32, seed=50))["loss"])
        assert b == pytest.approx(a, rel=1e-5)

    def test_consolidate_fp32(self, tmp_path):
        """zero_to_fp32 analog: full weights from a sharded checkpoint."""
        eng = make_engine(stage=3, mesh={"data": 1, "fsdp": 8})
        eng.train_batch(make_batch(eng.train_batch_size))
        eng.save_checkpoint(str(tmp_path), tag="c")
        full = consolidate(str(tmp_path / "c"))
        w1_key = [k for k in full if "w1" in k]
        assert len(w1_key) == 1
        w1 = full[w1_key[0]]
        assert w1.shape == (16, 64)
        np.testing.assert_array_equal(
            w1, np.asarray(jax.device_get(eng.state.master["w1"])))


class TestAsyncCheckpoint:
    def test_async_save_resume_parity(self, tmp_path):
        """checkpoint.async_save: training continues while the fragments
        are written on a worker thread (reference: nebula checkpoint
        engine); the resumed trajectory matches the synchronous save."""
        def mk(seed=0):
            p, ax, loss_fn = make_mlp(seed=seed)
            return ds.initialize(
                loss_fn=loss_fn, params=p, param_axes=ax,
                config=cfg_for(2, {"data": 2, "fsdp": 4},
                               checkpoint={"async_save": True}))

        eng = mk()
        for i in range(2):
            eng.train_batch(make_batch(eng.train_batch_size, seed=i))
        eng.save_checkpoint(str(tmp_path), tag="a2")
        # the save runs in the background; the next (donating) step must
        # be safe immediately
        loss_cont = float(
            eng.train_batch(make_batch(32, seed=9))["loss"])
        eng.wait_checkpoint()

        eng2 = mk()
        eng2.load_checkpoint(str(tmp_path), tag="a2")
        loss_resume = float(
            eng2.train_batch(make_batch(32, seed=9))["loss"])
        assert loss_resume == pytest.approx(loss_cont, rel=1e-6)

    def test_latest_written_after_fragments(self, tmp_path):
        """`latest` is only written once every fragment landed: after the
        writer drains, the pointed-at tag is complete and loadable (a
        crash mid-save can never leave `latest` pointing at a torn tag)."""
        def mk(seed=0):
            p, ax, loss_fn = make_mlp(seed=seed)
            return ds.initialize(
                loss_fn=loss_fn, params=p, param_axes=ax,
                config=cfg_for(2, {"data": 2, "fsdp": 4},
                               checkpoint={"async_save": True}))

        eng = mk()
        eng.train_batch(make_batch(eng.train_batch_size, seed=0))
        eng.save_checkpoint(str(tmp_path))
        eng.wait_checkpoint()
        eng2 = mk()
        eng2.load_checkpoint(str(tmp_path))     # resolves via latest
        assert eng2.global_steps == 1
